"""Overlapped device input pipeline — prefetch-to-device + shape bucketing.

The reference Fluid runtime hides host I/O behind the executor's op
stream (reference: operators/reader/buffered_reader.cc double-buffers
host→device copies); TrainLoop previously fed raw numpy batches straight
into ``trainer.train_step``, so every step paid a blocking host→device
transfer and any last-batch shape drift silently retraced the jitted
step (PR 1's recompile tracker *records* this; this module *fixes* it).
Two pieces:

- :class:`DevicePrefetcher`: a sharding-aware prefetch-to-device
  iterator. A background thread (reusing the cancellable-queue machinery
  of ``data/reader.py``) runs the host half of the pipeline — transform,
  bucket-pad, ``jax.device_put`` onto the mesh — up to ``size`` batches
  ahead, so host work and the transfer overlap the device's compute on
  the previous step. ``size=0`` degrades to synchronous staging (the
  same code path, no thread) so bucketing works without prefetch.
- :class:`BucketPadder`: pads the batch axis of a pytree's batch-sized
  array leaves UP to a small fixed set of bucket sizes (``"pow2"`` or an
  explicit ascending list — boundary semantics shared with
  ``data/bucketing.py``), so the jitted train step compiles once per
  *bucket* instead of once per drifting shape (the ragged final batch of
  every epoch). Fixed-shape aux leaves and empty batches ride through
  untouched.

Donation safety: staged batches must never alias state a consumer's
jitted step donates. Host (numpy) inputs always produce fresh device
buffers; an input leaf that is *already* a committed ``jax.Array`` would
alias straight through ``device_put``, so with ``donate_safe=True``
(default) such leaves are copied before placement — a step that donates
its batch argument can never invalidate a buffer the source (or a later
yield) still holds.

Telemetry (all ``pt_input_*``, off-by-default like the rest): prefetch
queue depth gauge, host-wait-per-step histogram (time the consumer spent
blocked waiting for input — the number overlap is supposed to drive to
zero), bucket-pad-waste counter.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Iterable, Iterator, Optional, Union

import numpy as np

from .. import telemetry
from ..core.enforce import enforce
from .bucketing import round_to_bucket
from .reader import _PRODUCER_LOST, _get_bounded, _put_cancellable


@telemetry.cached_instruments
def _input_metrics(reg):
    """Input-pipeline instrument set (only reached when telemetry is
    on), memoized against the registry generation."""
    return {
        "queue_depth": reg.gauge(
            "pt_input_prefetch_queue_depth",
            "device batches staged ahead of the consumer"),
        "host_wait": reg.histogram(
            "pt_input_host_wait_seconds",
            "time the consumer spent blocked waiting for the next "
            "staged batch (0 ≈ input pipeline fully hidden)", unit="s"),
        "pad_rows": reg.counter(
            "pt_input_bucket_pad_rows_total",
            "batch-axis rows added by bucket padding, summed over "
            "array leaves (wasted compute bought for compile reuse)"),
        "batches": reg.counter(
            "pt_input_batches_total", "batches staged onto device"),
        "depth": reg.gauge(
            "pt_input_prefetch_depth",
            "current prefetch staging capacity (auto sizing grows it "
            "while host-wait p50 exceeds threshold)"),
    }


def _dominant_rows(leaves, axis: int) -> Optional[int]:
    """The batch-axis size of a pytree: the axis size shared by the
    most array leaves; ties break to the size carrying more total
    elements, then to the smaller size (deterministic). A batch mixing
    per-example leaves with fixed-size aux leaves (class weights, ...)
    resolves to the per-example size — a lone aux vector, even one
    longer than the batch, cannot outvote the real batch leaves — so
    aux leaves are never padded or miscounted."""
    counts: dict = {}
    elems: dict = {}
    for leaf in leaves:
        shape = getattr(leaf, "shape", None)
        if shape is None or len(shape) <= axis:
            continue
        n = int(shape[axis])
        sz = 1
        for s in shape:
            sz *= int(s)
        counts[n] = counts.get(n, 0) + 1
        elems[n] = elems.get(n, 0) + sz
    if not counts:
        return None
    return max(counts, key=lambda n: (counts[n], elems[n], -n))


class BucketPadder:
    """Pad the batch axis of a pytree's array leaves to a fixed bucket
    set.

    Only leaves whose ``axis`` size equals the pytree's dominant batch
    size (see :func:`_dominant_rows`) are padded — fixed-shape aux
    leaves ride through untouched. An empty (0-row) batch also rides
    through unpadded: fabricating rows from nothing would train on fake
    data.

    ``buckets``: ``"pow2"`` rounds the axis size up to the next power of
    two; an ascending list picks the first boundary >= n; a size beyond
    the last boundary stays exact (an accepted recompile — same
    semantics as :func:`..bucketing.round_to_bucket`). ``mode``:
    ``"zeros"`` fills with ``pad_value``; ``"edge"`` repeats the last
    real row, which keeps a mean loss a weighted mean of *real* examples
    (the last row double-counts) instead of diluting it with zeros.

    Padded rows participate in the step's reductions — a mean loss over
    a padded final batch is slightly dampened (zeros) or reweighted
    (edge). That is the standard static-shape tradeoff vs dropping the
    batch; thread the real row count through the batch yourself when the
    step must mask exactly.
    """

    def __init__(self, buckets: Union[str, Iterable[int]] = "pow2",
                 axis: int = 0, pad_value=0, mode: str = "zeros"):
        if buckets is not None and buckets != "pow2":
            buckets = sorted(int(b) for b in buckets)
            enforce(bool(buckets), "buckets must be non-empty")
            enforce(all(b >= 1 for b in buckets),
                    "bucket boundaries must be >= 1, got %s", buckets)
        enforce(mode in ("zeros", "edge"),
                "mode must be zeros|edge, got %s", mode)
        enforce(axis >= 0, "axis must be >= 0, got %s", axis)
        self.buckets = buckets
        self.axis = axis
        self.pad_value = pad_value
        self.mode = mode

    def bucket_size(self, n: int) -> int:
        return int(round_to_bucket(int(n), self.buckets))

    def pad(self, batch):
        """Pad ``batch`` (a pytree of arrays; non-array and non-batch
        leaves ride through) and return ``(padded, rows_added)``."""
        padded, rows_added, _ = self._pad_impl(batch)
        return padded, rows_added

    def _pad_impl(self, batch):
        """``(padded, rows_added, pre_pad_rows)`` — the 3-tuple form so
        the prefetch staging path gets the pre-pad batch size from the
        same single tree traversal."""
        import jax

        leaves, treedef = jax.tree_util.tree_flatten(batch)
        n = _dominant_rows(leaves, self.axis)
        if not n:  # no array leaves, or a 0-row batch: nothing to pad
            return batch, 0, n
        b = self.bucket_size(n)
        if b == n:
            return batch, 0, n
        rows_added = 0
        out = []
        for leaf in leaves:
            shape = getattr(leaf, "shape", None)
            if (shape is None or len(shape) <= self.axis
                    or int(shape[self.axis]) != n):
                out.append(leaf)  # non-batch leaf: exact shape
                continue
            arr = np.asarray(leaf)
            widths = [(0, 0)] * arr.ndim
            widths[self.axis] = (0, b - n)
            if self.mode == "edge":
                arr = np.pad(arr, widths, mode="edge")
            else:
                arr = np.pad(arr, widths, constant_values=self.pad_value)
            rows_added += b - n
            out.append(arr)
        if rows_added and telemetry.enabled():
            _input_metrics()["pad_rows"].inc(rows_added)
        return jax.tree_util.tree_unflatten(treedef, out), rows_added, n

    def __call__(self, batch):
        return self.pad(batch)[0]


class DevicePrefetcher:
    """Sharding-aware prefetch-to-device iterator.

    ``batches`` is a reader creator (zero-arg callable returning an
    iterator — the ``data.reader`` contract, re-iterable per epoch) or a
    plain iterable (single pass). Per staged batch, in the worker:
    ``transform`` (host-side, optional) → ``prefetch_rows`` (optional:
    called with the host batch so a host-backed embedding table can
    stage its rows host→chip overlapped with compute — see
    ``embedding.HostBackedTable.prefetch``) → :class:`BucketPadder`
    (when ``bucket_by`` is set) → ``jax.device_put`` with ``sharding``
    (or the mesh's ``P("dp")`` batch sharding when only ``mesh`` is
    given; plain default placement otherwise).

    ``stage_per_shard`` (sharding-plan staging): stage each leaf
    shard-by-shard — only the slices this process's devices hold are
    ``device_put``, and the global array assembles via
    ``jax.make_array_from_single_device_arrays``. Auto-enabled whenever
    the sharding spans non-addressable devices (a multi-host plan mesh),
    where it is the only staging that works AND each host's transfer
    volume drops to its own shard; force ``True`` to take the path on a
    fully-addressable mesh (tests do).

    ``size`` >= 1 enables the background staging thread with that many
    queue slots (2 = double buffering, 3 = triple); ``size=0`` stages
    synchronously in the consumer thread (bucketing without prefetch).
    ``size="auto"`` starts at depth 2 and GROWS the staging capacity by
    one (up to ``auto_cap``) whenever the p50 of the last
    ``AUTO_WINDOW`` host waits exceeds ``auto_threshold_s`` — the
    ``pt_input_host_wait_seconds`` signal fed back into the knob it
    measures (ROADMAP's auto-sized prefetch depth). Depth never
    shrinks: a deeper queue only costs idle slots once the producer
    keeps up, while thrashing the depth down would re-starve a bursty
    consumer. ``current_depth`` is the live value (/statusz shows it;
    ``pt_input_prefetch_depth`` gauges it).
    Abandoning the iterator mid-stream (``break``) releases the worker —
    no leaked thread, no device batches pinned for the process lifetime;
    a worker exception re-raises in the consumer.

    ``last_real_rows`` holds the PRE-pad batch-axis size of the most
    recently yielded batch (None before the first yield) — consumers
    reporting examples/sec must divide by this, not the padded shape,
    or bucketing inflates the metric by exactly the padding it adds.
    Updated by the consumer thread just before each yield, so it is
    in step with the batch being processed even while the worker runs
    ahead.
    """

    _END = object()

    AUTO_INITIAL = 2   # "auto" starting depth (double buffering)
    AUTO_CAP = 8       # default growth ceiling
    AUTO_WINDOW = 8    # host waits per growth decision
    AUTO_THRESHOLD_S = 1e-3  # p50 wait above this = input-bound

    def __init__(self, batches: Union[Callable[[], Iterator[Any]],
                                      Iterable[Any]],
                 *, size: Union[int, str] = 2, mesh=None, sharding=None,
                 transform: Optional[Callable] = None,
                 bucket_by=None, pad_value=0, axis: int = 0,
                 donate_safe: bool = True,
                 auto_cap: Optional[int] = None,
                 auto_threshold_s: Optional[float] = None,
                 stage_per_shard: Optional[bool] = None,
                 prefetch_rows: Optional[Callable[[Any], Any]] = None):
        self.auto = size == "auto"
        if self.auto:
            self.auto_cap = int(auto_cap if auto_cap is not None
                                else self.AUTO_CAP)
            size = min(self.AUTO_INITIAL, self.auto_cap)
            enforce(self.auto_cap >= 1,
                    "auto_cap must be >= 1, got %s", self.auto_cap)
        else:
            enforce(auto_cap is None and auto_threshold_s is None,
                    "auto_cap/auto_threshold_s only apply to "
                    "size='auto'")
            enforce(not isinstance(size, str),
                    "prefetch size must be an int or 'auto', got %r",
                    size)
            size = int(size)
            enforce(size >= 0, "prefetch size must be >= 0, got %s",
                    size)
            self.auto_cap = size
        self.auto_threshold_s = float(
            auto_threshold_s if auto_threshold_s is not None
            else self.AUTO_THRESHOLD_S)
        self.batches = batches
        self.size = int(size)
        self._depth = self.size  # live capacity (auto mode grows it)
        self.last_queue_depth: Optional[int] = None
        if sharding is None and mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            sharding = NamedSharding(mesh, PartitionSpec("dp"))
        self.sharding = sharding
        self.transform = transform
        if isinstance(bucket_by, BucketPadder) or bucket_by is None:
            self.padder = bucket_by
        else:
            self.padder = BucketPadder(bucket_by, axis=axis,
                                       pad_value=pad_value)
        # batch axis for last_real_rows accounting — honored with or
        # without a padder (a BucketPadder instance brings its own)
        if self.padder is not None:
            self.axis = self.padder.axis
        else:
            enforce(axis >= 0, "axis must be >= 0, got %s", axis)
            self.axis = int(axis)
        self.donate_safe = donate_safe
        # per-shard staging (the sharding-plan path): each process
        # device_puts ONLY the shard slices its own devices hold and
        # assembles the global array via
        # jax.make_array_from_single_device_arrays — on a multi-host
        # mesh no host ever materializes (or transfers) rows another
        # host consumes. Default None = automatic: forced ON whenever
        # the sharding spans devices this process cannot address (a
        # whole-batch device_put there would fail outright); OFF for
        # fully-addressable shardings where a single device_put lets
        # the runtime scatter (tests force it ON to exercise the path
        # single-process).
        if stage_per_shard is None:
            stage_per_shard = bool(
                self.sharding is not None
                and not getattr(self.sharding, "is_fully_addressable",
                                True))
        self.stage_per_shard = bool(stage_per_shard)
        enforce(not self.stage_per_shard or self.sharding is not None,
                "stage_per_shard needs a sharding (or mesh) to stage "
                "onto")
        # host-backed embedding hook: called with each (post-transform,
        # pre-pad) host batch from the staging thread, so e.g.
        # embedding.HostBackedTable.prefetch moves the NEXT step's rows
        # host->chip while the device computes the current step
        self.prefetch_rows = prefetch_rows
        self.last_real_rows: Optional[int] = None

    # -- staging (worker side) ----------------------------------------------

    def _put_per_shard(self, leaf):
        """Stage one leaf shard-by-shard: device_put ONLY the slices
        this process's devices own (``addressable_devices_indices_map``)
        and assemble the global array with
        ``jax.make_array_from_single_device_arrays`` — the per-host
        staging contract a multi-host sharding plan needs (a whole-array
        ``device_put`` cannot even target non-addressable devices).
        Donation safety matches the whole-array path: a live jax.Array
        source is sliced through an owned host copy, never aliased."""
        import jax

        host = np.asarray(leaf)
        parts = []
        for dev, idx in self.sharding.addressable_devices_indices_map(
                host.shape).items():
            part = host[idx]
            if self.donate_safe and isinstance(leaf, jax.Array):
                part = np.array(part)
            parts.append(jax.device_put(part, dev))
        return jax.make_array_from_single_device_arrays(
            host.shape, self.sharding, parts)

    def _source(self) -> Iterator[Any]:
        src = self.batches
        return src() if callable(src) else iter(src)

    def _stage(self, item):
        import jax
        import jax.numpy as jnp

        if self.transform is not None:
            item = self.transform(item)
        if self.prefetch_rows is not None:
            self.prefetch_rows(item)
        if self.padder is not None:
            # _pad_impl hands back the pre-pad batch size from its own
            # tree traversal — no second flatten on the hot path
            item, _, real_rows = self.padder._pad_impl(item)
        else:
            real_rows = _dominant_rows(
                jax.tree_util.tree_leaves(item), self.axis)

        def put(leaf):
            if getattr(leaf, "shape", None) is None:
                return leaf  # python scalar rides along untouched
            if (self.stage_per_shard and np.ndim(leaf) >= 1
                    and self.sharding is not None):
                return self._put_per_shard(leaf)
            if self.donate_safe and isinstance(leaf, jax.Array):
                # device_put on an already-placed array is an alias, and
                # a consumer step donating its batch would invalidate
                # the source's buffer (and any repeat yield of it) —
                # copy to a fresh buffer instead. Host arrays (the
                # common case) always produce fresh buffers anyway.
                leaf = jnp.array(leaf, copy=True)
            if self.sharding is not None:
                return jax.device_put(leaf, self.sharding)
            return jax.device_put(leaf)

        staged = jax.tree_util.tree_map(put, item)
        if telemetry.enabled():
            _input_metrics()["batches"].inc()
        return staged, real_rows

    # -- iteration (consumer side) ------------------------------------------

    @property
    def current_depth(self) -> int:
        """Live staging capacity (== ``size`` unless auto mode grew
        it)."""
        return self._depth

    def _maybe_grow(self, q: "queue.Queue", waits: list) -> None:
        """Auto sizing: one growth decision per full wait window. The
        p50 (not mean — a single slow batch must not grow the queue)
        above threshold means the consumer is input-bound; a deeper
        queue buys the worker more run-ahead."""
        if len(waits) < self.AUTO_WINDOW or self._depth >= self.auto_cap:
            return
        p50 = sorted(waits)[len(waits) // 2]
        waits.clear()  # fresh window either way (no double counting)
        if p50 <= self.auto_threshold_s:
            return
        self._depth += 1
        with q.mutex:
            # stdlib Queue reads maxsize dynamically under its mutex;
            # wake a producer blocked on the OLD bound
            q.maxsize = self._depth
            q.not_full.notify()
        if telemetry.enabled():
            _input_metrics()["depth"].set(self._depth)

    def __iter__(self):
        if self.size == 0:
            for item in self._source():
                staged, rows = self._stage(item)
                self.last_real_rows = rows
                self.last_queue_depth = 0
                yield staged
            return

        if telemetry.enabled():
            # export the starting capacity too — a healthy auto
            # pipeline that never grows must still be distinguishable
            # from no prefetcher at all
            _input_metrics()["depth"].set(self._depth)
        q: queue.Queue = queue.Queue(maxsize=self._depth)
        waits: list = []
        err = []
        stop = threading.Event()

        def worker():
            try:
                for item in self._source():
                    if not _put_cancellable(q, self._stage(item), stop):
                        return
                    if telemetry.enabled():
                        _input_metrics()["queue_depth"].set(q.qsize())
            except BaseException as e:  # propagate into the consumer
                err.append(e)
            finally:
                _put_cancellable(q, self._END, stop)

        wt = threading.Thread(target=worker, daemon=True,
                              name="pt-device-prefetch")
        wt.start()
        try:
            while True:
                telem = telemetry.enabled()
                # auto mode needs the wait signal with telemetry off
                # too — its feedback loop must not depend on metrics
                # being scraped
                if telem or self.auto:
                    t0 = time.perf_counter()
                # bounded by worker LIVENESS: a staging thread that
                # died without its end sentinel must never hang the
                # training loop (or this generator's teardown) forever
                item = _get_bounded(q, (wt,))
                if item is _PRODUCER_LOST:
                    if not err:
                        enforce(False, "prefetch worker died without "
                                "delivering its end sentinel")
                    break  # err re-raised below
                if telem or self.auto:
                    wait = time.perf_counter() - t0
                    if telem:
                        met = _input_metrics()
                        if item is not self._END:
                            met["host_wait"].observe(wait)
                        met["queue_depth"].set(q.qsize())
                    if (self.auto and item is not self._END
                            and self._depth < self.auto_cap):
                        # at the cap the window stops accumulating —
                        # nothing reads it again, and a long run must
                        # not grow the list one float per batch forever
                        waits.append(wait)
                        self._maybe_grow(q, waits)
                if item is self._END:
                    break
                staged, rows = item
                self.last_real_rows = rows
                self.last_queue_depth = q.qsize()
                yield staged
        finally:
            # consumer abandoned mid-stream (break/exception): release
            # the worker so it exits instead of pinning staged device
            # batches forever
            stop.set()
        if err:
            raise err[0]


def prefetch_to_device(batches, **kwargs) -> DevicePrefetcher:
    """Convenience front for :class:`DevicePrefetcher` (same kwargs)."""
    return DevicePrefetcher(batches, **kwargs)
