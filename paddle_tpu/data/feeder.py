"""Host→device feeding: DataFeeder + double-buffered DeviceLoader.

Capability parity with the reference's feed stack:
  - ``DataFeeder`` (reference: python/paddle/fluid/data_feeder.py — numpy →
    LoDTensor conversion) → here: batch-of-samples → stacked device arrays,
    placed with an optional NamedSharding (the multi-device feed_and_split
    path of parallel_executor.cc:545 becomes a sharded device_put).
  - ``PyReader``/``buffered_reader`` double-buffering (reference:
    python/paddle/fluid/reader.py:42, operators/reader/buffered_reader.cc) →
    ``DeviceLoader``: a background thread stages the next batch onto device
    while the current one computes — hiding host→HBM latency.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Iterator, Optional, Sequence

import jax
import numpy as np

from ..core.enforce import enforce
from .device_loader import DevicePrefetcher


class DataFeeder:
    """Convert a batch (list of sample tuples) into device arrays.

    feed_list names the fields, e.g. ``DataFeeder(["image", "label"])``;
    feed(batch) returns {"image": array, "label": array}.
    """

    def __init__(self, feed_list: Sequence[Any], place=None, program=None,
                 dtypes=None, sharding=None):
        # entries may be names or static Program Vars (the reference's
        # DataFeeder takes Variables); a Var carrying sequence metadata
        # (lod_src) gets ragged columns padded + a lengths companion.
        # Name entries resolve through ``program`` when given, so the
        # name-based pattern keeps its LoD handling.
        def resolve(v):
            if isinstance(v, str) and program is not None and \
                    hasattr(program, "vars") and v in program.vars:
                return program.vars[v]
            return None if isinstance(v, str) else v

        self.feed_vars = [resolve(v) for v in feed_list]
        self.feed_list = [v if isinstance(v, str) else v.name
                          for v in feed_list]
        self.dtypes = dtypes
        self.sharding = sharding
        self.place = place
        # recompilation management (SURVEY §7 hard part): pad ragged
        # sequence columns UP to a bucket boundary instead of the exact
        # batch max, so distinct batches share compiled shapes. None =
        # exact max (every new (B, T) pair recompiles); a sorted list
        # sets explicit boundaries; "pow2" rounds T to powers of two.
        self.length_buckets = None

    def set_length_buckets(self, buckets) -> "DataFeeder":
        """``buckets``: "pow2" or an ascending list of boundary lengths
        (a length above the last boundary pads to the batch max)."""
        if buckets is not None and buckets != "pow2":
            buckets = sorted(int(b) for b in buckets)
            enforce(buckets, "length_buckets must be non-empty")
        self.length_buckets = buckets
        return self

    def _bucket_len(self, t: int) -> int:
        from .bucketing import round_to_bucket

        return round_to_bucket(t, self.length_buckets)

    def feed(self, batch: Iterable[Any]):
        batch = list(batch)
        enforce(len(batch) > 0, "empty batch")
        first = batch[0]
        if not isinstance(first, (tuple, list)):
            batch = [(b,) for b in batch]
        ncols = len(batch[0])
        enforce(ncols == len(self.feed_list),
                "sample has %s fields, feed_list has %s", ncols,
                len(self.feed_list))
        out = {}
        for i, name in enumerate(self.feed_list):
            var = self.feed_vars[i] if i < len(self.feed_vars) else None
            if getattr(var, "lod_src2", None) is not None:
                # nested LoD (level 2): each sample is a LIST of
                # sub-sequences → pad to (B, N, T) with @LEN (B,) counts
                # and @LEN2 (B, N) per-sub-sequence lengths (reference:
                # framework/lod_tensor.h:229 nested offsets)
                samples = [[np.asarray(ss) for ss in s[i]] for s in batch]
                lens = np.array([len(s) for s in samples], np.int32)
                n = max(int(lens.max()), 1)
                tmax = max((c.shape[0] for s in samples for c in s),
                           default=1)
                t = self._bucket_len(int(tmax))
                first = next((c for s in samples for c in s), None)
                elem = first.shape[1:] if first is not None else ()
                squeeze = elem == (1,)
                dt = first.dtype if first is not None else np.float32
                arr = np.zeros((len(samples), n, t) +
                               (() if squeeze else elem), dt)
                lens2 = np.zeros((len(samples), n), np.int32)
                for r, s in enumerate(samples):
                    for q, c in enumerate(s):
                        arr[r, q, :c.shape[0]] = c[:, 0] if squeeze else c
                        lens2[r, q] = c.shape[0]
                if self.dtypes and self.dtypes[i] is not None:
                    arr = arr.astype(self.dtypes[i])
                out[name] = self._place(arr)
                out[var.lod_src] = self._place(lens)
                out[var.lod_src2] = self._place(lens2)
                continue
            col = [np.asarray(s[i]) for s in batch]
            lod_src = getattr(var, "lod_src", None)
            ragged = len({c.shape[:1] for c in col}) > 1
            if lod_src is not None or (ragged and col[0].ndim >= 1):
                # LoD replacement: pad ragged rows to the batch max and
                # emit the lengths companion (SURVEY §7; reference packs
                # these as LoD offsets, framework/lod_tensor.h:229)
                lens = np.array([c.shape[0] for c in col], np.int32)
                t = self._bucket_len(int(lens.max()))
                elem = col[0].shape[1:]
                # per-token [1] elem shape collapses (reference scalars)
                squeeze = elem == (1,)
                arr = np.zeros((len(col), t) + (() if squeeze else elem),
                               col[0].dtype)
                for r, c in enumerate(col):
                    arr[r, :c.shape[0]] = c[:, 0] if squeeze else c
                if self.dtypes and self.dtypes[i] is not None:
                    arr = arr.astype(self.dtypes[i])
                out[name] = self._place(arr)
                if lod_src is not None:
                    out[lod_src] = self._place(lens)
                continue
            arr = np.stack(col)
            if self.dtypes and self.dtypes[i] is not None:
                arr = arr.astype(self.dtypes[i])
            out[name] = self._place(arr)
        return out

    def _place(self, arr: np.ndarray):
        if self.sharding is not None:
            return jax.device_put(arr, self.sharding)
        if self.place is not None:
            return jax.device_put(arr, self.place.device())
        return jax.device_put(arr)

    def decorate_reader(self, reader, multi_devices: bool = False,
                        num_places=None, drop_last: bool = True):
        """reference: data_feeder.py decorate_reader — wrap a batch reader
        so it yields fed (device-placed, name-keyed) batches."""

        def fed():
            for batch in reader():
                yield self.feed(batch)

        return fed

    def feed_parallel(self, iterable, num_places=None):
        """reference: data_feeder.py feed_parallel — device sharding is a
        single global-array placement here (the mesh splits the batch);
        feeds each batch in turn."""
        for batch in iterable:
            yield self.feed(batch)


class DeviceLoader(DevicePrefetcher):
    """Double-buffered device feeder (PyReader analog).

    Thin compatibility front over
    :class:`..data.device_loader.DevicePrefetcher` — a daemon thread
    keeps up to ``capacity`` batches staged on device ahead of the
    consumer. The full pipeline (mesh-default sharding, bucket padding,
    telemetry) lives on the base class.
    """

    def __init__(self, batches: Callable[[], Iterator[Any]],
                 transform: Optional[Callable] = None,
                 sharding=None, capacity: int = 2):
        # capacity=0 used to mean an UNBOUNDED prefetch queue
        # (Queue(maxsize=0)); on the DevicePrefetcher base size=0 means
        # synchronous staging — reject it loudly rather than silently
        # serializing a caller who asked for maximum overlap
        enforce(capacity >= 1,
                "DeviceLoader capacity must be >= 1, got %s (use "
                "DevicePrefetcher(size=0) for synchronous staging)",
                capacity)
        super().__init__(batches, size=capacity, transform=transform,
                         sharding=sharding)
        self.capacity = capacity

    def reset(self):
        """Re-arm for a fresh epoch (PyReader.reset analog): iteration
        restarts the source and prefetch thread on the next __iter__."""
        return self
