"""Reader decorators — capability parity with paddle.reader
(reference: python/paddle/reader/decorator.py:36-360 — map_readers, buffered,
compose, chain, shuffle, firstn, xmap_readers, cache; plus paddle.batch
(reference: python/paddle/batch.py)).

A *reader creator* is a zero-arg callable returning an iterator of samples —
identical contract to the reference, so recipes port directly.
"""

from __future__ import annotations

import itertools
import queue
import random as pyrandom
import threading
from typing import Any, Callable, Iterable, Iterator, List

Reader = Callable[[], Iterator[Any]]


def map_readers(func: Callable, *readers: Reader) -> Reader:
    """reference: decorator.py map_readers."""

    def reader():
        its = [r() for r in readers]
        for items in zip(*its):
            yield func(*items)

    return reader


def shuffle(reader: Reader, buf_size: int, seed=None) -> Reader:
    """reference: decorator.py shuffle — buffered shuffle.

    With no explicit ``seed``, FLAGS_deterministic pins the stream to the
    global seed (pt.seed() if called, else FLAGS_seed — the reference's
    cpu/cudnn_deterministic knobs applied to the one nondeterminism source
    the compiler doesn't own: host-side shuffling). Successive epochs
    advance the permutation (seed + epoch), like the reference's shared
    RNG, while staying replayable across runs."""
    epoch = [0]

    def shuffled():
        from ..core import random as prandom
        from ..core.config import FLAGS

        eff_seed = seed
        if eff_seed is None and FLAGS.get("deterministic"):
            base = prandom._seed or FLAGS.get("seed")
            eff_seed = base + epoch[0]
            epoch[0] += 1
        rng = pyrandom.Random(eff_seed)
        buf: List[Any] = []
        for item in reader():
            buf.append(item)
            if len(buf) >= buf_size:
                rng.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            rng.shuffle(buf)
            yield from buf

    return shuffled


def chain(*readers: Reader) -> Reader:
    """reference: decorator.py chain."""

    def reader():
        for r in readers:
            yield from r()

    return reader


def compose(*readers: Reader, check_alignment: bool = True) -> Reader:
    """reference: decorator.py compose — zip readers into tuple samples."""

    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def reader():
        its = [r() for r in readers]
        if check_alignment:
            for items in itertools.zip_longest(*its):
                if any(i is None for i in items):
                    raise RuntimeError("composed readers have different lengths")
                yield sum((make_tuple(i) for i in items), ())
        else:
            # reference decorator.py: plain zip — trailing samples discarded
            for items in zip(*its):
                yield sum((make_tuple(i) for i in items), ())

    return reader


def buffered(reader: Reader, size: int) -> Reader:
    """reference: decorator.py buffered — background-thread prefetch."""

    end = object()

    def buffered_reader():
        q: queue.Queue = queue.Queue(maxsize=size)
        err: List[BaseException] = []
        stop = threading.Event()

        def worker():
            try:
                for item in reader():
                    if not _put_cancellable(q, item, stop):
                        return
            except BaseException as e:  # propagate into consumer
                err.append(e)
            finally:
                _put_cancellable(q, end, stop)

        t = threading.Thread(target=worker, daemon=True,
                             name="pt-reader-buffered")
        t.start()
        try:
            while True:
                item = _get_bounded(q, (t,))
                if item is _PRODUCER_LOST:
                    if not err:
                        raise RuntimeError(
                            "buffered reader worker died without "
                            "delivering its end sentinel")
                    break  # err re-raised below
                if item is end:
                    break
                yield item
        finally:
            # consumer may abandon mid-stream (break/exception): unblock the
            # worker so it exits instead of pinning buffered items forever
            stop.set()
        if err:
            raise err[0]

    return buffered_reader


def _put_cancellable(q: "queue.Queue", item, stop: "threading.Event") -> bool:
    """q.put that gives up once `stop` is set; returns False if cancelled."""
    while not stop.is_set():
        try:
            q.put(item, timeout=0.1)
            return True
        except queue.Full:
            continue
    return False


_CANCELLED = object()
_PRODUCER_LOST = object()


def _get_cancellable(q: "queue.Queue", stop: "threading.Event"):
    """q.get that gives up once `stop` is set; returns _CANCELLED then
    (otherwise an abandoned consumer would leak blocked threads)."""
    while not stop.is_set():
        try:
            return q.get(timeout=0.1)
        except queue.Empty:
            continue
    return _CANCELLED


def _get_bounded(q: "queue.Queue", threads, poll_s: float = 0.5):
    """Consumer-side q.get bounded by PRODUCER LIVENESS: blocks while
    any producer thread is alive, but a producer that died without
    delivering its end sentinel (a failed sentinel put, an interpreter
    tearing down) returns :data:`_PRODUCER_LOST` instead of hanging
    the consumer — and its generator teardown — forever. The liveness
    poll is idle-side only: a live queue hands items over at q.get
    speed."""
    while True:
        try:
            return q.get(timeout=poll_s)
        except queue.Empty:
            if not any(t.is_alive() for t in threads):
                # final drain: the producer may have enqueued its
                # sentinel and exited INSIDE the Empty->liveness
                # window — a clean epoch end must never be
                # misreported as a lost producer
                try:
                    return q.get_nowait()
                except queue.Empty:
                    return _PRODUCER_LOST


def firstn(reader: Reader, n: int) -> Reader:
    """reference: decorator.py firstn."""

    def reader_n():
        return itertools.islice(reader(), n)

    return reader_n


def cache(reader: Reader) -> Reader:
    """reference: decorator.py cache — materialize the whole stream on first
    use, replay thereafter. Full materialization up front (like the reference's
    tuple(reader())) so an abandoned first pass can't duplicate samples."""
    memo: List[Any] = []
    done = [False]

    def cached():
        if not done[0]:
            memo.extend(reader())
            done[0] = True
        yield from memo

    return cached


def xmap_readers(mapper: Callable, reader: Reader, process_num: int,
                 buffer_size: int, order: bool = False) -> Reader:
    """reference: decorator.py xmap_readers — parallel map via threads.
    (Threads, not processes: mappers are typically numpy, which releases
    the GIL; keeps the zero-copy contract.)"""

    end = object()

    def xreader():
        in_q: queue.Queue = queue.Queue(buffer_size)
        out_q: queue.Queue = queue.Queue(buffer_size)
        errors: List[BaseException] = []
        stop = threading.Event()

        def feeder():
            try:
                for i, item in enumerate(reader()):
                    if not _put_cancellable(in_q, (i, item), stop):
                        return
            except BaseException as e:
                errors.append(e)
            finally:
                # always release the workers, even if reader() raised
                for _ in range(process_num):
                    _put_cancellable(in_q, end, stop)

        def worker():
            try:
                while not stop.is_set():
                    item = _get_cancellable(in_q, stop)
                    if item is end or item is _CANCELLED:
                        return
                    i, x = item
                    if not _put_cancellable(out_q, (i, mapper(x)), stop):
                        return
            except BaseException as e:
                errors.append(e)
            finally:
                _put_cancellable(out_q, end, stop)

        threading.Thread(target=feeder, daemon=True,
                         name="pt-reader-xmap-feeder").start()
        workers = []
        for _ in range(process_num):
            w = threading.Thread(target=worker, daemon=True,
                                 name="pt-reader-xmap-worker")
            w.start()
            workers.append(w)

        def lost():
            # a worker that died without its sentinel must not hang
            # the consumer; surface the recorded error (or a typed one)
            if not errors:
                errors.append(RuntimeError(
                    "xmap worker died without delivering its end "
                    "sentinel"))

        finished = 0
        try:
            if order:
                pending = {}
                next_i = 0
                while finished < process_num:
                    item = _get_bounded(out_q, workers)
                    if item is _PRODUCER_LOST:
                        lost()
                        break
                    if item is end:
                        finished += 1
                        continue
                    i, y = item
                    pending[i] = y
                    while next_i in pending:
                        yield pending.pop(next_i)
                        next_i += 1
                for i in sorted(pending):
                    yield pending[i]
            else:
                while finished < process_num:
                    item = _get_bounded(out_q, workers)
                    if item is _PRODUCER_LOST:
                        lost()
                        break
                    if item is end:
                        finished += 1
                        continue
                    yield item[1]
        finally:
            # abandoned consumer: unblock feeder + workers so they exit
            stop.set()
        if errors:
            raise errors[0]

    return xreader


def batch(reader: Reader, batch_size: int, drop_last: bool = True) -> Reader:
    """reference: python/paddle/batch.py — group samples into lists.
    drop_last defaults True (static shapes: partial batches would recompile)."""

    def batch_reader():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    return batch_reader



class PipeReader:
    """Stream samples from a shell command's stdout (reference:
    python/paddle/reader/decorator.py PipeReader — left_cmd | parse)."""

    def __init__(self, command: str, bufsize: int = 8192,
                 file_type: str = "plain"):
        from ..core.enforce import enforce_in

        enforce_in(file_type, ("plain", "gzip"), "file_type")
        self.command = command
        self.bufsize = bufsize
        self.file_type = file_type

    def get_line(self, cut_lines: bool = True, line_break: str = "\n"):
        import subprocess
        import zlib

        proc = subprocess.Popen(self.command, shell=True,
                                stdout=subprocess.PIPE, bufsize=self.bufsize)
        decomp = (zlib.decompressobj(32 + zlib.MAX_WBITS)
                  if self.file_type == "gzip" else None)

        def inflate(data):
            # handle CONCATENATED gzip members (cat a.gz b.gz): restart the
            # decompressor on unused_data until the chunk is consumed
            nonlocal decomp
            out = b""
            while data:
                out += decomp.decompress(data)
                data = decomp.unused_data
                if data:
                    decomp = zlib.decompressobj(32 + zlib.MAX_WBITS)
                elif decomp.eof:
                    decomp = zlib.decompressobj(32 + zlib.MAX_WBITS)
                    break
            return out

        try:
            buf = b""
            for chunk in iter(lambda: proc.stdout.read(self.bufsize), b""):
                if decomp is not None:
                    chunk = inflate(chunk)
                buf += chunk
                if cut_lines:
                    lines = buf.split(line_break.encode())
                    buf = lines.pop()
                    for ln in lines:
                        yield ln.decode(errors="replace")
                else:
                    yield buf.decode(errors="replace")
                    buf = b""
            if buf:
                yield buf.decode(errors="replace")
        finally:
            proc.stdout.close()
            proc.wait()


import itertools as _itertools


class Fake:
    """Cache the first pass of a reader and replay it forever — IO-free
    re-feeding for benchmarks (reference: reader/decorator.py Fake)."""

    def __init__(self):
        self._cache = None

    def __call__(self, reader, length: int):
        def fake_reader():
            if self._cache is None:
                self._cache = list(_itertools.islice(reader(), length))
            if not self._cache:
                return  # empty source: nothing to replay
            for i in range(length):
                yield self._cache[i % len(self._cache)]

        return fake_reader


def _mp_feed(r, q):
    """Child body for multiprocess_reader (module-level: picklable under
    spawn/forkserver start methods). The sentinel ALWAYS goes out, even if
    the reader raises — otherwise the consumer would block forever."""
    try:
        for sample in r():
            q.put(sample)
    finally:
        q.put(None)


def multiprocess_reader(readers, use_pipe: bool = True,
                        queue_size: int = 1000):
    """Fan-in: run each reader in its own process, merge samples
    (reference: reader/decorator.py multiprocess_reader). Falls back to
    in-process chaining when the readers can't cross a process boundary
    (unpicklable closures under spawn)."""
    import multiprocessing as mp
    import pickle

    def reader():
        try:
            pickle.dumps(readers)
        except Exception:
            for r in readers:  # unpicklable: degrade to sequential chain
                yield from r()
            return
        ctx = mp.get_context()
        q = ctx.Queue(queue_size)
        procs = [ctx.Process(target=_mp_feed, args=(r, q), daemon=True)
                 for r in readers]
        for p in procs:
            p.start()
        live = len(procs)
        try:
            while live:
                try:
                    item = q.get(timeout=300)
                except Exception:
                    if not any(p.is_alive() for p in procs):
                        break  # all children died without sentinels
                    continue
                if item is None:
                    live -= 1
                else:
                    yield item
        finally:
            for p in procs:
                p.terminate()

    return reader


class _Creator:
    """``paddle.reader.creator`` namespace: readers from common sources."""

    @staticmethod
    def np_array(x):
        def reader():
            for row in x:
                yield row

        return reader

    @staticmethod
    def text_file(path: str):
        def reader():
            with open(path) as f:
                for line in f:
                    yield line.rstrip("\n")

        return reader

    @staticmethod
    def recordio(paths, buf_size: int = 100):
        from ..core.enforce import EnforceError

        raise EnforceError(
            "RecordIO was dropped by design (SURVEY 'what NOT to "
            "rebuild'); use creator.np_array / MultiSlotDataset")


creator = _Creator()
