"""Debug/visualization helpers (reference: python/paddle/fluid/debugger.py
program pretty-printer, graphviz.py + net_drawer.py dot export).

Works on the static-graph ``Program`` (op/var graph) — the dygraph path is
plain Python, debuggable directly.
"""

from __future__ import annotations

from typing import Optional

from .static.program import Program, _GradNode, _OpNode


def program_to_string(program: Program, with_shapes: bool = True) -> str:
    """Readable dump of a Program (debugger.py pprint analog)."""
    lines = [f"Program: {len(program.nodes)} nodes, "
             f"{len(program.vars)} vars"]
    lines.append("vars:")
    for name, v in program.vars.items():
        kind = "param" if name in program.param_names() else "var"
        shape = f" shape={tuple(v.shape)}" if with_shapes else ""
        lines.append(f"  {kind} {name}: dtype={v.dtype}{shape}")
    lines.append("ops:")
    for i, node in enumerate(program.nodes):
        if isinstance(node, _GradNode):
            lines.append(f"  [{i}] grad(loss={node.loss_name}) -> "
                         f"{', '.join(node.outputs)}")
        else:
            lines.append(f"  [{i}] {node.name}({', '.join(node.inputs)})"
                         f" -> {', '.join(node.outputs)}")
    return "\n".join(lines)


def print_program(program: Program) -> None:
    print(program_to_string(program))


def program_to_dot(program: Program, graph_name: str = "program") -> str:
    """Graphviz dot of the op/var dataflow (net_drawer.py / graph_viz_pass
    analog: op nodes as boxes, var nodes as ellipses)."""
    lines = [f"digraph {graph_name} {{", "  rankdir=TB;"]
    params = set(program.param_names())
    emitted_vars = set()

    def var_node(name):
        if name in emitted_vars:
            return
        emitted_vars.add(name)
        v = program.vars.get(name)
        shape = tuple(v.shape) if v is not None else "?"
        style = ("style=filled, fillcolor=lightblue" if name in params
                 else "style=solid")
        lines.append(f'  "v_{name}" [label="{name}\\n{shape}", '
                     f'shape=ellipse, {style}];')

    for i, node in enumerate(program.nodes):
        label = ("backward" if isinstance(node, _GradNode)
                 else node.name)
        lines.append(f'  "op_{i}" [label="{label}", shape=box, '
                     f'style=filled, fillcolor=lightgray];')
        # _GradNode carries no .inputs — its dataflow sources are the
        # loss it differentiates and the params it differentiates w.r.t.
        inputs = ([node.loss_name] + list(node.param_names)
                  if isinstance(node, _GradNode) else node.inputs)
        for inp in inputs:
            var_node(inp)
            lines.append(f'  "v_{inp}" -> "op_{i}";')
        for out in node.outputs:
            var_node(out)
            lines.append(f'  "op_{i}" -> "v_{out}";')
    lines.append("}")
    return "\n".join(lines)


def draw_program(program: Program, path: str) -> str:
    """Write dot to ``path``; render to .png alongside if graphviz's `dot`
    binary exists (net_drawer.py behavior)."""
    dot = program_to_dot(program)
    with open(path, "w") as f:
        f.write(dot)
    import shutil
    import subprocess

    if shutil.which("dot"):
        png = path.rsplit(".", 1)[0] + ".png"
        subprocess.run(["dot", "-Tpng", path, "-o", png], check=False)
        return png
    return path
