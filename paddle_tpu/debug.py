"""Debug/visualization helpers (reference: python/paddle/fluid/debugger.py
program pretty-printer, graphviz.py + net_drawer.py dot export).

Works on the static-graph ``Program`` (op/var graph) — the dygraph path is
plain Python, debuggable directly. Both renderers accept the
``analysis`` plane's findings (``diagnostics=`` — a list of
:class:`paddle_tpu.analysis.Diagnostic`): the pretty-printer annotates
offending ops/vars inline, the dot export colors dead ops::

    from paddle_tpu import analysis, debug
    diags = analysis.verify_program(prog, fetch_list=[loss])
    print(debug.program_to_string(prog, diagnostics=diags))
"""

from __future__ import annotations

from typing import Dict, List, Optional

from .static.program import Program, _GradNode, _OpNode


def _index_diags(diagnostics):
    """(by_node, by_var, rest) lookup maps for inline rendering. A
    diagnostic with a node index anchors to the op line; var-only ones
    anchor to the var line."""
    by_node: Dict[int, list] = {}
    by_var: Dict[str, list] = {}
    rest: List = []
    for d in diagnostics or []:
        if getattr(d, "node", None) is not None:
            by_node.setdefault(d.node, []).append(d)
        elif getattr(d, "var", None) is not None:
            by_var.setdefault(d.var, []).append(d)
        else:
            rest.append(d)
    return by_node, by_var, rest


def _mark(d) -> str:
    return "!" if d.severity == "error" else "*"


def program_to_string(program: Program, with_shapes: bool = True,
                      diagnostics: Optional[list] = None) -> str:
    """Readable dump of a Program (debugger.py pprint analog).
    ``diagnostics`` (from ``analysis.verify_program``) render inline
    next to the op/var they locate."""
    by_node, by_var, rest = _index_diags(diagnostics)
    lines = [f"Program: {len(program.nodes)} nodes, "
             f"{len(program.vars)} vars"]
    if diagnostics:
        n_err = sum(1 for d in diagnostics if d.severity == "error")
        lines.append(f"diagnostics: {len(diagnostics)} finding(s), "
                     f"{n_err} error(s)")
    lines.append("vars:")
    for name, v in program.vars.items():
        kind = "param" if name in program.param_names() else "var"
        shape = f" shape={tuple(v.shape)}" if with_shapes else ""
        lines.append(f"  {kind} {name}: dtype={v.dtype}{shape}")
        for d in by_var.get(name, []):
            lines.append(f"    {_mark(d)} [{d.code}] {d.message}")
    # var-anchored findings whose var is NOT recorded (an undefined
    # fetch target's PT-FETCH-004, a typo'd name) have no var line to
    # sit under — surface them in the trailer instead of dropping them
    for vname, ds in by_var.items():
        if vname not in program.vars:
            rest.extend(ds)
    lines.append("ops:")
    for i, node in enumerate(program.nodes):
        if isinstance(node, _GradNode):
            lines.append(f"  [{i}] grad(loss={node.loss_name}) -> "
                         f"{', '.join(node.outputs)}")
        else:
            lines.append(f"  [{i}] {node.name}({', '.join(node.inputs)})"
                         f" -> {', '.join(node.outputs)}")
        for d in by_node.get(i, []):
            lines.append(f"    {_mark(d)} [{d.code}] {d.message}")
    for d in rest:
        lines.append(f"{_mark(d)} [{d.code}] {d.message}")
    return "\n".join(lines)


def print_program(program: Program, diagnostics=None) -> None:
    print(program_to_string(program, diagnostics=diagnostics))


# dot fill colors: live ops vs ops a verifier diagnostic marked dead
# (PT-DEAD-003) vs ops carrying any error-severity finding
_OP_FILL = "lightgray"
_DEAD_FILL = "mistyrose"
_ERR_FILL = "lightcoral"


def program_to_dot(program: Program, graph_name: str = "program",
                   diagnostics: Optional[list] = None) -> str:
    """Graphviz dot of the op/var dataflow (net_drawer.py / graph_viz_pass
    analog: op nodes as boxes, var nodes as ellipses). With
    ``diagnostics``, dead ops (PT-DEAD-003) fill ``mistyrose`` and ops
    with error findings ``lightcoral``."""
    by_node, _, _ = _index_diags(diagnostics)
    lines = [f"digraph {graph_name} {{", "  rankdir=TB;"]
    params = set(program.param_names())
    emitted_vars = set()

    def var_node(name):
        if name in emitted_vars:
            return
        emitted_vars.add(name)
        v = program.vars.get(name)
        shape = tuple(v.shape) if v is not None else "?"
        style = ("style=filled, fillcolor=lightblue" if name in params
                 else "style=solid")
        lines.append(f'  "v_{name}" [label="{name}\\n{shape}", '
                     f'shape=ellipse, {style}];')

    for i, node in enumerate(program.nodes):
        label = ("backward" if isinstance(node, _GradNode)
                 else node.name)
        fill = _OP_FILL
        for d in by_node.get(i, []):
            if d.code.startswith("PT-DEAD"):
                fill = _DEAD_FILL
                label += "\\n(dead)"
                break
            if d.severity == "error":
                fill = _ERR_FILL
        lines.append(f'  "op_{i}" [label="{label}", shape=box, '
                     f'style=filled, fillcolor={fill}];')
        # _GradNode carries no .inputs — its dataflow sources are the
        # loss it differentiates and the params it differentiates w.r.t.
        inputs = ([node.loss_name] + list(node.param_names)
                  if isinstance(node, _GradNode) else node.inputs)
        for inp in inputs:
            var_node(inp)
            lines.append(f'  "v_{inp}" -> "op_{i}";')
        for out in node.outputs:
            var_node(out)
            lines.append(f'  "op_{i}" -> "v_{out}";')
    lines.append("}")
    return "\n".join(lines)


def draw_program(program: Program, path: str) -> str:
    """Write dot to ``path``; render to .png alongside if graphviz's `dot`
    binary exists (net_drawer.py behavior)."""
    dot = program_to_dot(program)
    with open(path, "w") as f:
        f.write(dot)
    import shutil
    import subprocess

    if shutil.which("dot"):
        png = path.rsplit(".", 1)[0] + ".png"
        subprocess.run(["dot", "-Tpng", path, "-o", png], check=False)
        return png
    return path
