"""Sharded embedding plane — giant tables on the Plan substrate.

The reference Fluid's signature production capability is distributed
sparse embedding over parameter servers (PAPER.md layer 5: SelectedRows
+ parameter_prefetch + distribute_lookup_table). This package is the
TPU-native rebuild, three planes over one table:

- **on-chip, sharded** — ``Plan(ep=N, tables=[...])`` row-shards
  registered tables over the ``ep`` mesh axis; the forward is
  ``parallel.sharded_embedding_lookup`` (local gather + one psum) and
  the backward is :func:`exchange.sparse_ep_update`: (unique ids, int8
  rows) on the wire, never the dense (V, D) gradient.
- **host-backed** — :class:`host_table.HostBackedTable` keeps
  authoritative rows in host RAM at scales no chip (or pod) holds,
  with an on-chip hot-row working set governed by
  :class:`cache.RowCache` (clock/second-chance LRU) and prefetched by
  the data plane (``DevicePrefetcher(prefetch_rows=...)``).
- **durable** — tables checkpoint through ``paddle_tpu.checkpoint``'s
  globally-committed two-phase path (per-shard files + checksums) and
  restore across ``ep`` shapes via the cross-plan-shape restore.

``bench.py --model deepfm_sparse --plan ep=8`` drives the full
vertical slice; the README's "Sharded embeddings" section is the
user-facing tour.
"""

from .cache import RowCache
from .host_table import HostBackedTable
from .exchange import (dense_grad_bytes, exchange_payload_bytes,
                       exchange_rows, record_exchange_bytes,
                       should_compress, sparse_ep_minimize_fn,
                       sparse_ep_update)
from ..parallel.sharded_embedding import (ShardedEmbedding,
                                          embedding_ep_rules,
                                          sharded_embedding_lookup)

__all__ = [
    "RowCache",
    "HostBackedTable",
    "ShardedEmbedding",
    "dense_grad_bytes",
    "embedding_ep_rules",
    "exchange_payload_bytes",
    "exchange_rows",
    "record_exchange_bytes",
    "sharded_embedding_lookup",
    "should_compress",
    "sparse_ep_minimize_fn",
    "sparse_ep_update",
]
