"""Hot-row working-set cache — the eviction substrate of the sharded
embedding plane.

The reference's parameter-server tables keep the authoritative rows on
the PS fleet and prefetch the batch's rows into trainer memory
(reference: operators/distributed/parameter_prefetch.cc); on TPU the
analogous split is host RAM (authoritative) vs HBM (working set), and
the policy that decides WHICH rows stay on-chip is this cache.

:class:`RowCache` maps integer row ids to fixed slots of a device-side
working-set array using the clock (second-chance) approximation of LRU:
every admitted id sets its slot's reference bit; the clock hand clears
bits as it sweeps and evicts the first unreferenced slot. O(1) amortized
per id, no per-access reordering (the LRU-list cost the clock scheme
exists to avoid), and the eviction order is deterministic for tests.

Deliberately generic — ids are any non-negative integers, slots are any
payload the caller stores at them — so the same substrate can back the
adapter-serving registry later (ROADMAP follow-on), not just embedding
rows. Thread-safe: one lock around the id->slot map and the clock state
(the prefetch thread and the training thread both admit).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..core.enforce import enforce


class RowCache:
    """Clock (second-chance LRU) cache of integer row ids over
    ``capacity`` fixed slots.

    - :meth:`admit` is the one mutating entry: every requested id ends
      up resident and gets a slot; misses claim free slots first, then
      evict via the clock sweep. Ids admitted in the same call are
      protected from each other's evictions.
    - :meth:`slots_of` is the read-only mapping (``-1`` for absent).
    - ``hits`` / ``misses`` / ``evictions`` count cumulatively; the
      telemetry counters of :class:`..host_table.HostBackedTable` are
      advanced from these.
    """

    def __init__(self, capacity: int):
        enforce(capacity >= 1, "RowCache capacity must be >= 1, got %s",
                capacity)
        self.capacity = int(capacity)
        self._slot_of: Dict[int, int] = {}
        self._ids = np.full(self.capacity, -1, np.int64)  # slot -> id
        self._ref = np.zeros(self.capacity, bool)  # second-chance bits
        self._hand = 0
        self._free = list(range(self.capacity - 1, -1, -1))
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self._lock = threading.Lock()

    def __len__(self) -> int:
        with self._lock:
            return len(self._slot_of)

    def __contains__(self, row_id: int) -> bool:
        with self._lock:
            return int(row_id) in self._slot_of

    def slots_of(self, ids) -> np.ndarray:
        """Slot of each id (-1 when not resident). Read-only: counters
        and reference bits stay untouched."""
        ids = np.asarray(ids, np.int64).reshape(-1)
        with self._lock:
            return np.asarray([self._slot_of.get(int(i), -1)
                               for i in ids], np.int64)

    def _evict_one(self, protected) -> Tuple[int, int]:
        """Clock sweep: clear reference bits until an unreferenced,
        unprotected slot comes up; evict it. Returns (slot, victim id).
        """
        for _ in range(4 * self.capacity):
            s = self._hand
            self._hand = (self._hand + 1) % self.capacity
            if s in protected:
                continue
            if self._ref[s]:
                self._ref[s] = False
                continue
            victim = int(self._ids[s])
            del self._slot_of[victim]
            self._ids[s] = -1
            self.evictions += 1
            return s, victim
        raise AssertionError("RowCache clock made 4 full sweeps without "
                             "finding a victim (capacity exhausted by "
                             "one batch?)")

    def admit(self, ids) -> Tuple[np.ndarray, np.ndarray, List[int]]:
        """Make every id resident. Returns ``(slots, was_miss,
        evicted_ids)`` — ``slots[i]`` is where ``ids[i]`` now lives,
        ``was_miss[i]`` marks ids the caller must fill (fetch the row
        into the working set at that slot), ``evicted_ids`` lists rows
        that lost their slot this call (write-through callers need no
        write-back; a dirty-row caller would flush these).

        ``ids`` should be deduplicated; a batch of distinct ids larger
        than ``capacity`` is refused (it cannot be co-resident).
        """
        ids = np.asarray(ids, np.int64).reshape(-1)
        enforce(ids.size <= self.capacity,
                "batch of %s distinct ids exceeds cache capacity %s",
                ids.size, self.capacity)
        slots = np.empty(ids.size, np.int64)
        was_miss = np.zeros(ids.size, bool)
        evicted: List[int] = []
        with self._lock:
            protected = set()
            for i, rid in enumerate(int(r) for r in ids):
                enforce(rid >= 0, "row id must be >= 0, got %s", rid)
                s = self._slot_of.get(rid)
                if s is None:
                    self.misses += 1
                    was_miss[i] = True
                    if self._free:
                        s = self._free.pop()
                    else:
                        s, victim = self._evict_one(protected)
                        evicted.append(victim)
                    self._slot_of[rid] = s
                    self._ids[s] = rid
                else:
                    self.hits += 1
                self._ref[s] = True
                protected.add(s)
                slots[i] = s
        return slots, was_miss, evicted

    def stats(self) -> Dict[str, float]:
        with self._lock:
            total = self.hits + self.misses
            return {
                "capacity": self.capacity,
                "resident": len(self._slot_of),
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "hit_rate": (self.hits / total) if total else 0.0,
            }
