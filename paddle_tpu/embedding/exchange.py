"""Sparse gradient exchange for ep-sharded tables — (unique ids, rows)
on the wire, never the dense (V, D) gradient.

The reference's trainers push SelectedRows — (row ids, row values)
pairs — to the parameter servers instead of dense table gradients
(reference: framework/selected_rows.h:32, MergeAdd in
operators/math/selected_rows_functor.cc). Under an SPMD ``Plan(ep=N)``
the same traffic shape is hand-written at the JAX level, because GSPMD
left to itself reduces the replicated-table gradient densely — V*D
floats per step for a batch that touched a few thousand rows.

Per step, inside one ``shard_map`` over the plan mesh:

1. **local MergeAdd** — each batch shard dedups its ids and
   segment-sums duplicate rows (``optimizer.sparse.merge_rows``)
   BEFORE anything hits the wire;
2. **int8 wire** — the merged row payload is quantized per-row through
   ``quant.ops.absmax_encode`` (the ``quant/collectives`` wire
   convention: int8 data + f32 scales riding along), all-gathered over
   the batch axis together with the ids; receivers decode to f32.
   Tiny payloads (< ``MIN_COMPRESS_SIZE`` elements, the
   ``quant/collectives`` floor) ride fp32 — scale overhead and noise
   on a toy table buy nothing;
3. **nan-poison** — a non-finite row gradient on ANY shard poisons
   every exchanged row with NaN (4-byte pmin'd finite flag), so the
   train loop's nan-guard keeps firing; a quantizer that laundered inf
   into a finite int8 payload would silently corrupt training;
4. **local scatter** — each ep shard keeps the in-range rows
   (global id - shard offset) and applies them through
   ``optimizer.sparse.apply_rows`` with out-of-bounds drop semantics.
   Update cost stays O(touched rows), flat in vocab.

Byte accounting is host-side per the ``quant/collectives`` convention
(traced code cannot touch counters): shapes are static, so
:func:`exchange_payload_bytes` computes the per-step payload once and
:func:`record_exchange_bytes` advances
``pt_collective_bytes_total{compressed=...}``.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..core.enforce import enforce
from ..core.mesh import get_mesh
from ..optimizer.sparse import apply_rows, find_sparse_embeddings, merge_rows
from ..quant.collectives import MIN_COMPRESS_SIZE, record_payload_bytes
from ..quant.ops import absmax_decode, absmax_encode
from ..utils.compat import shard_map

PyTree = Any


# ---------------------------------------------------------------------------
# payload-byte accounting (static shapes -> computed once per step fn)
# ---------------------------------------------------------------------------


def exchange_payload_bytes(num_ids: int, dim: int, batch_axis_size: int,
                           *, compressed: bool) -> int:
    """Bytes ONE device moves all-gathering its (ids, rows) payload
    over a ``batch_axis_size`` ring: (n-1) forwarding hops of its own
    chunk — int32 ids + int8 rows + one f32 scale per row when
    compressed, f32 rows otherwise. 0 on a degenerate axis (nothing
    crosses the wire; ep-only plans exchange in-place)."""
    n = int(batch_axis_size)
    if n <= 1:
        return 0
    ids_bytes = int(num_ids) * 4
    if compressed:
        row_bytes = int(num_ids) * (int(dim) + 4)  # int8 rows + f32 scale
    else:
        row_bytes = int(num_ids) * int(dim) * 4
    return (n - 1) * (ids_bytes + row_bytes)


def dense_grad_bytes(vocab: int, dim: int, axis_size: int) -> int:
    """The counterfactual this module exists to avoid: ring-allreducing
    the dense (V, D) fp32 table gradient over ``axis_size`` devices —
    2*(n-1)*ceil(V*D/n)*4 bytes per device per step."""
    n = int(axis_size)
    if n <= 1:
        return 0
    size = int(vocab) * int(dim)
    return 2 * (n - 1) * (-(-size // n)) * 4


def record_exchange_bytes(num_ids: int, dim: int, batch_axis_size: int,
                          *, compressed: bool) -> int:
    """Host-side per-step counter bump on
    ``pt_collective_bytes_total`` (no-op when telemetry is off).
    Returns the bytes recorded."""
    b = exchange_payload_bytes(num_ids, dim, batch_axis_size,
                               compressed=compressed)
    if compressed:
        record_payload_bytes(b, 0)
    else:
        record_payload_bytes(0, b)
    return b


# ---------------------------------------------------------------------------
# the in-shard exchange (call INSIDE a shard_map body)
# ---------------------------------------------------------------------------


def exchange_rows(uids, rows, axis_name: Optional[str], *,
                  compress: bool = True, key=None):
    """All-gather this shard's merged (ids, rows) over ``axis_name`` —
    the SelectedRows wire. Call inside a ``shard_map`` body (like
    ``quant.collectives.quantized_psum``).

    ``uids``: (K,) int ids (out-of-vocab sentinel slots welcome — the
    downstream scatter drops them); ``rows``: (K, D). Returns
    ``(all_ids (n*K,), all_rows (n*K, D) f32)`` identical on every
    device of the axis. ``axis_name=None`` (degenerate batch axis)
    skips the wire but keeps the poison/compress numerics so results
    don't depend on the mesh shape. ``key`` enables stochastic rounding
    of the int8 payload (unbiasedness is per-element; fold a per-device
    key in the caller).
    """
    rows = rows.astype(jnp.float32)
    ok = jnp.isfinite(rows).all().astype(jnp.int32)
    if axis_name is not None:
        ok = lax.pmin(ok, axis_name)
    if compress:
        q, sc = absmax_encode(rows, axis=1, key=key)
        if axis_name is not None:
            q = lax.all_gather(q, axis_name, tiled=True)
            sc = lax.all_gather(sc, axis_name, tiled=True)
        all_rows = absmax_decode(q, sc)
    else:
        all_rows = (lax.all_gather(rows, axis_name, tiled=True)
                    if axis_name is not None else rows)
    all_ids = (lax.all_gather(uids, axis_name, tiled=True)
               if axis_name is not None else uids)
    # non-finite anywhere -> poison every exchanged row (the nan-guard
    # contract shared with quantized_psum)
    all_rows = jnp.where(ok > 0, all_rows, jnp.nan)
    return all_ids, all_rows


# ---------------------------------------------------------------------------
# the sharded sparse update (global-level entry; composes under pjit)
# ---------------------------------------------------------------------------


def _resolve_batch_axis(mesh, batch_axis, leading):
    if batch_axis is not None and batch_axis not in mesh.shape:
        return None
    if batch_axis is not None and leading % int(mesh.shape[batch_axis]):
        return None  # odd batch (eval tail): replicate, still exact
    return batch_axis


def should_compress(ids_size: int, batch_axis_size: int, dim: int,
                    *, min_size: int = MIN_COMPRESS_SIZE) -> bool:
    """The tiny-table fp32 fallback gate (the ``quant/collectives``
    floor applied to the per-shard row payload)."""
    per_shard = -(-int(ids_size) // max(1, int(batch_axis_size)))
    return per_shard * int(dim) >= min_size


def sparse_ep_update(optimizer, table, ids, row_grads, leaf_state,
                     lr, step, *, mesh=None, table_axis: str = "ep",
                     batch_axis: Optional[str] = "dp",
                     compress: Optional[bool] = None, key=None
                     ) -> Tuple[jnp.ndarray, Dict[str, Any]]:
    """One row-sparse update of an ep-sharded ``table`` — local
    MergeAdd, int8 (ids, rows) exchange over the batch axis, per-shard
    scatter through the optimizer's ordinary ``update_leaf`` rule.

    - ``table``: (V, D), placed ``P(table_axis, None)``;
    - ``ids``: any int shape, batch-sharded over ``batch_axis``
      (replicated across ``table_axis``); ``row_grads``:
      ``ids.shape + (D,)``;
    - ``leaf_state``: the table's per-leaf optimizer state
      (``optimizer.init_leaf``) — leaves with a V leading dim are
      treated per-row and must be placed like the table;
    - ``compress=None`` auto-applies the tiny-payload fp32 fallback.

    Returns ``(new_table, new_leaf_state)`` with the same placements.
    The dense (V, D) gradient is never materialized on any device or
    wire.
    """
    mesh = mesh or get_mesh()
    enforce(table_axis in mesh.shape,
            "mesh has no %r axis (axes: %s)", table_axis,
            tuple(mesh.shape))
    n_ep = int(mesh.shape[table_axis])
    V, D = table.shape
    enforce(V % n_ep == 0,
            "vocab %s must divide %s axis size %s (pad the table)", V,
            table_axis, n_ep)
    rows_per_shard = V // n_ep
    batch_axis = _resolve_batch_axis(mesh, batch_axis, ids.shape[0])
    n_b = int(mesh.shape[batch_axis]) if batch_axis else 1
    if compress is None:
        compress = should_compress(ids.size, n_b, D)

    rowwise = {k: (hasattr(v, "ndim") and v.ndim >= 1
                   and v.shape[0] == V)
               for k, v in leaf_state.items()}
    state_specs = {k: P(table_axis, *([None] * (leaf_state[k].ndim - 1)))
                   if rw else P() for k, rw in rowwise.items()}
    ids_spec = P(batch_axis, *([None] * (ids.ndim - 1)))
    rows_spec = P(batch_axis, *([None] * (row_grads.ndim - 1)))

    def body(table_l, state_l, ids_l, rows_l, lr_, step_):
        # 1. local MergeAdd before the wire (fill slots carry id == V:
        #    out of every shard's range, dropped by the scatter)
        uids, merged = merge_rows(ids_l, rows_l, V)
        k = None
        if key is not None:
            k = jax.random.fold_in(key, lax.axis_index(table_axis))
            if batch_axis is not None:
                k = jax.random.fold_in(k, lax.axis_index(batch_axis))
        # 2./3. int8 exchange + nan-poison
        all_ids, all_rows = exchange_rows(uids, merged, batch_axis,
                                          compress=compress, key=k)
        # 4. localize to this shard's row window and scatter-update
        off = lax.axis_index(table_axis) * rows_per_shard
        loc = all_ids - off
        loc = jnp.where((loc >= 0) & (loc < rows_per_shard), loc,
                        rows_per_shard)
        return apply_rows(optimizer, table_l, loc, all_rows, state_l,
                          lr_, step_)

    fn = shard_map(
        body, mesh=mesh,
        in_specs=(P(table_axis, None), state_specs, ids_spec, rows_spec,
                  P(), P()),
        out_specs=(P(table_axis, None), state_specs),
        check_vma=False)
    return fn(table, leaf_state, ids, row_grads,
              jnp.asarray(lr, jnp.float32), jnp.asarray(step))


# ---------------------------------------------------------------------------
# the full train-step builder (the ep counterpart of
# optimizer.sparse.sparse_minimize_fn)
# ---------------------------------------------------------------------------


def sparse_ep_minimize_fn(model, forward_loss: Callable, optimizer, *,
                          plan=None, mesh=None, table_axis: str = "ep",
                          batch_axis: Optional[str] = "dp",
                          emb_optimizer=None,
                          compress: Optional[bool] = None, key=None):
    """Build ``(init_fn, step_fn)`` where every ``is_sparse`` embedding
    table updates through :func:`sparse_ep_update` (sparse exchange over
    the plan mesh) and the dense remainder follows the ordinary
    ``optimizer.apply``. Same contract as
    ``optimizer.sparse.sparse_minimize_fn``::

        state = init_fn(params)
        loss, params, state = compiled(params, state, *batch)

    Compile the step through ``parallel.compile_step(plan, step_fn,
    in_shardings=..., out_shardings=...)`` — the one-compile path; the
    exchange's ``shard_map`` composes inside the pjit trace exactly
    like ``sharded_embedding_lookup`` does in the forward.
    """
    from ..nn.sparse import Capture, Inject

    mesh_ = plan.mesh if plan is not None else (mesh or None)

    embs = find_sparse_embeddings(model)
    enforce(embs, "sparse_ep_minimize_fn: model has no is_sparse "
            "embeddings — use optimizer.minimize_fn / "
            "sparse_minimize_fn instead")
    emb_names = set(embs)
    eopt = emb_optimizer or optimizer
    layer_ids = {id(l) for l in embs.values()}
    by_layer = {id(l): n for n, l in embs.items()}

    def init_fn(params: Dict[str, Any]) -> Dict[str, Any]:
        dense = {k: v for k, v in params.items() if k not in emb_names}
        return {
            "dense": optimizer.init(dense),
            "sparse": {n: eopt.init_leaf(params[n]) for n in emb_names},
        }

    def step_fn(params, state, *args, **kwargs):
        tables = {n: params[n] for n in emb_names}
        dense = {k: v for k, v in params.items() if k not in emb_names}

        # phase 1: capture the ids each sparse layer consumes
        cap = Capture(layer_ids)
        with cap:
            forward_loss(params, *args, **kwargs)
        # phase 2: gather rows OUTSIDE the differentiated function
        rows = {slot: jnp.take(tables[by_layer[owner]], cap.ids[slot],
                               axis=0)
                for slot, owner in cap.owner.items()}

        def inner(dense_p, rows_map):
            inj = Inject(layer_ids, rows_map)
            with inj:
                return forward_loss({**dense_p, **tables}, *args,
                                    **kwargs)

        loss, (g_dense, g_rows) = jax.value_and_grad(
            inner, argnums=(0, 1))(dense, rows)

        step = state["dense"]["step"]
        new_dense, new_dense_state = optimizer.apply(
            dense, g_dense, state["dense"])

        lr = eopt.schedule(step)
        new_sparse_state = {}
        new_tables = dict(tables)
        for name in emb_names:
            slots = [s for s, o in cap.owner.items()
                     if by_layer[o] == name]
            tbl, st = new_tables[name], state["sparse"][name]
            for slot in slots:
                tbl, st = sparse_ep_update(
                    eopt, tbl, cap.ids[slot], g_rows[slot], st, lr,
                    step, mesh=mesh_, table_axis=table_axis,
                    batch_axis=batch_axis, compress=compress, key=key)
            new_tables[name] = tbl
            new_sparse_state[name] = st

        new_params = {**new_dense, **new_tables}
        return loss, new_params, {"dense": new_dense_state,
                                  "sparse": new_sparse_state}

    return init_fn, step_fn
