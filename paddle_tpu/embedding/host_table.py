"""Host-backed giant embedding tables — authoritative rows in host RAM,
hot rows on chip.

The reference serves 10^8-row tables from a parameter-server fleet and
pulls the batch's rows into trainer memory per step (reference:
framework/fleet/fleet_wrapper.h:55 PullSparseVarsSync,
operators/distributed/parameter_prefetch.cc). The TPU-native analog
needs no second fleet: host RAM is the parameter server. A
:class:`HostBackedTable` keeps the full (V, D) table as a numpy array
on the host and maintains an on-chip working set of hot rows governed
by :class:`.cache.RowCache` (clock/second-chance LRU over row ids).

Data plane per step (all host-driven — this is the feeding layer, not
traced code):

1. :meth:`prefetch` — dedup the NEXT batch's ids, admit them into the
   cache, and ``device_put`` only the missing rows into their slots.
   ``data.DevicePrefetcher`` calls this from its background staging
   thread (``prefetch_rows=`` hook), so the host->chip row transfer
   overlaps the current step's compute — the parameter_prefetch overlap
   without the RPC.
2. :meth:`lookup` — map ids to slots and gather from the working set.
3. :meth:`update` — write-through: new row values land in the host
   array (authoritative) AND in any resident working-set slot, so
   eviction never loses data and there is no dirty-row flush path.

Counters (`pt_embedding_cache_{hits,misses,evictions}_total`) advance
per call when telemetry is on; :meth:`statusz` is a ready-made section
for the debug server (``DebugServer.add_status("embedding", t.statusz)``).

Checkpointing rides ``paddle_tpu.checkpoint``: :meth:`save` writes the
host rows (checksummed, atomic-rename manifest), :meth:`load` restores
them — and a table trained ep-sharded on chip can be ingested via
:meth:`from_array`.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import telemetry
from ..core.enforce import enforce
from .cache import RowCache


@telemetry.cached_instruments
def _emb_metrics(reg):
    """Embedding-plane instrument set (only reached when telemetry is
    on)."""
    return {
        "hits": reg.counter(
            "pt_embedding_cache_hits_total",
            "host-backed table lookups served from the on-chip "
            "working set"),
        "misses": reg.counter(
            "pt_embedding_cache_misses_total",
            "host-backed table lookups that fetched rows host->chip"),
        "evictions": reg.counter(
            "pt_embedding_cache_evictions_total",
            "working-set rows evicted by the clock sweep"),
        "prefetched_rows": reg.counter(
            "pt_embedding_prefetched_rows_total",
            "rows staged host->chip by prefetch (the overlap path)"),
    }


class HostBackedTable:
    """(V, D) embedding table whose authoritative rows live in host RAM
    with an on-chip working set of ``capacity`` hot rows.

    ``rows`` may be passed (any array-like, copied to a host numpy
    array) or initialized N(0, 1/sqrt(D)) from ``seed``. ``capacity``
    bounds on-chip bytes at ``capacity * D * itemsize`` regardless of
    ``V`` — the table the chip could never hold is exactly the point.
    """

    def __init__(self, num_rows: int, dim: int, *, capacity: int,
                 dtype=jnp.float32, rows: Optional[Any] = None,
                 seed: int = 0, name: str = "table"):
        enforce(num_rows >= 1 and dim >= 1,
                "HostBackedTable needs num_rows/dim >= 1, got (%s, %s)",
                num_rows, dim)
        enforce(capacity >= 1, "capacity must be >= 1, got %s", capacity)
        self.num_rows, self.dim = int(num_rows), int(dim)
        self.name = name
        try:
            self._np_dtype = np.dtype(dtype)
        except TypeError:
            # exotic device dtypes mirror on host as f32
            self._np_dtype = np.dtype(np.float32)
        if rows is not None:
            rows = np.asarray(rows, self._np_dtype)
            enforce(rows.shape == (self.num_rows, self.dim),
                    "rows shape %s != (%s, %s)", rows.shape,
                    self.num_rows, self.dim)
            self.rows = np.array(rows, copy=True)
        else:
            rng = np.random.default_rng(seed)
            self.rows = (rng.standard_normal((self.num_rows, self.dim))
                         / np.sqrt(self.dim)).astype(self._np_dtype)
        self.cache = RowCache(capacity)
        self._ws = jnp.zeros((int(capacity), self.dim), dtype)
        # one lock orders prefetch (background staging thread) against
        # lookup/update (training thread): the cache has its own lock,
        # but slot assignment and the working-set fill must be atomic
        # together or a lookup could gather a slot before its row lands
        self._lock = threading.RLock()

    # -- data plane ----------------------------------------------------------

    def _admit_and_fill(self, uids: np.ndarray) -> int:
        """Admit unique ids; device_put missing rows. Returns #misses.
        Caller holds the lock."""
        if uids.size == 0:
            return 0
        slots, was_miss, evicted = self.cache.admit(uids)
        n_miss = int(was_miss.sum())
        if n_miss:
            fetch = uids[was_miss]
            payload = jnp.asarray(self.rows[fetch], self._ws.dtype)
            self._ws = self._ws.at[jnp.asarray(slots[was_miss])].set(
                payload)
        if telemetry.enabled():
            m = _emb_metrics()
            m["hits"].inc(int((~was_miss).sum()))
            m["misses"].inc(n_miss)
            if evicted:
                m["evictions"].inc(len(evicted))
        return n_miss

    def _check_ids(self, ids: np.ndarray) -> None:
        enforce(ids.size == 0 or (int(ids.min()) >= 0
                                  and int(ids.max()) < self.num_rows),
                "id out of range [0, %s) for table %r", self.num_rows,
                self.name)

    def prefetch(self, ids) -> int:
        """Stage the rows for ``ids`` host->chip ahead of use (the
        DevicePrefetcher overlap hook). Returns rows actually moved."""
        uids = np.unique(np.asarray(ids, np.int64).reshape(-1))
        self._check_ids(uids)
        with self._lock:
            n = self._admit_and_fill(uids)
        if n and telemetry.enabled():
            _emb_metrics()["prefetched_rows"].inc(n)
        return n

    def lookup(self, ids):
        """Rows for ``ids`` (any int shape) as a device array
        ``ids.shape + (D,)`` gathered from the working set (missing
        rows are fetched first — a fully prefetched batch gathers
        without touching the host)."""
        arr = np.asarray(ids, np.int64)
        flat = arr.reshape(-1)
        self._check_ids(flat)
        with self._lock:
            uids = np.unique(flat)
            self._admit_and_fill(uids)
            slots = self.cache.slots_of(flat)
            out = jnp.take(self._ws, jnp.asarray(slots), axis=0)
        return out.reshape(arr.shape + (self.dim,))

    def update(self, ids, new_rows) -> None:
        """Write-through row update: the host array is authoritative,
        resident working-set slots are patched in place — eviction
        never loses data."""
        flat = np.asarray(ids, np.int64).reshape(-1)
        self._check_ids(flat)
        vals = np.asarray(new_rows, self._np_dtype).reshape(
            flat.size, self.dim)
        with self._lock:
            self.rows[flat] = vals
            slots = self.cache.slots_of(flat)
            resident = slots >= 0
            if resident.any():
                self._ws = self._ws.at[jnp.asarray(slots[resident])].set(
                    jnp.asarray(vals[resident], self._ws.dtype))

    # -- reporting -----------------------------------------------------------

    @property
    def host_bytes(self) -> int:
        return int(self.rows.nbytes)

    @property
    def device_bytes(self) -> int:
        return int(self._ws.size) * self._ws.dtype.itemsize

    @property
    def hit_rate(self) -> float:
        return float(self.cache.stats()["hit_rate"])

    def statusz(self) -> Dict[str, Any]:
        """The ``/statusz`` embedding section (attach via
        ``DebugServer.add_status``) — host-side fields only, safe to
        render on every scrape."""
        s = self.cache.stats()
        s.update({
            "name": self.name,
            "rows": self.num_rows,
            "dim": self.dim,
            "host_bytes": self.host_bytes,
            "device_bytes": self.device_bytes,
        })
        return s

    # -- checkpoint ----------------------------------------------------------

    def save(self, directory: str) -> None:
        """Write the authoritative host rows through the checkpoint
        plane (manifest + checksums + atomic commit)."""
        from .. import checkpoint

        checkpoint.save_state(directory, {"rows": self.rows})

    @classmethod
    def load(cls, directory: str, *, capacity: int,
             dtype=jnp.float32, name: str = "table") -> "HostBackedTable":
        from .. import checkpoint

        tree = checkpoint.restore_state(directory)
        rows = np.asarray(tree["rows"])
        return cls(rows.shape[0], rows.shape[1], capacity=capacity,
                   dtype=dtype, rows=rows, name=name)

    @classmethod
    def from_array(cls, rows, *, capacity: int, dtype=jnp.float32,
                   name: str = "table") -> "HostBackedTable":
        """Ingest an existing (possibly ep-sharded, device-resident)
        table — e.g. to serve a table trained under ``Plan(ep=N)``."""
        host = np.asarray(rows)
        return cls(host.shape[0], host.shape[1], capacity=capacity,
                   dtype=dtype, rows=host, name=name)

    def __repr__(self):
        return (f"HostBackedTable({self.name!r}, rows={self.num_rows}, "
                f"dim={self.dim}, capacity={self.cache.capacity}, "
                f"resident={len(self.cache)})")
