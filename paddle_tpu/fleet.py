"""Fleet — unified distributed-training UX + multi-host bootstrap
(reference: python/paddle/fluid/incubate/fleet/base/fleet_base.py Fleet,
base/role_maker.py:28,95,175 RoleMaker/MPISymetricRoleMaker/
UserDefinedRoleMaker, incubate/fleet/collective/__init__.py:25,77
Collective fleet + DistributedStrategy).

TPU-native redesign: the reference's fleet wires trainers/pservers over RPC
(gen_nccl_id bootstrap, listen_and_serv). Here the control plane is JAX's
coordination service (`jax.distributed.initialize` — the gen_nccl_id
successor, SURVEY §5.8: control-plane RPC for bring-up only, tensor traffic
over ICI/DCN via compiler collectives). ``fleet.init()`` discovers the role
from PADDLE_*-style env vars, brings up the coordination service when
multi-process, builds the global mesh (dp over hosts x local parallelism),
and hands back sharded-training helpers. PS roles collapse into sharding
rules (ZeRO optimizer-state sharding + EP embeddings), so ``server`` roles
don't exist — every process is a worker.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import jax

from .core.config import DistributeConfig
from .core.enforce import enforce
from .core.mesh import build_mesh, get_mesh, set_mesh

__all__ = ["RoleMaker", "DistributedStrategy", "Fleet", "init", "instance"]


@dataclass
class RoleMaker:
    """Rank discovery (reference: base/role_maker.py RoleMakerBase /
    PaddleCloudRoleMaker env-var protocol). Reads, in priority order:
    explicit ctor args > PADDLE_TRAINER_ID/PADDLE_TRAINERS_NUM +
    PADDLE_TRAINER_ENDPOINTS > JAX_PROCESS_ID/JAX_NUM_PROCESSES +
    JAX_COORDINATOR_ADDRESS > single-process defaults."""

    rank: Optional[int] = None
    world_size: Optional[int] = None
    coordinator: Optional[str] = None
    endpoints: Optional[List[str]] = None

    def __post_init__(self):
        env = os.environ
        if self.rank is None:
            self.rank = int(env.get("PADDLE_TRAINER_ID",
                                    env.get("JAX_PROCESS_ID", 0)))
        if self.world_size is None:
            self.world_size = int(env.get("PADDLE_TRAINERS_NUM",
                                          env.get("JAX_NUM_PROCESSES", 1)))
        if self.endpoints is None:
            eps = env.get("PADDLE_TRAINER_ENDPOINTS", "")
            self.endpoints = [e for e in eps.split(",") if e]
        if self.coordinator is None:
            self.coordinator = env.get("JAX_COORDINATOR_ADDRESS")
            if self.coordinator is None and self.endpoints:
                # paddle convention: rank-0's endpoint is the coordinator
                self.coordinator = self.endpoints[0]
        enforce(0 <= self.rank < self.world_size,
                "rank %s out of range for world size %s", self.rank,
                self.world_size)

    def is_first_worker(self) -> bool:
        return self.rank == 0

    def worker_num(self) -> int:
        return self.world_size

    def worker_index(self) -> int:
        return self.rank


@dataclass
class DistributedStrategy:
    """reference: incubate/fleet/collective DistributedStrategy — knobs that
    shaped the NCCL graph now shape the mesh + step compilation."""

    dp: Optional[int] = None  # None → all remaining devices
    tp: int = 1
    pp: int = 1
    sp: int = 1
    ep: int = 1
    amp: Optional[str] = None          # mixed-precision policy name
    gradient_merge_steps: int = 1      # microbatch accumulation
    donate_inputs: bool = True
    # which mesh axis spans hosts (DCN) in multi-process runs; 'dp' is
    # the classic layout, 'tp'/'pp' prove model axes across processes
    # (reference NCCL2-across-trainers capability, test_dist_base.py:545)
    dcn_axis: str = "dp"


class Fleet:
    """Process-global fleet singleton (reference: fleet_base.py Fleet)."""

    def __init__(self):
        self._role: Optional[RoleMaker] = None
        self._strategy = DistributedStrategy()
        self._initialized = False

    # -- lifecycle -----------------------------------------------------------

    def init(self, role: Optional[RoleMaker] = None,
             strategy: Optional[DistributedStrategy] = None,
             connect: bool = True) -> "Fleet":
        """Bring up the distributed runtime. Multi-process: starts JAX's
        coordination service (rank 0 hosts it) so all hosts see the global
        device set. Single-process: no-op bootstrap, local devices only."""
        self._role = role or RoleMaker()
        # always reset: a failed earlier init must not leak its strategy
        self._strategy = strategy if strategy is not None \
            else DistributedStrategy()
        if self._role.world_size > 1 and connect:
            enforce(self._role.coordinator is not None,
                    "multi-process fleet needs a coordinator address "
                    "(JAX_COORDINATOR_ADDRESS or PADDLE_TRAINER_ENDPOINTS)")
            jax.distributed.initialize(
                coordinator_address=self._role.coordinator,
                num_processes=self._role.world_size,
                process_id=self._role.rank)
        self._initialized = True
        self._build_mesh()
        return self

    def _build_mesh(self):
        s = self._strategy
        n = len(jax.devices())
        model_par = s.tp * s.pp * s.sp * s.ep
        dp = s.dp if s.dp is not None else max(n // model_par, 1)
        enforce(dp * model_par == n,
                "strategy (dp=%s tp=%s pp=%s sp=%s ep=%s) does not cover "
                "%s devices", dp, s.tp, s.pp, s.sp, s.ep, n)
        enforce(s.dcn_axis in ("dp", "pp", "tp", "sp", "ep"),
                "unknown dcn_axis %r (mesh axes: dp/pp/tp/sp/ep)",
                s.dcn_axis)
        world = self._role.world_size
        if world > 1 and s.dcn_axis != "dp":
            from .core.mesh import build_multihost_mesh

            self.mesh = build_multihost_mesh(
                world, dcn_axis=s.dcn_axis, dp=dp, tp=s.tp, pp=s.pp,
                sp=s.sp, ep=s.ep)
        else:
            self.mesh = build_mesh(dp=dp, tp=s.tp, pp=s.pp, sp=s.sp,
                                   ep=s.ep)
        set_mesh(self.mesh)

    def shutdown(self):
        if self._role is not None and self._role.world_size > 1:
            jax.distributed.shutdown()
        self._initialized = False

    # -- role queries (reference fleet API names) ---------------------------

    @property
    def initialized(self) -> bool:
        return self._initialized

    def is_first_worker(self) -> bool:
        self._check()
        return self._role.is_first_worker()

    def worker_index(self) -> int:
        self._check()
        return self._role.worker_index()

    def worker_num(self) -> int:
        self._check()
        return self._role.worker_num()

    def worker_endpoints(self) -> List[str]:
        self._check()
        return list(self._role.endpoints or [])

    # -- training helpers ----------------------------------------------------

    def distributed_optimizer(self, optimizer):
        """reference: fleet.distributed_optimizer — wraps the optimizer per
        strategy (AMP decoration; DP gradient averaging is automatic: grads
        of dp-sharded batches all-reduce in the compiled step)."""
        self._check()
        if self._strategy.amp:
            from .amp import decorate

            optimizer = decorate(optimizer, policy=self._strategy.amp)
        return optimizer

    def trainer(self, model, optimizer, loss_fn, metrics_fn=None, **kw):
        """One-call training driver on the fleet mesh (the
        fleet.minimize + CompiledProgram path collapsed)."""
        self._check()
        from .parallel.api import Trainer

        return Trainer.supervised(
            model, optimizer, loss_fn, metrics_fn, mesh=self.mesh,
            amp=self._strategy.amp,
            grad_accum_steps=self._strategy.gradient_merge_steps, **kw)

    def controller(self, **kw):
        """Build a :class:`resilience.FleetController` wired to this
        fleet's role — rank/world from the RoleMaker env protocol (a
        full ``fleet.init()`` is NOT required: a worker that never
        brings up the coordination service still coordinates over the
        file transport), transport auto-selected (the JAX coordination
        client when connected, else the shared-filesystem fallback
        under ``PT_FLEET_DIR``). Feed it to
        ``TrainLoop.run(controller=...)``."""
        from .resilience.controller import FleetController

        role = self._role if self._role is not None else RoleMaker()
        kw.setdefault("rank", role.rank)
        kw.setdefault("world", role.world_size)
        return FleetController(**kw)

    def _check(self):
        enforce(self._initialized, "call fleet.init() first")


# module-level singleton, `from paddle_tpu import fleet; fleet.init()`
_fleet = Fleet()


def init(role: Optional[RoleMaker] = None,
         strategy: Optional[DistributedStrategy] = None,
         connect: bool = True) -> Fleet:
    return _fleet.init(role=role, strategy=strategy, connect=connect)


def instance() -> Fleet:
    return _fleet


def __getattr__(name):
    # delegate module attribute access to the singleton (fleet.worker_num()...)
    if hasattr(Fleet, name) and not name.startswith("_"):
        return getattr(_fleet, name)
    raise AttributeError(name)
