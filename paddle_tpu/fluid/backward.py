"""fluid.backward compat (reference: python/paddle/fluid/backward.py:394
append_backward; :619 calc_gradient — both over the static Program; the
eager path is jax.grad by construction)."""

from __future__ import annotations

from ..static.program import append_backward


def calc_gradient(targets, inputs, target_gradients=None, no_grad_set=None):
    """reference: backward.py calc_gradient:619 — gradients of ``targets``
    w.r.t. arbitrary program vars (not just parameters)."""
    names = [v.name if hasattr(v, "name") else v for v in
             (inputs if isinstance(inputs, (list, tuple)) else [inputs])]
    if isinstance(targets, (list, tuple)):
        total = targets[0]
        for t in targets[1:]:
            total = total + t  # summed objective: gradient contributions add
        targets = total
    pairs = append_backward(targets, parameter_list=names)
    grads = [g for _, g in pairs]
    return grads if isinstance(inputs, (list, tuple)) else grads[0]
