"""fluid.io compat (reference: python/paddle/fluid/io.py:98-1074 save/load
family + fluid/reader.py PyReader)."""

from __future__ import annotations

from ..layers import _PyReader as PyReader  # async device feed pipeline
from ..static.io import (load_inference_model, load_persistables,
                         save_inference_model, save_persistables)

# vars/params granularities collapse onto the same artifact writer: the
# persistable set IS the param set plus optimizer state in this design
# (reference io.py:98 save_vars / :228 save_params / :460 save_persistables)
save_vars = save_persistables
save_params = save_persistables
load_vars = load_persistables
load_params = load_persistables
