"""fluid.profiler compat (reference: python/paddle/fluid/profiler.py:39,126,
222) over the core profiler (RecordEvent spans + chrome-trace export +
jax.profiler device capture)."""

from __future__ import annotations

import contextlib

from ..core.profiler import (RecordEvent, export_chrome_trace, profiler,
                             record_event, start_profiler, stop_profiler)
from ..telemetry import trace as _trace


def reset_profiler():
    """reference: profiler.py reset_profiler — drop collected host events."""
    _trace.reset()


@contextlib.contextmanager
def cuda_profiler(output_file=None, output_mode=None, config=None):
    """Accelerator-trace passthrough (reference: platform/cuda_profiler.h).
    On TPU the device trace is jax.profiler's XPlane capture, steered by
    start_profiler(device_trace_dir=...)."""
    start_profiler(device_trace_dir=output_file)
    try:
        yield
    finally:
        stop_profiler(device_trace=output_file is not None)
