"""Dygraph model export — ``paddle.jit.save``-style (the reference's fluid
line only exports static Programs, io.py save_inference_model:898; its
successor API traces dygraph Layers. Here any ``nn.Layer`` exports to the
same StableHLO artifact (manifest v2) that ``static.load_inference_model``
and the C++ PJRT predictor (native/src/predictor.cc, ptserve) consume —
one serving format for both authoring modes, quantized models included
(buffers, e.g. frozen activation scales, are baked as constants)."""

from __future__ import annotations

import json
import os
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .core.enforce import enforce
from .nn.layer import Layer
from .utils import compat as _compat

_compat.jax_export()  # jax<0.5: jax.export is lazy; attribute access needs one import


def save(layer: Layer, dirname: str, example_args: Sequence,
         input_names: Optional[Sequence[str]] = None,
         batch_polymorphic: bool = True, method: str = "forward",
         method_kwargs: Optional[dict] = None) -> None:
    """Export ``layer.<method>(*example_args)`` (eval mode) as an
    inference artifact. ``example_args``: arrays or ShapeDtypeStructs;
    leading dims export symbolically when ``batch_polymorphic``.
    ``method`` lets a model export an alternative jittable entry point —
    e.g. TransformerNMT.greedy_decode_cached, so the SERVING artifact
    carries the K/V-cached decode loop, not just the teacher-forced
    forward; ``method_kwargs`` bakes static non-array options (e.g.
    ``{"max_len": 128}``) into the traced artifact."""
    layer.eval()
    params = {k: jnp.asarray(v) for k, v in layer.named_parameters().items()}
    buffers = {k: jnp.asarray(v) for k, v in layer.named_buffers().items()}
    names = list(input_names or [f"x{i}" for i in range(len(example_args))])
    enforce(len(names) == len(example_args),
            "input_names length %s != example args %s", len(names),
            len(example_args))

    mkw = dict(method_kwargs or {})

    def infer_fn(params, feeds):
        out, _ = layer.functional_call(
            params, *[feeds[n] for n in names], buffers=buffers,
            training=False, method=method, **mkw)
        return list(out) if isinstance(out, (tuple, list)) else [out]

    feed_specs, polymorphic = {}, False
    for name, a in zip(names, example_args):
        shape = tuple(np.shape(a)) if not hasattr(a, "shape") else tuple(
            a.shape)
        dtype = getattr(a, "dtype", np.asarray(a).dtype)
        if batch_polymorphic and len(shape) >= 1:
            polymorphic = True
            sym = jax.export.symbolic_shape(
                ",".join(["b"] + [str(d) for d in shape[1:]]))
            feed_specs[name] = jax.ShapeDtypeStruct(sym, dtype)
        else:
            feed_specs[name] = jax.ShapeDtypeStruct(shape, dtype)
    param_specs = {n: jax.ShapeDtypeStruct(v.shape, v.dtype)
                   for n, v in params.items()}
    try:
        exported = jax.export.export(jax.jit(infer_fn))(param_specs,
                                                        feed_specs)
    except Exception:
        if not polymorphic:
            raise
        polymorphic = False  # fall back to the example's concrete shapes
        for name, a in zip(names, example_args):
            shape = tuple(a.shape) if hasattr(a, "shape") else np.shape(a)
            dtype = getattr(a, "dtype", np.asarray(a).dtype)
            feed_specs[name] = jax.ShapeDtypeStruct(shape, dtype)
        exported = jax.export.export(jax.jit(infer_fn))(param_specs,
                                                        feed_specs)

    n_out = len(exported.out_avals)
    fetch_names = [f"out{i}" for i in range(n_out)]
    os.makedirs(dirname, exist_ok=True)
    with open(os.path.join(dirname, "program.stablehlo"), "wb") as f:
        f.write(exported.serialize())
    with open(os.path.join(dirname, "program.mlir.bc"), "wb") as f:
        f.write(exported.mlir_module_serialized)
    np.savez(os.path.join(dirname, "params.npz"),
             **{n: np.asarray(v) for n, v in params.items()})
    from .utils.atomic import atomic_write_text

    atomic_write_text(
        os.path.join(dirname, "manifest.json"),
        json.dumps({
            "feed_target_names": names,
            "fetch_target_names": fetch_names,
            "feed_shapes": {
                n: [-1 if polymorphic and i == 0 else int(d)
                    for i, d in enumerate(
                        a.shape if hasattr(a, "shape") else np.shape(a))]
                for n, a in zip(names, example_args)},
            "feed_dtypes": {n: np.dtype(feed_specs[n].dtype).name
                            for n in feed_specs},
            "arg_order": ([f"param:{n}" for n in sorted(params)] +
                          [f"feed:{n}" for n in sorted(feed_specs)]),
            "batch_polymorphic": polymorphic,
            # the producing toolchain identity (the aot-plane compat
            # gate) — consumers that rehydrate the serialized program
            # (rather than re-lowering the StableHLO) compare it
            "fingerprint": _compat.runtime_fingerprint(),
            "format": "stablehlo+npz/v2",
        }, indent=1))


def load(dirname: str):
    """Load a saved artifact as a predictor (shared loader with static)."""
    from .static.io import load_inference_model

    return load_inference_model(dirname)
