"""One-command multi-process bring-up — ``python -m paddle_tpu.launch``.

Capability equivalent of the reference's distributed launcher
(reference: python/paddle/distributed/launch.py:1 — spawns one trainer
process per device, wiring PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM /
PADDLE_TRAINER_ENDPOINTS env vars). Here the same env protocol feeds
``fleet.RoleMaker``; rank 0's endpoint doubles as the JAX coordination
-service address (the gen_nccl_id successor — reference:
operators/distributed_ops/gen_nccl_id_op.cc:31).

Usage:
    python -m paddle_tpu.launch --nproc 2 train.py [script args...]

Behavior:
- spawns ``nproc`` copies of the script, each with its rank env;
- rank 0 streams to this process's stdout/stderr, other ranks write
  ``<log_dir>/workerlog.<rank>`` (reference launcher's log layout);
- first failure terminates the whole job and replays the failing
  rank's log tail;
- exit code = first non-zero worker exit code, else 0.
"""

from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys
import time
from typing import List, Optional


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def build_worker_env(rank: int, nproc: int, endpoints: List[str],
                     base_env=None, platform: Optional[str] = None,
                     local_devices: Optional[int] = None) -> dict:
    """Env for one worker, RoleMaker's protocol (fleet.py:35): explicit
    args > PADDLE_* > JAX_* > single-process defaults.

    ``local_devices`` forces N virtual CPU devices per worker (the
    reference launcher's per-node --gpus analog for the multi-host
    simulation rig, SURVEY §7 'multi-host test rig without a pod')."""
    env = dict(os.environ if base_env is None else base_env)
    env["PADDLE_TRAINER_ID"] = str(rank)
    env["PADDLE_TRAINERS_NUM"] = str(nproc)
    env["PADDLE_TRAINER_ENDPOINTS"] = ",".join(endpoints)
    env["JAX_PROCESS_ID"] = str(rank)
    env["JAX_NUM_PROCESSES"] = str(nproc)
    env["JAX_COORDINATOR_ADDRESS"] = endpoints[0]
    if platform:
        env["JAX_PLATFORMS"] = platform
        # each process owns its local chip(s); a forced host-device count
        # would alias the same CPU into every rank
        env.pop("XLA_FLAGS", None)
    if local_devices:
        env["XLA_FLAGS"] = (
            env.get("XLA_FLAGS", "") +
            f" --xla_force_host_platform_device_count={local_devices}"
        ).strip()
    return env


def launch(script: str, script_args: List[str], *, nproc: int,
           endpoints: Optional[List[str]] = None,
           log_dir: str = "launch_logs", platform: Optional[str] = None,
           timeout: Optional[float] = None,
           local_devices: Optional[int] = None,
           grace: float = 30.0) -> int:
    """Spawn the job; returns the job's exit code (0 = all ranks ok).

    Preemption relay: a SIGTERM delivered to the launcher (TPU
    preemption hits the job's parent first) is forwarded as SIGTERM to
    every worker, giving each rank's
    :class:`resilience.PreemptionHandler` its grace window — workers
    finish the in-flight step, checkpoint, and exit 0. Workers still
    alive ``grace`` seconds after the relay are killed. During the
    relay window a non-zero worker exit no longer tears down its peers
    (they are already shutting down and deserve their own grace)."""
    if endpoints is None:
        endpoints = [f"127.0.0.1:{_free_port()}" for _ in range(nproc)]
    if len(endpoints) != nproc:
        raise ValueError(
            f"{len(endpoints)} endpoints for {nproc} processes")
    os.makedirs(log_dir, exist_ok=True)
    procs, logs, log_files = [], [], []
    for rank in range(nproc):
        env = build_worker_env(rank, nproc, endpoints, platform=platform,
                               local_devices=local_devices)
        if rank == 0:
            out, path = None, None  # inherit: rank 0 streams live
        else:
            path = os.path.join(log_dir, f"workerlog.{rank}")
            out = open(path, "w")
            log_files.append(out)
        logs.append(path)
        procs.append(subprocess.Popen(
            [sys.executable, script, *script_args], env=env,
            stdout=out, stderr=subprocess.STDOUT if out else None))

    relayed_at: List[Optional[float]] = [None]

    def _relay(signum, frame):
        if relayed_at[0] is not None:
            return  # second SIGTERM: the grace clock is already running
        relayed_at[0] = time.time()
        print(f"[launch] SIGTERM: relaying to {nproc} workers "
              f"(grace {grace}s)", file=sys.stderr)
        for q in procs:
            if q.poll() is None:
                q.send_signal(signal.SIGTERM)

    prev_term = None
    try:
        prev_term = signal.signal(signal.SIGTERM, _relay)
    except ValueError:
        pass  # not the main thread: no relay, workers get the default

    deadline = time.time() + timeout if timeout else None
    rc = 0
    try:
        pending = set(range(nproc))
        while pending:
            for rank in sorted(pending):
                p = procs[rank]
                code = p.poll()
                if code is None:
                    continue
                pending.discard(rank)
                if code != 0 and rc == 0:
                    rc = code
                    if relayed_at[0] is not None:
                        continue  # preempting: peers keep their grace
                    print(f"[launch] rank {rank} exited with {code}; "
                          "terminating job", file=sys.stderr)
                    if logs[rank]:
                        _replay_tail(logs[rank], rank)
                    for q in procs:
                        if q.poll() is None:
                            q.terminate()
            if relayed_at[0] is not None and pending and \
                    time.time() > relayed_at[0] + grace:
                print(f"[launch] grace window ({grace}s) expired; "
                      f"killing ranks {sorted(pending)}",
                      file=sys.stderr)
                for q in procs:
                    if q.poll() is None:
                        q.kill()
                rc = rc or 143  # the job WAS preempted, not clean
            if deadline and time.time() > deadline and pending:
                print(f"[launch] timeout after {timeout}s; terminating "
                      f"ranks {sorted(pending)}", file=sys.stderr)
                for q in procs:
                    if q.poll() is None:
                        q.terminate()
                rc = rc or 124
                break
            time.sleep(0.05)
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
    except KeyboardInterrupt:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGINT)
        raise
    finally:
        if prev_term is not None:
            signal.signal(signal.SIGTERM, prev_term)
        for f in log_files:
            f.close()
    return rc


def _replay_tail(path: str, rank: int, n: int = 40) -> None:
    try:
        with open(path) as f:
            lines = f.read().splitlines()
        print(f"[launch] last {min(n, len(lines))} lines of rank {rank} "
              f"({path}):", file=sys.stderr)
        for line in lines[-n:]:
            print(f"  [rank {rank}] {line}", file=sys.stderr)
    except OSError:
        pass


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.launch",
        description="multi-process distributed launcher (reference: "
                    "python -m paddle.distributed.launch)")
    ap.add_argument("--nproc", type=int, default=1,
                    help="number of worker processes (trainers)")
    ap.add_argument("--endpoints", default=None,
                    help="comma-separated host:port per rank (default: "
                    "free local ports; rank 0 = coordinator)")
    ap.add_argument("--log-dir", default="launch_logs",
                    help="directory for workerlog.<rank> files (rank 0 "
                    "streams to this terminal)")
    ap.add_argument("--platform", default=None,
                    help="force JAX_PLATFORMS in workers (e.g. cpu for "
                    "multi-process simulation on one host)")
    ap.add_argument("--local-devices", type=int, default=None,
                    help="force N virtual CPU devices per worker (the "
                    "multi-host simulation rig; per-node --gpus analog)")
    ap.add_argument("--timeout", type=float, default=None,
                    help="kill the job after this many seconds")
    ap.add_argument("--grace", type=float, default=30.0,
                    help="seconds workers get to checkpoint and exit "
                    "after a relayed SIGTERM before being killed "
                    "(preemption grace window)")
    ap.add_argument("script", help="training script to run per rank")
    ap.add_argument("script_args", nargs=argparse.REMAINDER,
                    help="arguments passed through to the script")
    args = ap.parse_args(argv)
    endpoints = (args.endpoints.split(",") if args.endpoints else None)
    return launch(args.script, args.script_args, nproc=args.nproc,
                  endpoints=endpoints, log_dir=args.log_dir,
                  platform=args.platform, timeout=args.timeout,
                  local_devices=args.local_devices, grace=args.grace)


if __name__ == "__main__":
    sys.exit(main())
