"""One-command multi-process bring-up — ``python -m paddle_tpu.launch``.

Capability equivalent of the reference's distributed launcher
(reference: python/paddle/distributed/launch.py:1 — spawns one trainer
process per device, wiring PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM /
PADDLE_TRAINER_ENDPOINTS env vars). Here the same env protocol feeds
``fleet.RoleMaker``; rank 0's endpoint doubles as the JAX coordination
-service address (the gen_nccl_id successor — reference:
operators/distributed_ops/gen_nccl_id_op.cc:31).

Usage:
    python -m paddle_tpu.launch --nproc 2 train.py [script args...]

Behavior:
- spawns ``nproc`` copies of the script, each with its rank env (plus
  the fleet-controller transport env: ``PT_FLEET_DIR`` under the log
  dir and a per-attempt ``PT_FLEET_RUN_ID`` — the same transport the
  step-agreed periodic-save transaction and the restore-step
  agreement ride, so a launched job gets multi-host durable
  checkpointing with no extra wiring);
- rank 0 streams to this process's stdout/stderr, other ranks write
  ``<log_dir>/workerlog.<rank>`` (reference launcher's log layout);
- a worker that exits non-zero FAIL-FASTS the job: the failing rank's
  log tail is replayed, the rank is marked ``dead`` through the fleet
  transport (surviving controllers drop it from the preempt agreement
  instead of hanging in the next barrier), peers get SIGTERM and the
  grace window to commit, and stragglers are killed when it expires —
  the launcher never hangs on survivors stuck in a dead rank's
  barrier;
- ``--elastic``: instead of dying with the lost worker, the job
  respawns on the N-1 surviving slots (fresh rank numbering, a fresh
  ``PT_FLEET_RUN_ID`` so no dead-attempt coordination state leaks) and
  resumes from the last COMMITTED checkpoint — the worker script's
  ordinary ``TrainLoop`` resume path reshards it onto the smaller
  process set (the cross-plan-shape restore);
- exit code = the final attempt's first non-zero worker exit code,
  else 0.

Serving bring-up (``--serve``): instead of training ranks, spawn
``--nproc`` serving REPLICA worker processes (+ ``--prefill-workers``
dedicated prefill workers) from a ``--spec module:fn`` decoder factory
and run the :mod:`paddle_tpu.serving_router` front end over them —
the one-command form of the production serving plane (README
"Production serving")."""

from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys
import time
from typing import List, Optional, Tuple

__all__ = ["build_worker_env", "launch", "main"]


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def build_worker_env(rank: int, nproc: int, endpoints: List[str],
                     base_env=None, platform: Optional[str] = None,
                     local_devices: Optional[int] = None,
                     fleet_dir: Optional[str] = None,
                     run_id: Optional[str] = None) -> dict:
    """Env for one worker, RoleMaker's protocol (fleet.py:35): explicit
    args > PADDLE_* > JAX_* > single-process defaults.

    ``local_devices`` forces N virtual CPU devices per worker (the
    reference launcher's per-node --gpus analog for the multi-host
    simulation rig, SURVEY §7 'multi-host test rig without a pod').

    ``fleet_dir``/``run_id`` seed the fleet controller's coordination
    transport (``resilience.controller``): the shared file-transport
    root and the per-attempt key namespace — an elastic restart gets a
    fresh ``run_id`` so a dead attempt's acks never read as live."""
    env = dict(os.environ if base_env is None else base_env)
    env["PADDLE_TRAINER_ID"] = str(rank)
    env["PADDLE_TRAINERS_NUM"] = str(nproc)
    env["PADDLE_TRAINER_ENDPOINTS"] = ",".join(endpoints)
    env["JAX_PROCESS_ID"] = str(rank)
    env["JAX_NUM_PROCESSES"] = str(nproc)
    env["JAX_COORDINATOR_ADDRESS"] = endpoints[0]
    if fleet_dir:
        env["PT_FLEET_DIR"] = fleet_dir
    if run_id:
        env["PT_FLEET_RUN_ID"] = run_id
    if platform:
        env["JAX_PLATFORMS"] = platform
        # each process owns its local chip(s); a forced host-device count
        # would alias the same CPU into every rank
        env.pop("XLA_FLAGS", None)
    if local_devices:
        env["XLA_FLAGS"] = (
            env.get("XLA_FLAGS", "") +
            f" --xla_force_host_platform_device_count={local_devices}"
        ).strip()
    return env


def _mark_dead(fleet_dir: str, run_id: str, rank: int) -> None:
    """Publish the fleet transport's ``dead.<rank>`` marker (the
    FileTransport key layout: ``<root>/<run_id>.<key>``) so surviving
    controllers drop the rank from the preempt agreement and exit
    clean inside the grace window instead of holding for a corpse.
    Plain-stdlib on purpose: the launcher stays importable without the
    framework's heavy deps on the hot teardown path."""
    try:
        os.makedirs(fleet_dir, exist_ok=True)
        path = os.path.join(fleet_dir, f"{run_id}.dead.{rank}")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write("1")
        os.replace(tmp, path)
    except OSError:
        pass  # best-effort: the grace-kill below still bounds teardown


def _run_attempt(script: str, script_args: List[str], *, nproc: int,
                 endpoints: List[str], log_dir: str,
                 platform: Optional[str],
                 local_devices: Optional[int], grace: float,
                 deadline: Optional[float], fleet_dir: str,
                 run_id: str,
                 relayed: List[bool]) -> Tuple[int, Optional[int]]:
    """One spawn of the whole worker set. Returns (exit code, the rank
    whose unexpected death triggered teardown — None for clean /
    relayed / timed-out attempts)."""
    procs, logs, log_files = [], [], []
    for rank in range(nproc):
        env = build_worker_env(rank, nproc, endpoints,
                               platform=platform,
                               local_devices=local_devices,
                               fleet_dir=fleet_dir, run_id=run_id)
        if rank == 0:
            out, path = None, None  # inherit: rank 0 streams live
        else:
            path = os.path.join(log_dir, f"workerlog.{rank}")
            out = open(path, "w")
            log_files.append(out)
        logs.append(path)
        procs.append(subprocess.Popen(
            [sys.executable, script, *script_args], env=env,
            stdout=out, stderr=subprocess.STDOUT if out else None))

    relayed_at: List[Optional[float]] = [None]
    # one grace clock for BOTH teardown kinds (preemption relay and
    # worker-failure fail-fast): once it expires, stragglers — e.g.
    # survivors wedged in a dead rank's coordination barrier — are
    # killed instead of hanging the launcher
    kill_at: List[Optional[float]] = [None]

    def _relay(signum, frame):
        if relayed_at[0] is not None:
            return  # second SIGTERM: the grace clock is already running
        relayed_at[0] = time.time()
        relayed[0] = True
        kill_at[0] = relayed_at[0] + grace
        print(f"[launch] SIGTERM: relaying to {nproc} workers "
              f"(grace {grace}s)", file=sys.stderr)
        for q in procs:
            if q.poll() is None:
                q.send_signal(signal.SIGTERM)

    prev_term = None
    try:
        prev_term = signal.signal(signal.SIGTERM, _relay)
    except ValueError:
        pass  # not the main thread: no relay, workers get the default

    rc = 0
    failed_rank: Optional[int] = None
    try:
        pending = set(range(nproc))
        while pending:
            for rank in sorted(pending):
                p = procs[rank]
                code = p.poll()
                if code is None:
                    continue
                pending.discard(rank)
                if code != 0 and rc == 0:
                    rc = code
                    if relayed_at[0] is not None:
                        continue  # preempting: peers keep their grace
                    failed_rank = rank
                    print(f"[launch] rank {rank} exited with {code}; "
                          f"failing fast (peers get {grace}s to "
                          "commit)", file=sys.stderr)
                    if logs[rank]:
                        _replay_tail(logs[rank], rank)
                    # dead marker FIRST: when the peers' SIGTERM lands
                    # their controllers already see the rank as gone
                    # and agree among the survivors
                    _mark_dead(fleet_dir, run_id, rank)
                    kill_at[0] = time.time() + grace
                    for q in procs:
                        if q.poll() is None:
                            q.terminate()
            if kill_at[0] is not None and pending and \
                    time.time() > kill_at[0]:
                print(f"[launch] grace window ({grace}s) expired; "
                      f"killing ranks {sorted(pending)}",
                      file=sys.stderr)
                for q in procs:
                    if q.poll() is None:
                        q.kill()
                if relayed_at[0] is not None:
                    rc = rc or 143  # the job WAS preempted, not clean
                kill_at[0] = None  # fired once; the kills are done
            if deadline and time.time() > deadline and pending:
                print(f"[launch] timeout; terminating ranks "
                      f"{sorted(pending)}", file=sys.stderr)
                for q in procs:
                    if q.poll() is None:
                        q.terminate()
                rc = rc or 124
                failed_rank = None  # a timeout is not an elastic event
                break
            time.sleep(0.05)
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
    except KeyboardInterrupt:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGINT)
        raise
    finally:
        if prev_term is not None:
            signal.signal(signal.SIGTERM, prev_term)
        for f in log_files:
            f.close()
    return rc, failed_rank


def launch(script: str, script_args: List[str], *, nproc: int,
           endpoints: Optional[List[str]] = None,
           log_dir: str = "launch_logs", platform: Optional[str] = None,
           timeout: Optional[float] = None,
           local_devices: Optional[int] = None,
           grace: float = 30.0, elastic: bool = False,
           max_restarts: Optional[int] = None,
           min_procs: int = 1) -> int:
    """Spawn the job; returns the job's exit code (0 = all ranks ok).

    Preemption relay: a SIGTERM delivered to the launcher (TPU
    preemption hits the job's parent first) is forwarded as SIGTERM to
    every worker, giving each rank's
    :class:`resilience.PreemptionHandler` its grace window — workers
    finish the in-flight step, checkpoint (fleet-coordinated when the
    script runs a :class:`resilience.FleetController`: every rank
    commits the SAME agreed step), and exit 0. Workers still alive
    ``grace`` seconds after the relay are killed. During the relay
    window a non-zero worker exit no longer tears down its peers (they
    are already shutting down and deserve their own grace).

    Fail-fast: outside a relay, the FIRST non-zero worker exit tears
    the job down — tail replay, ``dead`` marker through the fleet
    transport, SIGTERM to peers, hard kill when the grace window
    expires. Survivors with a controller exit clean (coordinated
    commit among the live ranks); survivors without one are bounded by
    the kill.

    Elastic (``--elastic``): a torn-down job respawns on the surviving
    ``nproc - 1`` slots — fresh ranks, fresh coordination namespace —
    and the worker script's resume path restores the last COMMITTED
    checkpoint onto the smaller process set. At most ``max_restarts``
    times (default ``nproc - 1``: down to one worker), never below
    ``min_procs``, never after a preemption relay or global timeout.
    """
    os.makedirs(log_dir, exist_ok=True)
    fleet_dir = os.path.join(log_dir, "fleet")
    deadline = time.time() + timeout if timeout else None
    restarts_left = 0
    if elastic:
        restarts_left = (max_restarts if max_restarts is not None
                         else max(nproc - 1, 0))
    attempt = 0
    cur_endpoints = endpoints
    relayed = [False]
    while True:
        eps = cur_endpoints
        if eps is None:
            eps = [f"127.0.0.1:{_free_port()}" for _ in range(nproc)]
        if len(eps) != nproc:
            raise ValueError(
                f"{len(eps)} endpoints for {nproc} processes")
        run_id = f"L{os.getpid()}a{attempt}"
        rc, failed_rank = _run_attempt(
            script, script_args, nproc=nproc, endpoints=eps,
            log_dir=log_dir, platform=platform,
            local_devices=local_devices, grace=grace,
            deadline=deadline, fleet_dir=fleet_dir, run_id=run_id,
            relayed=relayed)
        if rc == 0 or relayed[0] or failed_rank is None or \
                restarts_left <= 0:
            return rc
        if nproc - 1 < max(min_procs, 1):
            print(f"[launch] elastic: cannot drop below "
                  f"min_procs={max(min_procs, 1)}; giving up",
                  file=sys.stderr)
            return rc
        if deadline and time.time() > deadline:
            return rc
        restarts_left -= 1
        attempt += 1
        nproc -= 1
        if cur_endpoints is not None:
            # drop the dead slot's endpoint; survivors keep theirs
            cur_endpoints = [e for i, e in enumerate(cur_endpoints)
                             if i != failed_rank]
        print(f"[launch] elastic restart #{attempt}: rank "
              f"{failed_rank} died (rc {rc}); respawning on {nproc} "
              f"surviving worker(s) from the last committed "
              f"checkpoint", file=sys.stderr)


def _replay_tail(path: str, rank: int, n: int = 40) -> None:
    try:
        with open(path) as f:
            lines = f.read().splitlines()
        print(f"[launch] last {min(n, len(lines))} lines of rank {rank} "
              f"({path}):", file=sys.stderr)
        for line in lines[-n:]:
            print(f"  [rank {rank}] {line}", file=sys.stderr)
    except OSError:
        pass


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.launch",
        description="multi-process distributed launcher (reference: "
                    "python -m paddle.distributed.launch)")
    ap.add_argument("--nproc", type=int, default=1,
                    help="number of worker processes (trainers)")
    ap.add_argument("--endpoints", default=None,
                    help="comma-separated host:port per rank (default: "
                    "free local ports; rank 0 = coordinator)")
    ap.add_argument("--log-dir", default="launch_logs",
                    help="directory for workerlog.<rank> files (rank 0 "
                    "streams to this terminal)")
    ap.add_argument("--platform", default=None,
                    help="force JAX_PLATFORMS in workers (e.g. cpu for "
                    "multi-process simulation on one host)")
    ap.add_argument("--local-devices", type=int, default=None,
                    help="force N virtual CPU devices per worker (the "
                    "multi-host simulation rig; per-node --gpus analog)")
    ap.add_argument("--timeout", type=float, default=None,
                    help="kill the job after this many seconds")
    ap.add_argument("--grace", type=float, default=30.0,
                    help="seconds workers get to checkpoint and exit "
                    "after a relayed SIGTERM or a peer's death before "
                    "being killed (preemption/fail-fast grace window)")
    ap.add_argument("--elastic", action="store_true",
                    help="respawn the job on the N-1 surviving "
                    "workers (resuming from the last committed "
                    "checkpoint) when a worker dies, instead of dying "
                    "with it")
    ap.add_argument("--max-restarts", type=int, default=None,
                    help="elastic restart budget (default: nproc-1 — "
                    "shrink down to a single worker)")
    ap.add_argument("--min-procs", type=int, default=1,
                    help="never restart with fewer workers than this")
    ap.add_argument("--serve", action="store_true",
                    help="serving bring-up: spawn --nproc serving "
                    "replica workers (+ --prefill-workers) from --spec "
                    "and run the serving_router front end over them")
    ap.add_argument("--spec", default=None,
                    help="--serve: module:function returning each "
                    "replica's serving.BatchedDecoder; also accepts "
                    "the multi-model form name=module:fn,name2=... "
                    "(one replica set + page pool per model)")
    ap.add_argument("--from-artifact", dest="from_artifact",
                    default=None,
                    help="--serve: aot artifact dir (or checkpoint "
                    "root holding aot_step_N) — boot replicas "
                    "trace-free from serialized programs; --spec "
                    "becomes the traced fallback on fingerprint "
                    "mismatch (PT-AOT-601)")
    ap.add_argument("--spec-kw", dest="spec_kw", default=None,
                    help="--serve: JSON kwargs for the spec function")
    ap.add_argument("--prefill-workers", dest="prefill_workers",
                    type=int, default=0,
                    help="--serve: dedicated prefill workers "
                    "(prefill/decode disaggregation; 0 = chunked "
                    "prefill stays the in-replica fallback)")
    ap.add_argument("--port", type=int, default=0,
                    help="--serve: router front-end port (0 = "
                    "ephemeral)")
    ap.add_argument("--trace-sample", dest="trace_sample", type=float,
                    default=None,
                    help="--serve: head-based request-trace sampling "
                    "rate 0..1 (default PT_TRACE_SAMPLE or 1.0); the "
                    "router's /tracez?trace_id= merges each sampled "
                    "request's cross-process timeline")
    ap.add_argument("--dispatch", default="pull",
                    choices=("pull", "push"),
                    help="--serve: pull = replicas pull from the "
                    "central work-stealing dispatch queue (default); "
                    "push = legacy least-loaded placement")
    ap.add_argument("--prefix-hash-tokens", dest="prefix_hash_tokens",
                    type=int, default=64,
                    help="--serve: route by a rolling hash of the "
                    "first N prompt tokens (shared system prompts "
                    "land on one warm replica's prefix cache; 0 "
                    "disables)")
    ap.add_argument("--autoscale", default=None, metavar="MIN,MAX",
                    help="--serve: run the autoscaling control plane "
                    "— the router grows/shrinks its replica fleet "
                    "between MIN and MAX against the measured load "
                    "signals (queue wait, load factor, sheds); "
                    "scale-ups pre-warm from --from-artifact when "
                    "given")
    ap.add_argument("--reliability", action="store_true",
                    help="--serve: turn on the request reliability "
                    "plane — end-to-end deadlines from the SLO "
                    "class, SRE retry budgets, hedged dispatch past "
                    "the adaptive p95, and gray-failure quarantine "
                    "(circuit breaker + half-open probes)")
    ap.add_argument("--deadline-s", dest="deadline_s", type=float,
                    default=None,
                    help="--serve: fixed end-to-end request deadline "
                    "in seconds (implies --reliability; default "
                    "derives per-request budgets from the SLO "
                    "class's target TTFT)")
    ap.add_argument("script", nargs="?", default=None,
                    help="training script to run per rank (omitted "
                    "with --serve)")
    ap.add_argument("script_args", nargs=argparse.REMAINDER,
                    help="arguments passed through to the script")
    args = ap.parse_args(argv)
    if args.serve:
        if not (args.spec or args.from_artifact):
            ap.error("--serve requires --spec module:fn and/or "
                     "--from-artifact DIR")
        import json as _json

        from .serving_router import serve_main

        autoscale = None
        if args.autoscale:
            parts = args.autoscale.split(",")
            if len(parts) != 2:
                ap.error(f"--autoscale must be MIN,MAX, got "
                         f"{args.autoscale!r}")
            autoscale = (int(parts[0]), int(parts[1]))
        reliability = None
        if args.reliability or args.deadline_s is not None:
            from .resilience import ReliabilityConfig

            reliability = ReliabilityConfig(deadline_s=args.deadline_s)
        router = serve_main(
            args.spec, replicas=args.nproc,
            prefill_workers=args.prefill_workers, port=args.port,
            spec_kw=_json.loads(args.spec_kw) if args.spec_kw else None,
            log_dir=args.log_dir, trace_sample=args.trace_sample,
            dispatch=args.dispatch,
            prefix_hash_tokens=args.prefix_hash_tokens or None,
            from_artifact=args.from_artifact,
            autoscale=autoscale, reliability=reliability)
        print(f"[launch] router serving on {router.server.url()} over "
              f"{args.nproc} replica(s) + {args.prefill_workers} "
              f"prefill worker(s)"
              + (f", autoscaling {autoscale[0]}..{autoscale[1]}"
                 if autoscale else "")
              + (", reliability plane on" if reliability else ""),
              file=sys.stderr)
        import threading as _threading

        stop = _threading.Event()
        try:
            signal.signal(signal.SIGTERM, lambda *a: stop.set())
        except ValueError:
            pass  # not the main thread
        try:
            while not stop.wait(0.5):
                pass
        except KeyboardInterrupt:
            pass
        finally:
            scaler = getattr(router, "scaler", None)
            if scaler is not None:
                scaler.stop()  # no scale action may race the close
            router.close(replicas=True)
        return 0
    if not args.script:
        ap.error("script is required (unless --serve)")
    endpoints = (args.endpoints.split(",") if args.endpoints else None)
    return launch(args.script, args.script_args, nproc=args.nproc,
                  endpoints=endpoints, log_dir=args.log_dir,
                  platform=args.platform, timeout=args.timeout,
                  local_devices=args.local_devices, grace=args.grace,
                  elastic=args.elastic, max_restarts=args.max_restarts,
                  min_procs=args.min_procs)


if __name__ == "__main__":
    sys.exit(main())
