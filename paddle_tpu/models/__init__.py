"""Model zoo — the reference's benchmark/book models rebuilt TPU-first
(reference: benchmark/fluid/models/, tests/book/)."""

from . import bert, mnist, transformer

__all__ = ["bert", "mnist", "transformer"]
