"""Model zoo — the reference's benchmark/book models rebuilt TPU-first
(reference: benchmark/fluid/models/, tests/book/)."""

from . import (bert, deepfm, mnist, recommender, resnet, se_resnext,
               stacked_lstm, transformer, vgg)

__all__ = ["bert", "deepfm", "mnist", "recommender", "resnet",
           "se_resnext", "stacked_lstm", "transformer", "vgg"]
