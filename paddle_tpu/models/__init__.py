"""Model zoo — the reference's benchmark/book models rebuilt TPU-first
(reference: benchmark/fluid/models/, tests/book/)."""

from . import (alexnet, bert, deepfm, googlenet, gpt, mnist,
               recommender, resnet, se_resnext, speculative,
               stacked_lstm, transformer, vgg, vit)

__all__ = ["alexnet", "bert", "deepfm", "googlenet", "gpt", "mnist",
           "recommender", "resnet", "se_resnext", "speculative",
           "stacked_lstm", "transformer", "vgg", "vit"]
