"""BERT — BASELINE config 3 (bert-base pretraining: MLM + NSP).

The reference era has no in-tree BERT; this model is the framework's
transformer-encoder flagship, built on nn.transformer with the Pallas flash
attention path and TP-ready parameter names (see parallel/sharding.py rules).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax.numpy as jnp

from .. import nn
from ..metrics import accuracy
from ..nn.transformer import TransformerEncoder
from ..ops import loss as L


@dataclasses.dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position: int = 512
    type_vocab_size: int = 2
    dropout: float = 0.1
    use_flash: bool = True
    # None | 'ring' | 'ulysses' — shard attention over the 'sp' mesh axis
    seq_parallel: Optional[str] = None
    remat: bool = False        # jax.checkpoint per block (HBM for FLOPs)
    remat_policy: Optional[str] = None  # None (save nothing) | "dots"
    # sliding-window/local attention width (None = full; the flash
    # kernel skips out-of-band blocks — O(T*window) long-context mode)
    attn_window: Optional[int] = None
    scan_layers: bool = False  # lax.scan over stacked layers (needs
    #                            dropout == 0 while training)
    # > 0 swaps each block's dense FFN for a Switch-MoE FFN (nn.moe);
    # experts shard over the 'ep' mesh axis, per-layer load-balance aux
    # losses ride functional_call's new_buffers (*.ffn.aux_loss)
    moe_experts: int = 0
    moe_capacity_factor: float = 1.25

    @classmethod
    def base(cls):
        return cls()

    @classmethod
    def tiny(cls):
        """For tests: 2 layers, hidden 64."""
        return cls(vocab_size=1024, hidden_size=64, num_layers=2, num_heads=4,
                   intermediate_size=128, max_position=128, dropout=0.0)

    @classmethod
    def moe_smoke(cls, layers: int = 4):
        """The ONE bert_moe smoke configuration shared by the test suite
        and the multichip dryrun (capacity 2.0 keeps routing drops out of
        loss-match tolerances) — tune it in one place."""
        return cls(vocab_size=256, hidden_size=64, num_layers=layers,
                   num_heads=4, intermediate_size=128, max_position=32,
                   dropout=0.0, moe_experts=4, moe_capacity_factor=2.0)


class BertEmbeddings(nn.Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.tok = nn.Embedding(cfg.vocab_size, cfg.hidden_size)
        self.pos = nn.Embedding(cfg.max_position, cfg.hidden_size)
        self.seg = nn.Embedding(cfg.type_vocab_size, cfg.hidden_size)
        self.norm = nn.LayerNorm(cfg.hidden_size)
        self.drop = nn.Dropout(cfg.dropout)

    def forward(self, input_ids, token_type_ids=None, position_ids=None):
        t = input_ids.shape[1]
        if position_ids is None:
            position_ids = jnp.arange(t)[None, :]
        x = self.tok(input_ids) + self.pos(position_ids)
        if token_type_ids is not None:
            x = x + self.seg(token_type_ids)
        return self.drop(self.norm(x))


class BertModel(nn.Layer):
    def __init__(self, cfg: Optional[BertConfig] = None):
        super().__init__()
        self.cfg = cfg = cfg or BertConfig.base()
        self.embeddings = BertEmbeddings(cfg)
        self.encoder = TransformerEncoder(
            cfg.num_layers, cfg.hidden_size, cfg.num_heads,
            cfg.intermediate_size, cfg.dropout, activation="gelu",
            normalize_before=False, use_flash=cfg.use_flash,
            seq_parallel=cfg.seq_parallel, remat=cfg.remat,
            remat_policy=cfg.remat_policy,
            scan_layers=cfg.scan_layers, attn_window=cfg.attn_window,
            moe_experts=cfg.moe_experts,
            moe_capacity_factor=cfg.moe_capacity_factor)
        self.pooler = nn.Linear(cfg.hidden_size, cfg.hidden_size, act="tanh")

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                position_ids=None, segment_ids=None):
        """``segment_ids``/``position_ids``: the PACKED-batch form
        (data.bucketing.pack_sequences) — attention confined to each
        packed segment, positions restarting per segment."""
        x = self.embeddings(input_ids, token_type_ids, position_ids)
        mask = None
        if attention_mask is not None:
            # (B, T) keep-mask → broadcastable (B, 1, 1, T)
            mask = attention_mask[:, None, None, :]
        h = self.encoder(x, mask=mask, segment_ids=segment_ids)
        pooled = self.pooler(h[:, 0])
        return h, pooled


class BertForPretraining(nn.Layer):
    """MLM head (tied decoder weight not required for parity) + NSP head."""

    def __init__(self, cfg: Optional[BertConfig] = None):
        super().__init__()
        cfg = cfg or BertConfig.base()
        self.bert = BertModel(cfg)
        self.mlm_transform = nn.Linear(cfg.hidden_size, cfg.hidden_size,
                                       act="gelu")
        self.mlm_norm = nn.LayerNorm(cfg.hidden_size)
        self.mlm_decoder = nn.Linear(cfg.hidden_size, cfg.vocab_size)
        self.nsp = nn.Linear(cfg.hidden_size, 2)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        h, pooled = self.bert(input_ids, token_type_ids, attention_mask)
        mlm_logits = self.mlm_decoder(self.mlm_norm(self.mlm_transform(h)))
        nsp_logits = self.nsp(pooled)
        return mlm_logits, nsp_logits

    def forward_fused_loss(self, input_ids, mlm_labels, nsp_label,
                           token_type_ids=None, attention_mask=None,
                           vocab_chunk: int = 4096):
        """Pretrain loss WITHOUT materializing (B, T, V) logits: the MLM
        head goes through ops.fused_loss.linear_cross_entropy (chunked
        vocab scan — the HBM hot spot of MLM training; fused_loss.py
        docstring has the numbers)."""
        from ..core.dtypes import get_policy
        from ..ops.fused_loss import mean_linear_cross_entropy

        h, pooled = self.bert(input_ids, token_type_ids, attention_mask)
        h_mlm = self.mlm_norm(self.mlm_transform(h))
        b, t, d = h_mlm.shape
        # the vocab matmuls honor the AMP compute dtype (bf16 on the MXU),
        # exactly like the Linear head they replace; the op's logsumexp
        # accumulators stay fp32 internally
        pol = get_policy()
        mlm_loss = mean_linear_cross_entropy(
            pol.cast_to_compute(h_mlm.reshape(b * t, d)),
            pol.cast_to_compute(self.mlm_decoder.weight),
            pol.cast_to_compute(self.mlm_decoder.bias),
            mlm_labels.reshape(-1), chunk=vocab_chunk, ignore_index=-100)
        nsp_logits = self.nsp(pooled)
        nsp_loss = jnp.mean(L.softmax_with_cross_entropy(nsp_logits,
                                                         nsp_label))
        return mlm_loss + nsp_loss

    def forward_packed_loss(self, tokens, positions, segment_ids,
                            mlm_labels, vocab_chunk: int = 4096):
        """MLM loss over a PACKED batch (data.bucketing.pack_sequences
        layout: multiple sequences per row, segment id 0 = padding tail).
        Attention is confined to each segment via the Pallas packed-batch
        path, positions restart per segment, and padding tokens are
        excluded from the loss (ignore_index). NSP is skipped — a packed
        row holds many unrelated documents, so next-sentence pairing has
        no meaning there."""
        from ..core.dtypes import get_policy
        from ..ops.fused_loss import mean_linear_cross_entropy

        h, _ = self.bert(tokens, position_ids=positions,
                         segment_ids=segment_ids)
        h_mlm = self.mlm_norm(self.mlm_transform(h))
        b, t, d = h_mlm.shape
        labels = jnp.where(segment_ids > 0, mlm_labels, -100)
        pol = get_policy()
        return mean_linear_cross_entropy(
            pol.cast_to_compute(h_mlm.reshape(b * t, d)),
            pol.cast_to_compute(self.mlm_decoder.weight),
            pol.cast_to_compute(self.mlm_decoder.bias),
            labels.reshape(-1), chunk=vocab_chunk, ignore_index=-100)


def pretrain_loss(outputs, labels):
    """labels: dict(mlm_labels (B,T) with -100 = unmasked, nsp_label (B,))."""
    mlm_logits, nsp_logits = outputs
    mlm_labels = labels["mlm_labels"]
    valid = (mlm_labels >= 0)
    safe_labels = jnp.where(valid, mlm_labels, 0)
    tok_loss = L.softmax_with_cross_entropy(mlm_logits,
                                            safe_labels).squeeze(-1)
    mlm_loss = jnp.sum(tok_loss * valid) / jnp.maximum(jnp.sum(valid), 1)
    nsp_loss = jnp.mean(
        L.softmax_with_cross_entropy(nsp_logits, labels["nsp_label"]))
    return mlm_loss + nsp_loss


def pretrain_metrics(outputs, labels):
    mlm_logits, nsp_logits = outputs
    valid = (labels["mlm_labels"] >= 0)
    pred = jnp.argmax(mlm_logits, -1)
    mlm_acc = jnp.sum((pred == labels["mlm_labels"]) * valid) / \
        jnp.maximum(jnp.sum(valid), 1)
    return {"mlm_acc": mlm_acc,
            "nsp_acc": accuracy(nsp_logits, labels["nsp_label"])}
