"""Decoder-only causal LM (GPT/Llama-style) — the modern long-context
flagship workload, assembled from this framework's own pieces: RoPE
(ops.attention.rotary_embedding), GQA MultiHeadAttention on the Pallas
flash path, RMSNorm pre-norm blocks, SwiGLU (or Switch-MoE) FFNs,
KV-cached greedy decode, and a fused linear-CE training head.

Green-field relative to the reference (its transformer story is the
encoder-decoder NMT model, reference:
benchmark/fluid/models/machine_translation.py); this family exists so a
user scaling a decoder LM finds the whole recipe — causal flash
attention, sequence parallelism (seq_parallel='ring' supports GQA),
pipeline-able uniform blocks, MoE FFNs — in one model.

Geometry notes (TPU-first): head_dim 64/128 keeps the flash dispatch
gate open; hidden sizes stay multiples of 128 for MXU tiling; the block
is uniform h -> h so parallel.pipeline_apply and scan_layers both apply.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from .. import initializer as I
from .. import nn
from ..core.enforce import enforce
from ..nn.layer import Layer


@dataclasses.dataclass
class GPTConfig:
    vocab_size: int = 32000
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    num_kv_heads: Optional[int] = None   # < num_heads = GQA/MQA
    intermediate_size: int = 2048        # SwiGLU width
    max_position: int = 2048             # decode-cache capacity default
    rope_theta: float = 10000.0
    dropout: float = 0.0                 # residual/FFN dropout
    use_flash: bool = True
    remat: bool = False                  # jax.checkpoint per block
    # None | 'ring' | 'ulysses' — shard attention over the 'sp' axis
    # (ring supports GQA; see parallel.context_parallel)
    seq_parallel: Optional[str] = None
    attn_window: Optional[int] = None    # sliding-window local attention
    moe_experts: int = 0                 # > 0: Switch-MoE FFN over 'ep'
    moe_capacity_factor: float = 1.25
    tie_embeddings: bool = True          # LM head = embedding^T

    @classmethod
    def tiny(cls):
        """For tests: 2 layers, hidden 128, GQA 4q/2kv, head_dim 32."""
        return cls(vocab_size=512, hidden_size=128, num_layers=2,
                   num_heads=4, num_kv_heads=2, intermediate_size=256,
                   max_position=128)

    @classmethod
    def small(cls):
        """A llama-ish small config: head_dim 64 (flash-eligible)."""
        return cls(vocab_size=32000, hidden_size=768, num_layers=12,
                   num_heads=12, num_kv_heads=4, intermediate_size=2048,
                   max_position=2048)


class _SwiGLU(Layer):
    """Gated FFN: down(silu(gate(x)) * up(x)) — the Llama MLP."""

    def __init__(self, d_model: int, d_ff: int, dropout: float = 0.0):
        super().__init__()
        self.gate = nn.Linear(d_model, d_ff, bias_attr=False)
        self.up = nn.Linear(d_model, d_ff, bias_attr=False)
        self.down = nn.Linear(d_ff, d_model, bias_attr=False)
        self.drop = nn.Dropout(dropout)

    def forward(self, x):
        return self.drop(self.down(jax.nn.silu(self.gate(x)) * self.up(x)))


class GPTBlock(Layer):
    """Pre-norm decoder block: x + attn(rms(x)); x + ffn(rms(x)).
    Uniform h -> h (pipeline_apply / scan_layers compatible)."""

    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.attn_window = cfg.attn_window
        self.norm1 = nn.RMSNorm(cfg.hidden_size)
        self.self_attn = nn.MultiHeadAttention(
            cfg.hidden_size, cfg.num_heads, dropout=cfg.dropout,
            bias=False, use_flash=cfg.use_flash,
            seq_parallel=cfg.seq_parallel,
            num_kv_heads=cfg.num_kv_heads or cfg.num_heads,
            rotary=True, rotary_theta=cfg.rope_theta)
        self.norm2 = nn.RMSNorm(cfg.hidden_size)
        if cfg.moe_experts:
            self.ffn = nn.SwitchFFN(
                cfg.hidden_size, cfg.intermediate_size, cfg.moe_experts,
                capacity_factor=cfg.moe_capacity_factor)
        else:
            self.ffn = _SwiGLU(cfg.hidden_size, cfg.intermediate_size,
                               cfg.dropout)
        self.drop = nn.Dropout(cfg.dropout)

    def forward(self, x, kv_mask=None):
        x = x + self.drop(self.self_attn(
            self.norm1(x), causal=True, window=self.attn_window,
            attn_mask=None if kv_mask is None
            else kv_mask[:, None, None, :]))
        return x + self.ffn(self.norm2(x))


class GPTForCausalLM(Layer):
    """Token embedding -> N GPTBlocks -> final RMSNorm -> LM head.

    ``forward(ids)`` returns (B, T, V) logits (tied head when
    cfg.tie_embeddings). ``forward_loss(ids, labels)`` is the training
    entry: next-token shift + fused chunked linear-CE (the logits
    matrix never materializes; ops/fused_loss.py). ``greedy_decode``
    runs the KV-cached incremental loop (RoPE applied at each cache
    position — MultiHeadAttention.forward_step).
    """

    def __init__(self, cfg: GPTConfig):
        super().__init__()
        enforce((cfg.hidden_size // cfg.num_heads) % 2 == 0,
                "rotary needs an even head_dim, got %s",
                cfg.hidden_size // cfg.num_heads)
        self.cfg = cfg
        self.embed = nn.Embedding(cfg.vocab_size, cfg.hidden_size)
        self.blocks = nn.LayerList([GPTBlock(cfg)
                                    for _ in range(cfg.num_layers)])
        self.norm_f = nn.RMSNorm(cfg.hidden_size)
        if not cfg.tie_embeddings:
            self.create_parameter(
                "lm_head", (cfg.hidden_size, cfg.vocab_size), None,
                I.XavierUniform())

    def _head_weight(self):
        return (self.embed.weight.T if self.cfg.tie_embeddings
                else self.lm_head)

    def _trunk(self, ids, kv_mask=None):
        x = self.embed(ids)
        for blk in self.blocks:
            if self.cfg.remat:
                x = jax.checkpoint(
                    lambda h, b=blk: b(h, kv_mask=kv_mask))(x)
            else:
                x = blk(x, kv_mask=kv_mask)
        return self.norm_f(x)

    def forward(self, ids, kv_mask=None):
        h = self._trunk(ids, kv_mask=kv_mask)
        return h @ self._head_weight()

    def forward_loss(self, ids, labels=None, kv_mask=None,
                     vocab_chunk: int = 1024, ignore_index: int = -100):
        """Mean next-token CE. ``labels`` default to ids shifted left
        (standard causal-LM training); pass explicit labels with
        ``ignore_index`` holes for masked/padded positions."""
        from ..ops.fused_loss import mean_linear_cross_entropy

        h = self._trunk(ids, kv_mask=kv_mask)
        if labels is None:
            labels = jnp.concatenate(
                [ids[:, 1:],
                 jnp.full((ids.shape[0], 1), ignore_index, ids.dtype)],
                axis=1)
        b, t, d = h.shape
        w = self._head_weight()
        return mean_linear_cross_entropy(
            h.reshape(b * t, d), w, None, labels.reshape(-1),
            chunk=vocab_chunk, ignore_index=ignore_index)

    def _cached_blocks(self, x, caches, attn_step, head: bool = True):
        """ONE definition of the cached-decode block composition
        (norm1 -> attn -> residual -> ffn -> norm_f@head) shared by the
        chunk, single-step, and per-row-cursor entries — the attention
        flavor is the only thing that varies. ``head=False`` skips the
        (S, V) head projection (cache-only prefill; XLA would DCE the
        dead matmul under jit, but eager callers pay it for real)."""
        new_caches = []
        for blk, (ck, cv) in zip(self.blocks, caches):
            h = blk.norm1(x)
            a, ck, cv = attn_step(blk.self_attn, h, ck, cv)
            x = x + a
            x = x + blk.ffn(blk.norm2(x))
            new_caches.append((ck, cv))
        if not head:
            return None, new_caches
        return self.norm_f(x) @ self._head_weight(), new_caches

    def _chunk_logits(self, toks, caches, t0, head: bool = True,
                      decode_kernel: bool = False):
        """S KV-cached positions in one pass: embed ``toks`` (B, S), run
        every block's forward_chunk at cache indices [t0, t0+S), return
        ((B, S, V) logits, new caches). The speculative-decoding target
        scores its gamma+1 candidates with one call."""
        return self._cached_blocks(
            self.embed(toks), caches,
            lambda sa, h, ck, cv: sa.forward_chunk(
                h, ck, cv, t0, window=self.cfg.attn_window,
                decode_kernel=decode_kernel),
            head=head)

    def _step_logits(self, tok, caches, t, decode_kernel: bool = False):
        """One KV-cached position: ``tok`` (B,) -> ((B, V), caches)."""
        logits, caches = self._chunk_logits(
            tok[:, None], caches, t, decode_kernel=decode_kernel)
        return logits[:, 0], caches

    def _step_logits_rows(self, tok, caches, t_rows,
                          decode_kernel: bool = False):
        """One KV-cached position PER ROW at per-row cursors ``t_rows``
        (B,) — the continuous-batching step (serving.BatchedDecoder).
        ``tok`` (B,) -> ((B, V) logits, caches)."""
        logits, caches = self._cached_blocks(
            self.embed(tok[:, None]), caches,
            lambda sa, h, ck, cv: sa.forward_step_rows(
                h, ck, cv, t_rows, window=self.cfg.attn_window,
                decode_kernel=decode_kernel))
        return logits[:, 0], caches

    def _chunk_logits_rows(self, toks, caches, t0_rows):
        """S KV-cached positions PER ROW at per-row chunk starts
        ``t0_rows`` (B,) — the arena speculative verify: every slot
        scores its gamma+1 candidates at its OWN cursor in ONE pass.
        ``toks`` (B, S) -> ((B, S, V) logits, caches)."""
        return self._cached_blocks(
            self.embed(toks), caches,
            lambda sa, h, ck, cv: sa.forward_chunk_rows(
                h, ck, cv, t0_rows, window=self.cfg.attn_window))

    def _chunk_logits_paged_rows(self, toks, pools, table, t0_rows):
        """S positions PER ROW against PAGED caches at per-row chunk
        starts (see _chunk_logits_rows). ``toks`` (B, S)."""
        return self._cached_blocks(
            self.embed(toks), pools,
            lambda sa, h, kp, vp: sa.forward_chunk_paged_rows(
                h, kp, vp, table, t0_rows,
                window=self.cfg.attn_window))

    def _step_logits_paged(self, tok, pools, table, t_rows):
        """One position PER ROW against PAGED caches: ``pools`` is the
        per-block [(kpool, vpool), ...] list, ``table`` the shared
        (B, n_log) page table. ``tok`` (B,) -> ((B, V) logits, pools)."""
        logits, pools = self._cached_blocks(
            self.embed(tok[:, None]), pools,
            lambda sa, h, kp, vp: sa.forward_step_paged(
                h, kp, vp, table, t_rows,
                window=self.cfg.attn_window))
        return logits[:, 0], pools

    def _chunk_logits_paged(self, toks, pools, table_row, t0,
                            head: bool = True):
        """S prefill positions for ONE row against paged caches (see
        _step_logits_paged). ``toks`` (1, S)."""
        return self._cached_blocks(
            self.embed(toks), pools,
            lambda sa, h, kp, vp: sa.forward_chunk_paged(
                h, kp, vp, table_row, t0,
                window=self.cfg.attn_window),
            head=head)

    def generate(self, prompt_ids, max_len: int, *, key=None,
                 temperature: float = 1.0, top_k: int = 0,
                 top_p: float = 1.0, eos_id: Optional[int] = None,
                 capacity: Optional[int] = None):
        """KV-cached continuation of ``prompt_ids`` (B, Tp) to total
        length ``max_len``; returns (B, max_len) token ids.

        ``temperature == 0`` is exact greedy (argmax, no key needed);
        otherwise tokens are drawn via ops.sampling.sample_from_logits
        (temperature scaling, then top-k, then nucleus top-p), with a
        per-position key derived by ``fold_in`` so the draw stream is
        independent of batch size and prompt length. ``eos_id`` freezes
        a row once it emits eos (every later token is eos_id).

        O(T) per step via per-block K/V caches; RoPE rotates each
        cached K at its absolute position and each query at its own.
        Green-field vs the reference (its decoding story is beam search
        over the NMT encoder-decoder, reference:
        benchmark/fluid/models/machine_translation.py)."""
        from jax import lax

        from ..ops.sampling import sample_from_logits

        enforce(not self.training,
                "generate runs in eval mode (call .eval()); live "
                "dropout would break the token-identical-to-forward "
                "contract")
        b, tp = prompt_ids.shape
        cap = capacity or max(self.cfg.max_position, max_len)
        enforce(max_len > tp, "max_len %s must exceed prompt %s",
                max_len, tp)
        enforce(cap >= max_len, "cache capacity %s < max_len %s", cap,
                max_len)
        sampled = float(temperature) != 0.0
        if sampled:
            enforce(key is not None,
                    "temperature > 0 samples and needs a PRNG key; "
                    "pass temperature=0 for greedy decoding")
        caches = [blk.self_attn.init_cache(b, cap)
                  for blk in self.blocks]

        # prefill: teacher-force the prompt through the step loop (the
        # scan keeps ONE compiled block body for prefill + generation)
        tokens = jnp.concatenate(
            [prompt_ids,
             jnp.zeros((b, max_len - tp), prompt_ids.dtype)], axis=1)

        def scan_step(carry, t):
            tok_prev, caches, done = carry
            # the flash-decode kernel masks pos <= t in-kernel and reads
            # only live cache blocks (eligible shapes; XLA mask path
            # otherwise) — safe here: generate() never runs under vmap
            logits, caches = self._step_logits(tok_prev, caches, t,
                                               decode_kernel=True)
            if sampled:
                nxt = sample_from_logits(
                    logits, jax.random.fold_in(key, t), temperature,
                    top_k, top_p)
            else:
                nxt = jnp.argmax(logits, axis=-1)
            nxt = nxt.astype(prompt_ids.dtype)
            if eos_id is not None:
                nxt = jnp.where(done, jnp.asarray(eos_id, nxt.dtype),
                                nxt)
            # while still inside the prompt, feed the real next token
            inside = t + 1 < tp
            forced = lax.dynamic_index_in_dim(
                tokens, jnp.clip(t + 1, 0, max_len - 1), 1,
                keepdims=False)
            tok = jnp.where(inside, forced, nxt)
            if eos_id is not None:
                done = done | ((tok == eos_id) & jnp.logical_not(inside))
            return (tok, caches, done), tok

        (_, _, _), outs = lax.scan(
            scan_step,
            (tokens[:, 0], caches, jnp.zeros((b,), bool)),
            jnp.arange(max_len - 1))
        outs = jnp.swapaxes(outs, 0, 1)           # (B, max_len - 1)
        return jnp.concatenate([tokens[:, :1], outs], axis=1)

    def greedy_decode(self, prompt_ids, max_len: int,
                      capacity: Optional[int] = None):
        """KV-cached greedy continuation — generate(temperature=0)."""
        return self.generate(prompt_ids, max_len, temperature=0.0,
                             capacity=capacity)


def loss_fn(logits, labels, ignore_index: int = -100):
    """Plain (unfused) next-token CE over (B, T, V) logits — the test
    oracle for forward_loss."""
    b, t, v = logits.shape
    flat = logits.reshape(b * t, v).astype(jnp.float32)
    lbl = labels.reshape(-1)
    keep = lbl != ignore_index
    lp = jax.nn.log_softmax(flat)
    picked = jnp.take_along_axis(
        lp, jnp.clip(lbl, 0, v - 1)[:, None], axis=1)[:, 0]
    return -jnp.sum(jnp.where(keep, picked, 0.0)) / jnp.maximum(
        jnp.sum(keep), 1)
