"""SE-ResNeXt — reference: benchmark/fluid/models/se_resnext.py zoo entry
(also the reference's distributed regression model, tests/unittests/
dist_se_resnext.py). Grouped-conv bottleneck (cardinality 32) + squeeze-
and-excitation gating, built from framework layers."""

from __future__ import annotations

import jax.numpy as jnp

from .. import nn
from ..ops import loss as L
from .resnet import ResNet, _conv_bn


class SEBlock(nn.Layer):
    """Squeeze-excitation: global pool → bottleneck MLP → sigmoid scale."""

    def __init__(self, ch: int, reduction: int = 16,
                 data_format: str = "NCHW"):
        super().__init__()
        self.fc1 = nn.Linear(ch, max(ch // reduction, 4), act="relu")
        self.fc2 = nn.Linear(max(ch // reduction, 4), ch, act="sigmoid")
        self.data_format = data_format

    def forward(self, x):
        spatial = (2, 3) if self.data_format == "NCHW" else (1, 2)
        s = self.fc2(self.fc1(jnp.mean(x, axis=spatial)))  # (N, C)
        if self.data_format == "NCHW":
            return x * s[:, :, None, None]
        return x * s[:, None, None, :]


class SEBottleneck(nn.Layer):
    expansion = 2  # ResNeXt-style wide bottleneck

    def __init__(self, in_ch: int, ch: int, stride: int = 1,
                 cardinality: int = 32, reduction: int = 16,
                 data_format: str = "NCHW", **_):
        super().__init__()
        width = ch * 2
        out_ch = ch * self.expansion * 2
        df = data_format
        self.conv1 = _conv_bn(in_ch, width, 1, data_format=df)
        self.conv2 = _conv_bn(width, width, 3, stride=stride,
                              groups=cardinality, data_format=df)
        self.conv3 = _conv_bn(width, out_ch, 1, act=None, data_format=df)
        self.se = SEBlock(out_ch, reduction, data_format=df)
        self.short = (None if in_ch == out_ch and stride == 1
                      else _conv_bn(in_ch, out_ch, 1, stride=stride,
                                    act=None, data_format=df))

    def forward(self, x):
        y = self.se(self.conv3(self.conv2(self.conv1(x))))
        s = x if self.short is None else self.short(x)
        return jnp.maximum(y + s, 0.0)


class SEResNeXt(nn.Layer):
    """``data_format="NHWC"`` is the TPU-native layout (channels on the
    128-lane minor dim; no boundary transposes) — the bench default."""

    def __init__(self, depths=(3, 4, 6, 3), num_classes: int = 1000,
                 in_ch: int = 3, cardinality: int = 32,
                 data_format: str = "NCHW"):
        super().__init__()
        df = data_format
        self.data_format = df
        self.stem = _conv_bn(in_ch, 64, 7, stride=2, data_format=df)
        self.maxpool = nn.Pool2D(3, "max", stride=2, padding=1,
                                 data_format=df)
        widths = [64, 128, 256, 512]
        blocks = []
        cur = 64
        for stage, (w, n) in enumerate(zip(widths, depths)):
            for i in range(n):
                stride = 2 if (i == 0 and stage > 0) else 1
                blocks.append(SEBottleneck(cur, w, stride=stride,
                                           cardinality=cardinality,
                                           data_format=df))
                cur = w * SEBottleneck.expansion * 2
        self.blocks = nn.LayerList(blocks)
        self.head = nn.Linear(cur, num_classes)

    def forward(self, x):
        if self.data_format == "NHWC":
            x = jnp.transpose(x, (0, 2, 3, 1))  # accept NCHW inputs
        x = self.maxpool(self.stem(x))
        for blk in self.blocks:
            x = blk(x)
        spatial = (2, 3) if self.data_format == "NCHW" else (1, 2)
        return self.head(jnp.mean(x, axis=spatial))


def se_resnext50(num_classes: int = 1000, **kw) -> SEResNeXt:
    return SEResNeXt((3, 4, 6, 3), num_classes, **kw)


def loss_fn(logits, labels):
    return jnp.mean(L.softmax_with_cross_entropy(logits, labels))
