"""Speculative decoding: a small draft model proposes gamma tokens
autoregressively, the target model scores all gamma+1 positions in ONE
KV-cached forward_chunk, and a modified rejection test accepts a prefix
— the output distribution is EXACTLY the target model's (the
Leviathan/Chen 2023 construction), at a fraction of the target's
sequential steps whenever the draft agrees often.

TPU-first shape: every round does fixed-shape work (gamma draft steps +
one (gamma+1)-token target chunk), so the whole loop is one compiled
lax.while_loop; per-row progress is independent (each row accepts a
different prefix length), handled by vmapping a single-row loop over
the batch — cache writes at per-row dynamic offsets stay plain
dynamic_update_slice under the vmap. Rejected positions leave stale K/V
above the row's cursor; they are masked out by the <= t attention mask
and overwritten before ever becoming visible.

Green-field vs the reference (its decoding story is beam search over
the NMT encoder-decoder, reference:
benchmark/fluid/models/machine_translation.py, and the beam-search ops
paddle/fluid/operators/beam_search_op.cc); this is the modern
LM-serving analog of that "decode faster than one token per model
call" capability.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..core.enforce import enforce
from ..ops.sampling import filter_logits


def speculative_generate(target, draft, prompt_ids, max_len: int, *,
                         gamma: int = 4, key=None,
                         temperature: float = 1.0, top_k: int = 0,
                         top_p: float = 1.0,
                         eos_id: Optional[int] = None,
                         capacity: Optional[int] = None,
                         return_stats: bool = False):
    """Continue ``prompt_ids`` (B, Tp) to (B, max_len) token ids,
    drawing from the TARGET model's (filtered) distribution while
    running most positions through ``draft``.

    ``temperature == 0`` is exact greedy: the result is token-identical
    to ``target.greedy_decode`` (accepted drafts are exactly the
    positions where the two argmaxes agree). Otherwise tokens are
    provably distributed as target sampling with the same
    temperature/top_k/top_p chain. ``eos_id`` stops a row once emitted
    and fills the remainder of the row with eos.

    With ``return_stats`` also returns a dict with per-row
    ``accepted_drafts`` and ``rounds`` (mean accepted/round =
    gamma * acceptance rate; tokens per target call = 1 + that).

    Both models must share the vocabulary; draft quality only affects
    speed, never the output distribution.
    """
    enforce(gamma >= 1, "gamma must be >= 1, got %s", gamma)
    enforce(not target.training and not draft.training,
            "speculative_generate runs in eval mode (call .eval())")
    enforce(target.cfg.vocab_size == draft.cfg.vocab_size,
            "vocab mismatch: target %s vs draft %s",
            target.cfg.vocab_size, draft.cfg.vocab_size)
    b, tp = prompt_ids.shape
    enforce(max_len > tp, "max_len %s must exceed prompt %s", max_len,
            tp)
    cap = capacity or max(target.cfg.max_position, max_len + gamma)
    enforce(cap >= max_len + gamma,
            "cache capacity %s < max_len + gamma = %s (target chunk "
            "writes run past max_len on the last round)", cap,
            max_len + gamma)
    sampled = float(temperature) != 0.0
    if sampled:
        enforce(key is not None,
                "temperature > 0 samples and needs a PRNG key; "
                "pass temperature=0 for greedy decoding")
    else:
        key = jax.random.key(0)  # never consumed; uniform row signature
    # buffer padded past max_len so the (gamma+1)-token write of the
    # final round never clamps backward over valid tokens
    buf_len = max_len + gamma + 1

    def _filtered_logprobs(logits):
        return jax.nn.log_softmax(
            filter_logits(logits, temperature, top_k, top_p), axis=-1)

    def one_row(prompt_row, rkey):
        tokens = jnp.zeros((buf_len,), prompt_ids.dtype)
        tokens = lax.dynamic_update_slice(tokens, prompt_row, (0,))

        caches_t = [blk.self_attn.init_cache(1, cap)
                    for blk in target.blocks]
        caches_d = [blk.self_attn.init_cache(1, cap)
                    for blk in draft.blocks]
        # prefill caches for positions [0, tp-1): the main loop refeeds
        # the token at t-1 through BOTH models, so position tp-1 (and
        # later) is always cached by the loop itself
        if tp > 1:
            _, caches_t = target._chunk_logits(
                prompt_row[None, :tp - 1], caches_t, 0, head=False)
            _, caches_d = draft._chunk_logits(
                prompt_row[None, :tp - 1], caches_d, 0, head=False)

        def cond(carry):
            t, done = carry[1], carry[-1]
            return jnp.logical_and(t < max_len, jnp.logical_not(done))

        def body(carry):
            tokens, t, caches_t, caches_d, rnd, acc, rounds, done = carry
            last = lax.dynamic_slice(tokens, (t - 1,), (1,))    # (1,)

            def draft_step(c, i):
                tok, caches = c
                logits, caches = draft._step_logits(
                    tok[None], caches, t - 1 + i)               # (1, V)
                if sampled:
                    log_q = _filtered_logprobs(logits[0])       # (V,)
                    ki = jax.random.fold_in(
                        jax.random.fold_in(rkey, rnd), i)
                    d = jax.random.categorical(ki, log_q)
                else:
                    log_q = jnp.zeros((logits.shape[-1],),
                                      jnp.float32)
                    d = jnp.argmax(logits[0], axis=-1)
                d = d.astype(tokens.dtype)
                return (d, caches), (d, jnp.exp(log_q))

            (_, caches_d), (drafts, q_all) = lax.scan(
                draft_step, (last[0], caches_d), jnp.arange(gamma))
            # also cache d_{gamma-1}'s K/V at t+gamma-1 (logits unused):
            # on a fully-accepted round the cursor jumps past that
            # position and no later write covers it — a zero K row
            # there would be attended (logit 0) by every later draft
            # query, silently degrading acceptance. For n < gamma the
            # position is >= the new cursor and the next round's writes
            # overwrite it before any query attends it.
            _, caches_d = draft._step_logits(
                drafts[-1][None], caches_d, t - 1 + gamma)

            # target scores [last, d_0..d_{gamma-1}] in one chunk:
            # logits for positions t..t+gamma
            chunk = jnp.concatenate([last, drafts])[None]  # (1, gamma+1)
            logits_t, caches_t = target._chunk_logits(
                chunk, caches_t, t - 1)

            if sampled:
                p_all = jnp.exp(_filtered_logprobs(logits_t[0]))
                idx = jnp.arange(gamma)
                pi = p_all[idx, drafts]
                qi = q_all[idx, drafts]
                ku = jax.random.fold_in(
                    jax.random.fold_in(rkey, rnd), gamma)
                u = jax.random.uniform(ku, (gamma,))
                accept = u * qi < pi          # u < p/q without the /0
                n = jnp.sum(jnp.cumprod(accept.astype(jnp.int32)))
                # correction: residual max(p_n - q_n, 0) normalized; at
                # n == gamma q is all-zero so this IS the bonus draw
                # from p_gamma
                p_n = p_all[n]
                q_n = jnp.where(n < gamma,
                                q_all[jnp.minimum(n, gamma - 1)], 0.0)
                res = jnp.clip(p_n - q_n, 0.0, None)
                norm = jnp.sum(res)
                res = jnp.where(norm > 0, res / norm, p_n)
                kc = jax.random.fold_in(
                    jax.random.fold_in(rkey, rnd), gamma + 1)
                corr = jax.random.categorical(
                    kc, jnp.where(res > 0, jnp.log(res), -jnp.inf))
            else:
                tgt = jnp.argmax(logits_t[0], axis=-1)  # (gamma+1,)
                accept = drafts == tgt[:gamma].astype(drafts.dtype)
                n = jnp.sum(jnp.cumprod(accept.astype(jnp.int32)))
                corr = tgt[n]
            corr = corr.astype(tokens.dtype)

            slot = jnp.arange(gamma + 1)
            emitted = jnp.where(
                slot < n, jnp.concatenate([drafts, drafts[-1:]]),
                jnp.where(slot == n, corr, 0)).astype(tokens.dtype)
            tokens = lax.dynamic_update_slice(tokens, emitted, (t,))
            t_new = t + n + 1
            if eos_id is not None:
                done = done | jnp.any((emitted == eos_id) & (slot <= n))
            done = done | (t_new >= max_len)
            return (tokens, t_new, caches_t, caches_d, rnd + 1,
                    acc + n, rounds + 1, done)

        init = (tokens, jnp.asarray(tp, jnp.int32), caches_t, caches_d,
                jnp.asarray(0, jnp.int32), jnp.asarray(0, jnp.int32),
                jnp.asarray(0, jnp.int32), jnp.asarray(False))
        tokens, _, _, _, _, acc, rounds, _ = lax.while_loop(
            cond, body, init)
        out = tokens[:max_len]
        if eos_id is not None:
            pos = jnp.arange(max_len)
            hit = (out == eos_id) & (pos >= tp)
            first = jnp.argmax(hit)
            out = jnp.where(jnp.any(hit) & (pos > first),
                            jnp.asarray(eos_id, out.dtype), out)
        return out, acc, rounds

    row_keys = jax.vmap(lambda i: jax.random.fold_in(key, i))(
        jnp.arange(b))
    out, acc, rounds = jax.vmap(one_row)(prompt_ids, row_keys)
    if return_stats:
        return out, {"accepted_drafts": acc, "rounds": rounds}
    return out
