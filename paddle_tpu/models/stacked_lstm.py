"""Stacked dynamic LSTM sentiment model — BASELINE bench model
(reference: benchmark/fluid/models/stacked_dynamic_lstm.py — IMDB word ids →
embedding → [fc → lstm → max-pools] x N → concat pooled states → fc →
softmax over 2 classes; the reference's dynamic LoD batches become padded
(B, T) + lengths here, ops/sequence.py).
"""

from __future__ import annotations

import jax.numpy as jnp

from .. import nn
from ..metrics import accuracy
from ..ops import loss as L
from ..ops import rnn as R
from ..ops.sequence import sequence_mask


class StackedLSTM(nn.Layer):
    def __init__(self, vocab_size: int = 5149, embed_dim: int = 512,
                 hidden_dim: int = 512, num_layers: int = 3,
                 num_classes: int = 2, scan_unroll: int = 1):
        super().__init__()
        self.embedding = nn.Embedding(vocab_size, embed_dim)
        self.num_layers = num_layers
        for i in range(num_layers):
            in_dim = embed_dim if i == 0 else hidden_dim
            self.add_sublayer(f"fc{i}", nn.Linear(in_dim, hidden_dim))
            self.add_sublayer(f"lstm{i}", nn.LSTM(hidden_dim, hidden_dim,
                                                  scan_unroll=scan_unroll))
        self.out = nn.Linear(2 * hidden_dim, num_classes)

    def forward(self, ids, lengths):
        h = self.embedding(ids)  # (B, T, E)
        t = ids.shape[1]
        neg = jnp.asarray(-1e9, h.dtype)
        mask = sequence_mask(lengths, t, jnp.bool_)[:, :, None]
        last_h = last_cell = None
        for i in range(self.num_layers):
            h = getattr(self, f"fc{i}")(h)
            h, (hn, cn) = getattr(self, f"lstm{i}")(h, lengths=lengths)
            last_h, last_cell = h, cn
        # reference pools max over time of both the outputs and cell path
        pooled_h = jnp.max(jnp.where(mask, last_h, neg), axis=1)
        pooled_c = last_cell[0]  # (B, H) final cell state, single direction
        feat = jnp.concatenate([pooled_h, pooled_c], axis=-1)
        return self.out(feat)


def loss_fn(logits, label):
    return jnp.mean(L.softmax_with_cross_entropy(logits, label))


def eval_metrics(logits, label):
    return {"acc": accuracy(logits, label)}
