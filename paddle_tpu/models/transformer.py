"""Transformer NMT — BASELINE config 4 (reference:
benchmark/fluid/models/machine_translation.py, tests/book
test_machine_translation.py): encoder-decoder seq2seq with label-smoothed
cross entropy and greedy decode.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from .. import nn
from ..nn.transformer import (PositionalEncoding, TransformerDecoder,
                              TransformerEncoder)
from ..ops import loss as L


@dataclasses.dataclass
class NMTConfig:
    src_vocab: int = 32000
    tgt_vocab: int = 32000
    d_model: int = 512
    num_heads: int = 8
    num_encoder_layers: int = 6
    num_decoder_layers: int = 6
    dim_feedforward: int = 2048
    dropout: float = 0.1
    max_len: int = 1024
    label_smooth: float = 0.1
    bos_id: int = 0
    eos_id: int = 1
    pad_id: int = 2
    use_flash: bool = True
    # decoder-side self-attention SP only: the encoder always applies a
    # source padding mask, which the SP attention paths reject (see
    # nn.MultiHeadAttention); long-source SP needs packed sequences
    seq_parallel: Optional[str] = None

    @classmethod
    def base(cls):
        return cls()

    @classmethod
    def tiny(cls):
        return cls(src_vocab=512, tgt_vocab=512, d_model=64, num_heads=4,
                   num_encoder_layers=2, num_decoder_layers=2,
                   dim_feedforward=128, dropout=0.0, max_len=128)


class TransformerNMT(nn.Layer):
    def __init__(self, cfg: Optional[NMTConfig] = None):
        super().__init__()
        self.cfg = cfg = cfg or NMTConfig.base()
        self.src_emb = nn.Embedding(cfg.src_vocab, cfg.d_model,
                                    padding_idx=cfg.pad_id)
        self.tgt_emb = nn.Embedding(cfg.tgt_vocab, cfg.d_model,
                                    padding_idx=cfg.pad_id)
        self.pos_enc = PositionalEncoding(cfg.d_model, cfg.max_len,
                                          dropout=cfg.dropout)
        self.encoder = TransformerEncoder(
            cfg.num_encoder_layers, cfg.d_model, cfg.num_heads,
            cfg.dim_feedforward, cfg.dropout, use_flash=cfg.use_flash)
        self.decoder = TransformerDecoder(
            cfg.num_decoder_layers, cfg.d_model, cfg.num_heads,
            cfg.dim_feedforward, cfg.dropout, use_flash=cfg.use_flash,
            seq_parallel=cfg.seq_parallel)
        self.generator = nn.Linear(cfg.d_model, cfg.tgt_vocab)

    def encode(self, src_ids):
        src_pad = (src_ids != self.cfg.pad_id)
        memory = self.encoder(self.pos_enc(self.src_emb(src_ids)),
                              mask=src_pad[:, None, None, :])
        return memory, src_pad

    def forward(self, src_ids, tgt_ids):
        """Teacher-forced logits: tgt_ids is the decoder input (shifted)."""
        memory, src_pad = self.encode(src_ids)
        h = self.decoder(self.pos_enc(self.tgt_emb(tgt_ids)), memory,
                         cross_mask=src_pad[:, None, None, :], causal=True)
        return self.generator(h)

    def forward_fused_loss(self, src_ids, tgt_ids, tgt_labels,
                           vocab_chunk: int = 4096):
        """Training loss without the (B, T, tgt_vocab) logits tensor: the
        generator head runs through the chunked linear-cross-entropy
        (ops/fused_loss.py — same HBM argument as the BERT MLM head).
        ``tgt_labels`` uses pad_id positions as ignored."""
        from ..core.dtypes import get_policy
        from ..ops.fused_loss import mean_linear_cross_entropy

        memory, src_pad = self.encode(src_ids)
        h = self.decoder(self.pos_enc(self.tgt_emb(tgt_ids)), memory,
                         cross_mask=src_pad[:, None, None, :], causal=True)
        b, t, d = h.shape
        labels = jnp.where(tgt_labels == self.cfg.pad_id, -100, tgt_labels)
        pol = get_policy()  # vocab matmuls in the AMP compute dtype (bf16)
        return mean_linear_cross_entropy(
            pol.cast_to_compute(h.reshape(b * t, d)),
            pol.cast_to_compute(self.generator.weight),
            pol.cast_to_compute(self.generator.bias),
            labels.reshape(-1), chunk=vocab_chunk, ignore_index=-100)

    def greedy_decode(self, src_ids, max_len: int = 64):
        """Fixed-length greedy decode via lax.scan (static shapes — the
        reference's while_op beam search maps to compiled scan on TPU)."""
        cfg = self.cfg
        b = src_ids.shape[0]
        memory, src_pad = self.encode(src_ids)
        tokens = jnp.full((b, max_len + 1), cfg.pad_id, jnp.int32)
        tokens = tokens.at[:, 0].set(cfg.bos_id)
        finished = jnp.zeros((b,), jnp.bool_)

        def step(carry, t):
            tokens, finished = carry
            h = self.decoder(self.pos_enc(self.tgt_emb(tokens[:, :-1])),
                             memory, cross_mask=src_pad[:, None, None, :],
                             causal=True)
            # only row t is consumed — project just it, not all positions
            h_t = jax.lax.dynamic_index_in_dim(h, t, axis=1, keepdims=False)
            logits = self.generator(h_t)  # (b, vocab)
            next_tok = jnp.argmax(logits, -1).astype(jnp.int32)
            next_tok = jnp.where(finished, cfg.pad_id, next_tok)
            tokens = tokens.at[:, t + 1].set(next_tok)
            finished = finished | (next_tok == cfg.eos_id)
            return (tokens, finished), None

        (tokens, _), _ = jax.lax.scan(step, (tokens, finished),
                                      jnp.arange(max_len))
        return tokens[:, 1:]

    def _cached_step_hidden(self, tok, t, mem_kv, caches, cross_mask,
                            decode_kernel: bool = False):
        """One cached decode step shared by greedy and beam: embed the
        current token (B, ), add the absolute-position term, run every
        decoder layer against its K/V cache, final-norm. Returns
        (h_t (B, D), new_caches). ``decode_kernel`` opts the
        self-attention into the Pallas flash-decode path — greedy only;
        beam_decode runs this under vmap, where the scalar-prefetch
        pallas_call must not go."""
        from ..nn.transformer import decoder_layer_step

        emb = self.tgt_emb(tok[:, None])
        x_t = (emb * self.pos_enc.scale
               + self.pos_enc.pe[t][None, None, :].astype(emb.dtype))
        new_caches = []
        for layer, (mk, mv), (ck, cv) in zip(self.decoder.layers,
                                             mem_kv, caches):
            x_t, ck, cv = decoder_layer_step(
                layer, x_t, mk, mv, ck, cv, t, cross_mask=cross_mask,
                decode_kernel=decode_kernel)
            new_caches.append((ck, cv))
        if self.decoder.final_norm is not None:
            x_t = self.decoder.final_norm(x_t)
        return x_t[:, 0], new_caches

    def greedy_decode_cached(self, src_ids, max_len: int = 64):
        """Greedy decode with per-layer K/V caches: O(T) work per step
        instead of greedy_decode's full-prefix re-run (O(T^2) per step).
        Cross-attention memory K/V are projected ONCE. Token-identical
        to greedy_decode (pinned by test)."""
        from jax import lax

        from ..core.enforce import enforce

        cfg = self.cfg
        # greedy_decode would fail loudly past the pe table; the cached
        # path's per-step pe[t] would silently CLAMP (dynamic_slice) —
        # make it loud here too. And the no-dropout step path is only
        # token-identical to greedy_decode in eval mode.
        enforce(max_len <= self.pos_enc.pe.shape[0],
                "max_len %s exceeds the positional table (%s)",
                max_len, self.pos_enc.pe.shape[0])
        enforce(not self.training,
                "greedy_decode_cached requires eval mode (the cached "
                "step path applies no dropout); call model.eval()")
        b = src_ids.shape[0]
        memory, src_pad = self.encode(src_ids)
        cross_mask = src_pad[:, None, None, :]
        mem_kv = [layer.cross_attn.project_kv(memory)
                  for layer in self.decoder.layers]
        caches = [layer.self_attn.init_cache(b, max_len,
                                             dtype=memory.dtype)
                  for layer in self.decoder.layers]
        tokens = jnp.full((b, max_len + 1), cfg.pad_id, jnp.int32)
        tokens = tokens.at[:, 0].set(cfg.bos_id)
        finished = jnp.zeros((b,), jnp.bool_)

        def step(carry, t):
            tokens, finished, caches = carry
            word = lax.dynamic_index_in_dim(tokens, t, axis=1,
                                            keepdims=False)  # (b,)
            h_t, new_caches = self._cached_step_hidden(
                word, t, mem_kv, caches, cross_mask,
                decode_kernel=True)
            logits = self.generator(h_t)
            next_tok = jnp.argmax(logits, -1).astype(jnp.int32)
            next_tok = jnp.where(finished, cfg.pad_id, next_tok)
            tokens = tokens.at[:, t + 1].set(next_tok)
            finished = finished | (next_tok == cfg.eos_id)
            return (tokens, finished, new_caches), None

        (tokens, _, _), _ = lax.scan(step, (tokens, finished, caches),
                                     jnp.arange(max_len))
        return tokens[:, 1:]

    def beam_decode(self, src_ids, max_len: int = 64, beam_size: int = 4,
                    length_penalty: float = 0.6):
        """Beam-search decode, one source sentence batch at a time via vmap
        (reference capability: contrib/decoder/beam_search_decoder.py +
        beam_search op; here ops.beam_search's scan + pointer backtrack).

        Returns (B, beam_size, max_len) sequences best-first + scores.
        """
        from ..ops import decode as DCD

        cfg = self.cfg

        def one(src_row):
            memory, src_pad = self.encode(src_row[None])
            mem_k = jnp.repeat(memory, beam_size, axis=0)
            pad_k = jnp.repeat(src_pad, beam_size, axis=0)

            def step_fn(state, tok):
                tokens, t = state["tokens"], state["t"]
                tokens = tokens.at[:, t[0]].set(tok)
                h = self.decoder(self.pos_enc(self.tgt_emb(tokens)), mem_k,
                                 cross_mask=pad_k[:, None, None, :],
                                 causal=True)
                h_t = jax.lax.dynamic_index_in_dim(h, t[0], axis=1,
                                                   keepdims=False)
                logp = jax.nn.log_softmax(self.generator(h_t), -1)
                return logp, {"tokens": tokens, "t": t + 1}

            init = {"tokens": jnp.full((beam_size, max_len + 1), cfg.pad_id,
                                       jnp.int32),
                    "t": jnp.zeros((beam_size,), jnp.int32)}
            return DCD.beam_search(init, step_fn, beam_size=beam_size,
                                   max_len=max_len, bos_id=cfg.bos_id,
                                   end_id=cfg.eos_id,
                                   length_penalty=length_penalty)

        return jax.vmap(one)(src_ids)

    def beam_decode_cached(self, src_ids, max_len: int = 64,
                           beam_size: int = 4,
                           length_penalty: float = 0.6):
        """beam_decode with per-layer K/V caches in the beam state:
        ops.decode.beam_search already gathers the WHOLE state pytree by
        parent each step, so cache reordering across beam switches is
        automatic — each step costs O(T) instead of re-running the
        decoder over the full prefix. Result-identical to beam_decode
        (pinned by test); eval mode required."""
        from ..core.enforce import enforce
        from ..ops import decode as DCD

        cfg = self.cfg
        enforce(max_len <= self.pos_enc.pe.shape[0],
                "max_len %s exceeds the positional table (%s)",
                max_len, self.pos_enc.pe.shape[0])
        enforce(not self.training,
                "beam_decode_cached requires eval mode; call model.eval()")

        def one(src_row):
            memory, src_pad = self.encode(src_row[None])
            pad_b = jnp.repeat(src_pad, beam_size, axis=0)
            cross_mask = pad_b[:, None, None, :]
            # project cross K/V ONCE on the single memory row, then
            # repeat the projections — 1/beam_size of the matmul work
            mem_kv = [tuple(jnp.repeat(x, beam_size, axis=0)
                            for x in layer.cross_attn.project_kv(memory))
                      for layer in self.decoder.layers]

            def step_fn(state, tok):
                t = state["t"]
                h_t, new_caches = self._cached_step_hidden(
                    tok, t[0], mem_kv, state["caches"], cross_mask)
                logp = jax.nn.log_softmax(self.generator(h_t), -1)
                return logp, {"t": t + 1, "caches": new_caches}

            init = {"t": jnp.zeros((beam_size,), jnp.int32),
                    "caches": [layer.self_attn.init_cache(
                        beam_size, max_len, dtype=memory.dtype)
                        for layer in self.decoder.layers]}
            return DCD.beam_search(init, step_fn, beam_size=beam_size,
                                   max_len=max_len, bos_id=cfg.bos_id,
                                   end_id=cfg.eos_id,
                                   length_penalty=length_penalty)

        return jax.vmap(one)(src_ids)


def nmt_loss(logits, labels, pad_id: int = 2, label_smooth: float = 0.1):
    """Label-smoothed CE over non-pad positions (reference:
    label_smooth op + softmax_with_cross_entropy soft-label mode)."""
    vocab = logits.shape[-1]
    onehot = jax.nn.one_hot(labels, vocab, dtype=logits.dtype)
    soft = L.label_smooth(onehot, epsilon=label_smooth)
    tok_loss = L.softmax_with_cross_entropy(logits, soft,
                                            soft_label=True).squeeze(-1)
    keep = (labels != pad_id)
    return jnp.sum(tok_loss * keep) / jnp.maximum(jnp.sum(keep), 1)


def nmt_metrics(logits, labels, pad_id: int = 2):
    keep = (labels != pad_id)
    pred = jnp.argmax(logits, -1)
    acc = jnp.sum((pred == labels) * keep) / jnp.maximum(jnp.sum(keep), 1)
    return {"token_acc": acc}
