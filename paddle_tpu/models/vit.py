"""Vision Transformer (ViT-B/16-style) — the modern patch-attention
vision family next to the conv zoo (green-field: the reference's vision
story is the conv benchmark set, reference:
benchmark/fluid/models/resnet.py, vgg.py, se_resnext.py; this family
exists so a vision user scaling past convs finds the attention recipe
assembled from the same pieces the language models use).

TPU-first notes: patch embedding is ONE strided conv (stride = patch:
an MXU-shaped (P*P*C, D) matmul per patch, NHWC default); the encoder
is the shared nn.TransformerEncoder, so flash/remat/scan-layers/MoE and
the tp/pp/dp parallel recipes apply unchanged. hidden/heads keep
head_dim 64 and hidden a multiple of 128 for MXU tiling.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from .. import initializer as I
from .. import nn
from ..core.enforce import enforce
from ..nn.layer import Layer


@dataclasses.dataclass
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    num_channels: int = 3
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    num_classes: int = 1000
    dropout: float = 0.0
    pool: str = "cls"            # "cls" | "mean"
    layout: str = "NHWC"         # bench-sweepable like resnet
    remat: bool = False
    scan_layers: bool = False

    @classmethod
    def tiny(cls):
        """For tests: 32px/8-patch, hidden 64, 2 layers."""
        return cls(image_size=32, patch_size=8, hidden_size=64,
                   num_layers=2, num_heads=4, intermediate_size=128,
                   num_classes=10)

    @classmethod
    def base(cls):
        """ViT-B/16 geometry (~86M params, ~17.6 GFLOP fwd @224)."""
        return cls()


class ViT(Layer):
    """Patch conv -> [CLS] + learned positions -> pre-norm encoder ->
    pooled head. ``forward(images)`` takes NHWC (B, H, W, C) by default
    (NCHW with cfg.layout), returns (B, num_classes) logits."""

    def __init__(self, cfg: ViTConfig):
        super().__init__()
        enforce(cfg.image_size % cfg.patch_size == 0,
                "image %s not divisible by patch %s", cfg.image_size,
                cfg.patch_size)
        enforce(cfg.pool in ("cls", "mean"),
                "pool must be 'cls' or 'mean', got %r", cfg.pool)
        self.cfg = cfg
        grid = cfg.image_size // cfg.patch_size
        self.num_patches = grid * grid
        self.patch_embed = nn.Conv2D(
            cfg.num_channels, cfg.hidden_size, cfg.patch_size,
            stride=cfg.patch_size, data_format=cfg.layout)
        if cfg.pool == "cls":
            self.create_parameter("cls_token", (1, 1, cfg.hidden_size),
                                  None, I.Normal(scale=0.02))
        n_tok = self.num_patches + (1 if cfg.pool == "cls" else 0)
        self.create_parameter("pos_embed", (1, n_tok, cfg.hidden_size),
                              None, I.Normal(scale=0.02))
        self.drop = nn.Dropout(cfg.dropout)
        self.encoder = nn.TransformerEncoder(
            cfg.num_layers, cfg.hidden_size, cfg.num_heads,
            cfg.intermediate_size, dropout=cfg.dropout,
            activation="gelu", normalize_before=True,
            remat=cfg.remat, scan_layers=cfg.scan_layers)
        self.head = nn.Linear(cfg.hidden_size, cfg.num_classes)

    def forward(self, images):
        cfg = self.cfg
        p = self.patch_embed(images)
        if cfg.layout == "NHWC":
            b, gh, gw, d = p.shape
        else:
            b, d, gh, gw = p.shape
            p = jnp.transpose(p, (0, 2, 3, 1))
        enforce(gh * gw == self.num_patches,
                "got %sx%s patches for image %s/%s", gh, gw,
                cfg.image_size, cfg.patch_size)
        x = p.reshape(b, self.num_patches, cfg.hidden_size)
        if cfg.pool == "cls":
            cls = jnp.broadcast_to(self.cls_token,
                                   (b, 1, cfg.hidden_size))
            x = jnp.concatenate([cls.astype(x.dtype), x], axis=1)
        x = self.drop(x + self.pos_embed.astype(x.dtype))
        x = self.encoder(x)
        pooled = x[:, 0] if cfg.pool == "cls" else jnp.mean(x, axis=1)
        return self.head(pooled)


def loss_fn(logits, labels):
    """Mean CE over (B, num_classes) logits."""
    from ..ops import loss as L

    return jnp.mean(L.softmax_with_cross_entropy(logits, labels))
