"""Native (C++) runtime components, bound via ctypes (no pybind in this
environment). Currently: the multithreaded MultiSlot data feed
(src/datafeed.cc) — the reference's C++ ingestion role
(reference: framework/data_feed.h:55, operators/reader/buffered_reader.cc).

The shared library builds on demand with `make` (g++ is part of the
supported toolchain); import fails soft — ``available()`` reports status
and the pure-Python pipeline (paddle_tpu.data) is always there.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libptdatafeed.so")
_lib = None
_lib_lock = threading.Lock()
_build_error: Optional[str] = None


def _load():
    global _lib, _build_error
    with _lib_lock:
        if _lib is not None:
            return _lib
        if not os.path.exists(_SO):
            try:
                subprocess.run(["make", "-C", _DIR], check=True,
                               capture_output=True, text=True, timeout=300)
            except Exception as e:  # toolchain missing → soft-fail
                _build_error = getattr(e, "stderr", str(e)) or str(e)
                return None
        lib = ctypes.CDLL(_SO)
        lib.ptdf_create.restype = ctypes.c_void_p
        lib.ptdf_create.argtypes = [
            ctypes.POINTER(ctypes.c_char_p), ctypes.c_int, ctypes.c_char_p,
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int]
        lib.ptdf_destroy.argtypes = [ctypes.c_void_p]
        lib.ptdf_next.restype = ctypes.c_void_p
        lib.ptdf_next.argtypes = [ctypes.c_void_p]
        lib.ptdf_batch_free.argtypes = [ctypes.c_void_p]
        lib.ptdf_batch_size.restype = ctypes.c_int64
        lib.ptdf_batch_size.argtypes = [ctypes.c_void_p]
        lib.ptdf_batch_maxlen.restype = ctypes.c_int64
        lib.ptdf_batch_maxlen.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.ptdf_batch_ivalues.restype = ctypes.POINTER(ctypes.c_int64)
        lib.ptdf_batch_ivalues.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.ptdf_batch_fvalues.restype = ctypes.POINTER(ctypes.c_float)
        lib.ptdf_batch_fvalues.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.ptdf_batch_lengths.restype = ctypes.POINTER(ctypes.c_int64)
        lib.ptdf_batch_lengths.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.ptdf_error.restype = ctypes.c_int
        lib.ptdf_error.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                   ctypes.c_int]
        _lib = lib
        return _lib


def available() -> bool:
    """True if the native library is (or can be) built and loaded."""
    return _load() is not None


def build_error() -> Optional[str]:
    return _build_error


class MultiSlotFeed:
    """Iterate dense padded batches parsed by C++ worker threads.

    ``slots``: [(name, 'u'|'f'), ...] in file order. Yields
    {name: (values (B, maxlen), lengths (B,))} with int64/float32 values.
    The training thread never touches file IO or parsing — batches queue
    up to ``queue_capacity`` deep while the accelerator computes.
    """

    def __init__(self, files: Sequence[str],
                 slots: Sequence[Tuple[str, str]], batch_size: int,
                 num_threads: int = 2, queue_capacity: int = 8,
                 drop_last: bool = True):
        from ..core.enforce import enforce

        lib = _load()
        enforce(lib is not None,
                "native datafeed unavailable: %s", _build_error)
        for f in files:
            enforce(os.path.exists(f), "no such data file: %s", f)
        self._lib = lib
        self.slots = list(slots)
        spec = ",".join(f"{n}:{d}" for n, d in self.slots).encode()
        arr = (ctypes.c_char_p * len(files))(
            *[f.encode() for f in files])
        self._h = lib.ptdf_create(arr, len(files), spec, batch_size,
                                  num_threads, queue_capacity,
                                  1 if drop_last else 0)
        enforce(self._h is not None, "ptdf_create failed (bad slot spec?)")

    def __iter__(self) -> Iterator[Dict[str, Tuple[np.ndarray, np.ndarray]]]:
        lib = self._lib
        while True:
            b = lib.ptdf_next(self._h)
            if not b:
                break
            try:
                bs = lib.ptdf_batch_size(b)
                out = {}
                for i, (name, d) in enumerate(self.slots):
                    ml = lib.ptdf_batch_maxlen(b, i)
                    n = int(bs * ml)
                    if d == "u":
                        ptr = lib.ptdf_batch_ivalues(b, i)
                        vals = np.ctypeslib.as_array(ptr, (n,)).copy()
                    else:
                        ptr = lib.ptdf_batch_fvalues(b, i)
                        vals = np.ctypeslib.as_array(ptr, (n,)).copy()
                    lens = np.ctypeslib.as_array(
                        lib.ptdf_batch_lengths(b, i), (int(bs),)).copy()
                    out[name] = (vals.reshape(int(bs), int(ml)), lens)
                yield out
            finally:
                lib.ptdf_batch_free(b)
        err = ctypes.create_string_buffer(512)
        if lib.ptdf_error(self._h, err, 512):
            raise RuntimeError(f"native datafeed: {err.value.decode()}")

    def close(self):
        if getattr(self, "_h", None):
            self._lib.ptdf_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
