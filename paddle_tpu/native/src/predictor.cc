// C++ serving predictor over the PJRT C API — the Python-free serving path
// (capability parity with the reference's C++ inference stack:
// paddle/fluid/inference/api/analysis_predictor.h:46 AnalysisPredictor and
// the Python-free training/serving demo paddle/fluid/train/demo/
// demo_trainer.cc; the artifact replaces __model__ ProgramDesc + var files).
//
// Loads a save_inference_model directory:
//   manifest.json   — feed/fetch names, dtypes, arg order (calling conv)
//   params.npz      — persistable vars (zip of .npy, stored or deflate)
//   program.mlir.bc — StableHLO portable bytecode (compiled via
//                     PJRT_Client_Compile, format "mlir")
// and executes on any PJRT plugin (libtpu.so on a TPU VM; set
// PT_PJRT_PLUGIN to the plugin path). All entry points are C ABI for
// ctypes and for the standalone `ptserve` demo binary.
//
// Design note: artifact parsing (manifest/npz) is dependency-free and
// hermetically testable; only Run() needs a live PJRT device.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <dlfcn.h>
#include <fstream>
#include <map>
#include <memory>
#include <sstream>
#include <string>
#include <vector>
#include <zlib.h>

#include "xla/pjrt/c/pjrt_c_api.h"

#include "artifact_parsers.h"

namespace {

using ptnative::DtypeSize;
using ptnative::InflateRaw;
using ptnative::Json;
using ptnative::JsonParser;
using ptnative::NpyArray;
using ptnative::ParseNpy;
using ptnative::ReadNpz;
using ptnative::Status;

// ------------------------------------------------------------- dtypes -----
struct DtypeInfo {
  PJRT_Buffer_Type type;
  size_t size;
};

Status DtypeFromNumpy(const std::string& d, DtypeInfo* out) {
  // numpy descr (little-endian) or plain name from the manifest
  static const std::map<std::string, DtypeInfo> table = {
      {"<f4", {PJRT_Buffer_Type_F32, 4}},  {"float32", {PJRT_Buffer_Type_F32, 4}},
      {"<f8", {PJRT_Buffer_Type_F64, 8}},  {"float64", {PJRT_Buffer_Type_F64, 8}},
      {"<f2", {PJRT_Buffer_Type_F16, 2}},  {"float16", {PJRT_Buffer_Type_F16, 2}},
      {"<i4", {PJRT_Buffer_Type_S32, 4}},  {"int32", {PJRT_Buffer_Type_S32, 4}},
      {"<i8", {PJRT_Buffer_Type_S64, 8}},  {"int64", {PJRT_Buffer_Type_S64, 8}},
      {"|i1", {PJRT_Buffer_Type_S8, 1}},   {"int8", {PJRT_Buffer_Type_S8, 1}},
      {"|u1", {PJRT_Buffer_Type_U8, 1}},   {"uint8", {PJRT_Buffer_Type_U8, 1}},
      {"|b1", {PJRT_Buffer_Type_PRED, 1}}, {"bool", {PJRT_Buffer_Type_PRED, 1}},
  };
  auto it = table.find(d);
  if (it == table.end()) return Status::Err("unsupported dtype " + d);
  *out = it->second;
  return Status::Ok();
}

// ------------------------------------------------------------ PJRT glue ---
struct PjrtRuntime {
  void* dl = nullptr;
  const PJRT_Api* api = nullptr;
  PJRT_Client* client = nullptr;

  std::string ErrMsg(PJRT_Error* err) {
    PJRT_Error_Message_Args m{PJRT_Error_Message_Args_STRUCT_SIZE, nullptr,
                              err};
    api->PJRT_Error_Message(&m);
    std::string s(m.message, m.message_size);
    PJRT_Error_Destroy_Args d{PJRT_Error_Destroy_Args_STRUCT_SIZE, nullptr,
                              err};
    api->PJRT_Error_Destroy(&d);
    return s;
  }

  Status Init(const std::string& plugin_path) {
    dl = dlopen(plugin_path.c_str(), RTLD_NOW | RTLD_LOCAL);
    if (!dl) return Status::Err(std::string("dlopen: ") + dlerror());
    auto get = (const PJRT_Api* (*)())dlsym(dl, "GetPjrtApi");
    if (!get) return Status::Err("plugin has no GetPjrtApi symbol");
    api = get();
    PJRT_Plugin_Initialize_Args init{PJRT_Plugin_Initialize_Args_STRUCT_SIZE,
                                     nullptr};
    if (auto* err = api->PJRT_Plugin_Initialize(&init))
      return Status::Err("plugin init: " + ErrMsg(err));
    PJRT_Client_Create_Args args{PJRT_Client_Create_Args_STRUCT_SIZE,
                                 nullptr};
    if (auto* err = api->PJRT_Client_Create(&args))
      return Status::Err("client create: " + ErrMsg(err));
    client = args.client;
    return Status::Ok();
  }

  ~PjrtRuntime() {
    if (client && api) {
      PJRT_Client_Destroy_Args d{PJRT_Client_Destroy_Args_STRUCT_SIZE,
                                 nullptr, client};
      api->PJRT_Client_Destroy(&d);
    }
    if (dl) dlclose(dl);
  }
};

// ------------------------------------------------------------- predictor --
struct Predictor {
  std::string last_error;
  int num_state_outputs = 0;  // >0: training artifact, outputs loop back
  std::vector<std::string> feed_names, fetch_names, arg_order;
  std::map<std::string, std::string> feed_dtypes;
  std::map<std::string, std::vector<int64_t>> feed_shapes;
  std::map<std::string, NpyArray> params;
  std::string mlir_bc;

  std::unique_ptr<PjrtRuntime> rt;
  PJRT_LoadedExecutable* exec = nullptr;
  std::vector<PJRT_Buffer*> param_buffers;  // device-resident params
  // last run outputs
  std::vector<std::vector<uint8_t>> out_data;
  std::vector<std::vector<int64_t>> out_dims;
  std::vector<std::string> out_dtypes;

  Status LoadArtifact(const std::string& dir) {
    std::ifstream mf(dir + "/manifest.json");
    if (!mf) return Status::Err("cannot open manifest.json in " + dir);
    std::stringstream ss;
    ss << mf.rdbuf();
    std::string text = ss.str();
    JsonParser jp{text.c_str(), text.c_str() + text.size()};
    Json m = jp.parse();
    if (jp.fail || m.kind != Json::kObj)
      return Status::Err("manifest.json parse error");
    const Json* fmt = m.find("format");
    if (!fmt || (fmt->str != "stablehlo+npz/v2" &&
                 fmt->str != "stablehlo+npz/train/v1"))
      return Status::Err(
          "C++ predictor needs format stablehlo+npz/v2 or "
          "stablehlo+npz/train/v1, got " + (fmt ? fmt->str : "<missing>"));
    if (const Json* ns = m.find("num_state_outputs"))
      num_state_outputs = (int)ns->num;  // train program: loop state
    for (auto* key : {"feed_target_names", "fetch_target_names", "arg_order"}) {
      if (!m.find(key)) return Status::Err(std::string("manifest missing ") + key);
    }
    for (auto& j : m.find("feed_target_names")->arr)
      feed_names.push_back(j.str);
    for (auto& j : m.find("fetch_target_names")->arr)
      fetch_names.push_back(j.str);
    for (auto& j : m.find("arg_order")->arr) arg_order.push_back(j.str);
    if (const Json* fd = m.find("feed_dtypes"))
      for (auto& kv : fd->obj) feed_dtypes[kv.first] = kv.second.str;
    if (const Json* fs = m.find("feed_shapes"))
      for (auto& kv : fs->obj) {
        std::vector<int64_t> dims;
        for (auto& d : kv.second.arr) dims.push_back((int64_t)d.num);
        feed_shapes[kv.first] = dims;
      }
    Status st = ReadNpz(dir + "/params.npz", &params);
    if (!st.ok) return st;
    std::ifstream bc(dir + "/program.mlir.bc", std::ios::binary);
    if (!bc) return Status::Err("cannot open program.mlir.bc");
    std::stringstream bs;
    bs << bc.rdbuf();
    mlir_bc = bs.str();
    return Status::Ok();
  }

  Status Compile(const std::string& plugin_path) {
    rt = std::make_unique<PjrtRuntime>();
    Status st = rt->Init(plugin_path);
    if (!st.ok) return st;
    PJRT_Program prog{PJRT_Program_STRUCT_SIZE, nullptr};
    prog.code = const_cast<char*>(mlir_bc.data());
    prog.code_size = mlir_bc.size();
    static const char kFmt[] = "mlir";
    prog.format = kFmt;
    prog.format_size = sizeof(kFmt) - 1;
    PJRT_Client_Compile_Args args{PJRT_Client_Compile_Args_STRUCT_SIZE,
                                  nullptr};
    args.client = rt->client;
    args.program = &prog;
    // empty CompileOptionsProto: all-defaults serialization is 0 bytes is
    // invalid for some plugins; a minimal valid proto is field 3
    // (executable_build_options) absent → empty message works in practice
    static const char kEmpty[] = "";
    args.compile_options = kEmpty;
    args.compile_options_size = 0;
    if (auto* err = rt->api->PJRT_Client_Compile(&args))
      return Status::Err("compile: " + rt->ErrMsg(err));
    exec = args.executable;
    // push params to device once, in arg order
    for (auto& spec : arg_order) {
      if (spec.rfind("param:", 0) != 0) continue;
      auto it = params.find(spec.substr(6));
      if (it == params.end())
        return Status::Err("missing param " + spec.substr(6));
      PJRT_Buffer* buf = nullptr;
      st = HostToDevice(it->second.dtype, it->second.shape,
                        it->second.data.data(), &buf);
      if (!st.ok) return st;
      param_buffers.push_back(buf);
    }
    return Status::Ok();
  }

  Status HostToDevice(const std::string& dtype,
                      const std::vector<int64_t>& dims, const void* data,
                      PJRT_Buffer** out) {
    DtypeInfo di;
    Status st = DtypeFromNumpy(dtype, &di);
    if (!st.ok) return st;
    PJRT_Client_Devices_Args d{PJRT_Client_Devices_Args_STRUCT_SIZE, nullptr,
                               rt->client};
    rt->api->PJRT_Client_Devices(&d);
    if (d.num_devices == 0) return Status::Err("no PJRT devices");
    PJRT_Client_BufferFromHostBuffer_Args a{
        PJRT_Client_BufferFromHostBuffer_Args_STRUCT_SIZE, nullptr};
    a.client = rt->client;
    a.data = data;
    a.type = di.type;
    a.dims = dims.data();
    a.num_dims = dims.size();
    a.host_buffer_semantics =
        PJRT_HostBufferSemantics_kImmutableUntilTransferCompletes;
    a.device = d.devices[0];
    if (auto* err = rt->api->PJRT_Client_BufferFromHostBuffer(&a))
      return Status::Err("h2d: " + rt->ErrMsg(err));
    // wait for the copy before the host buffer may go away
    PJRT_Event_Await_Args w{PJRT_Event_Await_Args_STRUCT_SIZE, nullptr,
                            a.done_with_host_buffer};
    rt->api->PJRT_Event_Await(&w);
    PJRT_Event_Destroy_Args ed{PJRT_Event_Destroy_Args_STRUCT_SIZE, nullptr,
                               a.done_with_host_buffer};
    rt->api->PJRT_Event_Destroy(&ed);
    *out = a.buffer;
    return Status::Ok();
  }

  Status Run(const std::map<std::string, const void*>& feeds,
             const std::map<std::string, std::vector<int64_t>>& feed_dims) {
    if (!exec) return Status::Err("predictor not compiled (no PJRT plugin?)");
    std::vector<PJRT_Buffer*> args_bufs;
    std::vector<PJRT_Buffer*> feed_bufs;
    size_t pi = 0;
    for (auto& spec : arg_order) {
      if (spec.rfind("param:", 0) == 0) {
        args_bufs.push_back(param_buffers[pi++]);
      } else {
        std::string name = spec.substr(5);
        auto it = feeds.find(name);
        if (it == feeds.end()) return Status::Err("missing feed " + name);
        auto dt = feed_dtypes.count(name) ? feed_dtypes[name] : "float32";
        PJRT_Buffer* buf = nullptr;
        Status st = HostToDevice(dt, feed_dims.at(name), it->second, &buf);
        if (!st.ok) return st;
        feed_bufs.push_back(buf);
        args_bufs.push_back(buf);
      }
    }
    PJRT_ExecuteOptions opts{PJRT_ExecuteOptions_STRUCT_SIZE, nullptr};
    PJRT_LoadedExecutable_Execute_Args ex{
        PJRT_LoadedExecutable_Execute_Args_STRUCT_SIZE, nullptr};
    ex.executable = exec;
    ex.options = &opts;
    PJRT_Buffer** arg_list = args_bufs.data();
    PJRT_Buffer* const* const* al = &arg_list;
    ex.argument_lists = const_cast<PJRT_Buffer* const**>(al);
    ex.num_devices = 1;
    ex.num_args = args_bufs.size();
    size_t total_outputs = fetch_names.size() + num_state_outputs;
    std::vector<PJRT_Buffer*> outs(total_outputs);
    PJRT_Buffer** out_list = outs.data();
    PJRT_Buffer** const* ol = &out_list;
    ex.output_lists = const_cast<PJRT_Buffer** const*>(ol);
    ex.device_complete_events = nullptr;
    ex.execute_device = nullptr;
    if (auto* err = rt->api->PJRT_LoadedExecutable_Execute(&ex))
      return Status::Err("execute: " + rt->ErrMsg(err));
    // training artifact: the first num_state_outputs outputs become the
    // next step's param buffers (device-resident loop state — the C++
    // train loop never round-trips weights to host)
    if (num_state_outputs > 0) {
      for (auto* b : param_buffers) {
        PJRT_Buffer_Destroy_Args bd{PJRT_Buffer_Destroy_Args_STRUCT_SIZE,
                                    nullptr, b};
        rt->api->PJRT_Buffer_Destroy(&bd);
      }
      param_buffers.assign(outs.begin(), outs.begin() + num_state_outputs);
      outs.erase(outs.begin(), outs.begin() + num_state_outputs);
    }
    // device → host for each (non-state) output
    out_data.assign(outs.size(), {});
    out_dims.assign(outs.size(), {});
    out_dtypes.assign(outs.size(), "");
    for (size_t i = 0; i < outs.size(); i++) {
      PJRT_Buffer_Dimensions_Args da{PJRT_Buffer_Dimensions_Args_STRUCT_SIZE,
                                     nullptr, outs[i]};
      rt->api->PJRT_Buffer_Dimensions(&da);
      out_dims[i].assign(da.dims, da.dims + da.num_dims);
      PJRT_Buffer_ElementType_Args ta{
          PJRT_Buffer_ElementType_Args_STRUCT_SIZE, nullptr, outs[i]};
      rt->api->PJRT_Buffer_ElementType(&ta);
      size_t elt = 4;
      switch (ta.type) {
        case PJRT_Buffer_Type_F64: case PJRT_Buffer_Type_S64:
          elt = 8; out_dtypes[i] = ta.type == PJRT_Buffer_Type_F64 ?
              "float64" : "int64";
          break;
        case PJRT_Buffer_Type_S32: out_dtypes[i] = "int32"; break;
        case PJRT_Buffer_Type_PRED: elt = 1; out_dtypes[i] = "bool"; break;
        default: out_dtypes[i] = "float32";
      }
      size_t n = elt;
      for (auto dsz : out_dims[i]) n *= dsz;
      out_data[i].resize(n);
      PJRT_Buffer_ToHostBuffer_Args ha{
          PJRT_Buffer_ToHostBuffer_Args_STRUCT_SIZE, nullptr};
      ha.src = outs[i];
      ha.dst = out_data[i].data();
      ha.dst_size = n;
      if (auto* err = rt->api->PJRT_Buffer_ToHostBuffer(&ha))
        return Status::Err("d2h: " + rt->ErrMsg(err));
      PJRT_Event_Await_Args w{PJRT_Event_Await_Args_STRUCT_SIZE, nullptr,
                              ha.event};
      rt->api->PJRT_Event_Await(&w);
      PJRT_Event_Destroy_Args edd{PJRT_Event_Destroy_Args_STRUCT_SIZE,
                                  nullptr, ha.event};
      rt->api->PJRT_Event_Destroy(&edd);
      PJRT_Buffer_Destroy_Args bd{PJRT_Buffer_Destroy_Args_STRUCT_SIZE,
                                  nullptr, outs[i]};
      rt->api->PJRT_Buffer_Destroy(&bd);
    }
    for (auto* b : feed_bufs) {
      PJRT_Buffer_Destroy_Args bd{PJRT_Buffer_Destroy_Args_STRUCT_SIZE,
                                  nullptr, b};
      rt->api->PJRT_Buffer_Destroy(&bd);
    }
    return Status::Ok();
  }
};

}  // namespace

// ----------------------------------------------------------------- C ABI --
extern "C" {

void* ptpred_load(const char* model_dir) {
  auto* p = new Predictor();
  Status st = p->LoadArtifact(model_dir);
  if (!st.ok) p->last_error = st.message;
  return p;
}

int ptpred_ok(void* h) {
  return static_cast<Predictor*>(h)->last_error.empty() ? 1 : 0;
}

const char* ptpred_error(void* h) {
  return static_cast<Predictor*>(h)->last_error.c_str();
}

int ptpred_compile(void* h, const char* plugin_path) {
  auto* p = static_cast<Predictor*>(h);
  Status st = p->Compile(plugin_path);
  if (!st.ok) { p->last_error = st.message; return 0; }
  return 1;
}

int ptpred_num_feeds(void* h) {
  return (int)static_cast<Predictor*>(h)->feed_names.size();
}
const char* ptpred_feed_name(void* h, int i) {
  return static_cast<Predictor*>(h)->feed_names[i].c_str();
}
int ptpred_num_fetches(void* h) {
  return (int)static_cast<Predictor*>(h)->fetch_names.size();
}
int ptpred_feed_rank(void* h, int i) {
  auto* p = static_cast<Predictor*>(h);
  auto it = p->feed_shapes.find(p->feed_names[i]);
  return it == p->feed_shapes.end() ? -1 : (int)it->second.size();
}
int64_t ptpred_feed_dim(void* h, int i, int d) {
  auto* p = static_cast<Predictor*>(h);
  return p->feed_shapes[p->feed_names[i]][d];
}
const char* ptpred_feed_dtype(void* h, int i) {
  auto* p = static_cast<Predictor*>(h);
  auto it = p->feed_dtypes.find(p->feed_names[i]);
  return it == p->feed_dtypes.end() ? "float32" : it->second.c_str();
}
int ptpred_feed_elem_size(void* h, int i) {
  // element width in bytes, 0 if the dtype is unsupported — C-ABI view
  // of ptnative::DtypeSize so clients (ptserve) share ONE dtype table
  return (int)ptnative::DtypeSize(ptpred_feed_dtype(h, i));
}
int ptpred_num_state_outputs(void* h) {
  return static_cast<Predictor*>(h)->num_state_outputs;
}
const char* ptpred_fetch_name(void* h, int i) {
  return static_cast<Predictor*>(h)->fetch_names[i].c_str();
}
int ptpred_num_params(void* h) {
  return (int)static_cast<Predictor*>(h)->params.size();
}

// param introspection (hermetic npz test surface)
const char* ptpred_param_dtype(void* h, const char* name) {
  auto& ps = static_cast<Predictor*>(h)->params;
  auto it = ps.find(name);
  return it == ps.end() ? "" : it->second.dtype.c_str();
}
int ptpred_param_rank(void* h, const char* name) {
  auto& ps = static_cast<Predictor*>(h)->params;
  auto it = ps.find(name);
  return it == ps.end() ? -1 : (int)it->second.shape.size();
}
int64_t ptpred_param_dim(void* h, const char* name, int i) {
  return static_cast<Predictor*>(h)->params[name].shape[i];
}
const void* ptpred_param_data(void* h, const char* name, int64_t* nbytes) {
  auto& a = static_cast<Predictor*>(h)->params[name];
  *nbytes = (int64_t)a.data.size();
  return a.data.data();
}

// run: feeds as flat float32/int buffers in feed_names order
int ptpred_run(void* h, const void** feed_ptrs, const int64_t* dims,
               const int* ranks) {
  auto* p = static_cast<Predictor*>(h);
  std::map<std::string, const void*> feeds;
  std::map<std::string, std::vector<int64_t>> fdims;
  size_t off = 0;
  for (size_t i = 0; i < p->feed_names.size(); i++) {
    feeds[p->feed_names[i]] = feed_ptrs[i];
    fdims[p->feed_names[i]] =
        std::vector<int64_t>(dims + off, dims + off + ranks[i]);
    off += ranks[i];
  }
  Status st = p->Run(feeds, fdims);
  if (!st.ok) { p->last_error = st.message; return 0; }
  return 1;
}

int ptpred_out_rank(void* h, int i) {
  return (int)static_cast<Predictor*>(h)->out_dims[i].size();
}
int64_t ptpred_out_dim(void* h, int i, int d) {
  return static_cast<Predictor*>(h)->out_dims[i][d];
}
const char* ptpred_out_dtype(void* h, int i) {
  return static_cast<Predictor*>(h)->out_dtypes[i].c_str();
}
const void* ptpred_out_data(void* h, int i, int64_t* nbytes) {
  auto& d = static_cast<Predictor*>(h)->out_data[i];
  *nbytes = (int64_t)d.size();
  return d.data();
}

void ptpred_destroy(void* h) { delete static_cast<Predictor*>(h); }

}  // extern "C"
