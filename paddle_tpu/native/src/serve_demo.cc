// Standalone C++ serving harness — Python-free model serving (capability
// parity with the reference's Python-free path: paddle/fluid/train/demo/
// demo_trainer.cc loads ProgramDescs and runs them from C++, and the
// reference's inference/tests/api analyzer latency tests time the
// predictor; here we load a save_inference_model StableHLO artifact,
// serve it via PJRT, and report p50/p99 latency).
//
// Usage: ptserve <model_dir> <pjrt_plugin.so> [batch] [iters] [warmup]
//   Feeds zeros shaped per the manifest's feed_shapes/feed_dtypes (the
//   leading/-1 dim replaced by [batch]). iters > 1 times every run and
//   prints a latency summary JSON line (p50/p99/mean ms, examples/sec).
//   Exit 0 on success.

#include <algorithm>
#include <chrono>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

extern "C" {
void* ptpred_load(const char* model_dir);
int ptpred_ok(void* h);
const char* ptpred_error(void* h);
int ptpred_compile(void* h, const char* plugin_path);
int ptpred_num_feeds(void* h);
const char* ptpred_feed_name(void* h, int i);
int ptpred_feed_rank(void* h, int i);
int64_t ptpred_feed_dim(void* h, int i, int d);
const char* ptpred_feed_dtype(void* h, int i);
int ptpred_feed_elem_size(void* h, int i);
int ptpred_num_fetches(void* h);
const char* ptpred_fetch_name(void* h, int i);
int ptpred_run(void* h, const void** feed_ptrs, const int64_t* dims,
               const int* ranks);
int ptpred_out_rank(void* h, int i);
int64_t ptpred_out_dim(void* h, int i, int d);
const void* ptpred_out_data(void* h, int i, int64_t* nbytes);
void ptpred_destroy(void* h);
}

int main(int argc, char** argv) {
  if (argc < 3) {
    fprintf(stderr,
            "usage: %s <model_dir> <pjrt_plugin.so> [batch] [iters] "
            "[warmup]\n",
            argv[0]);
    return 64;
  }
  int64_t batch = argc > 3 ? atoll(argv[3]) : 1;
  int iters = argc > 4 ? atoi(argv[4]) : 1;
  int warmup = argc > 5 ? atoi(argv[5]) : 2;
  void* p = ptpred_load(argv[1]);
  if (!ptpred_ok(p)) {
    fprintf(stderr, "load failed: %s\n", ptpred_error(p));
    return 1;
  }
  int nf = ptpred_num_feeds(p);
  printf("model loaded: %d feeds, %d fetches\n", nf,
         ptpred_num_fetches(p));
  if (!ptpred_compile(p, argv[2])) {
    fprintf(stderr, "compile failed: %s\n", ptpred_error(p));
    return 2;
  }
  // pre-pass: resolve the EFFECTIVE batch before sizing any buffer —
  // a fixed-shape artifact (jit.save's concrete fallback) pins it to
  // the traced leading dim; an override would shape-mismatch at PJRT
  // execute with no useful message, and feeds must agree on it
  for (int i = 0; i < nf; i++) {
    int rank = ptpred_feed_rank(p, i);
    if (rank < 1) continue;
    int64_t d0 = ptpred_feed_dim(p, i, 0);
    if (d0 > 0 && d0 != batch) {
      if (argc > 3)
        fprintf(stderr,
                "note: feed %s has fixed batch %lld; ignoring "
                "requested batch %lld\n",
                ptpred_feed_name(p, i), (long long)d0, (long long)batch);
      batch = d0;
    }
  }
  // zero-filled feeds shaped from the manifest; negative/polymorphic
  // dims become the resolved [batch]
  std::vector<std::vector<uint8_t>> storage(nf);
  std::vector<const void*> ptrs(nf);
  std::vector<int64_t> dims;
  std::vector<int> ranks(nf);
  for (int i = 0; i < nf; i++) {
    int rank = ptpred_feed_rank(p, i);
    if (rank < 0) {  // no manifest shape: legacy demo fallback (B, 784)
      rank = 2;
      dims.push_back(batch);
      dims.push_back(784);
      storage[i].assign((size_t)batch * 784 * 4, 0);
    } else {
      size_t elems = 1;
      for (int d = 0; d < rank; d++) {
        int64_t dim = ptpred_feed_dim(p, i, d);
        if (dim < 0) dim = batch;
        dims.push_back(dim);
        elems *= (size_t)dim;
      }
      int esz = ptpred_feed_elem_size(p, i);
      if (esz <= 0) {
        fprintf(stderr, "unsupported feed dtype %s\n",
                ptpred_feed_dtype(p, i));
        return 4;
      }
      storage[i].assign(elems * (size_t)esz, 0);
    }
    ranks[i] = rank;
    ptrs[i] = storage[i].data();
  }
  std::vector<double> lat_ms;
  lat_ms.reserve(iters);
  for (int it = 0; it < warmup + iters; it++) {
    auto t0 = std::chrono::steady_clock::now();
    if (!ptpred_run(p, ptrs.data(), dims.data(), ranks.data())) {
      fprintf(stderr, "run failed: %s\n", ptpred_error(p));
      return 3;
    }
    auto t1 = std::chrono::steady_clock::now();
    if (it >= warmup)
      lat_ms.push_back(
          std::chrono::duration<double, std::milli>(t1 - t0).count());
  }
  for (int i = 0; i < ptpred_num_fetches(p); i++) {
    printf("fetch %s: shape(", ptpred_fetch_name(p, i));
    for (int d = 0; d < ptpred_out_rank(p, i); d++)
      printf("%s%lld", d ? "," : "", (long long)ptpred_out_dim(p, i, d));
    int64_t nbytes = 0;
    const float* data = (const float*)ptpred_out_data(p, i, &nbytes);
    printf(") first=%g\n", nbytes >= 4 ? data[0] : 0.0);
  }
  if (!lat_ms.empty()) {
    std::sort(lat_ms.begin(), lat_ms.end());
    double sum = 0;
    for (double v : lat_ms) sum += v;
    size_t n = lat_ms.size();
    double p50 = lat_ms[n / 2];
    // nearest-rank percentile: idx = ceil(0.99*n)-1. By definition this
    // still lands on the last sample for any n < 100 — a true p99 needs
    // >= 100 samples (the fill-list ptserve items pass iters=100) — so
    // max is reported as its own field and small-n p99 readings should
    // be read as max, not as a percentile.
    size_t p99_idx = (size_t)std::ceil(0.99 * (double)n);
    double p99 = lat_ms[p99_idx > 0 ? p99_idx - 1 : 0];
    double mx = lat_ms[n - 1];
    double mean = sum / n;
    // one JSON line, bench.py style — the analyzer-latency-test role
    printf(
        "{\"metric\": \"native_serve_latency_ms\", \"p50\": %.3f, "
        "\"p99\": %.3f, \"max\": %.3f, \"mean\": %.3f, \"batch\": %lld, "
        "\"iters\": %zu, \"examples_per_sec\": %.1f}\n",
        p50, p99, mx, mean, (long long)batch, n, batch * 1000.0 / mean);
  }
  ptpred_destroy(p);
  printf("ok\n");
  return 0;
}
