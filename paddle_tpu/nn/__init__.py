"""Layer API — dygraph-equivalent modules (reference: fluid/dygraph/nn.py),
functional under the hood (functional_call over param/buffer pytrees)."""

from .layer import Layer, LayerList, Parameter, Sequential
from .layers import (GELU, RNN, BatchNorm, BilinearTensorProduct, Conv2D,
                     Conv2DTranspose, Dropout, Embedding, Flatten, GroupNorm,
                     GRUCell, LayerNorm, Linear, LSTMCell, MultiHeadAttention,
                     Pool2D, PRelu, ReLU, RMSNorm, Sigmoid, Softmax,
                     SpectralNorm, Tanh)
from .lora import (LoRALinear, apply_lora, lora_parameters,
                   merge_lora)
from .moe import SwitchFFN
from .rnn_layers import GRU, LSTM
from .sampling_layers import NCE, HSigmoid
from .transformer import (FeedForward, LearnedPositionalEmbedding,
                          PositionalEncoding, TransformerDecoder,
                          TransformerDecoderLayer, TransformerEncoder,
                          TransformerEncoderLayer)

__all__ = [
    "Layer", "LayerList", "Parameter", "Sequential",
    "GELU", "RNN", "BatchNorm", "BilinearTensorProduct", "Conv2D",
    "Conv2DTranspose", "Dropout", "Embedding", "Flatten", "GroupNorm",
    "GRUCell", "LayerNorm", "Linear", "LSTMCell", "MultiHeadAttention",
    "Pool2D", "PRelu", "ReLU", "RMSNorm", "Sigmoid", "Softmax",
    "SpectralNorm", "Tanh",
    "GRU", "LSTM", "NCE", "HSigmoid", "SwitchFFN",
    "LoRALinear", "apply_lora", "lora_parameters", "merge_lora",
    "FeedForward", "LearnedPositionalEmbedding", "PositionalEncoding",
    "TransformerDecoder", "TransformerDecoderLayer", "TransformerEncoder",
    "TransformerEncoderLayer",
]
