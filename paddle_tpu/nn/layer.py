"""Layer: the module system — capability parity with fluid.dygraph.Layer
(reference: python/paddle/fluid/dygraph/layers.py) redesigned for JAX.

Design: a Layer is a *mutable container of arrays* (ergonomic, Paddle-style),
but every compiled entry point is *functional*: ``functional_call(params,
buffers, *args)`` injects state, runs forward, and returns updated buffers —
so ``jax.jit``/``grad``/``pjit`` see a pure function over pytrees. This is the
TPU-native answer to the reference's Tracer+VarBase machinery (reference:
paddle/fluid/imperative/tracer.h:44, layer.h:116): JAX *is* the tracer; the
Layer only has to organize state.

State collections:
  - params:  trainable (the reference's Parameter, framework.py:3476)
  - buffers: non-trainable persistent state (BN running stats)
Both are flat dicts keyed by dotted paths ("block1.conv.weight").
"""

from __future__ import annotations

import contextlib
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import jax
import jax.numpy as jnp

from ..core import random as prandom
from ..core.dtypes import default_dtype
from ..core.enforce import enforce, not_found


class Layer:
    """Base class for all network modules."""

    def __init__(self, name_scope: Optional[str] = None):
        # use object.__setattr__ to dodge our own __setattr__ bookkeeping
        object.__setattr__(self, "_params", {})
        object.__setattr__(self, "_buffers", {})
        object.__setattr__(self, "_sublayers", {})
        object.__setattr__(self, "training", True)
        object.__setattr__(self, "_rng_ctx", None)

    # --- attribute plumbing -------------------------------------------------

    def __setattr__(self, name: str, value: Any) -> None:
        if isinstance(value, Layer):
            self._sublayers[name] = value
            object.__setattr__(self, name, value)
        elif isinstance(value, Parameter):
            self._params[name] = value.value
            object.__setattr__(self, name, None)  # real access goes via property
        elif name in self.__dict__.get("_params", {}):
            # re-assigning an existing parameter updates the registry, so
            # forward and state_dict/Trainer never desync
            self._params[name] = jnp.asarray(value)
        elif name in self.__dict__.get("_buffers", {}):
            self._buffers[name] = jnp.asarray(value)
        else:
            object.__setattr__(self, name, value)

    def __getattr__(self, name: str):
        # only called when normal lookup fails or attr is None-placeholder
        params = self.__dict__.get("_params", {})
        if name in params:
            return params[name]
        buffers = self.__dict__.get("_buffers", {})
        if name in buffers:
            return buffers[name]
        raise AttributeError(f"{type(self).__name__} has no attribute {name!r}")

    def __getattribute__(self, name):
        val = object.__getattribute__(self, name)
        if val is None:
            # parameter/buffer placeholder — fetch live value
            d = object.__getattribute__(self, "__dict__")
            params = d.get("_params", {})
            if name in params:
                return params[name]
            buffers = d.get("_buffers", {})
            if name in buffers:
                return buffers[name]
        return val

    # --- parameter / buffer creation ---------------------------------------

    def create_parameter(self, name: str, shape, dtype=None,
                         initializer: Optional[Callable] = None,
                         is_bias: bool = False):
        """LayerHelper.create_parameter analog (reference: layer_helper.py:29
        param creation + default initializers)."""
        from ..initializer import Constant, XavierUniform

        dtype = dtype or default_dtype()
        if initializer is None:
            initializer = Constant(0.0) if is_bias else XavierUniform()
        key = prandom.key_for(f"{type(self).__name__}.{name}",
                              prandom.next_key())
        value = initializer(key, tuple(shape), dtype)
        self._params[name] = value
        object.__setattr__(self, name, None)
        return value

    def register_buffer(self, name: str, value) -> None:
        self._buffers[name] = jnp.asarray(value)
        object.__setattr__(self, name, None)

    def update_buffer(self, name: str, value) -> None:
        """Record a new buffer value during forward (BN running stats).
        Functional callers collect these via functional_call."""
        enforce(name in self._buffers, "unknown buffer %s", name)
        self._buffers[name] = value

    def add_sublayer(self, name: str, layer: "Layer") -> "Layer":
        self._sublayers[name] = layer
        object.__setattr__(self, name, layer)
        return layer

    # --- traversal ----------------------------------------------------------

    def named_sublayers(self, prefix: str = "") -> Iterator[Tuple[str, "Layer"]]:
        for name, sub in self._sublayers.items():
            path = f"{prefix}{name}"
            yield path, sub
            yield from sub.named_sublayers(prefix=f"{path}.")

    def sublayers(self) -> List["Layer"]:
        return [l for _, l in self.named_sublayers()]

    def named_parameters(self) -> Dict[str, Any]:
        out = {k: v for k, v in self._params.items()}
        for name, sub in self._sublayers.items():
            for k, v in sub.named_parameters().items():
                out[f"{name}.{k}"] = v
        return out

    def parameters(self) -> List[Any]:
        return list(self.named_parameters().values())

    def named_buffers(self) -> Dict[str, Any]:
        out = {k: v for k, v in self._buffers.items()}
        for name, sub in self._sublayers.items():
            for k, v in sub.named_buffers().items():
                out[f"{name}.{k}"] = v
        return out

    # --- state dict (reference: dygraph/checkpoint.py save/load) ------------

    def state_dict(self) -> Dict[str, Any]:
        out = dict(self.named_parameters())
        out.update({f"_buffer.{k}": v for k, v in self.named_buffers().items()})
        return out

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        params = {k: v for k, v in state.items() if not k.startswith("_buffer.")}
        buffers = {k[len("_buffer."):]: v for k, v in state.items()
                   if k.startswith("_buffer.")}
        self.set_parameters(params)
        self.set_buffers(buffers)

    def set_parameters(self, flat: Dict[str, Any]) -> None:
        own = {k: v for k, v in flat.items() if "." not in k}
        for k, v in own.items():
            enforce(k in self._params, "unknown parameter %s on %s", k,
                    type(self).__name__)
            self._params[k] = jnp.asarray(v)
        for name, sub in self._sublayers.items():
            prefix = f"{name}."
            subflat = {k[len(prefix):]: v for k, v in flat.items()
                       if k.startswith(prefix)}
            if subflat:
                sub.set_parameters(subflat)

    def set_buffers(self, flat: Dict[str, Any]) -> None:
        own = {k: v for k, v in flat.items() if "." not in k}
        for k, v in own.items():
            self._buffers[k] = jnp.asarray(v)
        for name, sub in self._sublayers.items():
            prefix = f"{name}."
            subflat = {k[len(prefix):]: v for k, v in flat.items()
                       if k.startswith(prefix)}
            if subflat:
                sub.set_buffers(subflat)

    # --- train/eval ---------------------------------------------------------

    def train(self) -> "Layer":
        object.__setattr__(self, "training", True)
        for sub in self._sublayers.values():
            sub.train()
        return self

    def eval(self) -> "Layer":
        object.__setattr__(self, "training", False)
        for sub in self._sublayers.values():
            sub.eval()
        return self

    # --- rng ----------------------------------------------------------------

    def rng(self, tag: str = "default"):
        """Fresh PRNG key for this layer during a functional call (dropout
        etc.). Outside functional_call falls back to the global stream."""
        ctx = _RNG_STACK[-1] if _RNG_STACK else None
        if ctx is None:
            return prandom.next_key()
        ctx["count"] += 1
        return jax.random.fold_in(
            jax.random.fold_in(ctx["key"], ctx["count"]),
            _stable_hash(tag))

    # --- calling ------------------------------------------------------------

    def forward(self, *args, **kwargs):
        raise NotImplementedError

    def __call__(self, *args, **kwargs):
        return self.forward(*args, **kwargs)

    def functional_call(self, params: Dict[str, Any], *args,
                        buffers: Optional[Dict[str, Any]] = None,
                        rng: Optional[jax.Array] = None,
                        training: Optional[bool] = None,
                        method: str = "forward", **kwargs):
        """Pure-function entry point: run ``method`` (default forward) with
        `params`/`buffers` injected; returns (output, new_buffers). Safe to
        jit/grad over."""
        saved_params = dict(self.named_parameters())
        saved_buffers = dict(self.named_buffers())
        saved_training = self.training
        try:
            self.set_parameters(params)
            if buffers is not None:
                self.set_buffers(buffers)
            if training is not None:
                (self.train if training else self.eval)()
            ctx = {"key": rng if rng is not None else jax.random.key(0),
                   "count": 0}
            _RNG_STACK.append(ctx)
            try:
                out = getattr(self, method)(*args, **kwargs)
            finally:
                _RNG_STACK.pop()
            new_buffers = dict(self.named_buffers())
            return out, new_buffers
        finally:
            self.set_parameters(saved_params)
            self.set_buffers(saved_buffers)
            (self.train if saved_training else self.eval)()

    def apply_fn(self) -> Callable:
        """Returns f(params, *args) -> output — convenience for loss closures
        on models without buffers."""

        def f(params, *args, **kwargs):
            out, _ = self.functional_call(params, *args, **kwargs)
            return out

        return f


_RNG_STACK: List[Dict[str, Any]] = []


@contextlib.contextmanager
def inject_state(*bindings):
    """Temporarily bind ``(model, params[, buffers])`` tuples — the
    multi-model sibling of Layer.functional_call for jit bodies that
    drive SEVERAL Layers at once (speculative decoding's target+draft,
    the serving arena's model+draft) or bound-method pipelines that
    functional_call's single-method entry can't express.

    Why it exists: a jitted closure over a Layer traces the weights as
    HLO CONSTANTS. Off-chip that only bloats the program; through a
    remote-compile relay (the axon tunnel POSTs the serialized program
    over HTTP) a 100M-param model baked into every program exceeds the
    relay's body limit (observed: HTTP 413 on every decode bench).
    Passing params/buffers through this context as jit ARGUMENTS keeps
    compiled programs weight-free. Restores the previous (concrete)
    state on exit — same discipline as functional_call."""
    saved = [(m, dict(m.named_parameters()), dict(m.named_buffers()))
             for m, *_ in bindings]
    try:
        for b in bindings:
            m, p = b[0], b[1]
            m.set_parameters(p)
            if len(b) > 2 and b[2]:
                m.set_buffers(b[2])
        yield
    finally:
        for m, p, bufs in saved:
            m.set_parameters(p)
            if bufs:
                m.set_buffers(bufs)


def stacked_parameters(layers) -> Dict[str, Any]:
    """Stack the params of structurally identical layers along a new
    leading axis — the uniform-block idiom shared by scan-over-layers
    encoders and the GPipe pipeline. Enforces matching param trees."""
    import jax.numpy as jnp

    from ..core.enforce import enforce

    per = [l.named_parameters() for l in layers]
    enforce(per, "stacked_parameters needs at least one layer")
    names = sorted(per[0])
    for i, p in enumerate(per[1:], 1):
        enforce(sorted(p) == names,
                "layer %s is not structurally identical to layer 0 "
                "(params %s vs %s)", i, sorted(p), names)
    return {k: jnp.stack([p[k] for p in per]) for k in names}


def _stable_hash(s: str) -> int:
    import zlib

    return zlib.crc32(s.encode()) & 0x7FFFFFFF


class Parameter:
    """Marker wrapper so `layer.w = Parameter(array)` registers a trainable."""

    def __init__(self, value):
        self.value = jnp.asarray(value)


class Sequential(Layer):
    """reference: dygraph Sequential."""

    def __init__(self, *layers: Layer):
        super().__init__()
        for i, l in enumerate(layers):
            self.add_sublayer(str(i), l)

    def forward(self, x):
        for l in self._sublayers.values():
            x = l(x)
        return x

    def __len__(self):
        return len(self._sublayers)

    def __getitem__(self, i: int) -> Layer:
        return self._sublayers[str(i)]


class LayerList(Layer):
    """reference: dygraph LayerList."""

    def __init__(self, layers=()):
        super().__init__()
        for i, l in enumerate(layers):
            self.add_sublayer(str(i), l)

    def append(self, layer: Layer) -> "LayerList":
        self.add_sublayer(str(len(self._sublayers)), layer)
        return self

    def __iter__(self):
        return iter(self._sublayers.values())

    def __len__(self):
        return len(self._sublayers)

    def __getitem__(self, i: int) -> Layer:
        return self._sublayers[str(i)]
