"""Standard layers — capability parity with fluid.dygraph.nn
(reference: python/paddle/fluid/dygraph/nn.py:35-2332 — Conv2D, Pool2D, FC,
BatchNorm, Embedding, LayerNorm, GRUUnit, NCE, PRelu, BilinearTensorProduct,
Conv2DTranspose, GroupNorm, SpectralNorm, TreeConv) plus the transformer
layers the model zoo needs (MultiHeadAttention etc. — assembled in the
reference from primitives, see nets.py:343 scaled_dot_product_attention).
"""

from __future__ import annotations

import math
from typing import Callable, Optional, Sequence, Union

import jax
import jax.numpy as jnp

from .. import initializer as I
from ..core.dtypes import default_dtype, get_policy
from ..core.enforce import enforce
from ..ops import math as OM
from ..ops import nn as ON
from .layer import Layer, LayerList


class Linear(Layer):
    """FC layer (reference: dygraph/nn.py FC / layers/nn.py fc:210)."""

    def __init__(self, in_features: int, out_features: int,
                 bias_attr: bool = True, act: Optional[str] = None,
                 weight_init=None, bias_init=None, dtype=None):
        super().__init__()
        self.in_features, self.out_features = in_features, out_features
        self.act = act
        self.create_parameter("weight", (in_features, out_features), dtype,
                              weight_init or I.XavierUniform())
        self.has_bias = bias_attr
        if bias_attr:
            self.create_parameter("bias", (out_features,), dtype,
                                  bias_init or I.Constant(0.0), is_bias=True)

    def forward(self, x):
        pol = get_policy()
        w = pol.cast_to_compute(self.weight)
        out = jnp.matmul(pol.cast_to_compute(x), w)
        if self.has_bias:
            out = out + pol.cast_to_compute(self.bias)
        out = pol.cast_to_output(out)
        return _apply_act(out, self.act)


class Conv2D(Layer):
    """reference: dygraph/nn.py Conv2D (NCHW, OIHW weights)."""

    def __init__(self, in_channels: int, out_channels: int,
                 kernel_size: Union[int, Sequence[int]], stride=1, padding=0,
                 dilation=1, groups: int = 1, bias_attr: bool = True,
                 act: Optional[str] = None, weight_init=None, dtype=None,
                 data_format: str = "NCHW"):
        super().__init__()
        k = (kernel_size,) * 2 if isinstance(kernel_size, int) else tuple(kernel_size)
        self.stride, self.padding, self.dilation, self.groups = stride, padding, dilation, groups
        self.act = act
        self.data_format = data_format
        self.create_parameter(
            "weight", (out_channels, in_channels // groups) + k, dtype,
            weight_init or I.MSRA(uniform=False))
        self.has_bias = bias_attr
        if bias_attr:
            self.create_parameter("bias", (out_channels,), dtype,
                                  I.Constant(0.0), is_bias=True)

    def forward(self, x):
        pol = get_policy()
        out = ON.conv2d(pol.cast_to_compute(x), pol.cast_to_compute(self.weight),
                        self.stride, self.padding, self.dilation, self.groups,
                        data_format=self.data_format)
        if self.has_bias:
            bshape = ((1, -1, 1, 1) if self.data_format == "NCHW"
                      else (1, 1, 1, -1))
            out = out + pol.cast_to_compute(self.bias).reshape(bshape)
        return _apply_act(pol.cast_to_output(out), self.act)


class Conv2DTranspose(Layer):
    """reference: dygraph/nn.py Conv2DTranspose (IOHW weights)."""

    def __init__(self, in_channels: int, out_channels: int, kernel_size,
                 stride=1, padding=0, dilation=1, groups: int = 1,
                 bias_attr: bool = True, act: Optional[str] = None, dtype=None):
        super().__init__()
        k = (kernel_size,) * 2 if isinstance(kernel_size, int) else tuple(kernel_size)
        self.stride, self.padding, self.dilation, self.groups = stride, padding, dilation, groups
        self.act = act
        self.create_parameter("weight",
                              (in_channels, out_channels // groups) + k, dtype,
                              I.XavierUniform())
        self.has_bias = bias_attr
        if bias_attr:
            self.create_parameter("bias", (out_channels,), dtype,
                                  I.Constant(0.0), is_bias=True)

    def forward(self, x):
        pol = get_policy()
        out = ON.conv2d_transpose(pol.cast_to_compute(x),
                                  pol.cast_to_compute(self.weight),
                                  self.stride, self.padding,
                                  self.dilation, self.groups)
        if self.has_bias:
            out = out + pol.cast_to_compute(self.bias).reshape(1, -1, 1, 1)
        return _apply_act(pol.cast_to_output(out), self.act)


class Pool2D(Layer):
    """reference: dygraph/nn.py Pool2D."""

    def __init__(self, kernel_size, pool_type: str = "max", stride=None,
                 padding=0, global_pooling: bool = False,
                 ceil_mode: bool = False, data_format: str = "NCHW"):
        super().__init__()
        self.kernel_size, self.pool_type = kernel_size, pool_type
        self.stride, self.padding = stride, padding
        self.global_pooling, self.ceil_mode = global_pooling, ceil_mode
        self.data_format = data_format

    def forward(self, x):
        return ON.pool2d(x, self.kernel_size, self.pool_type, self.stride,
                         self.padding, ceil_mode=self.ceil_mode,
                         global_pooling=self.global_pooling,
                         data_format=self.data_format)


class BatchNorm(Layer):
    """reference: dygraph/nn.py BatchNorm — running stats live in buffers;
    functional_call returns them updated."""

    def __init__(self, num_channels: int, momentum: float = 0.9,
                 epsilon: float = 1e-5, act: Optional[str] = None,
                 data_layout: str = "NCHW", dtype=None):
        super().__init__()
        self.momentum, self.epsilon = momentum, epsilon
        self.act, self.data_layout = act, data_layout
        self.create_parameter("weight", (num_channels,), dtype, I.Constant(1.0))
        self.create_parameter("bias", (num_channels,), dtype, I.Constant(0.0),
                              is_bias=True)
        self.register_buffer("mean", jnp.zeros((num_channels,)))
        self.register_buffer("variance", jnp.ones((num_channels,)))

    def forward(self, x):
        y, new_mean, new_var = ON.batch_norm(
            x, self.weight, self.bias, self.mean, self.variance,
            training=self.training, momentum=self.momentum,
            epsilon=self.epsilon, data_layout=self.data_layout)
        if self.training:
            self.update_buffer("mean", new_mean)
            self.update_buffer("variance", new_var)
        return _apply_act(y, self.act)


class LayerNorm(Layer):
    """reference: dygraph/nn.py LayerNorm."""

    def __init__(self, normalized_shape, epsilon: float = 1e-5,
                 scale: bool = True, shift: bool = True, dtype=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = (normalized_shape,)
        self.normalized_shape = tuple(normalized_shape)
        self.epsilon = epsilon
        self.has_scale, self.has_shift = scale, shift
        if scale:
            self.create_parameter("weight", self.normalized_shape, dtype,
                                  I.Constant(1.0))
        if shift:
            self.create_parameter("bias", self.normalized_shape, dtype,
                                  I.Constant(0.0), is_bias=True)

    def forward(self, x):
        begin = x.ndim - len(self.normalized_shape)
        return ON.layer_norm(
            x, self.weight if self.has_scale else None,
            self.bias if self.has_shift else None,
            begin_norm_axis=begin, epsilon=self.epsilon)


class GroupNorm(Layer):
    """reference: dygraph/nn.py GroupNorm."""

    def __init__(self, num_groups: int, num_channels: int,
                 epsilon: float = 1e-5, dtype=None):
        super().__init__()
        self.num_groups, self.epsilon = num_groups, epsilon
        self.create_parameter("weight", (num_channels,), dtype, I.Constant(1.0))
        self.create_parameter("bias", (num_channels,), dtype, I.Constant(0.0),
                              is_bias=True)

    def forward(self, x):
        return ON.group_norm(x, self.weight, self.bias,
                             groups=self.num_groups, epsilon=self.epsilon)


class RMSNorm(Layer):
    """Modern-transformer norm (no direct reference analog)."""

    def __init__(self, dim: int, epsilon: float = 1e-6, dtype=None):
        super().__init__()
        self.epsilon = epsilon
        self.create_parameter("weight", (dim,), dtype, I.Constant(1.0))

    def forward(self, x):
        return ON.rms_norm(x, self.weight, epsilon=self.epsilon)


class Embedding(Layer):
    """reference: dygraph/nn.py Embedding (lookup_table_op).

    ``is_sparse=True`` (reference lookup_table's is_sparse attr) marks the
    table for row-sparse gradient updates: a train step built with
    :func:`paddle_tpu.optimizer.sparse.sparse_minimize_fn` differentiates
    w.r.t. the gathered rows instead of the table, and the optimizer
    touches O(batch * seq) rows per step, not O(vocab) — the SelectedRows
    capability (reference: framework/selected_rows.h:32). Outside such a
    step the flag is inert (plain dense gather). The giant-table sharded
    variant lives in paddle_tpu.parallel.sharded_embedding."""

    def __init__(self, num_embeddings: int, embedding_dim: int,
                 padding_idx: Optional[int] = None, weight_init=None,
                 dtype=None, is_sparse: bool = False):
        super().__init__()
        self.padding_idx = padding_idx
        self.is_sparse = is_sparse
        self.create_parameter("weight", (num_embeddings, embedding_dim), dtype,
                              weight_init or I.XavierNormal())

    def forward(self, ids):
        from .sparse import Capture, Inject, active

        ctx = active()
        if ctx is not None and ctx.handles(self):
            if isinstance(ctx, Capture):
                ctx.record(self, ids)
            else:
                assert isinstance(ctx, Inject)
                rows = ctx.pop(self)
                if self.padding_idx is not None:
                    rows = jnp.where((ids == self.padding_idx)[..., None],
                                     0.0, rows)
                return rows
        return ON.embedding(ids, self.weight, self.padding_idx)


class Dropout(Layer):
    """reference: dropout layer (dropout_op)."""

    def __init__(self, p: float = 0.5, mode: str = "upscale_in_train"):
        super().__init__()
        self.p, self.mode = p, mode

    def forward(self, x):
        if not self.training or self.p == 0.0:
            return ON.dropout(x, self.p, training=False, mode=self.mode)
        return ON.dropout(x, self.p, key=self.rng("dropout"), training=True,
                          mode=self.mode)


class PRelu(Layer):
    """reference: dygraph/nn.py PRelu."""

    def __init__(self, mode: str = "all", channel: Optional[int] = None,
                 init: float = 0.25, dtype=None):
        super().__init__()
        self.mode = mode
        shape = (1,) if mode == "all" else (channel,)
        self.create_parameter("alpha", shape, dtype, I.Constant(init))

    def forward(self, x):
        return OM.prelu(x, self.alpha, self.mode)


class BilinearTensorProduct(Layer):
    """reference: dygraph/nn.py BilinearTensorProduct."""

    def __init__(self, in1_features: int, in2_features: int, out_features: int,
                 bias_attr: bool = True, dtype=None):
        super().__init__()
        self.create_parameter("weight",
                              (out_features, in1_features, in2_features), dtype,
                              I.XavierUniform())
        self.has_bias = bias_attr
        if bias_attr:
            self.create_parameter("bias", (out_features,), dtype,
                                  I.Constant(0.0), is_bias=True)

    def forward(self, x, y):
        return OM.bilinear_tensor_product(
            x, y, self.weight, self.bias if self.has_bias else None)


class SpectralNorm(Layer):
    """reference: dygraph/nn.py SpectralNorm — power-iteration weight norm.
    The u/v vectors are buffers updated each forward."""

    def __init__(self, weight_shape, dim: int = 0, power_iters: int = 1,
                 eps: float = 1e-12, dtype=None):
        super().__init__()
        self.dim, self.power_iters, self.eps = dim, power_iters, eps
        h = weight_shape[dim]
        w = math.prod(weight_shape) // h
        self.register_buffer("u", jax.random.normal(jax.random.key(0), (h,)))
        self.register_buffer("v", jax.random.normal(jax.random.key(1), (w,)))

    def forward(self, weight):
        h = weight.shape[self.dim]
        wmat = jnp.moveaxis(weight, self.dim, 0).reshape(h, -1)
        u, v = self.u, self.v
        for _ in range(self.power_iters):
            v = wmat.T @ u
            v = v / (jnp.linalg.norm(v) + self.eps)
            u = wmat @ v
            u = u / (jnp.linalg.norm(u) + self.eps)
        if self.training:
            self.update_buffer("u", u)
            self.update_buffer("v", v)
        sigma = u @ wmat @ v
        return weight / sigma


class GRUCell(Layer):
    """GRU step (reference: dygraph/nn.py GRUUnit / operators/gru_unit_op)."""

    def __init__(self, input_size: int, hidden_size: int, dtype=None):
        super().__init__()
        self.hidden_size = hidden_size
        self.create_parameter("w_ih", (input_size, 3 * hidden_size), dtype,
                              I.XavierUniform())
        self.create_parameter("w_hh", (hidden_size, 3 * hidden_size), dtype,
                              I.XavierUniform())
        self.create_parameter("bias", (3 * hidden_size,), dtype,
                              I.Constant(0.0), is_bias=True)

    def forward(self, x, h):
        gates = x @ self.w_ih + self.bias
        hh = h @ self.w_hh
        hs = self.hidden_size
        r = jax.nn.sigmoid(gates[..., :hs] + hh[..., :hs])
        z = jax.nn.sigmoid(gates[..., hs:2 * hs] + hh[..., hs:2 * hs])
        n = jnp.tanh(gates[..., 2 * hs:] + r * hh[..., 2 * hs:])
        new_h = (1.0 - z) * n + z * h
        return new_h, new_h


class LSTMCell(Layer):
    """LSTM step (reference: operators/lstm_unit_op / cudnn_lstm capability)."""

    def __init__(self, input_size: int, hidden_size: int,
                 forget_bias: float = 1.0, dtype=None):
        super().__init__()
        self.hidden_size, self.forget_bias = hidden_size, forget_bias
        self.create_parameter("w_ih", (input_size, 4 * hidden_size), dtype,
                              I.XavierUniform())
        self.create_parameter("w_hh", (hidden_size, 4 * hidden_size), dtype,
                              I.XavierUniform())
        self.create_parameter("bias", (4 * hidden_size,), dtype,
                              I.Constant(0.0), is_bias=True)

    def forward(self, x, state):
        h, c = state
        gates = x @ self.w_ih + h @ self.w_hh + self.bias
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        f = jax.nn.sigmoid(f + self.forget_bias)
        i = jax.nn.sigmoid(i)
        o = jax.nn.sigmoid(o)
        g = jnp.tanh(g)
        new_c = f * c + i * g
        new_h = o * jnp.tanh(new_c)
        return new_h, (new_h, new_c)


class RNN(Layer):
    """Run a cell over time via lax.scan (recurrent_op / DynamicRNN analog on
    padded batches; masking respects `lengths` like LoD did)."""

    def __init__(self, cell: Layer, time_major: bool = False):
        super().__init__()
        self.cell = cell
        self.time_major = time_major

    def forward(self, x, initial_state, lengths=None):
        from ..ops.control_flow import scan

        if not self.time_major:
            x = jnp.swapaxes(x, 0, 1)  # (T, B, D)
        t = x.shape[0]

        def step(carry, inp):
            state, pos = carry
            t_x, = inp
            out, new_state = self.cell(t_x, state)
            if lengths is not None:
                active = (pos < lengths).reshape((-1,) + (1,) * (out.ndim - 1))
                new_state = jax.tree_util.tree_map(
                    lambda n, o: jnp.where(active, n, o), new_state, state)
                out = out * active.astype(out.dtype)
            return (new_state, pos + 1), out

        (final_state, _), outs = scan(step, (initial_state, 0), (x,))
        if not self.time_major:
            outs = jnp.swapaxes(outs, 0, 1)
        return outs, final_state


class _MHADecodeMixin:
    """Incremental-decode pieces for MultiHeadAttention (KV cache).

    The reference era decodes with an RNN whose state is O(1) per step;
    the transformer analog needs the K/V of every past position. These
    methods keep decode O(T) per step instead of re-running the stack
    over the whole prefix (O(T^2) per step) the way naive scan decode
    does.
    """

    def init_cache(self, batch: int, capacity: int, dtype=None):
        """Zeroed (B, capacity, h_kv, hd) K and V caches."""
        dt = dtype or default_dtype()
        shape = (batch, capacity, self.num_kv_heads, self.head_dim)
        return jnp.zeros(shape, dt), jnp.zeros(shape, dt)

    def project_kv(self, key, value=None):
        """One-time K/V projection (cross-attention over fixed memory)."""
        value = key if value is None else value
        b, tk, _ = key.shape
        k = self.k_proj(key).reshape(b, tk, self.num_kv_heads,
                                     self.head_dim)
        v = self.v_proj(value).reshape(b, tk, self.num_kv_heads,
                                       self.head_dim)
        return k, v

    def attend_kv(self, query, k, v, attn_mask=None, q_positions=None,
                  decode_t=None, window=None):
        """Attention of ``query`` (B, Tq, D) against PRE-PROJECTED k/v.
        ``q_positions``: absolute positions for rotary queries (the
        cached K was rotated at write time — the RoPE cache
        convention). ``decode_t`` (with Tq == 1): the cache cursor —
        eligible shapes ride the Pallas flash-decode kernel, which
        applies the pos <= decode_t (and ``window``) mask in-kernel and
        reads only live cache blocks from HBM; ineligible shapes fall
        back to ``attn_mask`` (callers pass both)."""
        from ..ops.attention import (_get_flash_decode, decode_flash_ok,
                                     rotary_embedding,
                                     scaled_dot_product_attention)

        b, tq, d = query.shape
        q = self.q_proj(query).reshape(b, tq, self.num_heads,
                                       self.head_dim)
        if q_positions is not None:
            q = rotary_embedding(q, q_positions,
                                 theta=self.rotary_theta)
        if (decode_t is not None and tq == 1 and self.use_flash
                and decode_flash_ok(k.shape[1], self.head_dim)
                and _get_flash_decode() is not None):
            out = _get_flash_decode()(q, k, v, decode_t, window=window)
        else:
            out = scaled_dot_product_attention(
                q, k, v, mask=attn_mask, use_flash=self.use_flash)
        return self.out_proj(out.reshape(b, tq, d))

    def forward_chunk(self, x_chunk, cache_k, cache_v, t0, window=None,
                      decode_kernel: bool = False):
        """S decode positions in ONE call: project the chunk's K/V into
        the caches at [t0, t0+S) and attend each position i over cache
        positions <= t0+i (optionally only the last ``window``).
        ``x_chunk``: (B, S, D); returns (out (B, S, D), cache_k,
        cache_v). One speculative-decoding target-scoring pass over
        gamma drafts = one forward_chunk; S=1 is the classic decode
        step. Caller guarantees t0+S <= capacity (dynamic_update_slice
        would silently clamp the write window otherwise)."""
        from jax import lax

        b, s, _ = x_chunk.shape
        cap = cache_k.shape[1]
        # one positions array shared by the k rotation here and the q
        # rotation inside attend_kv — they must never desynchronize
        pos_chunk = t0 + jnp.arange(s, dtype=jnp.int32)       # (S,)
        k_c, v_c = self._project_kv_t(x_chunk, pos_chunk)
        cache_k = lax.dynamic_update_slice_in_dim(
            cache_k, k_c.astype(cache_k.dtype), t0, axis=1)
        cache_v = lax.dynamic_update_slice_in_dim(
            cache_v, v_c.astype(cache_v.dtype), t0, axis=1)
        pos = jnp.arange(cap)
        keep = pos[None, :] <= pos_chunk[:, None]             # (S, cap)
        if window is not None:
            keep &= pos[None, :] > pos_chunk[:, None] - window
        out = self.attend_kv(
            x_chunk, cache_k, cache_v, attn_mask=keep[None, None],
            q_positions=pos_chunk if self.rotary else None,
            # the decode kernel is an OPT-IN (plain jit decode loops):
            # its scalar-prefetch pallas_call must not be dragged under
            # an outer vmap (the speculative per-row loop) where the
            # batching rule would reject it
            decode_t=(t0 if decode_kernel and s == 1 else None),
            window=window)
        return out, cache_k, cache_v

    def _project_kv_t(self, x_t, positions):
        """Project (and rotate) this step's K/V: x_t (B, S, D) ->
        (B, S, kv_heads, head_dim) each; ``positions`` (S,) or (B, S)
        absolute positions for the rotary K convention."""
        b, s, _ = x_t.shape
        k_t = self.k_proj(x_t).reshape(b, s, self.num_kv_heads,
                                       self.head_dim)
        v_t = self.v_proj(x_t).reshape(b, s, self.num_kv_heads,
                                       self.head_dim)
        if self.rotary:
            from ..ops.attention import rotary_embedding

            k_t = rotary_embedding(k_t, positions,
                                   theta=self.rotary_theta)
        return k_t, v_t

    def forward_step_paged(self, x_t, kpool, vpool, table, t_rows,
                           window=None):
        """One decode position PER ROW against a PAGED cache
        (ops/paged_kv.py): project+rotate this position's K/V, scatter
        into each row's page at its logical cursor, attend over the
        row's pages (paged kernel when eligible, gather fallback).
        ``x_t``: (B, 1, D); returns (out, kpool, vpool)."""
        from ..ops import paged_kv

        pos_rows = t_rows.astype(jnp.int32)[:, None]          # (B, 1)
        k_t, v_t = self._project_kv_t(x_t, pos_rows)
        kpool, vpool = paged_kv.write_rows(
            kpool, vpool, table, pos_rows[:, 0], k_t, v_t,
            kpool.shape[1])
        out = paged_kv.attend(
            self._rotated_q(x_t, pos_rows), kpool, vpool, table,
            pos_rows[:, 0], window=window)
        b, tq, d = x_t.shape
        return (self.out_proj(out.reshape(b, tq, d)), kpool, vpool)

    def forward_chunk_paged(self, x_chunk, kpool, vpool, table_row,
                            t0, window=None):
        """S prefill positions for ONE row (batch 1) against the paged
        cache: chunk-write, then attend each position i over pages up
        to t0+i (gather path — prefill runs once per request).
        ``x_chunk``: (1, S, D); returns (out, kpool, vpool)."""
        from ..ops import paged_kv
        from ..ops.attention import scaled_dot_product_attention

        b, s, d = x_chunk.shape
        pos_chunk = t0 + jnp.arange(s, dtype=jnp.int32)       # (S,)
        k_c, v_c = self._project_kv_t(x_chunk, pos_chunk)
        kpool, vpool = paged_kv.write_chunk(
            kpool, vpool, table_row, t0, k_c, v_c, kpool.shape[1])
        # static chunk extent (the bucketed-prefill case: t0 is a
        # Python int) -> gather/dequantize only the live page columns
        # instead of the row's full logical view
        upto = t0 + s if isinstance(t0, int) else None
        k = paged_kv.gather_rows(kpool, table_row[None], upto=upto)
        v = paged_kv.gather_rows(vpool, table_row[None], upto=upto)
        cap = k.shape[1]
        pos = jnp.arange(cap)
        keep = pos[None, :] <= pos_chunk[:, None]             # (S, cap)
        if window is not None:
            keep &= pos[None, :] > pos_chunk[:, None] - window
        out = scaled_dot_product_attention(
            self._rotated_q(x_chunk, pos_chunk), k, v,
            mask=keep[None, None], use_flash=False)
        return (self.out_proj(out.reshape(b, s, d)), kpool, vpool)

    def _rotated_q(self, query, positions):
        """Projected (and rotated) q for the paged paths — the same
        prologue attend_kv applies."""
        from ..ops.attention import rotary_embedding

        b, tq, d = query.shape
        q = self.q_proj(query).reshape(b, tq, self.num_heads,
                                       self.head_dim)
        if self.rotary:
            q = rotary_embedding(q, positions,
                                 theta=self.rotary_theta)
        return q

    def forward_step(self, x_t, cache_k, cache_v, t, window=None,
                     decode_kernel: bool = False):
        """One decode step (``x_t``: (B, 1, D)) — forward_chunk S=1."""
        return self.forward_chunk(x_t, cache_k, cache_v, t,
                                  window=window,
                                  decode_kernel=decode_kernel)

    def forward_step_rows(self, x_t, cache_k, cache_v, t_rows,
                          window=None, decode_kernel: bool = False):
        """One decode position PER ROW at per-row cursors ``t_rows``
        (B,) — the continuous-batching step (each serving slot at its
        own position). Cache writes land at each row's own index
        (vmapped dynamic_update_slice); attention rides the
        flash-decode kernel's per-row-cursor form when eligible, else
        a per-row masked XLA path. ``x_t``: (B, 1, D)."""
        from jax import lax

        b = x_t.shape[0]
        cap = cache_k.shape[1]
        pos_rows = t_rows.astype(jnp.int32)[:, None]          # (B, 1)
        k_t, v_t = self._project_kv_t(x_t, pos_rows)
        write = jax.vmap(lambda c, u, s: lax.dynamic_update_slice_in_dim(
            c, u, s, axis=0))
        cache_k = write(cache_k, k_t.astype(cache_k.dtype),
                        pos_rows[:, 0])
        cache_v = write(cache_v, v_t.astype(cache_v.dtype),
                        pos_rows[:, 0])
        pos = jnp.arange(cap)[None, :]
        keep = pos <= pos_rows
        if window is not None:
            keep &= pos > pos_rows - window
        out = self.attend_kv(
            x_t, cache_k, cache_v,
            attn_mask=keep[:, None, None, :],
            q_positions=pos_rows if self.rotary else None,
            decode_t=(pos_rows[:, 0] if decode_kernel else None),
            window=window)
        return out, cache_k, cache_v

    def forward_chunk_rows(self, x_chunk, cache_k, cache_v, t0_rows,
                           window=None):
        """S decode positions PER ROW at per-row chunk starts
        ``t0_rows`` (B,) — the speculative verify chunk over a
        continuous-batching arena (each slot scores its gamma+1
        candidates at its OWN cursor). ``x_chunk``: (B, S, D); returns
        (out (B, S, D), cache_k, cache_v). Caller contract matches
        forward_chunk: position i of row b attends cache positions
        <= t0_rows[b]+i; writes at t0+S past capacity clamp (retired
        rows park past capacity — junk at the clamped tail is
        overwritten by a later real write before any query attends
        it)."""
        from jax import lax

        b, s, _ = x_chunk.shape
        cap = cache_k.shape[1]
        pos_chunk = (t0_rows.astype(jnp.int32)[:, None]
                     + jnp.arange(s, dtype=jnp.int32)[None, :])  # (B, S)
        k_c, v_c = self._project_kv_t(x_chunk, pos_chunk)
        write = jax.vmap(lambda c, u, t: lax.dynamic_update_slice_in_dim(
            c, u, t, axis=0))
        cache_k = write(cache_k, k_c.astype(cache_k.dtype),
                        t0_rows.astype(jnp.int32))
        cache_v = write(cache_v, v_c.astype(cache_v.dtype),
                        t0_rows.astype(jnp.int32))
        pos = jnp.arange(cap)
        keep = pos[None, None, :] <= pos_chunk[:, :, None]   # (B, S, cap)
        if window is not None:
            keep &= pos[None, None, :] > pos_chunk[:, :, None] - window
        out = self.attend_kv(
            x_chunk, cache_k, cache_v, attn_mask=keep[:, None],
            q_positions=pos_chunk if self.rotary else None,
            window=window)
        return out, cache_k, cache_v

    def forward_chunk_paged_rows(self, x_chunk, kpool, vpool, table,
                                 t0_rows, window=None):
        """S decode positions PER ROW against the PAGED cache at
        per-row chunk starts (the paged-arena speculative verify
        chunk): chunk-write every row's candidates at its own logical
        offset (OOB rows drop — parked cursors), attend over each
        row's pages via the gather path (S is gamma+1-small; the paged
        decode kernel stays the S=1 hot loop). ``x_chunk``: (B, S, D);
        returns (out, kpool, vpool)."""
        from ..ops import paged_kv
        from ..ops.attention import scaled_dot_product_attention

        b, s, d = x_chunk.shape
        pos_chunk = (t0_rows.astype(jnp.int32)[:, None]
                     + jnp.arange(s, dtype=jnp.int32)[None, :])  # (B, S)
        k_c, v_c = self._project_kv_t(x_chunk, pos_chunk)
        kpool, vpool = paged_kv.write_chunk_rows(
            kpool, vpool, table, t0_rows.astype(jnp.int32), k_c, v_c,
            kpool.shape[1])
        k = paged_kv.gather_rows(kpool, table)
        v = paged_kv.gather_rows(vpool, table)
        cap = k.shape[1]
        pos = jnp.arange(cap)
        keep = pos[None, None, :] <= pos_chunk[:, :, None]   # (B, S, cap)
        if window is not None:
            keep &= pos[None, None, :] > pos_chunk[:, :, None] - window
        out = scaled_dot_product_attention(
            self._rotated_q(x_chunk, pos_chunk), k, v,
            mask=keep[:, None], use_flash=False)
        return (self.out_proj(out.reshape(b, s, d)), kpool, vpool)


class MultiHeadAttention(_MHADecodeMixin, Layer):
    """Transformer attention. The reference builds this from primitives
    (nets.py:343 scaled_dot_product_attention); here it's a first-class layer
    with an optional Pallas flash-attention path on TPU."""

    def __init__(self, embed_dim: int, num_heads: int, dropout: float = 0.0,
                 bias: bool = True, use_flash: bool = True,
                 seq_parallel: Optional[str] = None, dtype=None,
                 num_kv_heads: Optional[int] = None,
                 rotary: bool = False, rotary_theta: float = 10000.0):
        super().__init__()
        enforce(embed_dim % num_heads == 0,
                "embed_dim %s not divisible by heads %s", embed_dim, num_heads)
        # RoPE on q/k after projection (self-attention decoder blocks);
        # applied on the GLOBAL arrays before any SP sharding, so ring/
        # Ulysses see position-correct rotations
        self.rotary = rotary
        self.rotary_theta = float(rotary_theta)
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        # GQA/MQA: fewer K/V heads than Q heads (the flash kernel reads
        # shared K/V blocks via its index map; XLA repeats heads)
        self.num_kv_heads = num_kv_heads or num_heads
        enforce(num_heads % self.num_kv_heads == 0,
                "num_heads %s not divisible by num_kv_heads %s",
                num_heads, self.num_kv_heads)
        self.dropout_p = dropout
        self.use_flash = use_flash
        # None | "ring" | "ulysses": shard attention over the 'sp' mesh axis
        self.seq_parallel = seq_parallel
        # GQA under SP (r5): ring rotates kv blocks with their fewer
        # heads; Ulysses shards whole groups and enforces
        # kv_heads % sp == 0 at CALL time (the mesh isn't known here) —
        # its typed error points at ring for kv_heads < sp
        kv_dim = self.num_kv_heads * self.head_dim
        self.q_proj = Linear(embed_dim, embed_dim, bias_attr=bias)
        self.k_proj = Linear(embed_dim, kv_dim, bias_attr=bias)
        self.v_proj = Linear(embed_dim, kv_dim, bias_attr=bias)
        self.out_proj = Linear(embed_dim, embed_dim, bias_attr=bias)

    def forward(self, query, key=None, value=None, attn_mask=None,
                causal: bool = False, segment_ids=None,
                window: Optional[int] = None):
        key = query if key is None else key
        value = key if value is None else value
        b, tq, d = query.shape
        tk = key.shape[1]
        h, hd = self.num_heads, self.head_dim
        q = self.q_proj(query).reshape(b, tq, h, hd)
        k, v = self.project_kv(key, value)
        if self.rotary:
            from ..ops.attention import rotary_embedding

            enforce(tk == tq, "rotary MHA is self-attention shaped "
                    "(tq=%s != tk=%s)", tq, tk)
            pos = jnp.arange(tq)
            q = rotary_embedding(q, pos, theta=self.rotary_theta)
            k = rotary_embedding(k, pos, theta=self.rotary_theta)

        if self.seq_parallel is not None:
            # key-padding masks ((B, Tk) or (B, 1, 1, Tk)) ride the SP
            # paths (ring rotates the mask block with its K/V; Ulysses
            # all-gathers it); anything per-head/per-query is an explicit
            # error, never a silent fall-back to full attention — the
            # full path materializes (B,H,T,T) scores and would OOM on
            # exactly the sequence lengths SP exists for
            kv_mask = None
            if attn_mask is not None:
                from ..ops.attention import _as_kv_mask

                kv_mask = _as_kv_mask(attn_mask, b, tk)
                enforce(kv_mask is not None,
                        "seq_parallel=%s supports only key-padding masks "
                        "((B, Tk) or (B, 1, 1, Tk)); got shape %s",
                        self.seq_parallel, attn_mask.shape)
            enforce(not (self.training and self.dropout_p > 0),
                    "seq_parallel attention does not support attention "
                    "dropout; set dropout=0 on MultiHeadAttention")
            if self.seq_parallel == "ring":
                enforce(tk == tq, "ring attention requires self-attention "
                        "shapes (tq=%s != tk=%s); use 'ulysses' for "
                        "cross-attention", tq, tk)
            from ..parallel.context_parallel import context_parallel_attention

            kw = ({"use_flash": self.use_flash}
                  if self.seq_parallel in ("ulysses", "ring") else {})
            out = context_parallel_attention(
                q, k, v, impl=self.seq_parallel, causal=causal,
                kv_mask=kv_mask, segment_ids=segment_ids, window=window,
                **kw)
        else:
            from ..ops.attention import scaled_dot_product_attention

            out = scaled_dot_product_attention(
                q, k, v, mask=attn_mask, causal=causal,
                dropout_p=self.dropout_p if self.training else 0.0,
                dropout_key=self.rng("attn_dropout") if (self.training and self.dropout_p > 0) else None,
                use_flash=self.use_flash, segment_ids=segment_ids,
                window=window)
        out = out.reshape(b, tq, d)
        return self.out_proj(out)


def _apply_act(x, act: Optional[str]):
    if act is None:
        return x
    fn = getattr(OM, act, None) or getattr(jax.nn, act, None)
    enforce(fn is not None, "unknown activation %s", act)
    return fn(x)


# Activation layers (paddle-style class wrappers)
class ReLU(Layer):
    def forward(self, x):
        return OM.relu(x)


class GELU(Layer):
    def __init__(self, approximate: bool = False):
        super().__init__()
        self.approximate = approximate

    def forward(self, x):
        return OM.gelu(x, self.approximate)


class Sigmoid(Layer):
    def forward(self, x):
        return OM.sigmoid(x)


class Tanh(Layer):
    def forward(self, x):
        return OM.tanh(x)


class Softmax(Layer):
    def __init__(self, axis: int = -1):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return ON.softmax(x, self.axis)


class Flatten(Layer):
    def __init__(self, start_axis: int = 1):
        super().__init__()
        self.start_axis = start_axis

    def forward(self, x):
        from ..ops.tensor import flatten

        return flatten(x, self.start_axis)


class MultiBoxHead(Layer):
    """SSD detection head over multiple feature maps (reference:
    python/paddle/fluid/layers/detection.py multi_box_head): a 3x3 conv
    per map predicts box deltas (4A channels) and class logits (CA
    channels); priors come from ops.detection.prior_box per map.

    ``in_channels``: channel count of each input feature map (the fluid
    version infers these from the graph; eager layers declare them).
    min/max sizes follow the fluid ratio derivation when not given.
    """

    def __init__(self, in_channels: Sequence[int], image_size,
                 num_classes: int, *, base_size: Optional[int] = None,
                 aspect_ratios: Sequence[Sequence[float]] = (),
                 min_ratio: int = 20, max_ratio: int = 90,
                 min_sizes: Optional[Sequence[float]] = None,
                 max_sizes: Optional[Sequence[float]] = None,
                 steps: Optional[Sequence[float]] = None,
                 variances: Sequence[float] = (0.1, 0.1, 0.2, 0.2),
                 flip: bool = True, clip: bool = False,
                 offset: float = 0.5, dtype=None):
        super().__init__()
        from ..ops import detection as _D

        n_maps = len(in_channels)
        self.image_size = ((image_size, image_size)
                           if isinstance(image_size, int) else
                           tuple(image_size))
        base = base_size or self.image_size[0]
        if min_sizes is None:
            # fluid derivation: first map at base*10%%, the rest spread
            # min_ratio..max_ratio evenly (layers/detection.py)
            min_sizes, max_sizes = [base * 0.1], [base * 0.2]
            if n_maps > 1:
                step = int(math.floor((max_ratio - min_ratio)
                                      / max(n_maps - 2, 1)))
                for r in range(min_ratio, max_ratio + 1, max(step, 1)):
                    min_sizes.append(base * r / 100.0)
                    max_sizes.append(base * (r + step) / 100.0)
                min_sizes = min_sizes[:n_maps]
                max_sizes = max_sizes[:n_maps]
        self.min_sizes = [([s] if not isinstance(s, (list, tuple)) else
                           list(s)) for s in min_sizes]
        self.max_sizes = [([s] if not isinstance(s, (list, tuple)) else
                           list(s)) for s in (max_sizes or [])]
        if not aspect_ratios:
            aspect_ratios = [[2.0]] * n_maps
        self.aspect_ratios = [list(a) for a in aspect_ratios]
        self.steps = steps
        self.variances = tuple(variances)
        self.flip, self.clip, self.offset = flip, clip, offset
        self.num_classes = num_classes

        self.num_priors = []
        self.loc_convs = LayerList()
        self.conf_convs = LayerList()
        for i, c_in in enumerate(in_channels):
            a = _D.prior_box_count(
                self.min_sizes[i],
                self.max_sizes[i] if self.max_sizes else (),
                self.aspect_ratios[i], flip)
            self.num_priors.append(a)
            self.loc_convs.append(Conv2D(c_in, a * 4, 3, padding=1,
                                         dtype=dtype))
            self.conf_convs.append(Conv2D(c_in, a * num_classes, 3,
                                          padding=1, dtype=dtype))

    def forward(self, inputs):
        from ..ops import detection as _D

        locs, confs, boxes, variances = [], [], [], []
        for i, x in enumerate(inputs):
            n = x.shape[0]
            loc = self.loc_convs[i](x)          # (N, 4A, H, W)
            conf = self.conf_convs[i](x)        # (N, CA, H, W)
            h, w = x.shape[2], x.shape[3]
            locs.append(jnp.transpose(loc, (0, 2, 3, 1))
                        .reshape(n, -1, 4))
            confs.append(jnp.transpose(conf, (0, 2, 3, 1))
                         .reshape(n, -1, self.num_classes))
            step = ((self.steps[i], self.steps[i])
                    if self.steps else (0.0, 0.0))
            b, v = _D.prior_box(
                (h, w), self.image_size, self.min_sizes[i],
                self.max_sizes[i] if self.max_sizes else (),
                self.aspect_ratios[i], variances=self.variances,
                flip=self.flip, clip=self.clip, step=step,
                offset=self.offset)
            boxes.append(b.reshape(-1, 4))
            variances.append(v.reshape(-1, 4))
        return (jnp.concatenate(locs, 1), jnp.concatenate(confs, 1),
                jnp.concatenate(boxes, 0), jnp.concatenate(variances, 0))
