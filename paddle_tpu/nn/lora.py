"""LoRA — low-rank adaptation for parameter-efficient fine-tuning.

out = x @ W_frozen + (alpha/r) * dropout(x) @ A @ B, with A (in, r)
normal-initialized and B (r, out) zero-initialized, so an adapted model
is EXACTLY the base model at step 0 and only (in+out)*r values train
per wrapped projection.

Framework-native shape: ``apply_lora`` rewrites Linear sublayers in
place the way quant.quantize_model wraps quantizable layers; the frozen
base weight/bias move from params to BUFFERS, so the trainable
dict (``named_parameters``) is exactly the adapter set plus whatever
was never wrapped — a Trainer or a hand-rolled value_and_grad sees only
what should move, and the frozen weights still ride functional_call /
jit donation as buffers instead of being baked into the executable as
constants. ``merge_lora`` folds A@B back into plain Linears for
serving/export.

Green-field vs the reference (its fine-tuning story is full-parameter
training; nearest spirit: the slim distill/prune package,
/root/reference/python/paddle/fluid/contrib/slim/ — adapt a big model
cheaply instead of retraining it).
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence

import jax.numpy as jnp

from .. import initializer as I
from ..core.dtypes import get_policy
from ..core.enforce import enforce
from .layer import Layer
from .layers import Dropout, Linear, _apply_act


class LoRALinear(Layer):
    """A Linear with its weight frozen (buffer) plus a trainable
    low-rank delta. Drop-in: same forward contract (bias, act,
    AMP policy) as the Linear it wraps."""

    def __init__(self, inner: Linear, r: int,
                 alpha: Optional[float] = None, dropout: float = 0.0):
        super().__init__()
        enforce(isinstance(inner, Linear),
                "LoRALinear wraps nn.Linear, got %s",
                type(inner).__name__)
        enforce(r >= 1, "rank must be >= 1, got %s", r)
        self.in_features = inner.in_features
        self.out_features = inner.out_features
        self.act = inner.act
        self.has_bias = inner.has_bias
        self.r = r
        self.scale = float(alpha if alpha is not None else r) / r
        # frozen base: buffers, not params — out of the trainable dict,
        # still threaded through functional_call/checkpoints
        self.register_buffer("weight", inner.weight)
        if inner.has_bias:
            self.register_buffer("bias", inner.bias)
        self.drop = Dropout(dropout)
        self.create_parameter("lora_a", (self.in_features, r), None,
                              I.Normal(scale=0.02))
        self.create_parameter("lora_b", (r, self.out_features), None,
                              I.Constant(0.0))

    def forward(self, x):
        pol = get_policy()
        xc = pol.cast_to_compute(x)
        out = jnp.matmul(xc, pol.cast_to_compute(self.weight))
        delta = jnp.matmul(
            jnp.matmul(pol.cast_to_compute(self.drop(x)),
                       pol.cast_to_compute(self.lora_a)),
            pol.cast_to_compute(self.lora_b))
        out = out + self.scale * delta
        if self.has_bias:
            out = out + pol.cast_to_compute(self.bias)
        return _apply_act(pol.cast_to_output(out), self.act)

    def merged_weight(self):
        """W + (alpha/r) A@B in the base weight's dtype."""
        delta = (self.lora_a.astype(jnp.float32)
                 @ self.lora_b.astype(jnp.float32))
        return (self.weight.astype(jnp.float32)
                + self.scale * delta).astype(self.weight.dtype)

    def to_linear(self) -> Linear:
        """A plain Linear with the adapter folded in (serving/export)."""
        # constant init: the weight is overwritten on the next line, and
        # a Xavier draw here would both waste work and advance the
        # global PRNG stream once per merged layer
        lin = Linear(self.in_features, self.out_features,
                     bias_attr=self.has_bias, act=self.act,
                     weight_init=I.Constant(0.0),
                     bias_init=I.Constant(0.0))
        lin._params["weight"] = self.merged_weight()
        if self.has_bias:
            lin._params["bias"] = self.bias
        return lin


def apply_lora(model: Layer, r: int, alpha: Optional[float] = None,
               dropout: float = 0.0,
               targets: Optional[Sequence[str]] = None,
               predicate: Optional[Callable[[str, Layer], bool]] = None,
               ) -> List[str]:
    """Wrap matching Linear sublayers of ``model`` in place; returns the
    wrapped paths. ``targets``: attribute-name suffixes to adapt (e.g.
    ("q_proj", "v_proj") — the classic attention recipe); None adapts
    every Linear. ``predicate(path, layer)`` further filters. Do this
    BEFORE snapshotting params: the trainable dict shrinks to the
    adapters (+ never-wrapped layers); frozen weights become buffers."""
    from .rewrite import rewrite_linears

    return rewrite_linears(
        model, lambda lin: LoRALinear(lin, r, alpha, dropout),
        targets=targets, predicate=predicate,
        skip=lambda sub: isinstance(sub, LoRALinear),
        what="apply_lora")


def lora_parameters(model: Layer) -> dict:
    """The trainable adapter subset of ``model.named_parameters()`` —
    what the fine-tuning optimizer should see."""
    return {k: v for k, v in model.named_parameters().items()
            if k.endswith("lora_a") or k.endswith("lora_b")}


def merge_lora(model: Layer) -> List[str]:
    """Fold every LoRALinear back into a plain Linear in place (the
    adapter disappears into the weight; forward is byte-for-byte the
    adapted model's in eval mode). Returns the merged paths."""
    merged: List[str] = []

    def rewrite(layer: Layer, prefix: str):
        for name, sub in list(layer._sublayers.items()):
            path = f"{prefix}{name}"
            if isinstance(sub, LoRALinear):
                layer._sublayers[name] = sub.to_linear()
                object.__setattr__(layer, name, layer._sublayers[name])
                merged.append(path)
            else:
                rewrite(sub, f"{path}.")

    rewrite(model, "")
    return merged
