"""Mixture-of-Experts FFN — Switch-style top-1 routing over the 'ep'
mesh axis.

Green-field TPU design (the reference has no MoE; its expert-parallel
niche is PSLib's giant sharded embeddings, which this framework covers
with parallel.ShardedEmbedding — SURVEY §2.5). This layer completes the
'ep' axis story for TRANSFORMER compute: expert weights shard
``P('ep', ...)``, routing uses the dense one-hot dispatch/combine
einsum formulation (Mesh-TensorFlow / Switch-Transformer lineage) so the
whole layer is static-shaped, MXU-friendly, and the SPMD partitioner
inserts the token all-to-all between the data-parallel token layout and
the expert-parallel compute layout — no sorting, no ragged shapes, no
host control flow.

Semantics (Switch Transformer, top-1):
- router: softmax over ``num_experts`` logits per token; each token goes
  to its argmax expert with its gate probability as the scale.
- capacity: each expert processes at most ``ceil(tokens/E * cf)``
  tokens; overflow tokens are DROPPED (output zeros — callers keep the
  residual connection, so dropped tokens pass through identity).
- aux loss: ``E * sum_e(fraction_e * mean_prob_e)`` (the Switch
  load-balance loss; 1.0 at perfect balance), returned per call for the
  trainer to weight.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..core.enforce import enforce
from .. import initializer as I
from .layer import Layer

__all__ = ["SwitchFFN", "switch_moe"]


def switch_moe(x, router_w, w1, b1, w2, b2, *, capacity: int,
               act=jax.nn.gelu, top_k: int = 1):
    """Functional top-k MoE over tokens (k=1: Switch; k=2: GShard).

    x: (S, D) tokens; router_w: (D, E); w1: (E, D, F); b1: (E, F);
    w2: (E, F, D); b2: (E, D). Returns (y (S, D), aux_loss scalar,
    z_loss scalar, kept_fraction scalar — kept = the fraction of
    (token, choice) assignments that fit capacity; z_loss is the ST-MoE
    router stability term mean(logsumexp(logits)^2), weighted ~1e-3 by
    the trainer to keep router logits from drifting large).

    top-2 follows GShard's ordering: every token's FIRST choice claims
    its expert slot before any second choice does, and the two gates are
    renormalized to sum to 1 per token.
    """
    enforce(top_k in (1, 2), "top_k must be 1 or 2, got %s", top_k)
    s = x.shape[0]
    e = router_w.shape[1]
    logits = (x @ router_w).astype(jnp.float32)        # (S, E)
    probs = jax.nn.softmax(logits, axis=-1)
    z = jax.nn.logsumexp(logits, axis=-1)              # (S,)
    z_loss = jnp.mean(z * z)
    top_p, top_i = jax.lax.top_k(probs, top_k)         # (S, k)
    # Switch top-1 scales by the RAW router probability; GShard top-2
    # renormalizes the two gates to sum to 1 per token
    gates = (top_p if top_k == 1
             else top_p / jnp.sum(top_p, axis=-1, keepdims=True))
    onehots = [jax.nn.one_hot(top_i[:, j], e, dtype=jnp.float32)
               for j in range(top_k)]                  # k x (S, E)
    # positions within each expert's queue (arrival order — deterministic,
    # shard-invariant prefix sums); ALL first choices precede second ones
    pos = [jnp.cumsum(onehots[0], axis=0) * onehots[0]]  # (S, E), 1-based
    if top_k == 2:
        first_counts = jnp.sum(onehots[0], axis=0)     # (E,)
        pos.append((jnp.cumsum(onehots[1], axis=0) + first_counts[None, :])
                   * onehots[1])
    dmask = jnp.zeros((s, e, capacity), x.dtype)
    combine = jnp.zeros((s, e, capacity), x.dtype)
    kept_ct = jnp.zeros((), jnp.float32)
    for j in range(top_k):
        keep = (pos[j] > 0) & (pos[j] <= capacity)
        pos_c = jnp.clip(pos[j] - 1, 0, capacity - 1).astype(jnp.int32)
        slot = jax.nn.one_hot(pos_c, capacity, dtype=x.dtype)  # (S, E, C)
        dm = slot * keep.astype(x.dtype)[..., None]
        dmask = dmask + dm
        combine = combine + dm * gates[:, j].astype(x.dtype)[:, None, None]
        # BOOL mask counted in f32: a bf16 dmask sum saturates at 256
        # under the mixed_bf16 policy and would corrupt the metric
        kept_ct = kept_ct + jnp.sum(keep.astype(jnp.float32))
    expert_in = jnp.einsum("sec,sd->ecd", dmask, x)    # (E, C, D)
    h = act(jnp.einsum("ecd,edf->ecf", expert_in, w1) + b1[:, None, :])
    out_e = jnp.einsum("ecf,efd->ecd", h, w2) + b2[:, None, :]
    y = jnp.einsum("sec,ecd->sd", combine, out_e)      # dropped -> zeros
    # load-balance aux over FIRST-choice assignment (Switch/GShard form):
    # E * sum_e(fraction_of_tokens_e * mean_prob_e)
    frac = jnp.mean(onehots[0], axis=0)                # (E,)
    mean_prob = jnp.mean(probs, axis=0)                # (E,)
    aux = e * jnp.sum(frac * mean_prob)
    kept = kept_ct / (s * top_k)
    return (y, aux.astype(jnp.float32), z_loss.astype(jnp.float32),
            kept.astype(jnp.float32))


class SwitchFFN(Layer):
    """Drop-in MoE replacement for the position-wise FFN.

    ``forward(x (B, T, D)) -> (B, T, D)``; the load-balance aux loss
    and kept-token fraction of the call ride the BUFFER mechanism
    (``aux_loss``/``kept_fraction`` — functional callers collect them
    from functional_call's new_buffers, the BatchNorm-stats contract;
    the trainer adds ``aux_weight * aux_loss`` to the objective, 0.01 in
    the Switch paper).

    Expert weights are stacked ``(E, ...)``; under a mesh, place them
    ``P('ep', ...)`` (:func:`expert_param_spec`) and the partitioner
    inserts the token all-to-all between the dp token layout and the
    ep expert layout (golden-HLO tested).
    """

    def __init__(self, d_model: int, d_ff: int, num_experts: int,
                 capacity_factor: float = 1.25,
                 act=jax.nn.gelu, dtype=None, router_top_k: int = 1):
        super().__init__()
        enforce(num_experts >= 2, "SwitchFFN needs >= 2 experts, got %s",
                num_experts)
        enforce(capacity_factor > 0.0,
                "capacity_factor must be > 0, got %s", capacity_factor)
        enforce(router_top_k in (1, 2),
                "router_top_k must be 1 (Switch) or 2 (GShard), got %s",
                router_top_k)
        self.num_experts = num_experts
        self.capacity_factor = float(capacity_factor)
        self.act = act
        self.router_top_k = router_top_k
        self.create_parameter("router_w", (d_model, num_experts),
                              dtype, I.XavierUniform())
        self.create_parameter("w1", (num_experts, d_model, d_ff), dtype,
                              I.XavierUniform())
        self.create_parameter("b1", (num_experts, d_ff), dtype,
                              I.Constant(0.0), is_bias=True)
        self.create_parameter("w2", (num_experts, d_ff, d_model), dtype,
                              I.XavierUniform())
        self.create_parameter("b2", (num_experts, d_model), dtype,
                              I.Constant(0.0), is_bias=True)
        self.register_buffer("aux_loss", jnp.zeros((), jnp.float32))
        self.register_buffer("router_z_loss", jnp.zeros((), jnp.float32))
        self.register_buffer("kept_fraction", jnp.ones((), jnp.float32))

    def capacity(self, tokens: int) -> int:
        # top-k routing makes k*tokens assignments: capacity scales with
        # k (GShard convention) or the second choices would nearly all
        # drop at the default factor
        return max(1, math.ceil(tokens * self.router_top_k
                                / self.num_experts
                                * self.capacity_factor))

    def forward(self, x):
        b, t, d = x.shape
        y, aux, z_loss, kept = switch_moe(
            x.reshape(b * t, d), self.router_w,
            self.w1, self.b1, self.w2, self.b2,
            capacity=self.capacity(b * t), act=self.act,
            top_k=self.router_top_k)
        self.update_buffer("aux_loss", aux)
        self.update_buffer("router_z_loss", z_loss)
        self.update_buffer("kept_fraction", kept)
        return y.reshape(b, t, d)


def expert_param_spec(axis: str = "ep"):
    """Sharding rules for SwitchFFN params: experts over ``axis``, the
    router replicated (tiny) — compose with transformer_tp_rules."""
    from jax.sharding import PartitionSpec as P

    return [
        (r"(^|\.)w1$", P(axis, None, None)),
        (r"(^|\.)b1$", P(axis, None)),
        (r"(^|\.)w2$", P(axis, None, None)),
        (r"(^|\.)b2$", P(axis, None)),
    ]
