"""Shared in-place Linear-rewrite traversal — ONE definition of the
walk that apply_lora (nn/lora.py) and apply_weight_only_int8
(quant/weight_only.py) both wrap: recursive _sublayers descent,
attribute-suffix targeting, predicate filter, re-binding via
object.__setattr__ (the quantize_model idiom, quant/qat.py)."""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence

from ..core.enforce import enforce
from .layer import Layer
from .layers import Linear


def rewrite_linears(model: Layer, make: Callable[[Linear], Layer],
                    targets: Optional[Sequence[str]] = None,
                    predicate: Optional[
                        Callable[[str, Layer], bool]] = None,
                    skip: Optional[Callable[[Layer], bool]] = None,
                    what: str = "rewrite_linears") -> List[str]:
    """Replace matching Linear sublayers of ``model`` with
    ``make(linear)`` in place; returns the rewritten paths.
    ``targets``: attribute-name suffixes (None = every Linear);
    ``predicate(path, layer)`` filters further; ``skip(layer)`` guards
    against double-wrapping (e.g. an already-wrapped type)."""
    done: List[str] = []

    def walk(layer: Layer, prefix: str):
        for name, sub in list(layer._sublayers.items()):
            path = f"{prefix}{name}"
            if skip is not None and skip(sub):
                continue
            if (isinstance(sub, Linear)
                    and (targets is None
                         or any(name == t or name.endswith(t)
                                for t in targets))
                    and (predicate is None or predicate(path, sub))):
                layer._sublayers[name] = make(sub)
                object.__setattr__(layer, name, layer._sublayers[name])
                done.append(path)
            else:
                walk(sub, f"{path}.")

    enforce(not isinstance(model, Linear),
            "%s rewrites sublayers; wrap a bare Linear directly", what)
    walk(model, "")
    enforce(done, "%s matched no Linear sublayers (targets=%s)", what,
            targets)
    return done
