"""Multi-layer recurrent network layers — the cudnn_lstm capability
(reference: paddle/fluid/operators/cudnn_lstm_op.cu.cc — stacked,
optionally bidirectional LSTM executed by one fused kernel; here the fusion
is XLA's job: the per-direction recurrences are ``lax.scan``s from
ops/rnn.py with input projections hoisted onto the MXU).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp

from .. import initializer as I
from ..core.enforce import enforce
from ..ops import rnn as R
from .layer import Layer
from .layers import Dropout


class _RecurrentBase(Layer):
    """Shared stacked/bidirectional plumbing for LSTM and GRU."""

    num_gates = 4  # LSTM; GRU overrides

    def __init__(self, input_size: int, hidden_size: int, num_layers: int = 1,
                 direction: str = "forward", dropout: float = 0.0,
                 dtype=None, scan_unroll: int = 1):
        super().__init__()
        # lax.scan unroll factor for the time recurrence (1 = no unroll);
        # a throughput knob, identical math
        self.scan_unroll = scan_unroll
        enforce(direction in ("forward", "bidirect", "bidirectional"),
                "direction must be forward|bidirect, got %s", direction)
        self.input_size, self.hidden_size = input_size, hidden_size
        self.num_layers = num_layers
        self.bidirectional = direction != "forward"
        self.dropout_p = dropout
        ndir = 2 if self.bidirectional else 1
        g = self.num_gates
        for layer in range(num_layers):
            in_sz = input_size if layer == 0 else hidden_size * ndir
            for d in range(ndir):
                sfx = f"l{layer}" + ("_rev" if d else "")
                self.create_parameter(f"w_ih_{sfx}", (in_sz, g * hidden_size),
                                      dtype, I.XavierUniform())
                self.create_parameter(f"w_hh_{sfx}",
                                      (hidden_size, g * hidden_size), dtype,
                                      I.XavierUniform())
                self.create_parameter(f"bias_{sfx}", (g * hidden_size,),
                                      dtype, I.Constant(0.0), is_bias=True)
        self.drop = Dropout(dropout) if dropout > 0 else None

    def _run_direction(self, x, sfx, lengths, is_reverse):
        raise NotImplementedError

    def _stack_states(self, finals):
        raise NotImplementedError

    def forward(self, x, lengths=None):
        """x: (B, T, D) → (outputs (B, T, H*ndir), final_states stacked over
        (num_layers*ndir, B, H))."""
        finals = []
        h = x
        for layer in range(self.num_layers):
            fwd_out, fwd_fin = self._run_direction(
                h, f"l{layer}", lengths, False)
            if self.bidirectional:
                bwd_out, bwd_fin = self._run_direction(
                    h, f"l{layer}_rev", lengths, True)
                h = jnp.concatenate([fwd_out, bwd_out], axis=-1)
                finals += [fwd_fin, bwd_fin]
            else:
                h = fwd_out
                finals.append(fwd_fin)
            if self.drop is not None and layer < self.num_layers - 1:
                h = self.drop(h)
        return h, self._stack_states(finals)


class LSTM(_RecurrentBase):
    """Stacked (bi)LSTM. Final states: (h (L*ndir, B, H), c (L*ndir, B, H))."""

    num_gates = 4

    def _run_direction(self, x, sfx, lengths, is_reverse):
        return R.lstm(x, getattr(self, f"w_ih_{sfx}"),
                      getattr(self, f"w_hh_{sfx}"),
                      bias=getattr(self, f"bias_{sfx}"), lengths=lengths,
                      is_reverse=is_reverse, unroll=self.scan_unroll)

    def _stack_states(self, finals):
        return (jnp.stack([f[0] for f in finals]),
                jnp.stack([f[1] for f in finals]))


class GRU(_RecurrentBase):
    """Stacked (bi)GRU. Final state: (L*ndir, B, H)."""

    num_gates = 3

    def _run_direction(self, x, sfx, lengths, is_reverse):
        return R.gru(x, getattr(self, f"w_ih_{sfx}"),
                     getattr(self, f"w_hh_{sfx}"),
                     bias=getattr(self, f"bias_{sfx}"), lengths=lengths,
                     is_reverse=is_reverse, unroll=self.scan_unroll)

    def _stack_states(self, finals):
        return jnp.stack(finals)
