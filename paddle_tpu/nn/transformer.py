"""Transformer layers — encoder/decoder stacks over MultiHeadAttention.

The reference assembles transformers in model code from primitives
(reference: benchmark/fluid/models/machine_translation.py,
python/paddle/fluid/nets.py:343 scaled_dot_product_attention); here the
stack is first-class so the flash/ring-attention kernel paths and TP/SP
sharding rules have a single home.

TPU notes: pre-norm by default (stable in bf16), GELU FFN, static shapes
(padding/masking handles ragged batches — see ops/sequence.py).
"""

from __future__ import annotations

import math
from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..core.enforce import enforce
from .layer import Layer, LayerList
from .layers import Dropout, Embedding, LayerNorm, Linear, MultiHeadAttention


class FeedForward(Layer):
    """Position-wise FFN: Linear → act → dropout → Linear."""

    def __init__(self, d_model: int, dim_feedforward: int,
                 dropout: float = 0.1, activation: str = "gelu"):
        super().__init__()
        self.fc1 = Linear(d_model, dim_feedforward, act=activation)
        self.fc2 = Linear(dim_feedforward, d_model)
        self.drop = Dropout(dropout)

    def forward(self, x):
        return self.fc2(self.drop(self.fc1(x)))


class TransformerEncoderLayer(Layer):
    """``moe_experts > 0`` swaps the dense FFN for a Switch-MoE FFN
    (:class:`~paddle_tpu.nn.moe.SwitchFFN`) — experts shard over the
    'ep' mesh axis; the load-balance aux loss rides the layer's buffers
    (collect ``*.ffn.aux_loss`` from functional_call's new_buffers)."""

    def __init__(self, d_model: int, nhead: int, dim_feedforward: int,
                 dropout: float = 0.1, activation: str = "gelu",
                 normalize_before: bool = True, use_flash: bool = True,
                 seq_parallel=None, attn_window=None,
                 moe_experts: int = 0,
                 moe_capacity_factor: float = 1.25):
        super().__init__()
        self.normalize_before = normalize_before
        # sliding-window/local attention width (None = full)
        self.attn_window = attn_window
        # attention-probability dropout is unsupported under SP (the ring/
        # a2a paths have no per-probability RNG plan yet); residual/FFN
        # dropout below stays active, so regularization is not silently lost
        self.self_attn = MultiHeadAttention(
            d_model, nhead, dropout=0.0 if seq_parallel else dropout,
            use_flash=use_flash, seq_parallel=seq_parallel)
        if moe_experts:
            from .moe import SwitchFFN

            self.ffn = SwitchFFN(d_model, dim_feedforward, moe_experts,
                                 capacity_factor=moe_capacity_factor)
        else:
            self.ffn = FeedForward(d_model, dim_feedforward, dropout,
                                   activation)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.drop1 = Dropout(dropout)
        self.drop2 = Dropout(dropout)

    def forward(self, x, mask=None, segment_ids=None):
        if self.normalize_before:
            x = x + self.drop1(self.self_attn(self.norm1(x), attn_mask=mask,
                                              segment_ids=segment_ids,
                                              window=self.attn_window))
            x = x + self.drop2(self.ffn(self.norm2(x)))
        else:
            x = self.norm1(x + self.drop1(self.self_attn(
                x, attn_mask=mask, segment_ids=segment_ids,
                window=self.attn_window)))
            x = self.norm2(x + self.drop2(self.ffn(x)))
        return x


class TransformerDecoderLayer(Layer):
    def __init__(self, d_model: int, nhead: int, dim_feedforward: int,
                 dropout: float = 0.1, activation: str = "gelu",
                 normalize_before: bool = True, use_flash: bool = True,
                 seq_parallel=None, attn_window=None):
        super().__init__()
        self.normalize_before = normalize_before
        # sliding-window width for the causal SELF-attention (the
        # Mistral-style decoder pattern); cross-attention stays full
        self.attn_window = attn_window
        # attention-probability dropout off under SP (see EncoderLayer note)
        self.self_attn = MultiHeadAttention(
            d_model, nhead, dropout=0.0 if seq_parallel else dropout,
            use_flash=use_flash, seq_parallel=seq_parallel)
        # cross-attention keeps the standard path: its K/V length is the
        # (short) memory length, not the SP-sharded decoder length
        self.cross_attn = MultiHeadAttention(d_model, nhead, dropout=dropout,
                                             use_flash=use_flash)
        self.ffn = FeedForward(d_model, dim_feedforward, dropout, activation)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.norm3 = LayerNorm(d_model)
        self.drop1 = Dropout(dropout)
        self.drop2 = Dropout(dropout)
        self.drop3 = Dropout(dropout)

    def forward(self, x, memory, self_mask=None, cross_mask=None,
                causal: bool = True):
        if self.normalize_before:
            x = x + self.drop1(self.self_attn(self.norm1(x),
                                              attn_mask=self_mask,
                                              causal=causal,
                                              window=self.attn_window))
            x = x + self.drop2(self.cross_attn(self.norm2(x), memory, memory,
                                               attn_mask=cross_mask))
            x = x + self.drop3(self.ffn(self.norm3(x)))
        else:
            x = self.norm1(x + self.drop1(self.self_attn(
                x, attn_mask=self_mask, causal=causal,
                window=self.attn_window)))
            x = self.norm2(x + self.drop2(self.cross_attn(
                x, memory, memory, attn_mask=cross_mask)))
            x = self.norm3(x + self.drop3(self.ffn(x)))
        return x


class TransformerEncoder(Layer):
    """``remat=True`` wraps each block in ``jax.checkpoint`` so backward
    recomputes block activations instead of storing every layer's — the
    HBM-for-FLOPs trade that makes long-sequence training fit (TPU
    guidance: rematerialize at block boundaries). Applies on every call
    when enabled; meant for the jitted training path (eager callers
    should leave the default False)."""

    def __init__(self, num_layers: int, d_model: int, nhead: int,
                 dim_feedforward: int, dropout: float = 0.1,
                 activation: str = "gelu", normalize_before: bool = True,
                 use_flash: bool = True, seq_parallel=None,
                 remat: bool = False, scan_layers: bool = False,
                 attn_window=None, remat_policy: Optional[str] = None,
                 moe_experts: int = 0, moe_capacity_factor: float = 1.25):
        super().__init__()
        self.layers = LayerList([
            TransformerEncoderLayer(d_model, nhead, dim_feedforward, dropout,
                                    activation, normalize_before, use_flash,
                                    seq_parallel, attn_window=attn_window,
                                    moe_experts=moe_experts,
                                    moe_capacity_factor=moe_capacity_factor)
            for _ in range(num_layers)])
        self.final_norm = LayerNorm(d_model) if normalize_before else None
        self.remat = remat
        # None = save nothing (recompute everything); "dots" = save
        # matmul outputs and recompute only the elementwise tail — less
        # recompute FLOPs for a bit more HBM (the standard policy sweep
        # for MFU at long sequence). Validated HERE so a policy on a
        # non-remat encoder fails loudly instead of silently not running
        enforce(remat_policy in (None, "dots"),
                "remat_policy must be None or 'dots', got %r", remat_policy)
        enforce(remat_policy is None or remat,
                "remat_policy=%r requires remat=True", remat_policy)
        self.remat_policy = remat_policy
        # scan-over-layers: one traced block applied via lax.scan over
        # stacked per-layer params — the compiled module stays O(1) in
        # depth (compile time + HLO size for 24/48-layer stacks) and the
        # scan body is the natural remat boundary. Dropout must be 0:
        # the scan body shares one RNG stream, which would correlate
        # masks across layers (checked per-call: scan_layers is a plain
        # attribute).
        self._dropout_p = dropout
        self.scan_layers = scan_layers

    def _ckpt_policy(self):
        import jax

        if self.remat_policy is None:
            return None
        return jax.checkpoint_policies.dots_with_no_batch_dims_saveable

    def forward(self, x, mask=None, segment_ids=None):
        import jax
        from jax import lax

        if self.scan_layers and len(self.layers) > 1:
            enforce(self._dropout_p == 0.0 or not self.training,
                    "scan_layers needs dropout == 0 in training (one "
                    "traced body would reuse its RNG across layers); "
                    "unroll instead")
            from .layer import stacked_parameters

            stacked = stacked_parameters(self.layers)
            template = self.layers[0]

            def body(h, pl):
                out, _ = template.functional_call(
                    pl, h, mask=mask, segment_ids=segment_ids,
                    training=self.training)
                return out, None

            if self.remat:
                # prevent_cse is unnecessary inside scan (JAX docs) and
                # would insert optimization barriers per iteration
                body = jax.checkpoint(body, prevent_cse=False,
                                      policy=self._ckpt_policy())
            x = lax.scan(body, x, stacked)[0]
        else:
            for layer in self.layers:
                if self.remat:
                    x = jax.checkpoint(
                        lambda h, _l=layer: _l(h, mask=mask,
                                               segment_ids=segment_ids),
                        policy=self._ckpt_policy())(x)
                else:
                    x = layer(x, mask=mask, segment_ids=segment_ids)
        if self.final_norm is not None:
            x = self.final_norm(x)
        return x


class TransformerDecoder(Layer):
    def __init__(self, num_layers: int, d_model: int, nhead: int,
                 dim_feedforward: int, dropout: float = 0.1,
                 activation: str = "gelu", normalize_before: bool = True,
                 use_flash: bool = True, seq_parallel=None,
                 attn_window=None):
        super().__init__()
        self.layers = LayerList([
            TransformerDecoderLayer(d_model, nhead, dim_feedforward, dropout,
                                    activation, normalize_before, use_flash,
                                    seq_parallel, attn_window=attn_window)
            for _ in range(num_layers)])
        self.final_norm = LayerNorm(d_model) if normalize_before else None

    def forward(self, x, memory, self_mask=None, cross_mask=None,
                causal: bool = True):
        for layer in self.layers:
            x = layer(x, memory, self_mask=self_mask, cross_mask=cross_mask,
                      causal=causal)
        if self.final_norm is not None:
            x = self.final_norm(x)
        return x


class PositionalEncoding(Layer):
    """Sinusoidal position signal (reference: the NMT model's
    position_encoding_init, benchmark/fluid/models/machine_translation.py)."""

    def __init__(self, d_model: int, max_len: int = 4096,
                 dropout: float = 0.0, scale_embedding: bool = True):
        super().__init__()
        enforce(d_model % 2 == 0, "d_model must be even, got %s", d_model)
        pos = np.arange(max_len)[:, None]
        div = np.exp(np.arange(0, d_model, 2) * (-math.log(10000.0) / d_model))
        pe = np.zeros((max_len, d_model), np.float32)
        pe[:, 0::2] = np.sin(pos * div)
        pe[:, 1::2] = np.cos(pos * div)
        self.register_buffer("pe", pe)
        self.scale = math.sqrt(d_model) if scale_embedding else 1.0
        self.drop = Dropout(dropout)

    def forward(self, x):
        t = x.shape[1]
        out = x * self.scale + self.pe[None, :t].astype(x.dtype)
        return self.drop(out)


class LearnedPositionalEmbedding(Layer):
    """BERT-style learned positions."""

    def __init__(self, max_len: int, d_model: int):
        super().__init__()
        self.emb = Embedding(max_len, d_model)

    def forward(self, x):
        t = x.shape[1]
        positions = jnp.arange(t)[None, :]
        return x + self.emb(positions)


def decoder_layer_step(layer, x_t, mem_k, mem_v, cache_k, cache_v, t,
                       cross_mask=None, decode_kernel: bool = False):
    """One incremental-decode step of a TransformerDecoderLayer: the
    self-attention runs against the layer's K/V cache (O(T) per step —
    the transformer analog of the reference RNN decoder's O(1) state),
    cross-attention against PRE-PROJECTED memory K/V. ``x_t``: (B, 1, D).
    Returns (out_t, cache_k, cache_v). Mirrors
    TransformerDecoderLayer.forward's pre/post-norm residual layout
    (eval mode: dropout is identity)."""
    w = layer.attn_window
    if layer.normalize_before:
        h, cache_k, cache_v = layer.self_attn.forward_step(
            layer.norm1(x_t), cache_k, cache_v, t, window=w,
            decode_kernel=decode_kernel)
        x_t = x_t + h
        x_t = x_t + layer.cross_attn.attend_kv(layer.norm2(x_t), mem_k,
                                               mem_v, attn_mask=cross_mask)
        x_t = x_t + layer.ffn(layer.norm3(x_t))
    else:
        h, cache_k, cache_v = layer.self_attn.forward_step(
            x_t, cache_k, cache_v, t, window=w,
            decode_kernel=decode_kernel)
        x_t = layer.norm1(x_t + h)
        x_t = layer.norm2(x_t + layer.cross_attn.attend_kv(
            x_t, mem_k, mem_v, attn_mask=cross_mask))
        x_t = layer.norm3(x_t + layer.ffn(x_t))
    return x_t, cache_k, cache_v
