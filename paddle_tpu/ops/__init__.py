"""Functional op library — the capability equivalent of the reference's
operator registry (reference: paddle/fluid/operators/, 290 forward ops,
SURVEY Appendix A). Ops are composable lowering rules to XLA HLO; gradients
come from JAX VJP (replacing GradOpDescMaker); hand-written kernels live in
``paddle_tpu.ops.pallas``.
"""

from . import (control_flow, decode, detection, detection_extra, loss, math,
               nn, nn_extra, reduction, rnn, sampling, sequence, tensor)
from .decode import (beam_search, beam_search_batch_step,
                     beam_search_decode_lod, beam_search_step,
                     crf_decoding, ctc_align, gather_beams,
                     ctc_greedy_decode, ctc_loss, edit_distance,
                     linear_chain_crf)
from .detection import (anchor_generator, bipartite_match, box_clip,
                        box_coder, collect_fpn_proposals, density_prior_box,
                        distribute_fpn_proposals, generate_proposals,
                        iou_similarity, matrix_nms, multiclass_nms, nms,
                        polygon_box_transform, prior_box, roi_align, roi_pool,
                        target_assign, yolo_box)
from .control_flow import (TensorArray, case, cond, equal, fori_loop,
                           greater_equal, greater_than, less_equal, less_than,
                           logical_and, logical_not, logical_or, logical_xor,
                           not_equal, scan, static_rnn, switch_case,
                           while_loop)
from .loss import (bpr_loss, cross_entropy, hinge_loss, huber_loss, kldiv_loss,
                   label_smooth, log_loss, margin_rank_loss, mse_loss,
                   modified_huber_loss, npair_loss, rank_loss,
                   sigmoid_cross_entropy_with_logits, smooth_l1_loss,
                   softmax_with_cross_entropy, square_error_cost)
from .math import (abs, acos, asin, atan, bilinear_tensor_product, brelu,
                   ceil, clip, clip_by_norm, cos, cos_sim, cumsum,
                   elementwise_add, elementwise_div, elementwise_floordiv,
                   elementwise_max, elementwise_min, elementwise_mod,
                   elementwise_mul, elementwise_pow, elementwise_sub, elu,
                   exp, floor, gelu, hard_shrink, hard_sigmoid, increment,
                   isfinite, l1_norm, leaky_relu, log, logsigmoid, logsumexp,
                   matmul, maxout, mul, pow, prelu, reciprocal, relu, relu6,
                   round, rsqrt, scale, selu, sigmoid, sign, sin, soft_relu,
                   softplus, softshrink, softsign, sqrt, square,
                   squared_l2_distance, squared_l2_norm, stanh, swish, tanh,
                   tanh_shrink, thresholded_relu)
from .nn import (adaptive_pool2d, batch_norm, conv2d, conv2d_transpose, conv3d,
                 depthwise_conv2d, dropout, embedding, group_norm,
                 interpolate, l2_normalize, layer_norm, log_softmax, lrn,
                 one_hot, pad2d, pixel_shuffle, pool2d, rms_norm,
                 shuffle_channel, softmax, space_to_depth)
from .reduction import (mean, reduce_all, reduce_any, reduce_max, reduce_mean,
                        reduce_min, reduce_prod, reduce_sum)
from .rnn import (conv_shift, dynamic_rnn, gru, gru_unit, lstm, lstm_unit,
                  lstmp, row_conv, sequence_conv)
from .sampling import (hsigmoid_loss, nce_loss, sample_classes,
                       sample_from_logits, sample_logits, sampling_id,
                       top_k_logits, top_p_logits)
from .sequence import (sequence_concat, sequence_enumerate, sequence_expand,
                       sequence_mask, sequence_pad, sequence_pool,
                       sequence_reverse, sequence_slice, sequence_softmax,
                       sequence_unpad)
from .tensor import (arg_max, arg_min, argsort, assign, cast, concat, crop,
                     diag, expand, expand_as, eye, fill_constant,
                     fill_constant_batch_size_like, fill_zeros_like, flatten,
                     gather, gather_nd, gaussian_random, linspace, multiplex,
                     ones, pad, pad_constant_like, reshape, reverse, scatter,
                     scatter_nd_add, shape, slice, split, squeeze, stack,
                     top_k, transpose, tril, triu, truncated_gaussian_random,
                     uniform_random, unsqueeze, unstack, where, zeros)

from .nn_extra import (affine_channel, affine_grid, bilinear_interp,
                       conv3d_transpose, cvm, data_norm,
                       depthwise_conv2d_transpose, fsp_matrix,
                       max_pool2d_with_index, max_pool3d_with_index,
                       nearest_interp, pool3d, similarity_focus, spp,
                       tree_conv, unpool)
from .detection_extra import (box_decoder_and_assign,
                              generate_proposal_labels, mine_hard_examples,
                              psroi_pool, roi_perspective_transform,
                              rpn_target_assign, yolov3_loss)
from .sequence import (add_position_encoding, chunk_eval,
                       sequence_reshape,
                       sequence_scatter)

# --- name aliases: reference op names whose capability lives under a
# different (or newer-generation) name here -------------------------------
from .loss import softmax_with_cross_entropy as cross_entropy2  # *2 = stable variant
from .decode import ctc_loss as warpctc
from .nn import embedding as lookup_table
from .nn import l2_normalize as norm
from .math import elementwise_sub as minus
from .tensor import arange as range  # noqa: A001 - matches reference name
from .tensor import fill_constant as fill
from .tensor import reshape as reshape2
from .tensor import transpose as transpose2
from .tensor import flatten as flatten2
from .tensor import squeeze as squeeze2
from .tensor import unsqueeze as unsqueeze2
from .sequence import hash_embedding_ids as hash  # noqa: A001
