"""Attention ops.

The reference has no attention op — it composes matmul+softmax in python
(reference: python/paddle/fluid/nets.py:343 scaled_dot_product_attention).
Here attention is first-class: an XLA path (compiler-fused) and a Pallas
flash-attention path for long sequences (paddle_tpu.ops.pallas.flash_attention)
selected automatically on TPU.

Layout convention: (batch, seq, heads, head_dim) — "BTHD".
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from ..core.enforce import enforce


def scaled_dot_product_attention(q, k, v, mask=None, causal: bool = False,
                                 dropout_p: float = 0.0, dropout_key=None,
                                 scale: Optional[float] = None,
                                 use_flash: bool = True,
                                 segment_ids=None,
                                 window: Optional[int] = None):
    """q: (B, Tq, H, D), k/v: (B, Tk, H, D) → (B, Tq, H, D).

    mask: broadcastable to (B, H, Tq, Tk); True/1 = keep, False/0 = mask out.
    segment_ids: (B, T) int ids for packed batches (self-attention only);
    positions attend within their own segment. Composes with causal/mask.
    window: sliding-window/local attention — attend only keys within
    ``window - 1`` positions (lookback-only when causal, symmetric band
    otherwise); the flash kernel SKIPS out-of-band blocks (O(T*window)
    compute, the long-context local-attention pattern).
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    enforce(segment_ids is None or q.shape[1] == k.shape[1],
            "segment_ids requires self-attention shapes (tq=%s != tk=%s)",
            q.shape[1], k.shape[1])
    enforce(window is None or window >= 1,
            "window must be >= 1, got %s", window)
    if use_flash and (dropout_p == 0.0 or dropout_key is not None):
        # key-padding masks (the broadcast (B, 1, 1, Tk) form every
        # ragged-batch model emits) ride the flash kernel; anything else
        # falls back to XLA — including 2D masks, whose historical
        # broadcast semantics are per-QUERY (Tq, Tk), right-aligned
        # against the (B, H, Tq, Tk) logits; promoting a (B, Tk)-shaped
        # one to key-padding would silently change meaning when B == Tq.
        # Attention-probability dropout runs INSIDE the kernel (in-kernel
        # counter-based mask) — the training configs with dropout keep
        # the no-HBM-scores property instead of falling back.
        kv_mask = _as_kv_mask(mask, q.shape[0], k.shape[1])
        if mask is None or kv_mask is not None:
            flash = _get_flash()
            if flash is not None and _flash_ok(q, k, causal,
                                               window=window):
                return flash(q, k, v, causal=causal, scale=scale,
                             kv_mask=kv_mask, segment_ids=segment_ids,
                             dropout_p=dropout_p, dropout_key=dropout_key,
                             window=window)
    return xla_attention(q, k, v, mask=mask, causal=causal,
                         dropout_p=dropout_p, dropout_key=dropout_key,
                         scale=scale, segment_ids=segment_ids,
                         window=window)


def _as_kv_mask(mask, b: int, tk: int):
    """Normalize a keep-mask to the (B, Tk) key-padding form, or None if
    it constrains per-head/per-query and must stay on the XLA path.
    Only the explicit (B, 1, 1, Tk) broadcast form qualifies — a bare 2D
    mask means per-query (Tq, Tk) under the documented right-aligned
    broadcast, never key padding."""
    if mask is None:
        return None
    if mask.ndim == 4 and mask.shape[0] in (1, b) and mask.shape[1] == 1 \
            and mask.shape[2] == 1 and mask.shape[3] == tk:
        import jax.numpy as _jnp

        return _jnp.broadcast_to(mask[:, 0, 0, :], (b, tk))
    return None


def xla_attention(q, k, v, mask=None, causal: bool = False,
                  dropout_p: float = 0.0, dropout_key=None,
                  scale: Optional[float] = None, segment_ids=None,
                  window: Optional[int] = None):
    """Reference XLA implementation — materializes (B, H, Tq, Tk) scores."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if k.shape[2] != q.shape[2]:
        # GQA/MQA: expand the shared K/V heads (kv-major, matching the
        # flash kernel's head -> head // group mapping)
        group = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, group, axis=2)
        v = jnp.repeat(v, group, axis=2)
    if window is not None:
        enforce(window >= 1, "window must be >= 1, got %s", window)
        tq, tk = q.shape[1], k.shape[1]
        rows = jnp.arange(tq)[:, None] + (tk - tq)  # offset-aligned rows
        cols = jnp.arange(tk)[None, :]
        band = rows - cols < window
        if not causal:
            band = band & (cols - rows < window)
        mask = band if mask is None else (mask.astype(jnp.bool_) & band)
    if segment_ids is not None:
        ids = segment_ids
        seg = (ids[:, None, :, None] == ids[:, None, None, :])
        mask = seg if mask is None else (mask.astype(jnp.bool_) & seg)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    neg = jnp.finfo(logits.dtype).min
    keep = None
    if causal:
        tq, tk = logits.shape[-2], logits.shape[-1]
        keep = jnp.tril(jnp.ones((tq, tk), jnp.bool_), tk - tq)
        logits = jnp.where(keep, logits, neg)
    if mask is not None:
        mask = mask.astype(jnp.bool_)
        keep = mask if keep is None else (keep & mask)
        logits = jnp.where(mask, logits, neg)
    probs = jax.nn.softmax(logits, axis=-1)
    if keep is not None:
        # rows with no valid key output zeros (flash-kernel convention),
        # not a uniform average of V
        any_valid = jnp.any(jnp.broadcast_to(keep, logits.shape), -1,
                            keepdims=True)
        probs = jnp.where(any_valid, probs, 0.0)
    if dropout_p > 0.0:
        enforce(dropout_key is not None, "attention dropout requires a key")
        keep = jax.random.bernoulli(dropout_key, 1.0 - dropout_p, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_p), 0.0).astype(probs.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


@functools.lru_cache(maxsize=1)
def _get_flash():
    try:
        from .pallas.flash_attention import flash_attention

        return flash_attention
    except Exception:
        return None


_FORCE_FLASH = False

# head dims both Pallas kernels support — ONE list so the decode and
# training dispatch gates never desynchronize
_FLASH_HEAD_DIMS = (64, 128, 256)


class force_flash:
    """Context manager: route eligible shapes to the flash kernel even
    off-TPU (interpret mode). For tests that must exercise the Pallas
    dispatch + partitioning path on the virtual CPU mesh — production
    dispatch stays backend-gated.

    CAVEAT (trace-time flag, jit cache): the flag is read when a
    function is TRACED, not when it is called — a function first jitted
    inside this context keeps the flash path via jax's jit cache after
    the context exits (and one jitted outside keeps the XLA path inside
    it). Tests that flip the flag must trace fresh functions (or call
    ``.clear_cache()`` on the jitted fn) on each side of the toggle.
    The flag is also process-global, not thread-local — don't toggle it
    concurrently from multiple threads."""

    def __init__(self, enabled: bool = True):
        self.enabled = enabled

    def __enter__(self):
        global _FORCE_FLASH
        self._prev = _FORCE_FLASH
        _FORCE_FLASH = self.enabled
        return self

    def __exit__(self, *exc):
        global _FORCE_FLASH
        _FORCE_FLASH = self._prev
        return False


def rotary_embedding(x, positions, theta: float = 10000.0):
    """Rotary position embedding (RoPE) over (B, T, H, D) with even D.

    ``positions``: (T,) or (B, T) integer absolute positions — decode
    passes the cache index, sequence-parallel callers pass GLOBAL
    positions (rotation happens on the pre-shard arrays, so sharded
    attention sees position-correct q/k). Rotate-half convention
    (GPT-NeoX/Llama): pairs are (x[..., i], x[..., i + D/2]).

    Green-field (the reference era predates RoPE; its positional story
    is learned position tables, reference:
    python/paddle/fluid/layers/nn.py position_encoding role).
    """
    d = x.shape[-1]
    enforce(d % 2 == 0, "rotary needs an even head_dim, got %s", d)
    half = d // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    ang = positions.astype(jnp.float32)[..., None] * freqs  # (..., T, half)
    # insert the head axis before the feature axis; (T, half) inputs
    # broadcast over batch AND heads, (B, T, half) over heads only
    cos = jnp.cos(ang)[..., None, :]
    sin = jnp.sin(ang)[..., None, :]
    x1, x2 = x[..., :half], x[..., half:]
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin],
                          axis=-1)
    return out.astype(x.dtype)


@functools.lru_cache(maxsize=1)
def _get_flash_decode():
    try:
        from .pallas.flash_decode import flash_decode

        return flash_decode
    except Exception:
        return None


def decode_flash_ok(capacity: int, d: int,
                    pool_dtype: str = "f32",
                    page_size: Optional[int] = None) -> bool:
    """Dispatch gate for the single-position decode kernel
    (pallas/flash_decode.py): TPU backend (or force_flash), supported
    head dim, block-divisible cache capacity. A separate gate from
    flash_shape_ok — decode shapes (tq=1 against a fixed capacity)
    never satisfy the training kernel's block rules. ``pool_dtype``
    keys the tuned verdict per KV storage form ("f32" | "int8" — the
    int8 paged variant dequantizes in-kernel and has its own measured
    winner). ``page_size``: for paged pools the page IS the kernel
    block, fixed by the deployed pool rather than chosen at dispatch —
    a tuned entry carrying per-page verdicts (``use_flash_by_page``,
    tools/pallas_tune.py) answers for THAT page size; the aggregate
    ``use_flash`` (measured at the tuner's best page) only decides
    when the deployed page was never swept."""
    if (not _FORCE_FLASH
            and jax.default_backend() not in ("tpu", "axon")):
        return False
    try:
        from .pallas.flash_decode import decode_block_k
    except Exception:  # kernel unavailable -> XLA mask path
        return False
    if d not in _FLASH_HEAD_DIMS or decode_block_k(capacity) is None:
        return False
    from .pallas.tuning import get_tuned_decode

    tuned = get_tuned_decode(capacity, d, pool_dtype)
    if tuned is None:
        return True
    by_page = tuned.get("use_flash_by_page")
    if page_size is not None and by_page is not None:
        verdict = by_page.get(str(page_size))
        if verdict is not None:
            return bool(verdict)
    return tuned.get("use_flash", True)


def _flash_ok(q, k, causal: bool = False, window=None) -> bool:
    """Flash kernel constraints for (B, T, H, D) operands — see
    flash_shape_ok for the actual gate."""
    return flash_shape_ok(q.shape[1], k.shape[1], q.shape[-1],
                          causal=causal, window=window)


def flash_shape_ok(tq, tk, d, causal: bool = False, window=None) -> bool:
    """Flash kernel constraints: TPU backend, block-divisible seq lens,
    supported head dim — and the autotuner's measured verdict when one
    exists (tools/pallas_tune.py records use_flash=False for shape
    buckets where the XLA fallback won on-chip). Shape-level so the
    ring-attention dispatch (parallel/context_parallel.py) can gate on
    its PER-SHARD (t/sp) block shape."""
    if (not _FORCE_FLASH
            and jax.default_backend() not in ("tpu", "axon")):
        return False
    # 64-divisible seqs use block=64 (the tuner measures that shape too:
    # tools/pallas_tune.py short-seq fallback); the measured use_flash
    # verdict below still decides whether the kernel actually wins there
    if not (tq % 64 == 0 and tk % 64 == 0 and d in _FLASH_HEAD_DIMS):
        return False
    if window is not None and window < tk:
        # tuned verdicts are measured at DENSE attention; banded flash
        # skips out-of-band blocks (O(T*window)) while the XLA fallback
        # stays O(T^2) — a dense use_flash=False must not veto it.
        # window >= tk is dense in disguise: fall through to the verdict
        return True
    from .pallas.tuning import attention_key, get_tuned

    tuned = get_tuned(attention_key(tq, tk, d, causal))
    if tuned is not None and not tuned.get("use_flash", True):
        return False
    return True
