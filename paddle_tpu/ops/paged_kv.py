"""Functional paged-KV cache ops (vLLM-style): K/V live in a SHARED
(pages, page_size, kv_heads, head_dim) pool; a request's logical cache
is its page-id sequence. These are the jit-safe array ops — write one
position per row, write a prompt chunk for one row, attend over the
pages (Pallas paged kernel when eligible, gather fallback). The
host-side allocator is paddle_tpu.serving.PagedKVPool.

Green-field (the modern serving-memory capability; the reference's
serving holds one contiguous buffer per request,
/root/reference/paddle/fluid/inference/api/api_impl.cc role).
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp


def write_rows(kpool, vpool, table, t_rows, k_t, v_t, page_size: int):
    """One position per row at LOGICAL cursors ``t_rows`` (B,): scatter
    k_t/v_t (B, 1, kv, hd) into each row's page. Cursors past the
    row's table capacity DROP (the contiguous cache's OOB-scatter
    semantics) instead of clamp-corrupting the last live page."""
    n_log = table.shape[1]
    rows = jnp.arange(table.shape[0])
    valid = t_rows < n_log * page_size
    col = jnp.minimum(t_rows // page_size, n_log - 1)
    # invalid rows get an out-of-pool page id -> mode="drop"
    page = jnp.where(valid, table[rows, col], kpool.shape[0])
    off = t_rows % page_size
    kpool = kpool.at[page, off].set(k_t[:, 0].astype(kpool.dtype),
                                    mode="drop")
    vpool = vpool.at[page, off].set(v_t[:, 0].astype(vpool.dtype),
                                    mode="drop")
    return kpool, vpool


def write_chunk(kpool, vpool, table_row, t0, k_c, v_c, page_size: int):
    """S consecutive positions for ONE row starting at logical ``t0``:
    k_c/v_c (1, S, kv, hd). Positions past the table capacity drop
    (see write_rows)."""
    s = k_c.shape[1]
    n_log = table_row.shape[0]
    pos = t0 + jnp.arange(s)
    valid = pos < n_log * page_size
    col = jnp.minimum(pos // page_size, n_log - 1)
    page = jnp.where(valid, table_row[col], kpool.shape[0])
    off = pos % page_size
    kpool = kpool.at[page, off].set(k_c[0].astype(kpool.dtype),
                                    mode="drop")
    vpool = vpool.at[page, off].set(v_c[0].astype(vpool.dtype),
                                    mode="drop")
    return kpool, vpool


def write_chunk_rows(kpool, vpool, table, t0_rows, k_c, v_c,
                     page_size: int):
    """S consecutive positions PER ROW starting at per-row logical
    cursors ``t0_rows`` (B,): k_c/v_c (B, S, kv, hd) — the speculative
    verify-chunk write (every row lands its gamma+1 candidate K/V at
    its OWN offset). Positions past the table capacity drop (see
    write_rows)."""
    b, s = k_c.shape[:2]
    n_log = table.shape[1]
    pos = t0_rows[:, None] + jnp.arange(s)[None, :]           # (B, S)
    valid = pos < n_log * page_size
    col = jnp.minimum(pos // page_size, n_log - 1)
    rows = jnp.arange(b)[:, None]
    page = jnp.where(valid, table[rows, col], kpool.shape[0])
    off = pos % page_size
    kpool = kpool.at[page, off].set(k_c.astype(kpool.dtype),
                                    mode="drop")
    vpool = vpool.at[page, off].set(v_c.astype(vpool.dtype),
                                    mode="drop")
    return kpool, vpool


def gather_rows(pool, table):
    """Assemble each row's LOGICAL cache: (B, n_log*page_size, kv, hd).
    The fallback/prefill view; the decode kernel never materializes
    it."""
    b, n_log = table.shape
    return pool[table].reshape(b, n_log * pool.shape[1],
                               *pool.shape[2:])


def attend(q, kpool, vpool, table, t_rows,
           window: Optional[int] = None):
    """Decode attention over the paged cache: the Pallas paged kernel
    when eligible, else gather-the-pages + masked XLA. ``t_rows``:
    scalar or (B,) logical cursors."""
    from . import attention as A

    d = q.shape[-1]
    page_size, n_log = kpool.shape[1], table.shape[1]
    # scalar cursor broadcasts on BOTH paths (the kernel already
    # broadcasts; the gather fallback must match)
    t_rows = jnp.broadcast_to(jnp.asarray(t_rows, jnp.int32),
                              (q.shape[0],))
    if (A.decode_flash_ok(page_size * n_log, d)
            and A._get_flash_decode() is not None):
        from .pallas.flash_decode import flash_decode_paged

        return flash_decode_paged(q, kpool, vpool, table, t_rows,
                                  window=window)
    k = gather_rows(kpool, table)
    v = gather_rows(vpool, table)
    pos = jnp.arange(n_log * page_size)[None, :]
    keep = pos <= t_rows[:, None]
    if window is not None:
        keep &= pos > t_rows[:, None] - window
    return A.scaled_dot_product_attention(
        q, k, v, mask=keep[:, None, None, :], use_flash=False)
