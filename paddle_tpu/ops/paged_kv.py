"""Functional paged-KV cache ops (vLLM-style): K/V live in a SHARED
(pages, page_size, kv_heads, head_dim) pool; a request's logical cache
is its page-id sequence. These are the jit-safe array ops — write one
position per row, write a prompt chunk for one row, attend over the
pages (Pallas paged kernel when eligible, gather fallback). The
host-side allocator is paddle_tpu.serving.PagedKVPool.

Pools come in two storage forms, transparent to every caller:

- a plain float array (the original layout), or
- :class:`QuantizedPool` — int8 values + per-(page, position, kv_head)
  float32 scales (the ``quant.ops.absmax_encode`` wire format over each
  head_dim vector). KV bytes set the concurrent-session ceiling per
  chip, so int8 KV ~= 3.7x the pages of fp32 (1 + 4/head_dim bytes per
  element vs 4) at the same HBM. Writes QUANTIZE ON APPEND (each K/V
  vector encoded once, at write time); attention DEQUANTIZES only the
  blocks it touches (never the whole pool), so the working set stays
  O(live tokens). Quantized decode rides the SAME Pallas paged kernel
  as float pools when eligible: int8 blocks stream from HBM with their
  scale blocks prefetched along the same clamped page walk, and dequant
  happens in VMEM as a per-block epilogue (flash_decode_paged's
  k_scale/v_scale form) — O(t) DMA plus ~4x fewer HBM bytes per block.
  The gather path remains the fallback (CPU, ineligible shapes,
  measured use_flash=False verdicts).

This module is the ONE place that branches on the pool storage form —
kernels and serving code take raw arrays (PT-LINT-308 pins it).

Green-field (the modern serving-memory capability; the reference's
serving holds one contiguous buffer per request,
/root/reference/paddle/fluid/inference/api/api_impl.cc role).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax.numpy as jnp


class QuantizedPool(NamedTuple):
    """int8 paged K or V pool: ``q`` (pages, page_size, kv_heads,
    head_dim) int8 values, ``scale`` (pages, page_size, kv_heads)
    float32 per-vector abs-max scales (dequant = ``q * scale``). A
    pytree — threads through jitted step functions exactly like the
    float pool it replaces; ``shape``/``dtype`` mirror the float pool's
    so shape-driven callers (page_size, OOB page ids) never branch."""

    q: jnp.ndarray
    scale: jnp.ndarray

    @property
    def shape(self):
        return self.q.shape

    @property
    def dtype(self):
        return self.q.dtype

    @property
    def nbytes(self) -> int:
        """Device bytes of the pool (values + scales) — the serving
        density accounting (`pt_serving_kv_pool_bytes`)."""
        return quantized_pool_nbytes(self.q.shape)


def quantized_pool_nbytes(shape) -> int:
    """Device bytes a :class:`QuantizedPool` with value layout
    ``shape`` = (pages, page_size, kv_heads, head_dim) costs: int8
    values + one f32 scale per (page, position, kv_head) vector. THE
    wire-format byte formula — ``QuantizedPool.nbytes`` and serving's
    ``PagedKVPool.pool_nbytes`` both read it, so the density accounting
    can't drift from the storage layout."""
    pages, page_size, kv_heads, head_dim = shape
    vecs = pages * page_size * kv_heads
    return vecs * head_dim + vecs * 4


def _encode_vectors(x):
    """(..., head_dim) float -> (q int8, scale (...,)) per-vector
    abs-max int8 (the shared quant.ops convention)."""
    from ..quant.ops import absmax_encode

    q, scale = absmax_encode(x, axis=-1)
    return q, scale[..., 0]


def _pool_write(pool, page, off, x):
    """Scatter ``x`` (K/V vectors) into the pool at [page, off] with
    OOB-drop semantics — quantize-on-append for QuantizedPool, plain
    dtype-cast store otherwise. ``page``/``off`` index arrays broadcast
    per the caller's layout."""
    if isinstance(pool, QuantizedPool):
        q, s = _encode_vectors(x)
        return QuantizedPool(pool.q.at[page, off].set(q, mode="drop"),
                             pool.scale.at[page, off].set(s, mode="drop"))
    return pool.at[page, off].set(x.astype(pool.dtype), mode="drop")


def write_rows(kpool, vpool, table, t_rows, k_t, v_t, page_size: int):
    """One position per row at LOGICAL cursors ``t_rows`` (B,): scatter
    k_t/v_t (B, 1, kv, hd) into each row's page. Cursors past the
    row's table capacity DROP (the contiguous cache's OOB-scatter
    semantics) instead of clamp-corrupting the last live page."""
    n_log = table.shape[1]
    rows = jnp.arange(table.shape[0])
    valid = t_rows < n_log * page_size
    col = jnp.minimum(t_rows // page_size, n_log - 1)
    # invalid rows get an out-of-pool page id -> mode="drop"
    page = jnp.where(valid, table[rows, col], kpool.shape[0])
    off = t_rows % page_size
    kpool = _pool_write(kpool, page, off, k_t[:, 0])
    vpool = _pool_write(vpool, page, off, v_t[:, 0])
    return kpool, vpool


def write_chunk(kpool, vpool, table_row, t0, k_c, v_c, page_size: int):
    """S consecutive positions for ONE row starting at logical ``t0``:
    k_c/v_c (1, S, kv, hd). Positions past the table capacity drop
    (see write_rows)."""
    s = k_c.shape[1]
    n_log = table_row.shape[0]
    pos = t0 + jnp.arange(s)
    valid = pos < n_log * page_size
    col = jnp.minimum(pos // page_size, n_log - 1)
    page = jnp.where(valid, table_row[col], kpool.shape[0])
    off = pos % page_size
    kpool = _pool_write(kpool, page, off, k_c[0])
    vpool = _pool_write(vpool, page, off, v_c[0])
    return kpool, vpool


def write_chunk_rows(kpool, vpool, table, t0_rows, k_c, v_c,
                     page_size: int):
    """S consecutive positions PER ROW starting at per-row logical
    cursors ``t0_rows`` (B,): k_c/v_c (B, S, kv, hd) — the speculative
    verify-chunk write (every row lands its gamma+1 candidate K/V at
    its OWN offset). Positions past the table capacity drop (see
    write_rows)."""
    b, s = k_c.shape[:2]
    n_log = table.shape[1]
    pos = t0_rows[:, None] + jnp.arange(s)[None, :]           # (B, S)
    valid = pos < n_log * page_size
    col = jnp.minimum(pos // page_size, n_log - 1)
    rows = jnp.arange(b)[:, None]
    page = jnp.where(valid, table[rows, col], kpool.shape[0])
    off = pos % page_size
    kpool = _pool_write(kpool, page, off, k_c)
    vpool = _pool_write(vpool, page, off, v_c)
    return kpool, vpool


def export_pages(pool, ids):
    """Materialize the CONTENTS of pages ``ids`` (n,) — the
    prefill→decode KV-handoff wire payload: ``(n, page_size, kv_heads,
    head_dim)`` values for a float pool, ``(q, scale)`` arrays for a
    :class:`QuantizedPool` (int8 values + per-vector scales travel
    together, so a handoff never silently dequantizes). Pure gather —
    the caller owns any device→host transfer."""
    if isinstance(pool, QuantizedPool):
        return pool.q[ids], pool.scale[ids]
    return pool[ids]


def import_pages(pool, ids, payload):
    """Write :func:`export_pages` payloads into pages ``ids`` of
    ``pool`` (the decode-side half of the KV handoff). Storage forms
    must match: a quantized payload only lands in a quantized pool —
    re-quantizing a dequantized handoff would double the quantization
    error, so the mismatch is a typed error instead."""
    from ..core.enforce import enforce

    if isinstance(pool, QuantizedPool):
        enforce(isinstance(payload, tuple) and len(payload) == 2,
                "quantized pool needs a (q, scale) payload, got %s",
                type(payload).__name__)
        q, scale = payload
        return QuantizedPool(
            pool.q.at[ids].set(jnp.asarray(q, jnp.int8)),
            pool.scale.at[ids].set(jnp.asarray(scale, jnp.float32)))
    enforce(not isinstance(payload, tuple),
            "float pool cannot import a quantized (q, scale) payload "
            "— kv_dtype must match across the handoff")
    return pool.at[ids].set(jnp.asarray(payload).astype(pool.dtype))


def gather_rows(pool, table, upto: Optional[int] = None,
                full: bool = False):
    """Assemble each row's LOGICAL cache: (B, n_cols*page_size, kv, hd).
    The fallback/prefill view; the decode kernel never materializes
    it. Quantized pools dequantize HERE — only the gathered rows ever
    exist in float.

    ``upto``: STATIC bound on the live positions (the prefill path,
    where the chunk extent t0+S is a Python int) — only the first
    ``ceil(upto / page_size)`` table columns are gathered/dequantized,
    so a short prompt over a long table stops materializing (and for
    quantized pools, dequantizing) the full logical view in float32.
    None (traced cursors: the decode fallback) or ``full=True`` (the
    explicit full-view escape for tests/handoffs) keeps the whole
    view."""
    b, n_log = table.shape
    ps = pool.shape[1]
    if upto is not None and not full:
        n_cols = min(n_log, max(1, -(-int(upto) // ps)))
        table = table[:, :n_cols]
        n_log = n_cols
    if isinstance(pool, QuantizedPool):
        vals = (pool.q[table].astype(jnp.float32)
                * pool.scale[table][..., None])
        return vals.reshape(b, n_log * ps, *pool.shape[2:])
    return pool[table].reshape(b, n_log * ps, *pool.shape[2:])


def attend(q, kpool, vpool, table, t_rows,
           window: Optional[int] = None):
    """Decode attention over the paged cache: the Pallas paged kernel
    when eligible — float AND quantized pools; int8 pools hand the
    kernel their raw (values, scales) planes and dequant runs as the
    kernel's per-block VMEM epilogue — else gather-the-pages + masked
    XLA (dequant on the gathered rows). ``t_rows``: scalar or (B,)
    logical cursors. THE storage-form dispatch boundary: nothing past
    this call branches on :class:`QuantizedPool`."""
    from . import attention as A

    d = q.shape[-1]
    page_size, n_log = kpool.shape[1], table.shape[1]
    # scalar cursor broadcasts on BOTH paths (the kernel already
    # broadcasts; the gather fallback must match)
    t_rows = jnp.broadcast_to(jnp.asarray(t_rows, jnp.int32),
                              (q.shape[0],))
    quantized = isinstance(kpool, QuantizedPool)
    if (A.decode_flash_ok(page_size * n_log, d,
                          "int8" if quantized else "f32", page_size)
            and A._get_flash_decode() is not None):
        from .pallas.flash_decode import flash_decode_paged

        if quantized:
            return flash_decode_paged(
                q, kpool.q, vpool.q, table, t_rows,
                k_scale=kpool.scale, v_scale=vpool.scale,
                window=window)
        return flash_decode_paged(q, kpool, vpool, table, t_rows,
                                  window=window)
    k = gather_rows(kpool, table)
    v = gather_rows(vpool, table)
    pos = jnp.arange(n_log * page_size)[None, :]
    keep = pos <= t_rows[:, None]
    if window is not None:
        keep &= pos > t_rows[:, None] - window
    return A.scaled_dot_product_attention(
        q, k, v, mask=keep[:, None, None, :], use_flash=False)
