"""Flash attention — blockwise online-softmax attention as a Pallas TPU
kernel, with a custom VJP (recompute-based backward).

Capability role: the reference has no attention op at all (it composes
matmul+softmax in python, reference: python/paddle/fluid/nets.py:343); its
hand-written-kernel niche is `operators/jit/`. Here the niche is filled
TPU-natively: Q/K/V stream HBM→VMEM block by block, scores never materialize
in HBM, softmax runs online with a running (max, sum), and the MXU sees only
dense (block_q × d) @ (d × block_k) matmuls.

Layout: (batch, seq, heads, head_dim) at the API; internally (batch*heads,
seq, head_dim). Sequence lengths must be divisible by the block sizes (the
framework-level caller pads — ragged semantics are handled one level up, see
ops/sequence.py).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ...utils import compat

compat.fix_custom_partitioning_static_args()

try:  # pltpu only resolves on TPU builds; interpret mode needs none of it
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
_NEG_INF = -1e30  # safe large-negative (finite: avoids inf-inf NaNs in bwd)


def _vmem_spec(shape, index_map):
    if _VMEM is not None:
        return pl.BlockSpec(shape, index_map, memory_space=_VMEM)
    return pl.BlockSpec(shape, index_map)


def _seed_spec(n_rows):
    """Per-(batch*head) dropout seeds: a (1, B*H) int32 row in SMEM, the
    FULL array per grid step (a (1,1) sub-block would violate the Mosaic
    block-divisibility rule; B*H ints of SMEM are nothing). The kernel
    picks its scalar with the grid row: ``seed_ref[0, bh]``. Addressing
    the seed by (b, h) identity — instead of hashing a single scalar
    with the flattened LOCAL bh index — makes the dropout mask invariant
    to how the call is partitioned: a batch/head shard receives exactly
    the seed rows it owns, so sharded and unsharded runs drop identical
    entries."""
    imap = lambda *_: (0, 0)
    if pltpu is not None:
        return pl.BlockSpec((1, n_rows), imap, memory_space=pltpu.SMEM)
    return pl.BlockSpec((1, n_rows), imap)


def _scratch(shape, dtype):
    if pltpu is not None:
        return pltpu.VMEM(shape, dtype)
    return pl.MemoryRef(shape, dtype) if hasattr(pl, "MemoryRef") else None


def _dropout_keep(seed, row0, col0, bq, bk, dropout_p):
    """Deterministic keep-mask for attention-probability dropout, from a
    counter-based integer hash of (per-(b,h) seed, global row, global
    col) — the same mask is rebuilt bit-identically by the backward
    kernels (no RNG state crosses the fwd/bwd boundary) and the ops are
    plain int32 iota/arithmetic, legal in Mosaic AND interpret mode.
    The (batch, head) identity lives in the SEED (one int32 per (b, h),
    see _seed_spec) rather than in the hash, so the mask depends only on
    global coordinates and is identical under any batch/head sharding.
    int32 overflow wraps (two's complement) under XLA, which is exactly
    what a mix function wants."""
    rows = row0 + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    cols = col0 + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    # rows pass through a NONLINEAR mix before cols join: a single
    # linear combination rows*A + cols*B would make every position pair
    # offset by a fixed lattice vector (A*dr + B*dc == 0 mod 2^32) hash
    # identically for all seeds — correlated dropout along diagonals
    x = rows * jnp.int32(-1640531527) + seed    # 0x9E3779B9
    x = x ^ (x >> 16)
    x = x * jnp.int32(-2048144777)              # 0x85EBCA77 as int32
    x = x ^ (x >> 13)
    x = x + cols * jnp.int32(-1028477379)
    x = x ^ (x >> 16)
    x = x * jnp.int32(-1119713537)
    x = x ^ (x >> 15)
    x = x * jnp.int32(-1640531527)
    x = x ^ (x >> 16)
    u = (x & jnp.int32(0x7FFFFFFF)).astype(jnp.float32) * (1.0 / 2147483648.0)
    return u >= dropout_p


def _band_j_lo(i, *, block_q, block_k, offset, window):
    # leftmost k-block a q-block can see under the window (may be < 0)
    return (i * block_q + offset - (window - 1)) // block_k


def _band_i_lo(j, *, block_q, block_k, offset, window, causal):
    # topmost q-block that can see k-block j under the window
    back = 0 if causal else (window - 1)
    return (j * block_k - offset - back) // block_q


def _band_width_j(*, block_q, block_k, window, causal, n_j):
    # k-blocks a q-block can touch: band span rounded up + alignment slack
    span = block_q - 1 + (window - 1) + (0 if causal else window - 1)
    return min(n_j, span // block_k + 2)


def _band_width_i(*, block_q, block_k, window, causal, n_i):
    span = block_k - 1 + (window - 1) + (0 if causal else window - 1)
    return min(n_i, span // block_q + 2)


def _banded_imap(lo_fn, n, row_fn=lambda b: b, zeros=1):
    """ONE definition of the banded index-map clamp, shared by every
    spec (k/v and q-side, both grid orders; ``zeros`` trailing unit
    coordinates — 2 for the 4-D blocked mask layout): maps (grid row,
    outer block, band step) -> (row_fn(row), clip(lo_fn(outer) + step),
    0...). The kernels recover the same index with the same
    expression — a single source for the band arithmetic."""

    def imap(b, outer, step):
        return (row_fn(b), jnp.clip(lo_fn(outer) + step, 0, n - 1),
                *([0] * zeros))

    return imap


def _block_should_run(i, j, *, causal, window, offset, block_q, block_k):
    """Block-level skip predicate shared by fwd/dq/dkv: a causal block
    runs iff its lowest row can see its first column; a window adds
    band-overlap limits on both sides (out-of-band blocks skip ALL
    compute — the O(T*window) point of local attention)."""
    run = ((i * block_q + block_q - 1 + offset >= j * block_k)
           if causal else True)
    if window is not None:
        lo = i * block_q + offset - (window - 1)   # leftmost visible col
        run &= j * block_k + block_k - 1 >= lo
        if not causal:
            hi = i * block_q + block_q - 1 + offset + (window - 1)
            run &= j * block_k <= hi
    return run


def _apply_causal_band(s, i, j, *, causal, window, offset, block_q,
                       block_k):
    """Per-entry causal/band mask shared by fwd/dq/dkv (same global
    coordinates in all three — a desync between forward and backward
    masking would corrupt gradients silently)."""
    if not causal and window is None:
        return s
    rows = (i * block_q + offset + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0))
    cols = (j * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1))
    if causal:
        s = jnp.where(rows >= cols, s, _NEG_INF)
    if window is not None:
        band = rows - cols < window
        if not causal:
            band &= cols - rows < window
        s = jnp.where(band, s, _NEG_INF)
    return s


def _use_interpret() -> bool:
    # keep in sync with ops.attention._flash_ok: any real-TPU backend name
    # must compile via Mosaic, everything else tests via interpret mode
    return jax.default_backend() not in ("tpu", "axon")


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, *refs, scale, causal, window,
                has_mask, has_segs, dropout_p, offset, block_q, block_k,
                num_k_blocks, banded=False, n_j=None):
    refs = list(refs)
    kvm_ref = refs.pop(0) if has_mask else None
    qseg_ref = refs.pop(0) if has_segs else None
    kseg_ref = refs.pop(0) if has_segs else None
    seed_ref = refs.pop(0) if dropout_p > 0.0 else None
    o_ref, lse_ref, acc_ref, m_ref, l_ref = refs
    # program_id is read OUTSIDE pl.when bodies (interpret-mode lowering
    # cannot resolve it inside the conditional)
    bh, i, jj = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    if banded:
        # banded (windowed) grid: axis 2 walks only the band; recover
        # the real k-block index (the specs clamp identically, so the
        # loaded block matches; out-of-range steps are skipped)
        j_raw = _band_j_lo(i, block_q=block_q, block_k=block_k,
                           offset=offset, window=window) + jj
        j = jnp.clip(j_raw, 0, n_j - 1)
        in_range = (j_raw >= 0) & (j_raw < n_j)
    else:
        j, in_range = jj, True

    @pl.when(jj == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    should_run = in_range & _block_should_run(
        i, j, causal=causal, window=window, offset=offset,
        block_q=block_q, block_k=block_k)

    @pl.when(should_run)
    def _body():
        # matmul inputs stay in their native dtype (bf16 in production):
        # bf16 x bf16 -> f32 via preferred_element_type runs at full MXU
        # rate, while a pre-cast to f32 would drop to the fp32 matmul
        # rate (4-8x slower on v5e) for zero accuracy gain in the
        # accumulator
        q = q_ref[0]                      # (bq, d)
        k = k_ref[0]                      # (bk, d)
        v = v_ref[0]                      # (bk, d)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # (bq, bk) f32
        s = _apply_causal_band(s, i, j, causal=causal, window=window,
                               offset=offset, block_q=block_q,
                               block_k=block_k)
        if has_mask:
            # key-padding keep-mask (1, bk) broadcasting over q rows;
            # the j-th block arrives via the index map (blocked layout)
            kvm = kvm_ref[0, 0]
            s = jnp.where(kvm > 0, s, _NEG_INF)
        if has_segs:
            # packed sequences: attend only within the same segment.
            # q-side ids arrive (bq, 1) via the lse-style layout, kv-side
            # (1, bk) via the blocked index map — broadcast equality
            # gives the (bq, bk) block mask with no in-kernel transpose
            qseg = qseg_ref[0]                       # (bq, 1)
            kseg = kseg_ref[0, 0]                    # (1, bk)
            s = jnp.where(qseg == kseg, s, _NEG_INF)
        m_prev = m_ref[:, :1]                              # (bq, 1)
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                             # (bq, bk)
        if causal or window is not None or has_mask or has_segs:
            # a fully-masked row has m_new == _NEG_INF, making the
            # masked exp(s - m_new) = exp(0) = 1 instead of 0
            p = jnp.where(s <= _NEG_INF * 0.5, 0.0, p)
        alpha = jnp.exp(m_prev - m_new)                    # (bq, 1)
        # l accumulates the UNdropped p: dropout applies to the softmax
        # probabilities, not to their normalizer
        l_new = alpha * l_ref[:, :1] + jnp.sum(p, axis=-1, keepdims=True)
        if dropout_p > 0.0:
            keep = _dropout_keep(seed_ref[0, bh],
                                 i * block_q + offset, j * block_k,
                                 block_q, block_k, dropout_p)
            p = jnp.where(keep, p * (1.0 / (1.0 - dropout_p)), 0.0)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        acc_ref[:] = acc_ref[:] * alpha + pv
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(jj == num_k_blocks - 1)
    def _finish():
        l = l_ref[:, :1]
        l = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows → zeros, not NaN
        o_ref[0] = (acc_ref[:] / l).astype(o_ref.dtype)
        lse_ref[0] = m_ref[:, :1] + jnp.log(jnp.maximum(l, 1e-37))


def _qseg_spec(nheads, block_q):
    # q-side segment ids (B, Tq, 1) int32; (block_q, 1) last-two dims is
    # the lse layout — legal for any block_q multiple of 8
    return _vmem_spec((1, block_q, 1),
                      lambda b, i, j, _h=nheads: (b // _h, i, 0))


def _kv_row_fold(bh, nheads, kv_heads):
    # k/v may carry FEWER heads than q (GQA/MQA): q-grid row bh maps to
    # kv row batch*kv_heads + (head // group) — the kernel reads the
    # shared K/V block via the index map instead of materializing a
    # head-repeat in HBM. ONE definition: fwd/dq/dkv all fold with it.
    if kv_heads == nheads:
        return bh
    group = nheads // kv_heads
    return (bh // nheads) * kv_heads + (bh % nheads) // group


def _kv_spec(block_k, d, nheads, kv_heads, kv_arg_pos=2):
    """K/V block spec; ``kv_arg_pos`` names which grid arg is the
    kv-block index (2 for the fwd/dq (b, i, j) grids, 1 for the dkv
    swapped (b, j, i) grid)."""

    def imap(*args, _h=nheads, _kv=kv_heads, _p=kv_arg_pos):
        return (_kv_row_fold(args[0], _h, _kv), args[_p], 0)

    return _vmem_spec((1, block_k, d), imap)


def _mask_block_spec(nheads, block_k, j_pos=2, banded_lo=None,
                     n_j=None):
    """kv-side mask/segment block spec over the (B, n_j, 1, block_k)
    BLOCKED layout (the call sites reshape the (B, 1, Tk) row): the
    grid's k-block index picks the j-th chunk via the INDEX MAP on a
    LEADING (untiled) dim, so the kernel never slices the lane dim at
    a dynamic offset — Mosaic cannot prove ``j * block_k`` is
    lane-aligned when block_k is not a multiple of 128, and the seq-64
    NMT shape (block_k=64) failed TPU compilation exactly there
    ("cannot statically prove that index in dimension 2 is a multiple
    of 128"). The last TWO dims stay (1, block_k) == the array's own
    trailing dims, which satisfies the Mosaic tiling rule for ANY
    block_k; n_j must NOT sit in the sublane slot (a (1-of-n_j) block
    there violates the divisible-by-8-or-full rule whenever n_j > 1 —
    caught by tests/test_pallas_mosaic_lowering.py). ``j_pos`` names
    the grid arg carrying the k-block index (2 for the fwd/dq
    (b, i, j) grids, 1 for the dkv (b, j, i) grid); ``banded_lo``
    switches to the banded clamp (the kernels recover the same
    index)."""
    if banded_lo is not None:
        return _vmem_spec((1, 1, 1, block_k), _banded_imap(
            banded_lo, n_j, lambda b, _h=nheads: b // _h, zeros=2))

    def imap(*args, _h=nheads, _p=j_pos):
        return (args[0] // _h, args[_p], 0, 0)

    return _vmem_spec((1, 1, 1, block_k), imap)


def _block_mask(m, n_j, block_k):
    """(B, 1, Tk) kv-side mask/segment row -> (B, n_j, 1, block_k)
    blocked layout for _mask_block_spec (None passes through)."""
    if m is None:
        return None
    return m.reshape(m.shape[0], n_j, 1, block_k)


def _fwd_call(q, k, v, kvm, qseg, kseg, seed, nheads, kv_heads, causal,
              window, scale, dropout_p, block_q, block_k, interpret):
    bh, tq, d = q.shape
    tk = k.shape[1]
    offset = tk - tq
    n_j = tk // block_k
    n_band = (_band_width_j(block_q=block_q, block_k=block_k,
                            window=window, causal=causal, n_j=n_j)
              if window is not None else n_j)
    banded = window is not None and n_band < n_j
    grid = (bh, tq // block_q, n_band if banded else n_j)
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, window=window,
        has_mask=kvm is not None, has_segs=qseg is not None,
        dropout_p=dropout_p, offset=offset, block_q=block_q,
        block_k=block_k, num_k_blocks=grid[2], banded=banded, n_j=n_j)
    # lse carried as (bh, tq, 1): the trailing unit dim keeps the block's
    # last-two-dims (block_q, 1) legal for the Mosaic (8, 128) tiling rule
    out_shape = (
        jax.ShapeDtypeStruct((bh, tq, d), q.dtype),
        jax.ShapeDtypeStruct((bh, tq, 1), jnp.float32),
    )
    j_lo = functools.partial(_band_j_lo, block_q=block_q,
                             block_k=block_k, offset=offset,
                             window=window)
    if banded:
        # k/v specs walk only the band: jj -> clamp(j_lo(i) + jj); the
        # pipeline then never streams out-of-band K/V blocks from HBM
        kv_spec = _vmem_spec((1, block_k, d), _banded_imap(
            j_lo, n_j, lambda b: _kv_row_fold(b, nheads, kv_heads)))
    else:
        kv_spec = _kv_spec(block_k, d, nheads, kv_heads)
    mask_spec = _mask_block_spec(
        nheads, block_k, j_pos=2,
        banded_lo=j_lo if banded else None, n_j=n_j)
    in_specs = [
        _vmem_spec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        kv_spec,
        kv_spec,
    ]
    inputs = (q, k, v)
    if kvm is not None:
        in_specs.append(mask_spec)
        inputs += (_block_mask(kvm, n_j, block_k),)
    if qseg is not None:
        in_specs.append(_qseg_spec(nheads, block_q))
        in_specs.append(mask_spec)  # kv-side: blocked layout
        inputs += (qseg, _block_mask(kseg, n_j, block_k))
    if dropout_p > 0.0:
        in_specs.append(_seed_spec(q.shape[0]))
        inputs += (seed,)
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=(
            _vmem_spec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            _vmem_spec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ),
        out_shape=out_shape,
        scratch_shapes=[
            _scratch((block_q, d), jnp.float32),
            _scratch((block_q, 128), jnp.float32),
            _scratch((block_q, 128), jnp.float32),
        ],
        interpret=interpret,
    )(*inputs)
    return o, lse


# ---------------------------------------------------------------------------
# backward (recompute p from q,k + saved lse — no score materialization)
# ---------------------------------------------------------------------------


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *refs,
               scale, causal, window, has_mask, has_segs, dropout_p,
               offset, block_q, block_k, num_k_blocks, banded=False,
               n_j=None):
    refs = list(refs)
    kvm_ref = refs.pop(0) if has_mask else None
    qseg_ref = refs.pop(0) if has_segs else None
    kseg_ref = refs.pop(0) if has_segs else None
    seed_ref = refs.pop(0) if dropout_p > 0.0 else None
    dq_ref, dq_acc = refs
    bh, i, jj = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    if banded:
        j_raw = _band_j_lo(i, block_q=block_q, block_k=block_k,
                           offset=offset, window=window) + jj
        j = jnp.clip(j_raw, 0, n_j - 1)
        in_range = (j_raw >= 0) & (j_raw < n_j)
    else:
        j, in_range = jj, True

    @pl.when(jj == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    should_run = in_range & _block_should_run(
        i, j, causal=causal, window=window, offset=offset,
        block_q=block_q, block_k=block_k)

    @pl.when(should_run)
    def _body():
        # native-dtype matmul inputs (see _fwd_kernel note): p/ds are
        # quantized back to the input dtype before feeding the MXU —
        # the standard flash-backward precision contract
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0]      # (bq, 1)
        delta = delta_ref[0]  # (bq, 1)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        s = _apply_causal_band(s, i, j, causal=causal, window=window,
                               offset=offset, block_q=block_q,
                               block_k=block_k)
        if has_mask:
            kvm = kvm_ref[0, 0]  # j-th block via the index map
            s = jnp.where(kvm > 0, s, _NEG_INF)
        if has_segs:
            qseg = qseg_ref[0]
            kseg = kseg_ref[0, 0]
            s = jnp.where(qseg == kseg, s, _NEG_INF)
        p = jnp.exp(s - lse)
        if causal or window is not None or has_mask or has_segs:
            # fully-masked rows carry lse == _NEG_INF (see fwd _finish)
            p = jnp.where(s <= _NEG_INF * 0.5, 0.0, p)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if dropout_p > 0.0:
            # same counter-based mask as fwd: out = (m ⊙ y / keep) @ v,
            # so dL/dy = (do @ v^T) ⊙ m / keep and ds = y ⊙ (dL/dy − δ)
            keep = _dropout_keep(seed_ref[0, bh],
                                 i * block_q + offset, j * block_k,
                                 block_q, block_k, dropout_p)
            dp = jnp.where(keep, dp * (1.0 / (1.0 - dropout_p)), 0.0)
        ds = (p * (dp - delta) * scale).astype(k.dtype)
        dq_acc[:] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(jj == num_k_blocks - 1)
    def _finish():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *refs,
                scale, causal, window, has_mask, has_segs, dropout_p,
                offset, block_q, block_k, num_q_blocks, banded=False,
                n_i=None):
    refs = list(refs)
    kvm_ref = refs.pop(0) if has_mask else None
    qseg_ref = refs.pop(0) if has_segs else None
    kseg_ref = refs.pop(0) if has_segs else None
    seed_ref = refs.pop(0) if dropout_p > 0.0 else None
    dk_ref, dv_ref, dk_acc, dv_acc = refs
    bh = pl.program_id(0)
    j, ii = pl.program_id(1), pl.program_id(2)  # kv block outer, q inner
    if banded:
        i_raw = _band_i_lo(j, block_q=block_q, block_k=block_k,
                           offset=offset, window=window,
                           causal=causal) + ii
        i = jnp.clip(i_raw, 0, n_i - 1)
        in_range = (i_raw >= 0) & (i_raw < n_i)
    else:
        i, in_range = ii, True

    @pl.when(ii == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    should_run = in_range & _block_should_run(
        i, j, causal=causal, window=window, offset=offset,
        block_q=block_q, block_k=block_k)

    @pl.when(should_run)
    def _body():
        # native-dtype matmul inputs (see _fwd_kernel note)
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0]      # (bq, 1)
        delta = delta_ref[0]  # (bq, 1)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        s = _apply_causal_band(s, i, j, causal=causal, window=window,
                               offset=offset, block_q=block_q,
                               block_k=block_k)
        if has_mask:
            kvm = kvm_ref[0, 0]  # j-th block via the index map
            s = jnp.where(kvm > 0, s, _NEG_INF)
        if has_segs:
            qseg = qseg_ref[0]
            kseg = kseg_ref[0, 0]
            s = jnp.where(qseg == kseg, s, _NEG_INF)
        p = jnp.exp(s - lse)                               # (bq, bk) f32
        if causal or window is not None or has_mask or has_segs:
            p = jnp.where(s <= _NEG_INF * 0.5, 0.0, p)
        p_v = p  # dv uses the DROPPED probabilities (out = p_drop @ v)
        if dropout_p > 0.0:
            keep = _dropout_keep(seed_ref[0, bh],
                                 i * block_q + offset, j * block_k,
                                 block_q, block_k, dropout_p)
            p_v = jnp.where(keep, p * (1.0 / (1.0 - dropout_p)), 0.0)
        dv_acc[:] += jax.lax.dot_general(
            p_v.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)            # (bk, d)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)            # (bq, bk)
        if dropout_p > 0.0:
            dp = jnp.where(keep, dp * (1.0 / (1.0 - dropout_p)), 0.0)
        ds = (p * (dp - delta) * scale).astype(q.dtype)
        dk_acc[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)            # (bk, d)

    @pl.when(ii == num_q_blocks - 1)
    def _finish():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd_call(q, k, v, kvm, qseg, kseg, seed, nheads, kv_heads, o, lse,
              do, causal, window, scale, dropout_p, block_q, block_k,
              interpret, delta=None):
    bh, tq, d = q.shape
    tk = k.shape[1]
    offset = tk - tq
    if delta is None:  # ring callers pass the hop-invariant value once
        delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                        axis=-1, keepdims=True)  # (bh, tq, 1)
    has_mask = kvm is not None
    has_segs = qseg is not None
    n_j, n_i = tk // block_k, tq // block_q
    band_j = (_band_width_j(block_q=block_q, block_k=block_k,
                            window=window, causal=causal, n_j=n_j)
              if window is not None else n_j)
    banded_j = window is not None and band_j < n_j
    band_i = (_band_width_i(block_q=block_q, block_k=block_k,
                            window=window, causal=causal, n_i=n_i)
              if window is not None else n_i)
    banded_i = window is not None and band_i < n_i

    j_lo = functools.partial(_band_j_lo, block_q=block_q,
                             block_k=block_k, offset=offset,
                             window=window)
    i_lo = functools.partial(_band_i_lo, block_q=block_q,
                             block_k=block_k, offset=offset,
                             window=window, causal=causal)
    kv_imap_banded = _banded_imap(
        j_lo, n_j, lambda b: _kv_row_fold(b, nheads, kv_heads))
    q_imap_banded = _banded_imap(i_lo, n_i)

    dq_kv_spec = (_vmem_spec((1, block_k, d), kv_imap_banded)
                  if banded_j else _kv_spec(block_k, d, nheads, kv_heads))
    dq_in_specs = [
        _vmem_spec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        dq_kv_spec,
        dq_kv_spec,
        _vmem_spec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        _vmem_spec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        _vmem_spec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
    ]
    # blocked kv-side mask layout (see _mask_block_spec): the grid's
    # k-block index picks the chunk, shared by dq (j = args[2], banded
    # clamp when windowed) and dkv (j = args[1], never banded over j)
    kvm_b = _block_mask(kvm, n_j, block_k)
    kseg_b = _block_mask(kseg, n_j, block_k)
    dq_mask_spec = _mask_block_spec(
        nheads, block_k, j_pos=2,
        banded_lo=j_lo if banded_j else None, n_j=n_j)
    dq_inputs = (q, k, v, do, lse, delta)
    if has_mask:
        dq_in_specs.append(dq_mask_spec)
        dq_inputs += (kvm_b,)
    if has_segs:
        dq_in_specs.append(_qseg_spec(nheads, block_q))
        dq_in_specs.append(dq_mask_spec)
        dq_inputs += (qseg, kseg_b)
    if dropout_p > 0.0:
        dq_in_specs.append(_seed_spec(q.shape[0]))
        dq_inputs += (seed,)
    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel, scale=scale, causal=causal, window=window,
            has_mask=has_mask, has_segs=has_segs, dropout_p=dropout_p,
            offset=offset, block_q=block_q, block_k=block_k,
            num_k_blocks=band_j if banded_j else n_j, banded=banded_j,
            n_j=n_j),
        grid=(bh, n_i, band_j if banded_j else n_j),
        in_specs=dq_in_specs,
        out_specs=_vmem_spec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, tq, d), q.dtype),
        scratch_shapes=[_scratch((block_q, d), jnp.float32)],
        interpret=interpret,
    )(*dq_inputs)

    dkv_q_spec = (_vmem_spec((1, block_q, d), q_imap_banded) if banded_i
                  else _vmem_spec((1, block_q, d),
                                  lambda b, j, i: (b, i, 0)))
    dkv_q1_spec = (_vmem_spec((1, block_q, 1), q_imap_banded) if banded_i
                   else _vmem_spec((1, block_q, 1),
                                   lambda b, j, i: (b, i, 0)))
    dkv_in_specs = [
        dkv_q_spec,
        _kv_spec(block_k, d, nheads, kv_heads, kv_arg_pos=1),
        _kv_spec(block_k, d, nheads, kv_heads, kv_arg_pos=1),
        dkv_q_spec,
        dkv_q1_spec,
        dkv_q1_spec,
    ]
    # dkv grid is (b, j, i): the k-block index is args[1] (plain even
    # when banded — dkv bands over i, not j)
    dkv_mask_spec = _mask_block_spec(nheads, block_k, j_pos=1)
    dkv_inputs = (q, k, v, do, lse, delta)
    if has_mask:
        dkv_in_specs.append(dkv_mask_spec)
        dkv_inputs += (kvm_b,)
    if has_segs:
        # q-side spec must use the SWAPPED grid order: i is program_id(2)
        if banded_i:
            dkv_in_specs.append(_vmem_spec((1, block_q, 1), _banded_imap(
                i_lo, n_i, lambda b, _h=nheads: b // _h)))
        else:
            dkv_in_specs.append(_vmem_spec(
                (1, block_q, 1),
                lambda b, j, i, _h=nheads: (b // _h, i, 0)))
        dkv_in_specs.append(dkv_mask_spec)
        dkv_inputs += (qseg, kseg_b)
    if dropout_p > 0.0:
        dkv_in_specs.append(_seed_spec(q.shape[0]))
        dkv_inputs += (seed,)
    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel, scale=scale, causal=causal, window=window,
            has_mask=has_mask, has_segs=has_segs, dropout_p=dropout_p,
            offset=offset, block_q=block_q, block_k=block_k,
            num_q_blocks=band_i if banded_i else n_i, banded=banded_i,
            n_i=n_i),
        grid=(bh, n_j, band_i if banded_i else n_i),
        in_specs=dkv_in_specs,
        out_specs=(
            _vmem_spec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            _vmem_spec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((bh, tk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, tk, d), v.dtype),
        ),
        scratch_shapes=[
            _scratch((block_k, d), jnp.float32),
            _scratch((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(*dkv_inputs)
    if kv_heads != nheads:
        # dk/dv came back per Q-head; sum each group onto its shared
        # K/V head (h is kv-major: head = kv_head * group + g)
        group = nheads // kv_heads
        b = bh // nheads
        dk = dk.reshape(b, kv_heads, group, tk, d).sum(2).reshape(
            b * kv_heads, tk, d)
        dv = dv.reshape(b, kv_heads, group, tk, d).sum(2).reshape(
            b * kv_heads, tk, d)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# partitioned 4D layer: custom_partitioning INSIDE custom_vjp
#
# XLA's SPMD partitioners (GSPMD and Shardy) have no rule for a Pallas
# custom call: under plain pjit auto-sharding they would ALL-GATHER
# q/k/v and run the kernel replicated (the round-3 flagship gap —
# VERDICT r3 #3). The fix is the pattern production JAX stacks use:
# wrap the forward and backward pallas_call bundles in
# jax.experimental.custom_partitioning (which is NOT differentiable) and
# put the pair under ONE jax.custom_vjp. Attention is embarrassingly
# parallel over batch and heads, so the sharding rule declares batch/head
# dims passthrough and seq/head_dim need-replication; each device then
# runs the kernel on its local (b/dp, t, h/tp, d) shard with no
# collectives and no q/k/v gather.
#
# Capability lineage: the reference runs its hand-written jit kernels
# inside graphs parallelized by the multi-device graph pass (reference:
# paddle/fluid/operators/jit/README.en.md,
# framework/ir/multi_devices_graph_pass/multi_devices_graph_pass.cc:450);
# here the "pass" is the SPMD partitioner and this rule teaches it the
# kernel's layout contract.
#
# The boundary arrays are kept unit-dim-free: kvm/qseg/kseg cross as
# (B, T) and lse as (B, H, T); the kernel-layout reshapes ((B,1,Tk),
# (B,Tq,1), (bh,Tq,1)) happen inside the per-shard body.
# ---------------------------------------------------------------------------


def _unpack_opt(args, has_mask, has_segs, has_seed):
    """(q, k, v, *optionals) -> (q, k, v, kvm, seg, seed)."""
    it = iter(args[3:])
    kvm = next(it) if has_mask else None
    seg = next(it) if has_segs else None
    seed = next(it) if has_seed else None
    return args[0], args[1], args[2], kvm, seg, seed


def _fwd4(q, k, v, kvm, seg, seed, *, causal, window, scale,
          dropout_p, block_q, block_k, interpret):
    """Forward on (B, T, H, D) arrays (global or per-shard): flatten to
    the kernel layout, run, unflatten. Returns (o BTHD, lse (B, H, Tq))."""
    b, tq, h, d = q.shape
    tk, hkv = k.shape[1], k.shape[2]
    qf = q.transpose(0, 2, 1, 3).reshape(b * h, tq, d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * hkv, tk, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * hkv, tk, d)
    kvm3 = None if kvm is None else kvm.astype(jnp.float32).reshape(b, 1, tk)
    # q side reads (block_q, 1) lse-layout blocks, kv side full-row
    # slices — two views of the ONE (B, T) ids array that crossed the
    # partition boundary
    qseg3 = None if seg is None else seg.astype(jnp.int32).reshape(b, tq, 1)
    kseg3 = None if seg is None else seg.astype(jnp.int32).reshape(b, 1, tk)
    seed2 = None if seed is None else seed.reshape(1, b * h)
    o, lse = _fwd_call(qf, kf, vf, kvm3, qseg3, kseg3, seed2, h, hkv,
                       causal, window, scale, dropout_p, block_q, block_k,
                       interpret)
    return (o.reshape(b, h, tq, d).transpose(0, 2, 1, 3),
            lse.reshape(b, h, tq))


def _bwd4(q, k, v, kvm, seg, seed, o, lse, do, *, causal, window,
          scale, dropout_p, block_q_bwd, block_k_bwd, interpret):
    """Backward on (B, T, H, D) arrays; returns (dq, dk, dv) in BTHD
    (dk/dv carry the K/V head count — already group-summed under GQA)."""
    b, tq, h, d = q.shape
    tk, hkv = k.shape[1], k.shape[2]
    qf = q.transpose(0, 2, 1, 3).reshape(b * h, tq, d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * hkv, tk, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * hkv, tk, d)
    of = o.transpose(0, 2, 1, 3).reshape(b * h, tq, d)
    dof = do.transpose(0, 2, 1, 3).reshape(b * h, tq, d)
    lsef = lse.reshape(b * h, tq, 1)
    kvm3 = None if kvm is None else kvm.astype(jnp.float32).reshape(b, 1, tk)
    qseg3 = None if seg is None else seg.astype(jnp.int32).reshape(b, tq, 1)
    kseg3 = None if seg is None else seg.astype(jnp.int32).reshape(b, 1, tk)
    seed2 = None if seed is None else seed.reshape(1, b * h)
    dq, dk, dv = _bwd_call(qf, kf, vf, kvm3, qseg3, kseg3, seed2, h, hkv,
                           of, lsef, dof, causal, window, scale, dropout_p,
                           block_q_bwd, block_k_bwd, interpret)
    return (dq.reshape(b, h, tq, d).transpose(0, 2, 1, 3),
            dk.reshape(b, hkv, tk, d).transpose(0, 2, 1, 3),
            dv.reshape(b, hkv, tk, d).transpose(0, 2, 1, 3))


def resolve_block_sizes(tq, tk, d, causal, block_q=None, block_k=None,
                        block_q_bwd=None, block_k_bwd=None):
    """Resolve the four kernel block sizes from the autotuned table
    (ops/pallas/tuning.py), falling back pow2-wise to sizes that divide
    the sequence lengths. Shared by flash_attention and the
    ring-attention per-step calls (parallel/context_parallel.py), which
    see t/sp-sized blocks and must resolve against THOSE shapes."""
    tuned = {}
    if None in (block_q, block_k, block_q_bwd, block_k_bwd):
        from .tuning import attention_key, get_tuned

        tuned = get_tuned(attention_key(tq, tk, d, causal)) or {}

    def _resolve(given, key, seq, default):
        # pow2 buckets can hold shapes the tuned block doesn't divide
        # (e.g. 384 in the 512 bucket with block 256) — walk a fallback
        # chain (tuned -> default -> 64) and take the first block that
        # divides the seq, rather than trip the divisibility error in
        # flash_attention (the dispatch gate admits any 64-divisible
        # seq, so e.g. 192 must resolve to 64, not crash on the 128
        # default)
        if given is not None:
            return min(given, seq)
        for cand in (tuned.get(key), default, 64):
            if cand and seq % min(cand, seq) == 0:
                return min(cand, seq)
        return min(default, seq)

    block_q = _resolve(block_q, "block_q", tq, DEFAULT_BLOCK_Q)
    block_k = _resolve(block_k, "block_k", tk, DEFAULT_BLOCK_K)
    # the backward kernels (dq + dkv) have their own arithmetic-intensity
    # sweet spot; tuned independently, defaulting to the forward blocks
    block_q_bwd = _resolve(block_q_bwd, "block_q_bwd", tq, block_q)
    block_k_bwd = _resolve(block_k_bwd, "block_k_bwd", tk, block_k)
    return block_q, block_k, block_q_bwd, block_k_bwd


# ---------------------------------------------------------------------------
# ring-attention per-step entry points (parallel/context_parallel.py)
#
# Ring attention holds the q rows home and rotates K/V blocks around the
# 'sp' mesh axis. Each hop runs the SAME pallas kernels as single-chip
# flash on (q_local, kv_block) — these two wrappers differ from
# _fwd4/_bwd4 only in that (a) the forward RETURNS the logsumexp so the
# ring loop can merge hops flash-decoding style, and (b) the q-side and
# kv-side segment ids are INDEPENDENT arrays (q ids stay home, kv ids
# travel with their block). No GQA/window/dropout (the ring dispatch
# gates those to the einsum path).
# ---------------------------------------------------------------------------


def ring_fwd_block(q, k, v, kvm, qseg, kseg, *, causal, scale, block_q,
                   block_k, interpret):
    """One ring hop's flash forward: local q (B, Tq, H, D) against one
    rotating K/V block (B, Tk, Hkv, D; Hkv | H — GQA blocks rotate with
    their FEWER heads, the kernel's index map shares them across each
    group). Returns (o, lse): o is the block-normalized output and
    lse = m + log(l) its per-row logsumexp ((B, H, Tq)) — exactly the
    pair the flash-decoding merge needs. ``causal`` here means THIS
    block is the diagonal one (same global offsets); strictly-past
    blocks are called with causal=False and strictly-future ones are
    skipped by the caller."""
    b, tq, h, d = q.shape
    tk, hkv = k.shape[1], k.shape[2]
    qf = q.transpose(0, 2, 1, 3).reshape(b * h, tq, d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * hkv, tk, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * hkv, tk, d)
    kvm3 = None if kvm is None else kvm.astype(jnp.float32).reshape(b, 1, tk)
    qseg3 = None if qseg is None else qseg.astype(jnp.int32).reshape(b, tq, 1)
    kseg3 = None if kseg is None else kseg.astype(jnp.int32).reshape(b, 1, tk)
    o, lse = _fwd_call(qf, kf, vf, kvm3, qseg3, kseg3, None, h, hkv,
                       causal, None, scale, 0.0, block_q, block_k,
                       interpret)
    return (o.reshape(b, h, tq, d).transpose(0, 2, 1, 3),
            lse.reshape(b, h, tq))


def ring_bwd_block(q, k, v, kvm, qseg, kseg, o, lse, do, *, causal,
                   scale, block_q, block_k, interpret, delta=None):
    """One ring hop's flash backward under the GLOBAL softmax: p is
    recomputed against the ring-merged lse and delta = rowsum(do * o)
    uses the FINAL output, so the returned (dq, dk, dv) are this
    (q rows, kv block) pair's exact contributions to the global
    gradients — the standard flash backward decomposition, evaluated one
    hop at a time. ``o``/``do``: final output / upstream cotangent
    (B, Tq, H, D); ``lse``: ring-merged (B, H, Tq); ``delta``: optional
    precomputed rowsum(do*o) ((B, Tq, H) — hop-invariant, so the ring
    loop computes it once instead of n times). Under GQA (k/v carry
    Hkv < H heads) dk/dv come back group-summed onto the Hkv heads."""
    b, tq, h, d = q.shape
    tk, hkv = k.shape[1], k.shape[2]
    qf = q.transpose(0, 2, 1, 3).reshape(b * h, tq, d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * hkv, tk, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * hkv, tk, d)
    of = o.transpose(0, 2, 1, 3).reshape(b * h, tq, d)
    dof = do.transpose(0, 2, 1, 3).reshape(b * h, tq, d)
    lsef = lse.reshape(b * h, tq, 1)
    deltaf = (None if delta is None
              else delta.transpose(0, 2, 1).reshape(b * h, tq, 1))
    kvm3 = None if kvm is None else kvm.astype(jnp.float32).reshape(b, 1, tk)
    qseg3 = None if qseg is None else qseg.astype(jnp.int32).reshape(b, tq, 1)
    kseg3 = None if kseg is None else kseg.astype(jnp.int32).reshape(b, 1, tk)
    dq, dk, dv = _bwd_call(qf, kf, vf, kvm3, qseg3, kseg3, None, h, hkv,
                           of, lsef, dof, causal, None, scale, 0.0,
                           block_q, block_k, interpret, delta=deltaf)
    return (dq.reshape(b, h, tq, d).transpose(0, 2, 1, 3),
            dk.reshape(b, hkv, tk, d).transpose(0, 2, 1, 3),
            dv.reshape(b, hkv, tk, d).transpose(0, 2, 1, 3))


def _attn_rule(has_mask, has_segs, has_seed, gqa, bwd):
    """Einsum-style Shardy sharding rule + need-replication factors for
    the fwd/bwd custom calls. b (batch) and the head factor are
    passthrough (shardable); tq/tk/d must be replicated (the kernel
    computes full attention rows locally). Under GQA the q tensor
    crosses the boundary as 5-D (b, tq, kv_heads, group, d) so the
    KV-HEAD factor g is SHARED with k/v and shards consistently — a
    head shard then owns whole kv groups (group itself is pinned
    replicated: splitting a group would orphan its shared K/V)."""
    if gqa:
        qm, km = "b tq g grp d", "b tk g d"
        lse, seed = "b g grp tq", "b g grp"
    else:
        qm, km = "b tq h d", "b tk h d"
        lse, seed = "b h tq", "b h"
    ins = [qm, km, km]
    if has_mask:
        ins.append("b tk")
    if has_segs:
        ins.append("b tq")
    if has_seed:
        ins.append(seed)
    if bwd:
        ins += [qm, lse, qm]               # o, lse, do
        outs = [qm, km, km]                # dq, dk, dv
    else:
        outs = [qm, lse]                   # o, lse
    # need_replication must be sorted by factor first-appearance index:
    # non-GQA b=0, tq=1, h=2, d=3, tk=4; GQA b=0, tq=1, g=2, grp=3,
    # d=4, tk=5
    need = ("tq", "grp", "d", "tk") if gqa else ("tq", "d", "tk")
    rule = ", ".join(ins) + " -> " + ", ".join(outs)
    return rule, need


def _attn_shardings(mesh, q_sharding, has_mask, has_segs, has_seed, gqa,
                    bwd):
    """Supported NamedShardings for every operand/result, derived from
    the partitioner's suggestion for q: keep its batch/head axes, pin
    everything else replicated."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    msh = getattr(q_sharding, "mesh", None) or mesh
    spec = tuple(q_sharding.spec) if q_sharding is not None else ()
    spec = spec + (None,) * ((5 if gqa else 4) - len(spec))
    bax = spec[0]
    hax = spec[2]  # kv-head dim under GQA (q crosses as 5-D), else heads

    def S(*parts):
        return NamedSharding(msh, P(*parts))

    if gqa:
        qs = S(bax, None, hax, None, None)   # (b, tq, kv, group, d)
        ks = S(bax, None, hax, None)         # (b, tk, kv, d)
        lse_s = S(bax, hax, None, None)      # (b, kv, group, tq)
        seed_s = S(bax, hax, None)           # (b, kv, group)
    else:
        qs = ks = S(bax, None, hax, None)
        lse_s = S(bax, hax, None)
        seed_s = S(bax, hax)
    args = [qs, ks, ks]
    if has_mask:
        args.append(S(bax, None))
    if has_segs:
        args.append(S(bax, None))
    if has_seed:
        args.append(seed_s)
    if bwd:
        args += [qs, lse_s, qs]
        results = (qs, ks, ks)
    else:
        results = (qs, lse_s)
    return msh, tuple(args), results


@functools.lru_cache(maxsize=None)
def _partitioned(bwd, has_mask, has_segs, has_seed, gqa, causal, window,
                 scale, dropout_p, blk_a, blk_b, interpret):
    """Build (and cache per static config) the custom_partitioning-wrapped
    forward or backward call."""
    from jax.experimental.custom_partitioning import custom_partitioning

    if bwd:
        def impl(*args):
            q, k, v, kvm, seg, seed = _unpack_opt(
                args[:-3], has_mask, has_segs, has_seed)
            o, lse, do = args[-3], args[-2], args[-1]
            if gqa:  # 5-D boundary (see _attn_rule) -> kernel 4-D forms
                b, tq, kv, grp, d = q.shape
                q = q.reshape(b, tq, kv * grp, d)
                o = o.reshape(b, tq, kv * grp, d)
                do = do.reshape(b, tq, kv * grp, d)
                lse = lse.reshape(b, kv * grp, tq)
                seed = (None if seed is None
                        else seed.reshape(seed.shape[0], kv * grp))
            dq, dk, dv = _bwd4(q, k, v, kvm, seg, seed, o, lse, do,
                               causal=causal, window=window, scale=scale,
                               dropout_p=dropout_p, block_q_bwd=blk_a,
                               block_k_bwd=blk_b, interpret=interpret)
            if gqa:
                dq = dq.reshape(b, tq, kv, grp, d)
            return dq, dk, dv
    else:
        def impl(*args):
            q, k, v, kvm, seg, seed = _unpack_opt(
                args, has_mask, has_segs, has_seed)
            if gqa:  # 5-D boundary (see _attn_rule) -> kernel 4-D forms
                b, tq, kv, grp, d = q.shape
                q = q.reshape(b, tq, kv * grp, d)
                seed = (None if seed is None
                        else seed.reshape(seed.shape[0], kv * grp))
            o, lse = _fwd4(q, k, v, kvm, seg, seed, causal=causal,
                           window=window, scale=scale, dropout_p=dropout_p,
                           block_q=blk_a, block_k=blk_b,
                           interpret=interpret)
            if gqa:
                o = o.reshape(b, tq, kv, grp, d)
                lse = lse.reshape(b, kv, grp, tq)
            return o, lse

    wrapped = custom_partitioning(impl)
    rule, need = _attn_rule(has_mask, has_segs, has_seed, gqa, bwd)

    def partition(mesh, arg_shapes, result_shape):
        q_sh = arg_shapes[0].sharding
        if hasattr(q_sh, "spec"):
            msh, arg_sh, res_sh = _attn_shardings(
                mesh, q_sh, has_mask, has_segs, has_seed, gqa, bwd)
        else:
            # inside a partial-manual shard_map region the partitioner
            # hands opaque GSPMDShardings; its suggestion already went
            # through the sdy sharding rule (seq/head_dim pinned
            # replicated), so echo it and lower on the local shards
            msh = mesh
            arg_sh = tuple(a.sharding for a in arg_shapes)
            res_sh = jax.tree_util.tree_map(
                lambda x: x.sharding, result_shape)

        def lower_fn(*args):
            return impl(*args)

        return msh, lower_fn, res_sh, arg_sh

    def infer_sharding_from_operands(mesh, arg_shapes, shape):
        from jax.sharding import NamedSharding, PartitionSpec as P

        q_sh = arg_shapes[0].sharding
        if not hasattr(q_sh, "spec"):
            # GSPMD mode inside a manual region hands opaque shardings
            # (same case the partition callback guards): conservatively
            # replicate the results; partition() still lowers sharded
            return jax.tree_util.tree_map(
                lambda _: NamedSharding(mesh, P()), shape)
        return _attn_shardings(mesh, q_sh, has_mask, has_segs, has_seed,
                               gqa, bwd)[2]

    compat.def_partition(
        wrapped,
        partition=partition,
        infer_sharding_from_operands=infer_sharding_from_operands,
        sharding_rule=rule,
        need_replication_factors=need)
    return wrapped


# ---------------------------------------------------------------------------
# custom_vjp over the partitioned calls, (batch, seq, heads, head_dim)
# ---------------------------------------------------------------------------


def _opt_args(q, k, v, kvm, seg, seed):
    return (q, k, v) + tuple(a for a in (kvm, seg, seed) if a is not None)


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(6, 7, 8, 9, 10, 11, 12, 13, 14))
def _flash(q, k, v, kvm, seg, seed, causal, window, scale, dropout_p,
           block_q, block_k, block_q_bwd, block_k_bwd, interpret):
    o, _ = _flash_fwd(q, k, v, kvm, seg, seed, causal, window, scale,
                      dropout_p, block_q, block_k, block_q_bwd,
                      block_k_bwd, interpret)
    return o


def _gqa_pack(q, seed, hkv):
    """4-D (b, t, h, d) q / (b, h) seed -> the 5-D/3-D GQA boundary
    forms whose kv-head dim shards with k/v (see _attn_rule)."""
    b, tq, h, d = q.shape
    grp = h // hkv
    q5 = q.reshape(b, tq, hkv, grp, d)
    seed3 = None if seed is None else seed.reshape(b, hkv, grp)
    return q5, seed3


def _flash_fwd(q, k, v, kvm, seg, seed, causal, window, scale, dropout_p,
               block_q, block_k, block_q_bwd, block_k_bwd, interpret):
    gqa = k.shape[2] != q.shape[2]
    fwd = _partitioned(False, kvm is not None, seg is not None,
                       seed is not None, gqa, causal, window, scale,
                       dropout_p, block_q, block_k, interpret)
    if gqa:
        b, tq, h, d = q.shape
        q5, seed3 = _gqa_pack(q, seed, k.shape[2])
        o5, lse = fwd(*_opt_args(q5, k, v, kvm, seg, seed3))
        o = o5.reshape(b, tq, h, d)
    else:
        o, lse = fwd(*_opt_args(q, k, v, kvm, seg, seed))
    # lse is stored in the call's boundary layout ((b, kv, grp, tq)
    # under GQA) and handed back to the bwd call unchanged
    return o, (q, k, v, kvm, seg, seed, o, lse)


def _flash_bwd(causal, window, scale, dropout_p, block_q, block_k,
               block_q_bwd, block_k_bwd, interpret, res, do):
    q, k, v, kvm, seg, seed, o, lse = res
    gqa = k.shape[2] != q.shape[2]
    bwd = _partitioned(True, kvm is not None, seg is not None,
                       seed is not None, gqa, causal, window, scale,
                       dropout_p, block_q_bwd, block_k_bwd, interpret)
    if gqa:
        b, tq, h, d = q.shape
        hkv = k.shape[2]
        grp = h // hkv
        q5, seed3 = _gqa_pack(q, seed, hkv)
        o5 = o.reshape(b, tq, hkv, grp, d)
        do5 = do.reshape(b, tq, hkv, grp, d)
        dq5, dk, dv = bwd(*(_opt_args(q5, k, v, kvm, seg, seed3)
                            + (o5, lse, do5)))
        dq = dq5.reshape(b, tq, h, d)
    else:
        dq, dk, dv = bwd(*(_opt_args(q, k, v, kvm, seg, seed)
                           + (o, lse, do)))
    # the keep-mask, segment ids and dropout seed carry no gradients
    return dq, dk, dv, None, None, None


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, causal: bool = False,
                    scale: Optional[float] = None,
                    kv_mask=None,
                    segment_ids=None,
                    window: Optional[int] = None,
                    dropout_p: float = 0.0,
                    dropout_key=None,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    block_q_bwd: Optional[int] = None,
                    block_k_bwd: Optional[int] = None,
                    interpret: Optional[bool] = None):
    """Blockwise attention over (batch, seq, heads, head_dim) inputs.

    Sequence lengths must divide the block sizes (shrunk automatically for
    short sequences). Differentiable (custom VJP, recompute backward).
    Block sizes default to the autotuned table (ops/pallas/tuning.py,
    written by tools/pallas_tune.py on real hardware) and fall back to
    128x128.

    ``kv_mask``: optional (batch, tk) keep-mask (True/nonzero = attend) —
    the key-padding form every ragged-batch model needs (the LoD
    replacement, ops/sequence.py); masked keys contribute nothing and
    fully-masked rows output zeros, matching ops.attention.xla_attention.
    Arbitrary (B, H, Tq, Tk) masks stay on the XLA path.

    ``segment_ids``: optional (batch, t) int ids for PACKED batches
    (multiple sequences per row, the padding-free pretraining layout):
    positions attend only within their own segment; composes with
    ``causal`` and ``kv_mask``. Self-attention only (tq == tk).

    ``dropout_p``/``dropout_key``: attention-probability dropout INSIDE
    the kernel — scores still never materialize in HBM (the whole point
    at long seq; the XLA fallback with dropout pays the (B,H,T,T)
    tensor). The keep-mask comes from a counter-based hash of the seed
    and global coordinates, so the backward rebuilds it bit-identically
    with no stored mask.
    """
    b, tq, h, d = q.shape
    tk = k.shape[1]
    h_kv = k.shape[2]
    if h_kv != h:
        # GQA/MQA: fewer K/V heads than Q heads; the kernel reads the
        # shared block via its index map (no head-repeat in HBM)
        if h % h_kv or v.shape[2] != h_kv:
            raise ValueError(
                f"kv heads ({h_kv}, v={v.shape[2]}) must divide q heads "
                f"({h}) and match each other")
    if scale is None:
        scale = d ** -0.5
    block_q, block_k, block_q_bwd, block_k_bwd = resolve_block_sizes(
        tq, tk, d, causal, block_q, block_k, block_q_bwd, block_k_bwd)
    if tq % block_q or tk % block_k or tq % block_q_bwd or tk % block_k_bwd:
        raise ValueError(
            f"seq lens ({tq},{tk}) must be divisible by blocks "
            f"({block_q},{block_k}) and bwd blocks "
            f"({block_q_bwd},{block_k_bwd}); pad upstream")
    if interpret is None:
        interpret = _use_interpret()
    kvm = None
    if kv_mask is not None:
        if kv_mask.shape != (b, tk):
            raise ValueError(
                f"kv_mask must be (batch, tk) = ({b},{tk}), got "
                f"{kv_mask.shape}")
        kvm = kv_mask.astype(jnp.float32)
    if not 0.0 <= dropout_p < 1.0:
        raise ValueError(f"dropout_p must be in [0, 1), got {dropout_p}")
    if window is not None and window < 1:
        raise ValueError(f"window must be >= 1, got {window}")
    seed = None
    if dropout_p > 0.0:
        if dropout_key is None:
            raise ValueError("dropout_p > 0 requires dropout_key")
        # one int32 seed per (batch, head): the kernel addresses dropout
        # by global (b, h) identity + global coordinates, so the mask is
        # bit-identical under any batch/head sharding (see _seed_spec)
        seed = jax.random.randint(dropout_key, (b, h), -2 ** 31,
                                  2 ** 31 - 1, dtype=jnp.int32)
    seg = None
    if segment_ids is not None:
        if tq != tk:
            raise ValueError("segment_ids requires self-attention shapes "
                             f"(tq={tq} != tk={tk})")
        if segment_ids.shape != (b, tq):
            raise ValueError(
                f"segment_ids must be (batch, t) = ({b},{tq}), got "
                f"{segment_ids.shape}")
        seg = segment_ids.astype(jnp.int32)
    # 4D boundary: the partitioned fwd/bwd calls shard over batch/head
    # under pjit auto-sharding (no q/k/v all-gather) and flatten to the
    # kernel layout per shard
    return _flash(q, k, v, kvm, seg, seed, causal,
                  None if window is None else int(window), float(scale),
                  float(dropout_p), block_q, block_k, block_q_bwd,
                  block_k_bwd, interpret)
