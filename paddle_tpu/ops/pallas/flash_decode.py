"""Pallas flash-decode: single-position KV-cache attention.

The decode hot loop attends ONE query per (batch, head) against a
pre-allocated (B, capacity, H_kv, D) cache with a ``pos <= t`` mask.
The XLA fallback streams the FULL capacity from HBM every step even
when only t+1 positions are live; decode is bandwidth-bound, so that
over-read is the whole cost. This kernel walks kv blocks on a
(B, capacity/block_k) grid with the block index CLAMPED into the live
range [lo(t), t // block_k] via a scalar-prefetch index map — Mosaic
elides the DMA when consecutive grid steps map to the same block, so
HBM traffic is O(t) (O(window) with sliding-window attention), not
O(capacity).

All H query heads of one batch element ride one program as the row
dimension of the score matrix (a single decode row per head would
waste the 8-sublane tile); GQA/MQA groups take static per-kv-head
slices of those rows, reading each shared K/V block once. Online
softmax carries (m, l, acc) in VMEM scratch across kv blocks exactly
like the training kernel (flash_attention.py).

The paged form has an int8-native variant (ISSUE 15): K/V blocks
stream from HBM as raw int8 with their per-(page, pos, kv_head) f32
scales prefetched along the SAME clamped page walk, and dequant runs
in VMEM as a per-block epilogue before the online-softmax update —
quantized decode keeps the O(t) DMA behavior and moves ~4x fewer HBM
bytes per block. This module only ever sees raw arrays; the
QuantizedPool-vs-float dispatch lives in ops/paged_kv.attend
(PT-LINT-308 pins that boundary).

Inference-only: no VJP (the decode loop never differentiates).
Reference niche: the hand-tuned JIT kernel layer,
/root/reference/paddle/fluid/operators/jit/ — decode attention is the
op XLA leaves the most bandwidth on the table for.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ...core.enforce import enforce
from .flash_attention import _NEG_INF, _scratch, _use_interpret, pltpu

if pltpu is None:  # pragma: no cover
    # unlike the sibling training kernel, this one NEEDS pltpu
    # (PrefetchScalarGridSpec for the cursor); failing the import here
    # lets ops.attention's guarded importers fall back to the XLA path
    raise ImportError("flash_decode requires jax.experimental.pallas.tpu")

DEFAULT_DECODE_BLOCK_K = 256


def _decode_core(t_ref, q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref,
                 l_ref, ks_ref, vs_ref, *, scale, window, block_k, n_j,
                 nheads, kv_heads):
    """Shared online-softmax decode body. ``ks_ref``/``vs_ref``: the
    int8 variant's per-(position, kv_head) f32 scale blocks — when
    present, K/V blocks arrive as raw int8 and dequantize HERE, in
    VMEM, as an epilogue on each block before the softmax update (the
    pool streams ~4x fewer HBM bytes per block; float never exists
    outside the block working set). None = the float path, bit-for-bit
    the pre-int8 kernel."""
    b, j = pl.program_id(0), pl.program_id(1)
    t = t_ref[b]  # PER-ROW cursor (continuous batching: each slot at
    # its own position; the classic shared-cursor decode broadcasts)
    t_blk = t // block_k
    lo_blk = (jnp.maximum(t - window + 1, 0) // block_k
              if window is not None else 0)
    group = nheads // kv_heads

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    @pl.when((j <= t_blk) & (j >= lo_blk))
    def _body():
        q = q_ref[0]                                  # (H, D)
        parts = []
        for hk in range(kv_heads):
            qg = q[hk * group:(hk + 1) * group]       # (G, D)
            kk = k_ref[0, :, hk]                      # (block_k, D)
            if ks_ref is not None:
                # dequant epilogue: int8 block * per-vector scale, f32
                kk = (kk.astype(jnp.float32)
                      * ks_ref[0, :, hk][:, None])
                qg = qg.astype(jnp.float32)
            parts.append(jax.lax.dot_general(
                qg, kk, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32))
        s = jnp.concatenate(parts, axis=0) * scale    # (H, block_k)
        cols = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_k), 1)
        live = cols <= t
        if window is not None:
            live &= cols > t - window
        s = jnp.where(live, s, _NEG_INF)
        m_prev = m_ref[:, :1]                         # (H, 1)
        m_new = jnp.maximum(m_prev, jnp.max(s, -1, keepdims=True))
        p = jnp.exp(s - m_new)
        p = jnp.where(s <= _NEG_INF * 0.5, 0.0, p)
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_ref[:, :1] + jnp.sum(p, -1, keepdims=True)
        pvs = []
        for hk in range(kv_heads):
            vv = v_ref[0, :, hk]                      # (block_k, D)
            if vs_ref is not None:
                vv = (vv.astype(jnp.float32)
                      * vs_ref[0, :, hk][:, None])
            pg = p[hk * group:(hk + 1) * group]
            pvs.append(jax.lax.dot_general(
                pg.astype(vv.dtype), vv, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32))
        acc_ref[:] = acc_ref[:] * alpha + jnp.concatenate(pvs, axis=0)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(j == n_j - 1)
    def _finish():
        l = l_ref[:, :1]
        l = jnp.where(l == 0.0, 1.0, l)  # t<0 would divide by zero
        o_ref[0] = (acc_ref[:] / l).astype(o_ref.dtype)


def _decode_kernel(t_ref, q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref,
                   l_ref, **kw):
    """Float decode kernel — the core with no scale planes."""
    _decode_core(t_ref, q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref,
                 l_ref, None, None, **kw)


def _paged_kernel(t_ref, table_ref, *rest, **kw):
    """The paged variant IS _decode_kernel: page translation happens
    entirely in the specs' index maps (which consume table_ref); the
    kernel body masks by LOGICAL position only, so the online-softmax
    math stays defined once."""
    del table_ref
    _decode_kernel(t_ref, *rest, **kw)


def _paged_kernel_quant(t_ref, table_ref, q_ref, k_ref, ks_ref, v_ref,
                        vs_ref, o_ref, acc_ref, m_ref, l_ref, **kw):
    """int8 paged variant: K/V blocks stream raw int8 with their
    per-(page, pos, kv_head) scale blocks prefetched alongside (same
    page walk in the index maps); the core dequantizes per block in
    VMEM."""
    del table_ref
    _decode_core(t_ref, q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref,
                 l_ref, ks_ref, vs_ref, **kw)


def flash_decode_paged(q, kpool, vpool, table, t, *,
                       k_scale=None, v_scale=None,
                       window: Optional[int] = None,
                       scale: Optional[float] = None,
                       interpret: Optional[bool] = None):
    """Paged decode attention (vLLM-style): the KV cache lives in a
    SHARED page pool (pages, page_size, H_kv, D); each row's logical
    cache is the page sequence ``table[b]`` (B, n_logical) of physical
    page ids. One grid step loads one page — the scalar-prefetched
    table drives the DMA, so a row reads ONLY its own live pages and
    the pool can be sized to the live token count instead of
    slots x max-capacity. q: (B, 1, H, D); t: scalar or (B,) per-row
    cursors (LOGICAL positions). Returns (B, 1, H, D).

    int8 pools: pass the RAW int8 value pools as ``kpool``/``vpool``
    and their per-(page, pos, kv_head) f32 scale planes as
    ``k_scale``/``v_scale`` — scale blocks ride the same clamped page
    walk and dequant happens in VMEM per block (the epilogue), so
    quantized decode keeps the O(t) DMA behavior AND streams ~4x fewer
    HBM bytes per block. The storage-form dispatch (QuantizedPool or
    float) stays in ops/paged_kv.attend — this kernel only ever sees
    raw arrays.

    Entries of ``table`` beyond a row's live range may be garbage (the
    index map clamps to the live page walk); pages are block_k-sized by
    construction. The serving-side pool manager is
    paddle_tpu.serving.PagedKVPool."""
    b, tq, h, d = q.shape
    enforce(tq == 1, "flash_decode_paged takes one query position, "
            "got %s", tq)
    enforce(window is None or window >= 1,
            "window must be >= 1, got %s", window)
    enforce((k_scale is None) == (v_scale is None),
            "k_scale and v_scale come together (int8 pools) or not at "
            "all (float pools)")
    pages, block_k, kv_h, dk = kpool.shape
    enforce(dk == d, "pool head_dim %s != q head_dim %s", dk, d)
    enforce(h % kv_h == 0, "heads %s not divisible by kv heads %s", h,
            kv_h)
    quantized = k_scale is not None
    if quantized:
        for name, sc in (("k_scale", k_scale), ("v_scale", v_scale)):
            enforce(tuple(sc.shape) == (pages, block_k, kv_h),
                    "%s must be the pool's (pages, page_size, "
                    "kv_heads) scale plane %s, got %s",
                    name, (pages, block_k, kv_h), tuple(sc.shape))
    n_log = table.shape[1]
    enforce(table.shape[0] == b,
            "table rows %s != batch %s", table.shape[0], b)
    if scale is None:
        scale = d ** -0.5
    if interpret is None:
        interpret = _use_interpret()
    qh = q[:, 0]
    t_arr = jnp.broadcast_to(jnp.asarray(t, jnp.int32), (b,))
    table = table.astype(jnp.int32)

    def _live_page(b_, j, t_, table_):
        jj = jnp.minimum(j, t_[b_] // block_k)
        if window is not None:
            jj = jnp.maximum(
                jj, jnp.maximum(t_[b_] - window + 1, 0) // block_k)
        return jnp.clip(table_[b_, jj], 0, pages - 1)

    def kv_imap(b_, j, t_, table_):
        return (_live_page(b_, j, t_, table_), 0, 0, 0)

    def sc_imap(b_, j, t_, table_):
        # the scale plane walks the SAME clamped live pages
        return (_live_page(b_, j, t_, table_), 0, 0)

    qo_spec = pl.BlockSpec((1, h, d), lambda b_, j, t_, tb_: (b_, 0, 0))
    kv_spec = pl.BlockSpec((1, block_k, kv_h, d), kv_imap)
    kw = dict(scale=scale, window=window, block_k=block_k, n_j=n_log,
              nheads=h, kv_heads=kv_h)
    if quantized:
        sc_spec = pl.BlockSpec((1, block_k, kv_h), sc_imap)
        kernel = functools.partial(_paged_kernel_quant, **kw)
        in_specs = [qo_spec, kv_spec, sc_spec, kv_spec, sc_spec]
        operands = (t_arr, table, qh, kpool,
                    k_scale.astype(jnp.float32), vpool,
                    v_scale.astype(jnp.float32))
    else:
        kernel = functools.partial(_paged_kernel, **kw)
        in_specs = [qo_spec, kv_spec, kv_spec]
        operands = (t_arr, table, qh, kpool, vpool)
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(b, n_log),
            in_specs=in_specs,
            out_specs=qo_spec,
            scratch_shapes=[
                _scratch((h, d), jnp.float32),
                _scratch((h, 128), jnp.float32),
                _scratch((h, 128), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, h, d), q.dtype),
        interpret=interpret,
    )(*operands)
    return out[:, None]


def decode_block_k(capacity: int, d: Optional[int] = None) -> Optional[int]:
    """kv block for a cache capacity: the on-chip tuned winner when the
    table has one (tools/pallas_tune.py --decode), else the largest
    supported divisor. None = shape ineligible for the kernel."""
    if d is not None:
        from .tuning import get_tuned_decode

        tuned = get_tuned_decode(capacity, d, "f32")
        if tuned is not None:
            bk = tuned.get("block_k")
            if bk and capacity % bk == 0:
                return bk
    for bk in (DEFAULT_DECODE_BLOCK_K, 128, 64):
        if capacity % bk == 0:
            return bk
    return None


def flash_decode(q, k, v, t, *, window: Optional[int] = None,
                 scale: Optional[float] = None,
                 block_k: Optional[int] = None,
                 interpret: Optional[bool] = None):
    """One decode position: q (B, 1, H, D) against caches k/v
    (B, capacity, H_kv, D) with the ``pos <= t`` (and optional
    sliding-``window``) mask applied in-kernel. Returns (B, 1, H, D).
    ``t`` may be a traced scalar (one shared cursor) or a (B,) array
    of PER-ROW cursors (the continuous-batching step); either rides
    scalar prefetch into the index maps. Capacity must be divisible by
    ``block_k``."""
    b, tq, h, d = q.shape
    enforce(tq == 1, "flash_decode takes one query position, got %s",
            tq)
    cap, kv_h = k.shape[1], k.shape[2]
    enforce(h % kv_h == 0, "heads %s not divisible by kv heads %s", h,
            kv_h)
    enforce(window is None or window >= 1,
            "window must be >= 1, got %s", window)
    block_k = block_k or decode_block_k(cap, d)
    enforce(block_k is not None and cap % block_k == 0,
            "capacity %s not divisible by a supported block (%s)", cap,
            block_k)
    if scale is None:
        scale = d ** -0.5
    if interpret is None:
        interpret = _use_interpret()
    n_j = cap // block_k
    qh = q[:, 0]                                      # (B, H, D)
    t_arr = jnp.broadcast_to(jnp.asarray(t, jnp.int32), (b,))

    def kv_imap(b_, j, t_):
        jj = jnp.minimum(j, t_[b_] // block_k)
        if window is not None:
            jj = jnp.maximum(
                jj, jnp.maximum(t_[b_] - window + 1, 0) // block_k)
        return (b_, jj, 0, 0)

    kernel = functools.partial(
        _decode_kernel, scale=scale, window=window, block_k=block_k,
        n_j=n_j, nheads=h, kv_heads=kv_h)
    qo_spec = pl.BlockSpec((1, h, d), lambda b_, j, t_: (b_, 0, 0))
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b, n_j),
            in_specs=[
                qo_spec,
                pl.BlockSpec((1, block_k, kv_h, d), kv_imap),
                pl.BlockSpec((1, block_k, kv_h, d), kv_imap),
            ],
            out_specs=qo_spec,
            scratch_shapes=[
                _scratch((h, d), jnp.float32),
                _scratch((h, 128), jnp.float32),
                _scratch((h, 128), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, h, d), q.dtype),
        interpret=interpret,
    )(t_arr, qh, k, v)
    return out[:, None]
