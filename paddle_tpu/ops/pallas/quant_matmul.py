"""int8 quantized matmul — tiled Pallas TPU kernel with fused dequant.

Capability role: the reference's int8 inference stack (operators/
{quantize,dequantize,requantize}_op.cc + mkldnn int8 kernels + contrib/
int8_inference) runs quantized GEMMs on the CPU backend. The TPU-native
form: int8 A (activations, per-tensor scale) x int8 B (weights, per-tensor
or per-channel scale) accumulate in int32 on the MXU, dequantize to the
output dtype INSIDE the kernel epilogue — weights stay int8 in HBM (4x
smaller than fp32, half of bf16), and the dequant never materializes an
fp32 copy of B.

``quant_matmul`` picks the Pallas kernel on TPU and an XLA
preferred_element_type=int32 path elsewhere (same numerics — the tests
assert exact agreement, int8 math is exact in int32).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ...core.enforce import enforce
from ...utils import compat

compat.fix_custom_partitioning_static_args()

try:  # pltpu resolves on TPU builds; interpret mode needs none of it
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None


def _spec(shape, index_map):
    if _VMEM is not None:
        return pl.BlockSpec(shape, index_map, memory_space=_VMEM)
    return pl.BlockSpec(shape, index_map)


def _kernel(a_ref, b_ref, sa_ref, sb_ref, o_ref, acc_ref, *, k_tiles):
    """One (TM, TN) output tile: loop over K tiles accumulating int32 on
    the MXU; dequant epilogue on the last K step."""
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    a = a_ref[...]  # (TM, TK) int8
    b = b_ref[...]  # (TK, TN) int8
    acc_ref[...] += jax.lax.dot_general(
        a, b, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)

    @pl.when(k == k_tiles - 1)
    def _epilogue():
        scale = sa_ref[0] * sb_ref[...]          # (TN,) or scalar broadcast
        o_ref[...] = (acc_ref[...].astype(jnp.float32)
                      * scale[None, :]).astype(o_ref.dtype)


def _pallas_quant_matmul(a_i8, b_i8, a_scale, b_scale, *, out_dtype,
                         tile_m: int, tile_n: int, tile_k: int,
                         interpret: bool):
    m, k = a_i8.shape
    k2, n = b_i8.shape
    grid = (m // tile_m, n // tile_n, k // tile_k)
    b_scale_vec = jnp.broadcast_to(jnp.asarray(b_scale, jnp.float32), (n,))
    a_scale_arr = jnp.asarray(a_scale, jnp.float32).reshape(1)
    kernel = functools.partial(_kernel, k_tiles=grid[2])
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            _spec((tile_m, tile_k), lambda i, j, kk: (i, kk)),
            _spec((tile_k, tile_n), lambda i, j, kk: (kk, j)),
            pl.BlockSpec((1,), lambda i, j, kk: (0,)),
            pl.BlockSpec((tile_n,), lambda i, j, kk: (j,)),
        ],
        out_specs=_spec((tile_m, tile_n), lambda i, j, kk: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), out_dtype),
        scratch_shapes=[jax.ShapeDtypeStruct((tile_m, tile_n), jnp.int32)
                        if pltpu is None
                        else pltpu.VMEM((tile_m, tile_n), jnp.int32)],
        interpret=interpret,
    )(a_i8, b_i8, a_scale_arr, b_scale_vec)


def _qm_impl(a_i8, b_i8, a_scale_arr, b_scale_vec, *, out_dtype, tile_m,
             tile_n, tile_k, interpret):
    """Unpadded (global or per-shard) kernel invocation: pad to the tile
    grid (exact in integer math), run, slice back. Runs per shard under
    the partitioned call, so local shapes pad independently."""
    m, ka = a_i8.shape
    n = b_i8.shape[1]

    def _pad_to(arr, mult, axis):
        r = (-arr.shape[axis]) % mult
        if r == 0:
            return arr
        widths = [(0, 0)] * arr.ndim
        widths[axis] = (0, r)
        return jnp.pad(arr, widths)

    tm, tn, tk = min(tile_m, m), min(tile_n, n), min(tile_k, ka)
    a_p = _pad_to(_pad_to(a_i8, tm, 0), tk, 1)
    b_p = _pad_to(_pad_to(b_i8, tk, 0), tn, 1)
    bs_p = _pad_to(b_scale_vec, tn, 0)
    out = _pallas_quant_matmul(
        a_p, b_p, a_scale_arr, bs_p, out_dtype=out_dtype,
        tile_m=tm, tile_n=tn, tile_k=tk, interpret=interpret)
    return out[:m, :n]


@functools.lru_cache(maxsize=None)
def _partitioned_qm(out_dtype, tile_m, tile_n, tile_k, interpret):
    """custom_partitioning wrapper: the SPMD partitioners have no rule
    for a Pallas custom call and would all-gather the operands under
    pjit (same gap the flash kernel closed — see
    flash_attention.py). int8 GEMM shards over M (dp batch) and N
    (column-parallel weights, per-channel scales riding along); K and
    the scalar scale stay replicated."""
    from jax.experimental.custom_partitioning import custom_partitioning
    from jax.sharding import NamedSharding, PartitionSpec as P

    impl = functools.partial(_qm_impl, out_dtype=jnp.dtype(out_dtype),
                             tile_m=tile_m, tile_n=tile_n, tile_k=tile_k,
                             interpret=interpret)
    wrapped = custom_partitioning(lambda *args: impl(*args))

    def _axes_set(x):
        if x is None:
            return set()
        return set(x) if isinstance(x, tuple) else {x}

    def _shardings(mesh, a_sh, b_sh):
        msh = getattr(a_sh, "mesh", None) or mesh
        a_spec = tuple(a_sh.spec) + (None,) * (2 - len(tuple(a_sh.spec)))
        b_spec = tuple(b_sh.spec) + (None,) * (2 - len(tuple(b_sh.spec)))
        mx, nx = a_spec[0], b_spec[1]
        if _axes_set(mx) & _axes_set(nx):
            # e.g. FSDP-style weights sharded over the same axis as the
            # batch: one mesh axis cannot shard two output dims — keep
            # the batch sharding, re-replicate the weights' columns
            nx = None
        args = (NamedSharding(msh, P(mx, None)),
                NamedSharding(msh, P(None, nx)),
                NamedSharding(msh, P(None)),
                NamedSharding(msh, P(nx)))
        return msh, args, NamedSharding(msh, P(mx, nx))

    def partition(mesh, arg_shapes, result_shape):
        a_sh, b_sh = arg_shapes[0].sharding, arg_shapes[1].sharding
        if hasattr(a_sh, "spec") and hasattr(b_sh, "spec"):
            msh, arg_sh, res_sh = _shardings(mesh, a_sh, b_sh)
        else:  # opaque shardings inside a manual region: echo (see flash)
            msh = mesh
            arg_sh = tuple(s.sharding for s in arg_shapes)
            res_sh = result_shape.sharding

        def lower_fn(*args):
            return impl(*args)

        return msh, lower_fn, res_sh, arg_sh

    def infer_sharding_from_operands(mesh, arg_shapes, shape):
        a_sh, b_sh = arg_shapes[0].sharding, arg_shapes[1].sharding
        if not (hasattr(a_sh, "spec") and hasattr(b_sh, "spec")):
            return NamedSharding(mesh, P())
        return _shardings(mesh, a_sh, b_sh)[2]

    compat.def_partition(
        wrapped,
        partition=partition,
        infer_sharding_from_operands=infer_sharding_from_operands,
        sharding_rule="m k, k n, s, n -> m n",
        need_replication_factors=("k", "s"))
    return wrapped


def quant_matmul(a_i8, b_i8, a_scale, b_scale, *, out_dtype=jnp.float32,
                 tile_m: int = None, tile_n: int = None, tile_k: int = None,
                 use_pallas: bool = None, interpret: bool = False):
    """``dequant(a_i8 @ b_i8)``: int32 MXU accumulation, fused epilogue.

    a_i8 (M, K) int8 with scalar ``a_scale``; b_i8 (K, N) int8 with scalar
    or per-channel (N,) ``b_scale``. Returns (M, N) ``out_dtype``.
    Any shapes: when the kernel path runs, operands pad internally to the
    tile grid (exact in integer math) and the result slices back. Tile
    sizes default to the autotuned table (tuning.py) then 128^3.
    """
    m, ka = a_i8.shape
    kb, n = b_i8.shape
    enforce(ka == kb, "inner dims differ: %s vs %s", ka, kb)
    enforce(a_i8.dtype == jnp.int8 and b_i8.dtype == jnp.int8,
            "quant_matmul takes int8 operands, got %s/%s", a_i8.dtype,
            b_i8.dtype)
    # symbolic dims (jax.export batch-polymorphic serving artifacts)
    # can't bucket into the tuned table or feed a pallas grid — those
    # traces take the XLA dot_general path unconditionally (the Pallas
    # kernel is a runtime dispatch choice, not an artifact property)
    static_shape = all(isinstance(d, int) for d in (m, n, ka))
    if not static_shape:
        use_pallas = False
        interpret = False
    tuned = {}
    if static_shape and (tile_m is None or tile_n is None
                         or tile_k is None):
        from .tuning import get_tuned, matmul_key

        tuned = get_tuned(matmul_key(m, n, ka)) or {}
    tile_m = tile_m or tuned.get("tile_m", 128)
    tile_n = tile_n or tuned.get("tile_n", 128)
    tile_k = tile_k or tuned.get("tile_k", 128)
    if use_pallas is None:
        # axon is the tunneled TPU backend — same Mosaic compile path;
        # a recorded use_pallas=False verdict (no tile config compiled
        # on-chip) routes to the exact dot_general fallback instead of
        # re-hitting the same Mosaic failure
        use_pallas = (jax.default_backend() in ("tpu", "axon")
                      and tuned.get("use_pallas", True))
    if (use_pallas or interpret) and min(m, n, ka) > 0:
        # padding/tiling happens per shard inside the partitioned call
        # (callers never manage the tiling contract themselves)
        fn = _partitioned_qm(jnp.dtype(out_dtype).name, int(tile_m),
                             int(tile_n), int(tile_k), bool(interpret))
        return fn(a_i8, b_i8,
                  jnp.asarray(a_scale, jnp.float32).reshape(1),
                  jnp.broadcast_to(jnp.asarray(b_scale, jnp.float32),
                                   (n,)))
    acc = jax.lax.dot_general(a_i8, b_i8, (((1,), (0,)), ((), ())),
                              preferred_element_type=jnp.int32)
    scale = jnp.asarray(a_scale, jnp.float32) * \
        jnp.broadcast_to(jnp.asarray(b_scale, jnp.float32), (n,))
    return (acc.astype(jnp.float32) * scale[None, :]).astype(out_dtype)


def quantize_tensor(x, *, per_channel_axis=None):
    """Symmetric int8 quantization: returns (x_i8, scale). Per-channel
    along ``per_channel_axis`` (weights), per-tensor otherwise
    (activations) — reference quantize_op.cc abs-max convention."""
    if per_channel_axis is None:
        scale = jnp.max(jnp.abs(x)) / 127.0
        scale = jnp.maximum(scale, 1e-10)
        q = jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)
        return q, scale
    axes = tuple(i for i in range(x.ndim) if i != per_channel_axis)
    scale = jnp.max(jnp.abs(x), axis=axes) / 127.0
    scale = jnp.maximum(scale, 1e-10)
    shape = [1] * x.ndim
    shape[per_channel_axis] = -1
    q = jnp.clip(jnp.round(x / scale.reshape(shape)), -127,
                 127).astype(jnp.int8)
    return q, scale
