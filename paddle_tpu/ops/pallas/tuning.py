"""Pallas kernel tuning table — the runtime-benchmark-picked kernel
capability (reference: paddle/fluid/operators/jit/README.md:1 — the jit
KernelPool benchmarks candidate implementations per shape and caches the
winner; cuDNN autotuning plays the same role for convs, reference:
operators/conv_cudnn_op.cu.cc workspace search).

Here the tunables are Pallas grid/block sizes (and the flash-vs-XLA
dispatch choice). ``tools/pallas_tune.py`` sweeps candidates ON THE REAL
CHIP and persists winners to ``tuned_blocks.json`` next to this file,
keyed by (kernel, device_kind, shape bucket); kernels consult the table
at call time and fall back to the static defaults when no entry exists.
Entries tuned on one chip generation never apply to another (device_kind
is in the key).
"""

from __future__ import annotations

import functools
import json
import os
import threading
from typing import Dict, Optional

from ... import telemetry

_TABLE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "tuned_blocks.json")
_lock = threading.Lock()
_cache: Optional[Dict[str, dict]] = None
# keys set with persist=False — session-only overrides that must never
# reach the shared on-disk table
_session_only: set = set()


@functools.lru_cache(maxsize=1)
def _device_kind() -> str:
    # cached for the process: this sits on the eager dispatch path
    import jax

    try:
        d = jax.devices()[0]
    except Exception:
        return "unknown"
    kind = (getattr(d, "device_kind", "") or d.platform or "unknown")
    if d.platform == "cpu":
        return "cpu"
    gen = os.environ.get("PALLAS_AXON_TPU_GEN")
    return (gen or kind).lower().replace(" ", "_")


def _load() -> Dict[str, dict]:
    global _cache
    with _lock:
        if _cache is None:
            try:
                with open(_TABLE_PATH) as f:
                    _cache = json.load(f)
            except (OSError, ValueError):
                _cache = {}
        return _cache


def _pow2_bucket(n: int) -> int:
    """Round up to the next power of two — one table entry serves the
    whole bucket."""
    b = 1
    while b < n:
        b *= 2
    return b


def attention_key(tq: int, tk: int, d: int, causal: bool,
                  kind: Optional[str] = None) -> str:
    return (f"flash_attention|{kind or _device_kind()}|"
            f"tq{_pow2_bucket(tq)}|tk{_pow2_bucket(tk)}|d{d}|"
            f"{'causal' if causal else 'full'}")


def decode_key(capacity: int, d: int, kind: Optional[str] = None,
               pool_dtype: str = "f32") -> str:
    """Flash-decode bucket: capacity x head_dim x POOL DTYPE (t varies
    at runtime inside one compiled loop, heads only change the tiny row
    count). ``pool_dtype`` names the KV storage form — the int8 paged
    variant dequantizes in-kernel (different arithmetic intensity, its
    own winner), so entries are keyed per form. Float keys carry an
    explicit ``|pf32`` suffix; pre-dtype tables (no suffix) are honored
    for f32 lookups through :func:`get_tuned_decode`'s legacy fallback."""
    return (f"flash_decode|{kind or _device_kind()}|"
            f"cap{_pow2_bucket(capacity)}|d{d}|p{pool_dtype}")


def _legacy_decode_key(capacity: int, d: int,
                       kind: Optional[str] = None) -> str:
    """The pre-dtype (PR <15) decode key form — read-only back-compat."""
    return (f"flash_decode|{kind or _device_kind()}|"
            f"cap{_pow2_bucket(capacity)}|d{d}")


# keys already diagnosed as stale (warn ONCE per key per process) and
# the typed findings themselves (tests / CI assert on them)
_stale_dtype_seen: set = set()
_stale_dtype_findings: list = []


def stale_dtype_findings() -> list:
    """Typed PT-TUNE-501 findings emitted so far (cleared by
    :func:`reset_cache`)."""
    with _lock:
        return list(_stale_dtype_findings)


def _note_stale_dtype(key: str, legacy_key: str) -> None:
    """A device-matched decode entry exists under the LEGACY (pre-int8)
    key but the dtype-keyed entry is missing: the table predates the
    dtype-keyed schema for this shape. Silent fallback would quietly run
    static default blocks forever — emit a typed diagnostic instead so
    stale tables are visible (re-running tools/pallas_tune.py --decode
    on the chip clears it)."""
    import warnings

    from ...analysis.diagnostics import Diagnostic

    # check-and-record under _lock: concurrent decode traces (router
    # claim lanes) must not double-emit the warn-ONCE-per-key finding
    with _lock:
        if key in _stale_dtype_seen:
            return
        _stale_dtype_seen.add(key)
        diag = Diagnostic(
            code="PT-TUNE-501", severity="warning",
            message=(f"tuned_blocks.json has a device-matched decode entry "
                     f"at {legacy_key!r} but no dtype-keyed entry {key!r} "
                     f"— stale pre-int8 tuning table for this shape"),
            hint=("re-run tools/pallas_tune.py --decode on this chip to "
                  "record the dtype-keyed entries"),
            path=_TABLE_PATH)
        _stale_dtype_findings.append(diag)
    warnings.warn(str(diag), stacklevel=3)
    if telemetry.enabled():
        telemetry.registry().counter(
            "pt_tuning_stale_dtype_total",
            "decode tuning-table lookups that found only a pre-int8 "
            "legacy entry for a dtype-keyed shape").inc()


def get_tuned_decode(capacity: int, d: int, pool_dtype: str = "f32",
                     kind: Optional[str] = None) -> Optional[dict]:
    """Decode-table lookup under the dtype-keyed schema. f32 lookups
    fall back to the legacy (pre-dtype) key silently — same semantics,
    the on-disk chips' entries stay live AND a served legacy entry
    counts as a cache HIT (the kernel really launches with
    chip-measured blocks — the coverage signal must say so); other
    dtypes finding ONLY a legacy entry emit the typed PT-TUNE-501
    diagnostic and return None (static defaults run, but the staleness
    is visible)."""
    table = _load()
    key = decode_key(capacity, d, kind, pool_dtype)
    legacy_key = _legacy_decode_key(capacity, d, kind)
    entry = table.get(key)
    if entry is None and pool_dtype == "f32":
        entry = table.get(legacy_key)
    _count_lookup(entry is not None)   # ONE lookup, one hit-or-miss
    if entry is not None:
        return entry
    if pool_dtype != "f32" and table.get(legacy_key) is not None:
        _note_stale_dtype(key, legacy_key)
    return None


def matmul_key(m: int, n: int, k: int, kind: Optional[str] = None) -> str:
    return (f"quant_matmul|{kind or _device_kind()}|"
            f"m{_pow2_bucket(m)}|n{_pow2_bucket(n)}|k{_pow2_bucket(k)}")


def _count_lookup(hit: bool) -> None:
    """hit = a kernel launches with chip-measured blocks; miss = it
    runs on static defaults (the tuning-coverage signal)."""
    if telemetry.enabled():
        telemetry.registry().counter(
            "pt_tuning_cache_hits_total" if hit
            else "pt_tuning_cache_misses_total",
            "pallas tuning-table lookups "
            + ("served by" if hit else "absent from")
            + " tuned_blocks.json").inc()


def get_tuned(key: str) -> Optional[dict]:
    entry = _load().get(key)
    _count_lookup(entry is not None)
    return entry


def set_tuned(key: str, entry: dict, persist: bool = True) -> None:
    table = _load()
    with _lock:
        table[key] = entry
        if not persist:
            _session_only.add(key)
        else:
            _session_only.discard(key)
        if persist:
            # On DISK: union of disk and memory; disk wins on conflict
            # (a concurrent tuner's winners survive) except the key just
            # tuned, and memory keys absent from disk are re-persisted so
            # a corrupt/deleted file cannot shrink the write.
            # In MEMORY: our own entries win (persist=False overrides
            # stay deliberate); keys we lack adopt the disk value.
            disk = {}
            try:
                with open(_TABLE_PATH) as f:
                    disk = json.load(f)
            except (OSError, ValueError):
                pass
            merged = {k: v for k, v in table.items()
                      if k not in _session_only}
            merged.update(disk)
            merged[key] = entry
            for k, v in merged.items():
                table.setdefault(k, v)
            tmp = _TABLE_PATH + ".tmp"
            with open(tmp, "w") as f:
                json.dump(merged, f, indent=1, sort_keys=True)
            os.replace(tmp, _TABLE_PATH)


def reset_cache() -> None:
    """Drop the in-process cache (tests / after external table edits)."""
    global _cache
    with _lock:
        _cache = None
        _session_only.clear()
        _stale_dtype_seen.clear()
        del _stale_dtype_findings[:]
