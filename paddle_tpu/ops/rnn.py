"""Recurrent ops — capability parity with the reference RNN op family
(reference: paddle/fluid/operators/{lstm_op.cc, lstmp_op.cc, gru_op.cc,
gru_unit_op.cc, lstm_unit_op.cc, cudnn_lstm_op.cu.cc, row_conv_op.cc,
conv_shift_op.cc, sequence_ops/sequence_conv_op.cc}).

TPU-native design: the reference packs variable-length sequences via LoD and
runs hand-written CPU/CUDA recurrences; here every recurrence is a
``lax.scan`` over a dense padded batch ``(B, T, D)`` with a ``lengths`` mask
(the LoD replacement — see ops/sequence.py). The per-step matmuls are batched
onto the MXU; the input projection ``x @ W_ih`` for ALL timesteps is hoisted
out of the scan as one large matmul so the scan body only carries the
hidden-to-hidden matmul.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..core.enforce import enforce

_ACTS = {
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
    "relu": jax.nn.relu,
    "identity": lambda x: x,
}


def _act(name: str):
    enforce(name in _ACTS, "unknown activation %s", name)
    return _ACTS[name]


def lstm_unit(x_gates, h, c, forget_bias: float = 0.0,
              gate_activation: str = "sigmoid",
              cell_activation: str = "tanh",
              candidate_activation: str = "tanh"):
    """One LSTM step from pre-projected gates (reference:
    operators/lstm_unit_op.cc). ``x_gates``: (B, 4H) = x@W_ih + h@W_hh + b
    in i, f, g(c~), o order. Returns (new_h, new_c)."""
    gact, cact, candact = (_act(gate_activation), _act(cell_activation),
                           _act(candidate_activation))
    i, f, g, o = jnp.split(x_gates, 4, axis=-1)
    i = gact(i)
    f = gact(f + forget_bias)
    g = candact(g)
    new_c = f * c + i * g
    new_h = gact(o) * cact(new_c)
    return new_h, new_c


def gru_unit(x_gates, h, w_hh, gate_activation: str = "sigmoid",
             activation: str = "tanh"):
    """One GRU step (reference: operators/gru_unit_op.cc). ``x_gates``:
    (B, 3H) = x@W_ih + b in r, u(z), c order; ``w_hh``: (H, 3H)."""
    gact, act = _act(gate_activation), _act(activation)
    hsz = h.shape[-1]
    hh = h @ w_hh
    r = gact(x_gates[..., :hsz] + hh[..., :hsz])
    u = gact(x_gates[..., hsz:2 * hsz] + hh[..., hsz:2 * hsz])
    c = act(x_gates[..., 2 * hsz:] + r * hh[..., 2 * hsz:])
    return u * h + (1.0 - u) * c


def _mask_carry(new, old, active):
    """Freeze carried state for finished (padded) rows."""
    return jax.tree_util.tree_map(
        lambda n, o: jnp.where(active.reshape((-1,) + (1,) * (n.ndim - 1)),
                               n, o), new, old)


def lstm(x, w_ih, w_hh, bias=None, h0=None, c0=None, lengths=None,
         forget_bias: float = 0.0, is_reverse: bool = False,
         proj_weight=None, proj_activation: str = "identity",
         gate_activation: str = "sigmoid", cell_activation: str = "tanh",
         candidate_activation: str = "tanh", unroll: int = 1):
    """Full-sequence LSTM (reference: operators/lstm_op.cc; with
    ``proj_weight`` it is lstmp, reference: operators/lstmp_op.cc).

    x: (B, T, D); w_ih: (D, 4H); w_hh: (R, 4H) where R = H without
    projection, or the projection size with one; bias: (4H,);
    proj_weight: (H, R) optional recurrent projection.
    Returns (outputs (B, T, R), (h_T, c_T)).
    """
    b, t, _ = x.shape
    hsz = w_ih.shape[-1] // 4
    rsz = w_hh.shape[0]
    if h0 is None:
        h0 = jnp.zeros((b, rsz), x.dtype)
    if c0 is None:
        c0 = jnp.zeros((b, hsz), x.dtype)
    # hoist the input projection out of the recurrence: one (B*T, D)@(D, 4H)
    gates_x = x @ w_ih
    if bias is not None:
        gates_x = gates_x + bias
    gates_x = jnp.swapaxes(gates_x, 0, 1)  # (T, B, 4H)
    if is_reverse:
        gates_x = jnp.flip(gates_x, axis=0)

    def step(carry, inp):
        h, c, pos = carry
        gx, = inp
        gates = gx + h @ w_hh
        new_h, new_c = lstm_unit(gates, h, c, forget_bias, gate_activation,
                                 cell_activation, candidate_activation)
        if proj_weight is not None:
            new_h = _act(proj_activation)(new_h @ proj_weight)
        if lengths is not None:
            time = t - 1 - pos if is_reverse else pos
            active = time < lengths
            new_h, new_c = _mask_carry((new_h, new_c), (h, c), active)
            out = new_h * active.astype(new_h.dtype)[:, None]
        else:
            out = new_h
        return (new_h, new_c, pos + 1), out

    # unroll > 1 amortizes the per-step scan overhead on TPU (more
    # h @ w_hh matmuls visible per compiled loop body for XLA to
    # software-pipeline); identical math, swept by bench --scan-unroll
    (h_t, c_t, _), outs = lax.scan(step, (h0, c0, 0), (gates_x,),
                                   unroll=unroll)
    if is_reverse:
        outs = jnp.flip(outs, axis=0)
    return jnp.swapaxes(outs, 0, 1), (h_t, c_t)


def gru(x, w_ih, w_hh, bias=None, h0=None, lengths=None,
        is_reverse: bool = False, gate_activation: str = "sigmoid",
        activation: str = "tanh", unroll: int = 1):
    """Full-sequence GRU (reference: operators/gru_op.cc).

    x: (B, T, D); w_ih: (D, 3H); w_hh: (H, 3H); bias: (3H,).
    Returns (outputs (B, T, H), h_T)."""
    b, t, _ = x.shape
    hsz = w_hh.shape[0]
    if h0 is None:
        h0 = jnp.zeros((b, hsz), x.dtype)
    gates_x = x @ w_ih
    if bias is not None:
        gates_x = gates_x + bias
    gates_x = jnp.swapaxes(gates_x, 0, 1)
    if is_reverse:
        gates_x = jnp.flip(gates_x, axis=0)

    def step(carry, inp):
        h, pos = carry
        gx, = inp
        new_h = gru_unit(gx, h, w_hh, gate_activation, activation)
        if lengths is not None:
            time = t - 1 - pos if is_reverse else pos
            active = time < lengths
            new_h = _mask_carry(new_h, h, active)
            out = new_h * active.astype(new_h.dtype)[:, None]
        else:
            out = new_h
        return (new_h, pos + 1), out

    (h_t, _), outs = lax.scan(step, (h0, 0), (gates_x,), unroll=unroll)
    if is_reverse:
        outs = jnp.flip(outs, axis=0)
    return jnp.swapaxes(outs, 0, 1), h_t


def lstmp(x, w_ih, w_hh, proj_weight, bias=None, **kw):
    """Projected LSTM (reference: operators/lstmp_op.cc)."""
    return lstm(x, w_ih, w_hh, bias=bias, proj_weight=proj_weight, **kw)


def row_conv(x, weight, lengths=None):
    """Lookahead row convolution (reference: operators/row_conv_op.cc —
    DeepSpeech2's streaming-friendly context layer).

    x: (B, T, D); weight: (future_context, D). out[b, t] =
    sum_{k<context} w[k] * x[b, t+k] (zero past the sequence end)."""
    context = weight.shape[0]
    b, t, d = x.shape
    if lengths is not None:
        from .sequence import sequence_mask

        x = x * sequence_mask(lengths, t, x.dtype)[:, :, None]
    out = jnp.zeros_like(x)
    for k in range(context):  # context is small + static: unrolled, XLA fuses
        sl = x[:, k:, :] * weight[k][None, None, :]
        out = out.at[:, :t - k, :].add(sl)
    return out


def conv_shift(x, y):
    """Circular convolution (reference: operators/conv_shift_op.cc).
    x: (B, M); y: (B, N) with N odd, N <= M. out[b, i] =
    sum_j y[b, j] * x[b, (i + j - N//2) mod M]."""
    m, n = x.shape[1], y.shape[1]
    enforce(n % 2 == 1, "conv_shift filter width must be odd, got %s", n)
    half = n // 2
    # gather shifted copies; n is small/static so the loop unrolls
    out = jnp.zeros_like(x)
    for j in range(n):
        shift = j - half
        out = out + y[:, j:j + 1] * jnp.roll(x, -shift, axis=1)
    return out


def sequence_conv(x, weight, lengths=None, context_length: int = 3,
                  context_start: Optional[int] = None, bias=None):
    """Sequence convolution over time (reference:
    operators/sequence_ops/sequence_conv_op.cc): concatenate a context window
    of ``context_length`` frames around each timestep (zero outside the
    sequence) and project with ``weight``: (context_length * D, Dout).

    x: (B, T, D) padded; returns (B, T, Dout)."""
    b, t, d = x.shape
    if context_start is None:
        context_start = -(context_length // 2)
    enforce(weight.shape[0] == context_length * d,
            "sequence_conv weight rows %s != context_length*D %s",
            weight.shape[0], context_length * d)
    if lengths is not None:
        from .sequence import sequence_mask

        x = x * sequence_mask(lengths, t, x.dtype)[:, :, None]
    cols = []
    for k in range(context_length):
        offset = context_start + k
        shifted = jnp.roll(x, -offset, axis=1)
        if offset > 0:  # zero the wrapped-in tail
            mask = (jnp.arange(t) < t - offset).astype(x.dtype)
        elif offset < 0:
            mask = (jnp.arange(t) >= -offset).astype(x.dtype)
        else:
            mask = None
        if mask is not None:
            shifted = shifted * mask[None, :, None]
        cols.append(shifted)
    ctx = jnp.concatenate(cols, axis=-1)  # (B, T, context*D)
    out = ctx @ weight
    if bias is not None:
        out = out + bias
    return out


def dynamic_rnn(cell_fn, x, init_state, lengths=None, is_reverse=False):
    """Generic masked recurrence (the DynamicRNN capability, reference:
    python/paddle/fluid/layers/control_flow.py DynamicRNN — LoD-reordered
    execution replaced by a masked scan on the padded batch).

    cell_fn(x_t, state) -> (out_t, new_state); x: (B, T, D).
    Returns (outs (B, T, ...), final_state)."""
    b, t = x.shape[0], x.shape[1]
    xs = jnp.swapaxes(x, 0, 1)
    if is_reverse:
        xs = jnp.flip(xs, axis=0)

    def step(carry, inp):
        state, pos = carry
        xt, = inp
        out, new_state = cell_fn(xt, state)
        if lengths is not None:
            time = t - 1 - pos if is_reverse else pos
            active = time < lengths
            new_state = _mask_carry(new_state, state, active)
            out = out * active.astype(out.dtype).reshape(
                (-1,) + (1,) * (out.ndim - 1))
        return (new_state, pos + 1), out

    (final, _), outs = lax.scan(step, (init_state, 0), (xs,))
    if is_reverse:
        outs = jnp.flip(outs, axis=0)
    return jnp.swapaxes(outs, 0, 1), final
