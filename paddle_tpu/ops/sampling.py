"""Sampling-based classification ops — capability parity with the
reference's large-vocabulary training ops (reference:
paddle/fluid/operators/{nce_op.cc, hierarchical_sigmoid_op.cc,
sampling_id_op.cc, sample_logits_op.cc}; dygraph layers NCE/HSigmoid in
python/paddle/fluid/dygraph/nn.py).

TPU-native notes: all paths are static-shape and gather/matmul based so they
lower onto the MXU; samplers use JAX PRNG keys instead of the reference's
stateful CPU samplers (operators/math/sampler.cc). The log-uniform
("Zipfian") sampler matches the reference's LogUniformSampler distribution
P(k) = log(k+2)/log(k+1) normalized over the range.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ..core.enforce import enforce


def _log_uniform_sample(key, shape, range_max: int):
    """Zipfian sampler: P(k) ∝ log((k+2)/(k+1)) over [0, range_max)."""
    u = jax.random.uniform(key, shape)
    # inverse CDF: k = exp(u * log(range_max + 1)) - 1
    k = jnp.exp(u * jnp.log(float(range_max + 1))) - 1.0
    return jnp.clip(k.astype(jnp.int32), 0, range_max - 1)


def _log_uniform_prob(ids, range_max: int):
    idsf = ids.astype(jnp.float32)
    return (jnp.log((idsf + 2.0) / (idsf + 1.0))
            / jnp.log(float(range_max + 1)))


def _uniform_prob(ids, range_max: int):
    return jnp.full(ids.shape, 1.0 / range_max, jnp.float32)


def _uniform_sample(key, shape, range_max: int):
    return jax.random.randint(key, shape, 0, range_max)


_SAMPLERS = {
    "uniform": (_uniform_sample, _uniform_prob),
    "log_uniform": (_log_uniform_sample, _log_uniform_prob),
}


def _prob_fn(sampler: str):
    enforce(sampler in _SAMPLERS, "unknown sampler %s", sampler)
    return _SAMPLERS[sampler][1]


def sample_classes(key, shape, num_classes: int, sampler: str = "uniform"):
    """Draw negative class ids + their proposal probabilities."""
    enforce(sampler in _SAMPLERS, "unknown sampler %s", sampler)
    draw, prob = _SAMPLERS[sampler]
    ids = draw(key, shape, num_classes)
    return ids, prob(ids, num_classes)


def nce_loss(x, label, weight, bias=None, num_neg_samples: int = 10,
             sampler: str = "uniform", key: Optional[jax.Array] = None,
             custom_neg=None):
    """Noise-contrastive estimation loss (reference: operators/nce_op.cc;
    dygraph/nn.py NCE).

    x: (B, D) input features; label: (B,) true class ids;
    weight: (num_classes, D); bias: (num_classes,).
    Returns per-example cost (B,). The logit for class c is
    ``x·w_c + b_c - log(S * P_noise(c))`` (self-normalized NCE), trained as
    binary classification true-vs-noise, matching the reference's
    sigmoid-cross-entropy formulation.
    """
    num_classes = weight.shape[0]
    b = x.shape[0]
    label = label.reshape(b).astype(jnp.int32)
    if custom_neg is not None:
        neg = jnp.asarray(custom_neg)
        enforce(neg.ndim == 2 and neg.shape[0] == b,
                "custom_neg must be (B, S), got %s", neg.shape)
        neg_p = _prob_fn(sampler)(neg, num_classes)
    else:
        enforce(key is not None, "nce_loss requires a PRNG key")
        neg, neg_p = sample_classes(key, (b, num_neg_samples), num_classes,
                                    sampler)
    s = neg.shape[1]

    def logit(ids):  # ids: (B, K) → (B, K)
        w = weight[ids]                      # (B, K, D)
        out = jnp.einsum("bd,bkd->bk", x, w)
        if bias is not None:
            out = out + bias[ids]
        return out

    pos_prob = _prob_fn(sampler)(label, num_classes)
    pos_logit = logit(label[:, None])[:, 0] - jnp.log(s * pos_prob)
    neg_logit = logit(neg) - jnp.log(s * neg_p)
    # -log sigmoid(pos) - sum log(1 - sigmoid(neg)), numerically stable
    pos_cost = jax.nn.softplus(-pos_logit)
    neg_cost = jnp.sum(jax.nn.softplus(neg_logit), axis=1)
    return pos_cost + neg_cost


def _default_tree_codes(num_classes: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Complete-binary-tree paths for hsigmoid's default mode (reference:
    operators/math/matrix_bit_code.h SimpleCode: node index starts at
    label + num_classes, walk to root; code bit = node & 1).

    Returns (path_table (C, L), path_code (C, L)) with -1 padding,
    L = ceil(log2(num_classes))."""
    import numpy as np

    depth = max(int(np.ceil(np.log2(max(num_classes, 2)))), 1)
    table = -np.ones((num_classes, depth), np.int32)
    code = -np.ones((num_classes, depth), np.int32)
    for c in range(num_classes):
        node = c + num_classes
        i = 0
        while node > 1:
            # non-leaf node ids are 1..num_classes-1; row index = node/2 - 1
            table[c, i] = node // 2 - 1
            code[c, i] = node & 1
            node //= 2
            i += 1
    return jnp.asarray(table), jnp.asarray(code)


def hsigmoid_loss(x, label, weight, bias=None, num_classes: int = None,
                  path_table=None, path_code=None):
    """Hierarchical sigmoid loss (reference:
    operators/hierarchical_sigmoid_op.cc; math/matrix_bit_code.cc).

    x: (B, D); label: (B,); weight: (num_nodes, D) — one row per internal
    tree node; bias: (num_nodes,). Default: complete binary tree over
    ``num_classes``. Custom trees via path_table/path_code (B- or C-indexed
    (C, L) arrays, -1 padded) — the reference's "custom tree" mode.
    Returns per-example cost (B,)."""
    b = x.shape[0]
    label = label.reshape(b).astype(jnp.int32)
    if path_table is None:
        enforce(num_classes is not None,
                "hsigmoid needs num_classes or explicit paths")
        path_table, path_code = _default_tree_codes(num_classes)
    else:
        enforce(path_code is not None,
                "hsigmoid: path_code is required alongside path_table")
    rows = path_table[label]          # (B, L) node ids, -1 padded
    codes = path_code[label]          # (B, L) bits, -1 padded
    valid = rows >= 0
    safe_rows = jnp.maximum(rows, 0)
    w = weight[safe_rows]             # (B, L, D)
    logits = jnp.einsum("bd,bld->bl", x, w)
    if bias is not None:
        logits = logits + bias[safe_rows]
    # label bit 1 → sigmoid(logit), bit 0 → 1 - sigmoid(logit);
    # cost = softplus(logit) - code*logit  (stable BCE-with-logits)
    cost = jax.nn.softplus(logits) - codes.astype(logits.dtype) * logits
    return jnp.sum(jnp.where(valid, cost, 0.0), axis=1)


def sampling_id(probs, key, min: float = 0.0, max: float = 1.0):
    """Sample one class id per row of a probability matrix (reference:
    operators/sampling_id_op.cc — draws u~U(min,max) and walks the CDF).
    probs: (B, C) rows need not be perfectly normalized."""
    cdf = jnp.cumsum(probs, axis=-1)
    total = cdf[:, -1:]
    u = jax.random.uniform(key, (probs.shape[0], 1), minval=min,
                           maxval=max) * total
    ids = jnp.sum((cdf < u).astype(jnp.int32), axis=-1)
    return jnp.minimum(ids, probs.shape[-1] - 1)  # guard max>1 overshoot


def top_k_logits(logits, k: int):
    """Keep the k largest entries per row; push the rest to -inf.
    ``k <= 0`` is a no-op (no filtering). Ties at the k-th value all
    survive (the filter is by value threshold, not by rank)."""
    if k <= 0 or k >= logits.shape[-1]:
        return logits
    kth = jax.lax.top_k(logits, k)[0][..., -1:]
    return jnp.where(logits < kth, -jnp.inf, logits)


def top_p_logits(logits, p: float):
    """Nucleus filter: keep the smallest set of entries whose
    probability mass reaches ``p`` (the top entry always survives);
    push the rest to -inf. ``p >= 1`` is a no-op."""
    if p >= 1.0:
        return logits
    enforce(p > 0.0, "top_p must be in (0, 1], got %s (p <= 0 would "
            "filter every token)", p)
    srt = jnp.sort(logits, axis=-1)[..., ::-1]           # descending
    probs = jax.nn.softmax(srt.astype(jnp.float32), axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # an entry is kept while the mass BEFORE it is still < p, so the
    # set is the minimal prefix with cum >= p and is never empty
    keep = (cum - probs) < p
    thresh = jnp.min(jnp.where(keep, srt, jnp.inf), axis=-1,
                     keepdims=True).astype(logits.dtype)
    return jnp.where(logits < thresh, -jnp.inf, logits)


def filter_logits(logits, temperature: float = 1.0, top_k: int = 0,
                  top_p: float = 1.0):
    """The decoding filter chain without the draw: temperature scaling,
    then top-k, then nucleus (top-p), in float32. softmax of the result
    is the EXACT distribution sample_from_logits draws from — the
    contract speculative decoding's accept/reject test relies on.
    ``temperature`` must be > 0 here (argmax needs no filtering)."""
    enforce(temperature > 0.0, "temperature must be > 0, got %s",
            temperature)
    scaled = logits.astype(jnp.float32) / float(temperature)
    scaled = top_k_logits(scaled, top_k)
    return top_p_logits(scaled, top_p)


def sample_from_logits(logits, key, temperature: float = 1.0,
                       top_k: int = 0, top_p: float = 1.0):
    """Draw one token id per row: temperature scaling, then top-k, then
    nucleus (top-p) filtering, then a categorical draw — the standard LM
    decoding order. ``temperature == 0`` is exact argmax (no key use).
    Green-field next to :func:`sampling_id` (reference:
    operators/sampling_id_op.cc draws from given probs; modern decoder
    sampling needs the filtered-logits form)."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1)
    return jax.random.categorical(
        key, filter_logits(logits, temperature, top_k, top_p), axis=-1)


def sample_logits(logits, label, num_samples: int, key,
                  sampler: str = "log_uniform",
                  remove_accidental_hits: bool = True):
    """Sample negatives and gather their logits, correcting by -log Q
    (reference: operators/sample_logits_op.cc — the building block under
    sampled-softmax training).

    Returns (sampled_logits (B, 1+S), sampled_label (B,) — always 0, the
    true class sits in column 0 — and the sampled ids (B, 1+S))."""
    b, v = logits.shape
    label = label.reshape(b).astype(jnp.int32)
    neg, neg_p = sample_classes(key, (b, num_samples), v, sampler)
    ids = jnp.concatenate([label[:, None], neg], axis=1)
    pos_p = _prob_fn(sampler)(label, v)
    q = jnp.concatenate([pos_p[:, None], neg_p], axis=1)
    picked = jnp.take_along_axis(logits, ids, axis=1) - jnp.log(q)
    if remove_accidental_hits:
        # a sampled negative equal to the true label would fight the loss;
        # push it to -inf like the reference's remove_accidental_hits
        hit = ids == label[:, None]
        hit = hit.at[:, 0].set(False)
        picked = jnp.where(hit, jnp.asarray(-1e20, picked.dtype), picked)
    return picked, jnp.zeros((b,), jnp.int32), ids
