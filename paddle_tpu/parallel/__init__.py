"""Parallelism: collectives, data-parallel trainer, sharding rules.

TP/PP/SP/EP land as mesh-axis sharding rules (SURVEY §7 step 8); the mesh
itself lives in paddle_tpu.core.mesh.
"""

from .api import DataParallel, Trainer
from .plan import (Plan, compile_step, device_bytes, guard_no_resharding,
                   host_init, max_device_bytes)
from .context_parallel import (context_parallel_attention, ring_attention,
                               sharded_flash_attention, ulysses_attention)
from .collective import (allgather, allreduce, all_to_all, axis_index,
                         broadcast, ppermute, reduce_scatter)
from .dgc import (DGCMomentum, dgc_allreduce, quantized_allreduce,
                  top_k_sparsify)
from .geo_sgd import GeoSGDTrainer
from .hybrid import (build_bert_hybrid_step, build_gpt_hybrid_step,
                     build_hybrid_transformer_step)
from .pipeline import (GPipe, bubble_fraction, gpipe_ticks,
                       interleaved_ticks, pipeline_apply,
                       ring_order_layers, stage_param_sharding)
from .sharded_embedding import (ShardedEmbedding, embedding_ep_rules,
                                sharded_embedding_lookup)
from .sharding import (OptStateRules, constraint, infer_param_spec,
                       shard_params, transformer_tp_rules, zero_dp_rules)

__all__ = [
    "DataParallel", "Trainer",
    "Plan", "compile_step", "device_bytes", "guard_no_resharding",
    "host_init", "max_device_bytes",
    "allgather", "allreduce", "all_to_all",
    "axis_index", "broadcast", "context_parallel_attention", "ppermute",
    "reduce_scatter", "ring_attention",
    "sharded_flash_attention", "ulysses_attention",
    "GPipe", "pipeline_apply", "stage_param_sharding",
    "bubble_fraction", "gpipe_ticks", "interleaved_ticks",
    "ring_order_layers",
    "ShardedEmbedding", "embedding_ep_rules", "sharded_embedding_lookup",
    "OptStateRules", "constraint", "infer_param_spec", "shard_params",
    "transformer_tp_rules", "zero_dp_rules",
    "DGCMomentum", "dgc_allreduce", "quantized_allreduce", "top_k_sparsify",
    "build_hybrid_transformer_step", "build_bert_hybrid_step",
    "build_gpt_hybrid_step",
    "GeoSGDTrainer",
]
