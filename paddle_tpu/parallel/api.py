"""High-level parallel training — the ParallelExecutor/CompiledProgram/fleet
capability (reference: framework/parallel_executor.cc:195,
compiler.py:117 with_data_parallel, incubate/fleet/collective) as one object.

``Trainer`` owns (params, buffers, opt_state) placed on a mesh and a jitted
train step. Data parallelism is a *sharding*, not a program rewrite: params
replicated, batch split over "dp"; XLA inserts gradient all-reduces (the whole
multi_devices_graph_pass, reference: multi_devices_graph_pass.cc:450, becomes
compiler work). Buffers donate so updates are in-place in HBM.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import telemetry
from ..core import random as prandom
from ..core.config import BuildStrategy
from ..core.enforce import enforce
from ..core.mesh import get_mesh
from ..nn.layer import Layer
from ..optimizer.optimizers import Optimizer


@telemetry.cached_instruments
def _trainer_metrics(reg):
    """Trainer instrument set (only reached when telemetry is on)."""
    return {
        "dispatch": reg.histogram(
            "pt_trainer_dispatch_seconds",
            "train_step dispatch wall time (unfenced)", unit="s"),
    }


class Trainer:
    """Functional training driver.

    loss_builder(params, buffers, rng, batch) ->
        (loss, (metrics_dict, new_buffers))
    """

    def __init__(self, model: Layer, optimizer: Optimizer,
                 loss_builder: Callable, mesh=None,
                 build_strategy: Optional[BuildStrategy] = None,
                 param_spec: Optional[Dict[str, P]] = None,
                 opt_state_rules=None, amp: Optional[str] = None,
                 grad_accum_steps: int = 1):
        self.model = model
        self.optimizer = optimizer
        self.loss_builder = loss_builder
        self.mesh = mesh or get_mesh()
        self.strategy = build_strategy or BuildStrategy()
        # amp: policy name ("mixed_bf16" / "mixed_fp16" / ...) applied at
        # trace time around the loss (reference: contrib/mixed_precision
        # decorator capability; bf16 needs no loss scaling — pair
        # "mixed_fp16" with amp.decorate()'d optimizer for scaling)
        self.amp_policy = amp
        # gradient merge (reference: fleet DistributedStrategy
        # gradient_merge / gradient accumulation): average grads over K
        # micro-steps, apply the optimizer on the K-th
        enforce(grad_accum_steps >= 1, "grad_accum_steps must be >= 1")
        self.grad_accum_steps = grad_accum_steps

        rep = NamedSharding(self.mesh, P())

        def place(tree, spec_map=None):
            def put(path_leaf):
                return jax.device_put(path_leaf, rep)

            return jax.tree_util.tree_map(put, tree)

        self.params = place(model.named_parameters())
        if param_spec:
            for name, spec in param_spec.items():
                self.params[name] = jax.device_put(
                    self.params[name], NamedSharding(self.mesh, spec))
        self.buffers = place(model.named_buffers())
        # opt state inherits each param's sharding (init uses zeros_like on
        # the already-placed params) — re-placing replicated would defeat
        # param_spec's memory sharding for the moments
        self.opt_state = optimizer.init(self.params)
        if opt_state_rules is not None:
            # ZeRO-style: shard large moment leaves over dp (the PS-sharded
            # optimizer-state capability, reference:
            # transpiler/distribute_transpiler.py:702)
            self.opt_state = opt_state_rules.place(self.opt_state, self.mesh)
        self._rng = prandom.next_key()
        if self.grad_accum_steps > 1:
            self._accum = jax.tree_util.tree_map(jnp.zeros_like, self.params)
            self._accum_count = jnp.zeros((), jnp.int32)
            donate = (0, 1, 2, 3, 4) if self.strategy.donate_inputs else ()
            self._jit_step = jax.jit(self._accum_step, donate_argnums=donate)
        else:
            donate = (0, 1, 2) if self.strategy.donate_inputs else ()
            self._jit_step = jax.jit(self._step, donate_argnums=donate)
        self._jit_eval = jax.jit(self._eval_step)
        self._multi_cache = {}

    # --- pure step functions ------------------------------------------------

    def _step(self, params, buffers, opt_state, rng, batch):
        from ..amp import MixedPrecisionOptimizer
        from ..core.dtypes import policy_scope

        import contextlib

        scope = (policy_scope(self.amp_policy) if self.amp_policy
                 else contextlib.nullcontext())
        scaled = isinstance(self.optimizer, MixedPrecisionOptimizer)

        def lf(p):
            with scope:
                loss, (metrics, new_buffers) = self.loss_builder(
                    p, buffers, rng, batch)
            out_loss = (self.optimizer.scale_loss(loss, opt_state)
                        if scaled else loss)
            return out_loss, (loss, metrics, new_buffers)

        (_, (loss, metrics, new_buffers)), grads = jax.value_and_grad(
            lf, has_aux=True)(params)
        new_params, new_opt_state = self.optimizer.apply(params, grads,
                                                         opt_state)
        return loss, metrics, new_params, new_buffers, new_opt_state

    def _accum_step(self, params, buffers, opt_state, accum, count, rng,
                    batch):
        """Gradient-merge micro-step: accumulate; apply on the K-th."""
        import contextlib

        from ..amp import MixedPrecisionOptimizer
        from ..core.dtypes import policy_scope

        scope = (policy_scope(self.amp_policy) if self.amp_policy
                 else contextlib.nullcontext())
        scaled = isinstance(self.optimizer, MixedPrecisionOptimizer)

        def lf(p):
            with scope:
                loss, (metrics, new_buffers) = self.loss_builder(
                    p, buffers, rng, batch)
            out_loss = (self.optimizer.scale_loss(loss, opt_state)
                        if scaled else loss)
            return out_loss, (loss, metrics, new_buffers)

        (_, (loss, metrics, new_buffers)), grads = jax.value_and_grad(
            lf, has_aux=True)(params)
        k = self.grad_accum_steps
        accum = jax.tree_util.tree_map(lambda a, g: a + g, accum, grads)
        count = count + 1
        do_apply = count >= k
        mean_grads = jax.tree_util.tree_map(lambda a: a / k, accum)
        cand_params, cand_opt = self.optimizer.apply(params, mean_grads,
                                                     opt_state)
        sel = lambda new, old: jax.tree_util.tree_map(
            lambda n, o: jnp.where(do_apply, n, o), new, old)
        new_params = sel(cand_params, params)
        new_opt = sel(cand_opt, opt_state)
        accum = jax.tree_util.tree_map(
            lambda a: jnp.where(do_apply, jnp.zeros_like(a), a), accum)
        count = jnp.where(do_apply, 0, count)
        return (loss, metrics, new_params, new_buffers, new_opt, accum,
                count)

    def _eval_step(self, params, buffers, batch):
        import contextlib

        from ..core.dtypes import policy_scope

        scope = (policy_scope(self.amp_policy) if self.amp_policy
                 else contextlib.nullcontext())
        with scope:
            loss, (metrics, _) = self.loss_builder(params, buffers, None,
                                                   batch)
        return loss, metrics

    # --- driver API ---------------------------------------------------------

    def train_step(self, batch) -> Tuple[Any, Dict[str, Any]]:
        from ..core.profiler import RecordEvent

        # op-level span parity (reference: RecordEvent pushed around every
        # op run, platform/profiler.h:81) — here one span per compiled
        # step, doubling as the dispatch-time histogram when telemetry
        # is on (async dispatch: the fenced step time is train_loop's)
        hist = (_trainer_metrics()["dispatch"]
                if telemetry.enabled() else None)
        with RecordEvent("train_step", histogram=hist):
            self._rng, sub = jax.random.split(self._rng)
            if self.grad_accum_steps > 1:
                (loss, metrics, self.params, self.buffers, self.opt_state,
                 self._accum, self._accum_count) = self._jit_step(
                    self.params, self.buffers, self.opt_state, self._accum,
                    self._accum_count, sub, batch)
            else:
                loss, metrics, self.params, self.buffers, self.opt_state = \
                    self._jit_step(self.params, self.buffers, self.opt_state,
                                   sub, batch)
        return loss, metrics

    def train_steps(self, batch, n: int):
        """Run ``n`` fused update steps in ONE device dispatch via
        lax.scan — the reference's num_iteration_per_drop_scope /
        scope-buffered multi-iteration execution (ExecutionStrategy,
        details/scope_buffered_ssa_graph_executor.h:37) in compiled form.
        Cuts host→device round trips by n (the dominant cost through a
        remote-device tunnel). The batch is reused for each inner step;
        feed-per-step loops should call train_step instead. Returns the
        last step's (loss, metrics)."""
        from ..core.profiler import RecordEvent

        fn = self.steps_jit(n)
        with RecordEvent(f"train_steps[{n}]"):
            self._rng, sub = jax.random.split(self._rng)
            loss, metrics, self.params, self.buffers, self.opt_state = fn(
                self.params, self.buffers, self.opt_state, sub, batch)
        return loss, metrics

    def steps_jit(self, n: int):
        """The jitted ``n``-fused-step callable train_steps dispatches
        (built lazily, cached, NOT yet called — so callers may
        ``.lower()`` it for cost analysis before any donation happens).
        Signature: ``fn(params, buffers, opt_state, rng, batch)``."""
        enforce(self.grad_accum_steps == 1,
                "train_steps composes with plain steps only (use "
                "train_step for gradient merge)")
        enforce(n >= 1, "train_steps needs n >= 1, got %s", n)
        key = ("train_steps", int(n))
        fn = self._multi_cache.get(key)
        if fn is None:
            def many(params, buffers, opt_state, rng, batch):
                def body(carry, sub):
                    params, buffers, opt_state = carry
                    loss, metrics, params, buffers, opt_state = self._step(
                        params, buffers, opt_state, sub, batch)
                    return (params, buffers, opt_state), (loss, metrics)

                subs = jax.random.split(rng, n)
                (params, buffers, opt_state), (losses, metrics) = lax.scan(
                    body, (params, buffers, opt_state), subs)
                last = jax.tree_util.tree_map(lambda x: x[-1], metrics)
                return losses[-1], last, params, buffers, opt_state

            donate = (0, 1, 2) if self.strategy.donate_inputs else ()
            fn = jax.jit(many, donate_argnums=donate)
            self._multi_cache[key] = fn
        return fn

    def eval_step(self, batch):
        return self._jit_eval(self.params, self.buffers, batch)

    def sync_model(self) -> Layer:
        """Write current params/buffers back into the Layer (for save/export)."""
        self.model.set_parameters(jax.device_get(self.params))
        self.model.set_buffers(jax.device_get(self.buffers))
        return self.model

    def data_sharding(self) -> NamedSharding:
        """Sharding for input batches: leading dim over dp (feed via
        DataFeeder(sharding=...) — the feed_and_split analog)."""
        return NamedSharding(self.mesh, P("dp"))

    # --- checkpoint/resume (SURVEY §5.4) ------------------------------------

    def state(self) -> Dict[str, Any]:
        """Full resumable training state (params + buffers + optimizer
        moments + RNG) — what the reference persists via save_persistables
        (params + optimizer accumulators, reference: io.py:460)."""
        st = {"params": self.params, "buffers": self.buffers,
              "opt_state": self.opt_state,
              "rng": jax.random.key_data(self._rng)}
        if self.grad_accum_steps > 1:
            st["grad_accum"] = {"accum": self._accum,
                                "count": self._accum_count}
        return st

    def save_checkpoint(self, manager_or_dir, step: Optional[int] = None):
        from ..checkpoint import CheckpointManager, save_state

        if isinstance(manager_or_dir, CheckpointManager):
            enforce(step is not None,
                    "save_checkpoint(manager) needs a step number")
            manager_or_dir.save(step, self.state())
        else:
            save_state(manager_or_dir, self.state())

    def restore_checkpoint(self, manager_or_dir,
                           step: Optional[int] = None) -> None:
        """Restore in place, resharding saved leaves onto this trainer's
        mesh (works across mesh shapes — the survey's upgrade over the
        reference's shape-must-match load)."""
        from ..checkpoint import CheckpointManager, restore_state

        if isinstance(manager_or_dir, CheckpointManager):
            st = manager_or_dir.restore(step, mesh=self.mesh,
                                        target=self.state())
        else:
            st = restore_state(manager_or_dir, mesh=self.mesh,
                               target=self.state())
        self.params = st["params"]
        self.buffers = st["buffers"]
        self.opt_state = st["opt_state"]
        if self.grad_accum_steps > 1 and "grad_accum" in st:
            self._accum = st["grad_accum"]["accum"]
            self._accum_count = st["grad_accum"]["count"]
        self._rng = jax.random.wrap_key_data(jnp.asarray(st["rng"]))

    @classmethod
    def supervised(cls, model: Layer, optimizer: Optimizer,
                   loss_fn: Callable, metrics_fn: Optional[Callable] = None,
                   mesh=None, aux_loss_weight: float = 0.0,
                   router_z_loss_weight: float = 0.0,
                   **kw) -> "Trainer":
        """Convenience for (x, label) batches: batch = dict(x=..., label=...)
        or tuple (x, label).

        ``aux_loss_weight``/``router_z_loss_weight`` add those multiples
        of every buffer named ``*aux_loss`` / ``*router_z_loss`` to the
        TRAINING objective (eval_step reports the pure task loss) — the
        MoE load-balance/stability terms ride the buffer mechanism
        (nn.moe.SwitchFFN); the Switch-paper weights are 0.01 and the
        ST-MoE z weight 1e-3."""

        def loss_builder(params, buffers, rng, batch):
            if isinstance(batch, dict):
                x, label = batch["x"], batch["label"]
            else:
                x, label = batch
            training = rng is not None
            out, new_buffers = model.functional_call(
                params, x, buffers=buffers, rng=rng, training=training)
            loss = loss_fn(out, label)
            metrics = metrics_fn(out, label) if metrics_fn else {}
            if training and (aux_loss_weight or router_z_loss_weight):
                # regularizers join only the OPTIMIZED loss; eval stays
                # comparable to task-only baselines
                if aux_loss_weight:
                    loss = loss + aux_loss_weight * sum(
                        v for k, v in new_buffers.items()
                        if k.endswith("aux_loss"))
                if router_z_loss_weight:
                    loss = loss + router_z_loss_weight * sum(
                        v for k, v in new_buffers.items()
                        if k.endswith("router_z_loss"))
            return loss, (metrics, new_buffers)

        return cls(model, optimizer, loss_builder, mesh=mesh, **kw)


class DataParallel:
    """Dygraph-style wrapper (reference: dygraph/parallel.py:79 DataParallel)
    — here just a Trainer factory over an all-device dp mesh."""

    def __new__(cls, model: Layer, optimizer: Optimizer, loss_fn: Callable,
                metrics_fn=None, devices=None):
        from ..core.mesh import auto_mesh

        mesh = auto_mesh(devices)
        return Trainer.supervised(model, optimizer, loss_fn, metrics_fn,
                                  mesh=mesh)
