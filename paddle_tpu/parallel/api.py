"""High-level parallel training — the ParallelExecutor/CompiledProgram/fleet
capability (reference: framework/parallel_executor.cc:195,
compiler.py:117 with_data_parallel, incubate/fleet/collective) as one object.

``Trainer`` owns (params, buffers, opt_state) placed on a mesh and a jitted
train step. Data parallelism is a *sharding*, not a program rewrite: params
replicated, batch split over "dp"; XLA inserts gradient all-reduces (the whole
multi_devices_graph_pass, reference: multi_devices_graph_pass.cc:450, becomes
compiler work). Buffers donate so updates are in-place in HBM.

With a :class:`..plan.Plan` the trainer goes multi-chip: state is placed
**sharded by construction** (params staged host->shard, opt moments born
sharded from ``zeros_like`` on placed params — no device ever holds the
replicated bytes), and every step variant (plain / gradient-merge /
scan-fused / eval) compiles through one :func:`..plan.compile_step`
path — ``pjit`` with full in/out shardings + donation for explicit
(fsdp/tp) plans, a ``shard_map``-wrapped ``jax.jit`` for pure DP.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import telemetry
from ..core import random as prandom
from ..core.config import BuildStrategy
from ..core.enforce import enforce
from ..core.mesh import get_mesh
from ..nn.layer import Layer
from ..optimizer.optimizers import Optimizer
from .plan import Plan, compile_step, pmean_axes


@telemetry.cached_instruments
def _trainer_metrics(reg):
    """Trainer instrument set (only reached when telemetry is on)."""
    return {
        "dispatch": reg.histogram(
            "pt_trainer_dispatch_seconds",
            "train_step dispatch wall time (unfenced)", unit="s"),
    }


class Trainer:
    """Functional training driver.

    loss_builder(params, buffers, rng, batch) ->
        (loss, (metrics_dict, new_buffers))
    """

    def __init__(self, model: Layer, optimizer: Optimizer,
                 loss_builder: Callable, mesh=None,
                 build_strategy: Optional[BuildStrategy] = None,
                 param_spec: Optional[Dict[str, P]] = None,
                 opt_state_rules=None, amp: Optional[str] = None,
                 grad_accum_steps: int = 1, plan: Optional[Plan] = None,
                 grad_compression: Optional[str] = None):
        from ..quant.collectives import check_mode

        self.model = model
        self.optimizer = optimizer
        self.loss_builder = loss_builder
        self.plan = plan
        # compressed gradient allreduce (amp-style opt-in; "int8" |
        # "int8_sr"): trainer knob beats the plan's default. Applied at
        # the ONE reduce boundary every step variant shares (_step /
        # _accum_step / the scan-fused body), so plain, accum, and
        # fused steps all compile it in via the same compile_step path.
        self.grad_compression = check_mode(
            grad_compression if grad_compression is not None
            else (plan.grad_compression if plan is not None else None))
        if plan is not None:
            enforce(param_spec is None and opt_state_rules is None,
                    "plan subsumes param_spec/opt_state_rules — express "
                    "the specs as Plan rules instead")
            enforce(mesh is None or mesh is plan.mesh,
                    "pass either mesh or plan, not both (the plan owns "
                    "its mesh)")
            self.mesh = plan.mesh
        else:
            self.mesh = mesh or get_mesh()
        self.strategy = build_strategy or BuildStrategy()
        # amp: policy name ("mixed_bf16" / "mixed_fp16" / ...) applied at
        # trace time around the loss (reference: contrib/mixed_precision
        # decorator capability; bf16 needs no loss scaling — pair
        # "mixed_fp16" with amp.decorate()'d optimizer for scaling)
        self.amp_policy = amp
        # gradient merge (reference: fleet DistributedStrategy
        # gradient_merge / gradient accumulation): average grads over K
        # micro-steps, apply the optimizer on the K-th
        enforce(grad_accum_steps >= 1, "grad_accum_steps must be >= 1")
        self.grad_accum_steps = grad_accum_steps
        # axes the shard_map fallback reduces grads/loss over (empty for
        # plan-less and explicit-pjit compilation, where GSPMD inserts
        # the collectives)
        self._pmean_axes = pmean_axes(plan)
        if self.grad_compression is not None:
            enforce(plan is not None and plan.num_devices > 1,
                    "grad_compression compresses the gradient "
                    "allreduce — it needs a multi-device plan")

        rep = NamedSharding(self.mesh, P())

        if plan is not None:
            # sharded by construction: each param stages host->shard per
            # the plan (never materialized replicated on any device);
            # the model re-points at the placed arrays so the eager
            # init-time copies on the default device are released
            self.params = plan.place(model.named_parameters())
            model.set_parameters(self.params)
            self.buffers = plan.place(model.named_buffers())
            model.set_buffers(self.buffers)
        else:
            # same transfer discipline as Plan.place: record the put's
            # provenance (a cpu client may zero-copy a numpy-backed
            # leaf) and launder into runtime-owned buffers — these
            # leaves are about to be donated every step
            from ..analysis.donation import note_transfer
            from ..utils.memory import owned_on_device

            def place(tree):
                return jax.tree_util.tree_map(
                    lambda leaf: owned_on_device(note_transfer(
                        leaf, jax.device_put(leaf, rep))), tree)

            self.params = place(model.named_parameters())
            if param_spec:
                for name, spec in param_spec.items():
                    self.params[name] = jax.device_put(
                        self.params[name], NamedSharding(self.mesh, spec))
            self.buffers = place(model.named_buffers())
        # opt state inherits each param's sharding (init uses zeros_like on
        # the already-placed params) — re-placing replicated would defeat
        # the plan's/param_spec's memory sharding for the moments
        self.opt_state = optimizer.init(self.params)
        if plan is not None:
            # only non-mesh leaves (step counters, loss-scale scalars)
            # re-place; moments born sharded stay sharded (ZeRO-style)
            self.opt_state = plan.place_replicated(self.opt_state)
        elif opt_state_rules is not None:
            # ZeRO-style: shard large moment leaves over dp (the PS-sharded
            # optimizer-state capability, reference:
            # transpiler/distribute_transpiler.py:702)
            self.opt_state = opt_state_rules.place(self.opt_state, self.mesh)
        # static per-step collective payload for the host-side byte
        # counters (grads tree mirrors params; shapes never change
        # after init, so compute once and bump per dispatched step)
        self._comm_bytes = (0, 0)
        if self._pmean_axes:
            from ..quant.collectives import tree_payload_bytes

            ax_size = 1
            for a in self._pmean_axes:
                ax_size *= int(self.plan.mesh.shape[a])
            self._comm_bytes = tree_payload_bytes(
                self.params, ax_size, compression=self.grad_compression)
        self._rng = prandom.next_key()
        if plan is not None and plan.num_devices > 1:
            self._rng = jax.device_put(self._rng, rep)
        if self.grad_accum_steps > 1:
            self._accum = jax.tree_util.tree_map(jnp.zeros_like, self.params)
            self._accum_count = jnp.zeros((), jnp.int32)
            if plan is not None:
                self._accum_count = jax.device_put(self._accum_count, rep)
            donate = (0, 1, 2, 3, 4) if self.strategy.donate_inputs else ()
            self._jit_step = compile_step(
                plan, self._accum_step, donate_argnums=donate,
                **self._step_shardings(accum=True))
        else:
            donate = (0, 1, 2) if self.strategy.donate_inputs else ()
            self._jit_step = compile_step(
                plan, self._step, donate_argnums=donate,
                **self._step_shardings())
        self._jit_eval = compile_step(plan, self._eval_step,
                                      **self._eval_shardings())
        self._multi_cache = {}
        self._check_donation_safety(donate)

    def _check_donation_safety(self, donate) -> None:
        """Compile-time donation-provenance check (analysis/donation):
        every leaf the jitted step will donate must be runtime-owned —
        a host-backed one (the PR 6 restore-SIGSEGV class: cpu client
        zero-copying numpy temporaries) corrupts the heap only
        *sometimes*, so it is flagged HERE, before the first dispatch.
        Once per Trainer construction, skippable via
        FLAGS_static_verify=0 — zero steady-state cost."""
        from ..core.config import FLAGS

        if not donate or not FLAGS.get("static_verify"):
            return
        from ..analysis.diagnostics import format_diagnostics
        from ..analysis.donation import check_donation

        if self.grad_accum_steps > 1:
            args = (self.params, self.buffers, self.opt_state,
                    self._accum, self._accum_count, self._rng)
        else:
            args = (self.params, self.buffers, self.opt_state,
                    self._rng)
        diags = [d for d in check_donation(args, donate)
                 if d.severity == "error"]
        enforce(not diags, "train state failed the donation-safety "
                "check (FLAGS_static_verify=0 skips):\n%s",
                format_diagnostics(diags))

    # --- plan sharding derivation -------------------------------------------

    @staticmethod
    def _sharding_tree(tree):
        """Mirror a placed state tree into its shardings (every leaf is
        a mesh-placed jax.Array after init, so this IS the truth the
        pjit in/out shardings must match for a zero-copy steady state)."""
        return jax.tree_util.tree_map(lambda x: x.sharding, tree)

    def _step_shardings(self, accum: bool = False) -> Dict[str, Any]:
        """``compile_step`` kwargs for the train-step signatures. Only
        explicit plans need them (pjit); plan-less and pure-DP
        compilation derives everything from placement/shard_map."""
        if self.plan is None or not self.plan.explicit:
            return {}
        rep = NamedSharding(self.mesh, P())
        p_sh = self._sharding_tree(self.params)
        b_sh = self._sharding_tree(self.buffers)
        o_sh = self._sharding_tree(self.opt_state)
        batch_sh = self.plan.batch_sharding()
        if accum:
            # (params, buffers, opt_state, accum, count, rng, batch)
            return {
                "in_shardings": (p_sh, b_sh, o_sh, p_sh, rep, rep,
                                 batch_sh),
                "out_shardings": (rep, rep, p_sh, b_sh, o_sh, p_sh, rep),
            }
        # (params, buffers, opt_state, rng, batch) ->
        # (loss, metrics, params, buffers, opt_state)
        return {
            "in_shardings": (p_sh, b_sh, o_sh, rep, batch_sh),
            "out_shardings": (rep, rep, p_sh, b_sh, o_sh),
        }

    def _eval_shardings(self) -> Dict[str, Any]:
        if self.plan is None or not self.plan.explicit:
            return {}
        rep = NamedSharding(self.mesh, P())
        return {
            "in_shardings": (self._sharding_tree(self.params),
                             self._sharding_tree(self.buffers),
                             self.plan.batch_sharding()),
            "out_shardings": (rep, rep),
        }

    # --- pure step functions ------------------------------------------------

    def _shard_rng(self, rng):
        """Per-shard RNG under the shard_map fallback: fold the batch
        axes' indices into the key so dropout draws differ per shard
        (the replicated key would repeat masks across the dp axis)."""
        for ax in self._pmean_axes:
            rng = jax.random.fold_in(rng, lax.axis_index(ax))
        return rng

    def _pmean(self, tree):
        """Reduce per-shard values over the batch axes under the
        shard_map fallback (no-op when GSPMD owns the collectives)."""
        if not self._pmean_axes:
            return tree
        return lax.pmean(tree, self._pmean_axes)

    def _reduce_grads(self, grads, rng):
        """THE gradient reduce boundary — every step variant (plain /
        accum / scan-fused) funnels its grads through here, so the
        grad_compression opt-in lands in all of them from the one
        compile path. Shard_map fallback: int8 ring pmean
        (quant.collectives.quantized_pmean_tree) when compressed, plain
        pmean otherwise. Explicit (pjit/GSPMD) plans: the int8
        wire-format round-trip at the reduce boundary. No plan / no
        compression: identity (zero-cost contract — no quant code in
        the trace)."""
        comp = self.grad_compression
        sr_key = (jax.random.fold_in(rng, 0x51C8)
                  if comp == "int8_sr" else None)
        if self._pmean_axes:
            if comp is None or len(self._pmean_axes) != 1:
                # no single ring over a multi-axis reduce; the plan
                # vocabulary can't produce one today (pure DP is
                # exactly ("dp",)) but fail soft, not wrong
                return lax.pmean(grads, self._pmean_axes)
            from ..quant.collectives import quantized_pmean_tree

            ax = self._pmean_axes[0]
            return quantized_pmean_tree(
                grads, ax, int(self.plan.mesh.shape[ax]), key=sr_key)
        if comp is not None:
            from ..quant.collectives import compress_grads

            return compress_grads(grads, key=sr_key)
        return grads

    def _step(self, params, buffers, opt_state, rng, batch):
        from ..amp import MixedPrecisionOptimizer
        from ..core.dtypes import policy_scope

        import contextlib

        scope = (policy_scope(self.amp_policy) if self.amp_policy
                 else contextlib.nullcontext())
        scaled = isinstance(self.optimizer, MixedPrecisionOptimizer)
        rng = self._shard_rng(rng)

        def lf(p):
            with scope:
                loss, (metrics, new_buffers) = self.loss_builder(
                    p, buffers, rng, batch)
            out_loss = (self.optimizer.scale_loss(loss, opt_state)
                        if scaled else loss)
            return out_loss, (loss, metrics, new_buffers)

        (_, (loss, metrics, new_buffers)), grads = jax.value_and_grad(
            lf, has_aux=True)(params)
        # shard_map fallback: the gradient all-reduce is OURS to write
        # (mean over batch shards == grad of the global-mean loss);
        # loss/metrics/buffer updates reduce the same way so every
        # shard applies an identical update and outputs stay replicated.
        # Grads go through the dedicated reduce boundary (int8 ring
        # when grad_compression is on).
        loss, metrics, new_buffers = self._pmean(
            (loss, metrics, new_buffers))
        grads = self._reduce_grads(grads, rng)
        new_params, new_opt_state = self.optimizer.apply(params, grads,
                                                         opt_state)
        return loss, metrics, new_params, new_buffers, new_opt_state

    def _accum_step(self, params, buffers, opt_state, accum, count, rng,
                    batch):
        """Gradient-merge micro-step: accumulate; apply on the K-th."""
        import contextlib

        from ..amp import MixedPrecisionOptimizer
        from ..core.dtypes import policy_scope

        scope = (policy_scope(self.amp_policy) if self.amp_policy
                 else contextlib.nullcontext())
        scaled = isinstance(self.optimizer, MixedPrecisionOptimizer)
        rng = self._shard_rng(rng)

        def lf(p):
            with scope:
                loss, (metrics, new_buffers) = self.loss_builder(
                    p, buffers, rng, batch)
            out_loss = (self.optimizer.scale_loss(loss, opt_state)
                        if scaled else loss)
            return out_loss, (loss, metrics, new_buffers)

        (_, (loss, metrics, new_buffers)), grads = jax.value_and_grad(
            lf, has_aux=True)(params)
        loss, metrics, new_buffers = self._pmean(
            (loss, metrics, new_buffers))
        grads = self._reduce_grads(grads, rng)
        k = self.grad_accum_steps
        accum = jax.tree_util.tree_map(lambda a, g: a + g, accum, grads)
        count = count + 1
        do_apply = count >= k
        mean_grads = jax.tree_util.tree_map(lambda a: a / k, accum)
        cand_params, cand_opt = self.optimizer.apply(params, mean_grads,
                                                     opt_state)
        sel = lambda new, old: jax.tree_util.tree_map(
            lambda n, o: jnp.where(do_apply, n, o), new, old)
        new_params = sel(cand_params, params)
        new_opt = sel(cand_opt, opt_state)
        accum = jax.tree_util.tree_map(
            lambda a: jnp.where(do_apply, jnp.zeros_like(a), a), accum)
        count = jnp.where(do_apply, 0, count)
        return (loss, metrics, new_params, new_buffers, new_opt, accum,
                count)

    def _eval_step(self, params, buffers, batch):
        import contextlib

        from ..core.dtypes import policy_scope

        scope = (policy_scope(self.amp_policy) if self.amp_policy
                 else contextlib.nullcontext())
        with scope:
            loss, (metrics, _) = self.loss_builder(params, buffers, None,
                                                   batch)
        return self._pmean((loss, metrics))

    # --- driver API ---------------------------------------------------------

    def train_step(self, batch) -> Tuple[Any, Dict[str, Any]]:
        from ..core.profiler import RecordEvent

        # op-level span parity (reference: RecordEvent pushed around every
        # op run, platform/profiler.h:81) — here one span per compiled
        # step, doubling as the dispatch-time histogram when telemetry
        # is on (async dispatch: the fenced step time is train_loop's)
        hist = (_trainer_metrics()["dispatch"]
                if telemetry.enabled() else None)
        with RecordEvent("train_step", histogram=hist):
            self._rng, sub = jax.random.split(self._rng)
            if self.grad_accum_steps > 1:
                (loss, metrics, self.params, self.buffers, self.opt_state,
                 self._accum, self._accum_count) = self._jit_step(
                    self.params, self.buffers, self.opt_state, self._accum,
                    self._accum_count, sub, batch)
            else:
                loss, metrics, self.params, self.buffers, self.opt_state = \
                    self._jit_step(self.params, self.buffers, self.opt_state,
                                   sub, batch)
        if telemetry.enabled() and self._pmean_axes:
            from ..quant.collectives import record_payload_bytes

            record_payload_bytes(*self._comm_bytes)
        return loss, metrics

    def train_steps(self, batch, n: int):
        """Run ``n`` fused update steps in ONE device dispatch via
        lax.scan — the reference's num_iteration_per_drop_scope /
        scope-buffered multi-iteration execution (ExecutionStrategy,
        details/scope_buffered_ssa_graph_executor.h:37) in compiled form.
        Cuts host→device round trips by n (the dominant cost through a
        remote-device tunnel). The batch is reused for each inner step;
        feed-per-step loops should call train_step instead. Returns the
        last step's (loss, metrics)."""
        from ..core.profiler import RecordEvent

        fn = self.steps_jit(n)
        with RecordEvent(f"train_steps[{n}]"):
            self._rng, sub = jax.random.split(self._rng)
            loss, metrics, self.params, self.buffers, self.opt_state = fn(
                self.params, self.buffers, self.opt_state, sub, batch)
        if telemetry.enabled() and self._pmean_axes:
            from ..quant.collectives import record_payload_bytes

            # the fused dispatch runs n reduces (one per inner step)
            record_payload_bytes(self._comm_bytes[0] * n,
                                 self._comm_bytes[1] * n)
        return loss, metrics

    def steps_jit(self, n: int):
        """The jitted ``n``-fused-step callable train_steps dispatches
        (built lazily, cached, NOT yet called — so callers may
        ``.lower()`` it for cost analysis before any donation happens).
        Signature: ``fn(params, buffers, opt_state, rng, batch)``."""
        enforce(self.grad_accum_steps == 1,
                "train_steps composes with plain steps only (use "
                "train_step for gradient merge)")
        enforce(n >= 1, "train_steps needs n >= 1, got %s", n)
        key = ("train_steps", int(n))
        fn = self._multi_cache.get(key)
        if fn is None:
            def many(params, buffers, opt_state, rng, batch):
                def body(carry, sub):
                    params, buffers, opt_state = carry
                    loss, metrics, params, buffers, opt_state = self._step(
                        params, buffers, opt_state, sub, batch)
                    return (params, buffers, opt_state), (loss, metrics)

                subs = jax.random.split(rng, n)
                (params, buffers, opt_state), (losses, metrics) = lax.scan(
                    body, (params, buffers, opt_state), subs)
                last = jax.tree_util.tree_map(lambda x: x[-1], metrics)
                return losses[-1], last, params, buffers, opt_state

            donate = (0, 1, 2) if self.strategy.donate_inputs else ()
            # the scan-fused step rides the SAME compile path as the
            # single step: pjit shardings / shard_map wrap carry over
            # (the scan body calls _step, which is collective-aware)
            fn = compile_step(self.plan, many, donate_argnums=donate,
                              **self._step_shardings())
            self._multi_cache[key] = fn
        return fn

    def eval_step(self, batch):
        return self._jit_eval(self.params, self.buffers, batch)

    def sync_model(self) -> Layer:
        """Write current params/buffers back into the Layer (for save/export)."""
        self.model.set_parameters(jax.device_get(self.params))
        self.model.set_buffers(jax.device_get(self.buffers))
        return self.model

    def data_sharding(self) -> NamedSharding:
        """Sharding for input batches: the plan's batch sharding when
        one rides the trainer, else leading dim over dp (feed via
        DataFeeder(sharding=...) — the feed_and_split analog)."""
        if self.plan is not None:
            return self.plan.batch_sharding()
        return NamedSharding(self.mesh, P("dp"))

    # --- checkpoint/resume (SURVEY §5.4) ------------------------------------

    def state(self) -> Dict[str, Any]:
        """Full resumable training state (params + buffers + optimizer
        moments + RNG) — what the reference persists via save_persistables
        (params + optimizer accumulators, reference: io.py:460)."""
        st = {"params": self.params, "buffers": self.buffers,
              "opt_state": self.opt_state,
              "rng": jax.random.key_data(self._rng)}
        if self.grad_accum_steps > 1:
            st["grad_accum"] = {"accum": self._accum,
                                "count": self._accum_count}
        return st

    def save_checkpoint(self, manager_or_dir, step: Optional[int] = None):
        from ..checkpoint import CheckpointManager, save_state

        if isinstance(manager_or_dir, CheckpointManager):
            enforce(step is not None,
                    "save_checkpoint(manager) needs a step number")
            manager_or_dir.save(step, self.state())
        else:
            save_state(manager_or_dir, self.state())

    def state_shardings(self) -> Optional[Dict[str, Any]]:
        """Shardings of the live state (plan trainers only): what a
        restore must reshard saved leaves onto, regardless of the mesh
        the checkpoint was written from (dp=8 -> fsdp=4 x dp=2 works)."""
        if self.plan is None:
            return None
        sh: Dict[str, Any] = {
            "params": self._sharding_tree(self.params),
            "buffers": self._sharding_tree(self.buffers),
            "opt_state": self._sharding_tree(self.opt_state),
            "rng": self.plan.replicated(),
        }
        if self.grad_accum_steps > 1:
            sh["grad_accum"] = {
                "accum": self._sharding_tree(self._accum),
                "count": self.plan.replicated()}
        return sh

    def restore_checkpoint(self, manager_or_dir,
                           step: Optional[int] = None) -> None:
        """Restore in place, resharding saved leaves onto this trainer's
        mesh (works across mesh shapes — the survey's upgrade over the
        reference's shape-must-match load). Plan trainers reshard onto
        the PLAN's shardings, so a checkpoint written under any other
        plan shape restores straight into the declared layout."""
        from ..checkpoint import CheckpointManager, restore_state

        shardings = self.state_shardings()
        if isinstance(manager_or_dir, CheckpointManager):
            st = manager_or_dir.restore(step, mesh=self.mesh,
                                        shardings=shardings,
                                        target=self.state())
        else:
            st = restore_state(manager_or_dir, mesh=self.mesh,
                               shardings=shardings,
                               target=self.state())
        self.params = st["params"]
        self.buffers = st["buffers"]
        self.opt_state = st["opt_state"]
        if self.grad_accum_steps > 1 and "grad_accum" in st:
            self._accum = st["grad_accum"]["accum"]
            self._accum_count = st["grad_accum"]["count"]
        self._rng = jax.random.wrap_key_data(jnp.asarray(st["rng"]))

    @classmethod
    def supervised(cls, model: Layer, optimizer: Optimizer,
                   loss_fn: Callable, metrics_fn: Optional[Callable] = None,
                   mesh=None, aux_loss_weight: float = 0.0,
                   router_z_loss_weight: float = 0.0,
                   **kw) -> "Trainer":
        """Convenience for (x, label) batches: batch = dict(x=..., label=...)
        or tuple (x, label).

        ``aux_loss_weight``/``router_z_loss_weight`` add those multiples
        of every buffer named ``*aux_loss`` / ``*router_z_loss`` to the
        TRAINING objective (eval_step reports the pure task loss) — the
        MoE load-balance/stability terms ride the buffer mechanism
        (nn.moe.SwitchFFN); the Switch-paper weights are 0.01 and the
        ST-MoE z weight 1e-3."""

        def loss_builder(params, buffers, rng, batch):
            if isinstance(batch, dict):
                x, label = batch["x"], batch["label"]
            else:
                x, label = batch
            training = rng is not None
            out, new_buffers = model.functional_call(
                params, x, buffers=buffers, rng=rng, training=training)
            loss = loss_fn(out, label)
            metrics = metrics_fn(out, label) if metrics_fn else {}
            if training and (aux_loss_weight or router_z_loss_weight):
                # regularizers join only the OPTIMIZED loss; eval stays
                # comparable to task-only baselines
                if aux_loss_weight:
                    loss = loss + aux_loss_weight * sum(
                        v for k, v in new_buffers.items()
                        if k.endswith("aux_loss"))
                if router_z_loss_weight:
                    loss = loss + router_z_loss_weight * sum(
                        v for k, v in new_buffers.items()
                        if k.endswith("router_z_loss"))
            return loss, (metrics, new_buffers)

        return cls(model, optimizer, loss_builder, mesh=mesh, **kw)


class DataParallel:
    """Dygraph-style wrapper (reference: dygraph/parallel.py:79 DataParallel)
    — here just a Trainer factory over an all-device dp mesh."""

    def __new__(cls, model: Layer, optimizer: Optimizer, loss_fn: Callable,
                metrics_fn=None, devices=None):
        from ..core.mesh import auto_mesh

        mesh = auto_mesh(devices)
        return Trainer.supervised(model, optimizer, loss_fn, metrics_fn,
                                  mesh=mesh)
