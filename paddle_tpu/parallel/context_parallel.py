"""Context/sequence parallelism — ring attention and Ulysses all-to-all.

The reference has no sequence-parallel story at all (SURVEY.md §5.7; its long
-sequence mechanism is LoDTensor packing, reference: framework/lod_tensor.h:110).
These are green-field TPU designs:

- **Ring attention**: shard the sequence over the ``sp`` mesh axis; K/V blocks
  rotate around the ring via ``lax.ppermute`` (one ICI hop per step) while each
  device accumulates its Q-block's attention with a running online softmax
  (max/sum carries, exactly the flash-attention recurrence lifted to the mesh
  level). Peak memory per device is O(seq/sp); compute overlaps with the
  collective permute under XLA's async scheduling.

- **Ulysses**: all-to-all swaps sequence sharding for head sharding, runs a
  full (optionally Pallas flash) attention locally over seq with heads/sp heads
  per device, and all-to-alls back. Two a2a hops; requires heads % sp == 0.

Both are differentiable end-to-end: ring via autodiff through the
``lax.scan``+``ppermute`` loop (step compute wrapped in ``jax.checkpoint`` so
backward recomputes scores instead of storing (t×t) blocks), Ulysses via the
flash kernel's custom VJP plus the self-transposing all-to-alls.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from ..utils.compat import shard_map
from jax import lax
from jax.sharding import PartitionSpec as P

from ..core.enforce import enforce
from ..core.mesh import get_mesh

_NEG_INF = -1e30  # finite: avoids inf-inf NaNs under autodiff


def _shard_with_optional(inner, mesh, spec, mspec, q, k, v, kv_mask,
                         segment_ids):
    """shard_map an ``inner(q, k, v, km, seg)`` with OPTIONAL (B, T)
    inputs: shard_map specs are positional, so each supplied optional
    appends an arg+spec pair and the wrapper re-slots them (None for the
    absent ones) — one place for the plumbing both ring and Ulysses use."""
    args, in_specs = [q, k, v], [spec, spec, spec]
    km_i = seg_i = None
    if kv_mask is not None:
        km_i = len(args)
        args.append(kv_mask)
        in_specs.append(mspec)
    if segment_ids is not None:
        seg_i = len(args)
        args.append(segment_ids)
        in_specs.append(mspec)

    def wrapper(*xs):
        return inner(xs[0], xs[1], xs[2],
                     xs[km_i] if km_i is not None else None,
                     xs[seg_i] if seg_i is not None else None)

    fn = shard_map(wrapper, mesh=mesh, in_specs=tuple(in_specs),
                   out_specs=spec, check_vma=False)
    return fn(*args)


# ---------------------------------------------------------------------------
# ring attention
# ---------------------------------------------------------------------------


def _ring_step_compute(qf, acc, m, l, kc, vc, kmc, qseg, ksegc, src,
                       my_idx, *, t_local, causal, window, scale):
    """One ring step's flash-style accumulation (no collectives; wrapped in
    jax.checkpoint by the caller so backward recomputes the (t×t) scores).
    ``kmc``: the K/V block's key-padding keep-mask (b, t_local) rotating
    around the ring with it, or None. ``qseg``/``ksegc``: packed-batch
    segment ids — q side fixed to this shard, kv side rotating with its
    block; attention stays within a segment. ``window``: sliding-window
    band in GLOBAL positions."""
    # q/k stay in their native dtype (bf16 in production): bf16 inputs
    # with an f32 preferred_element_type run at the full MXU rate, while
    # a pre-cast to f32 would drop to the fp32 matmul rate (4-8x slower
    # on v5e) with no accumulator benefit
    b, t, h, d = qf.shape
    hkv = kc.shape[2]
    if hkv != h:
        # GQA: the K/V blocks rotate the ring with their FEWER heads
        # (h/hkv x less ICI traffic and carry memory than expanding up
        # front); the grouped einsum shares each kv head across its
        # group, kv-major head order matching the kernel/xla paths
        q5 = qf.reshape(b, t, hkv, h // hkv, d)
        s = jnp.einsum("bqegd,bked->begqk", q5, kc,
                       preferred_element_type=jnp.float32)
        s = s.reshape(b, h, t, kc.shape[1]) * scale
    else:
        s = jnp.einsum("bqhd,bkhd->bhqk", qf, kc,
                       preferred_element_type=jnp.float32) * scale
    if causal or window is not None:
        rows = my_idx * t_local + lax.broadcasted_iota(
            jnp.int32, (t_local, t_local), 0)
        cols = src * t_local + lax.broadcasted_iota(
            jnp.int32, (t_local, t_local), 1)
        if causal:
            s = jnp.where(rows >= cols, s, _NEG_INF)
        if window is not None:
            band = rows - cols < window
            if not causal:
                band &= cols - rows < window
            s = jnp.where(band, s, _NEG_INF)
    if kmc is not None:
        s = jnp.where(kmc[:, None, None, :], s, _NEG_INF)
    if qseg is not None:
        s = jnp.where(qseg[:, None, :, None] == ksegc[:, None, None, :],
                      s, _NEG_INF)
    m_cur = jnp.max(s, axis=-1, keepdims=True)          # (b,h,t,1)
    m_new = jnp.maximum(m, m_cur)
    p = jnp.exp(s - m_new)
    if kmc is not None or qseg is not None or window is not None:
        # a fully-masked row keeps m_new == _NEG_INF, turning the masked
        # exp(s - m_new) into exp(0) = 1; zero those entries so l stays 0
        # and the final o is 0 (causal alone can't fully mask a row —
        # the diagonal is always visible; a window CAN fully mask a row
        # of an off-diagonal step block)
        p = jnp.where(s <= _NEG_INF * 0.5, 0.0, p)
    alpha = jnp.exp(m - m_new)
    l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
    if hkv != h:
        p5 = p.astype(vc.dtype).reshape(b, hkv, h // hkv, t, kc.shape[1])
        pv = jnp.einsum("begqk,bked->bqegd", p5, vc,
                        preferred_element_type=jnp.float32)
        pv = pv.reshape(b, t, h, d)
    else:
        pv = jnp.einsum("bhqk,bkhd->bqhd", p.astype(vc.dtype), vc,
                        preferred_element_type=jnp.float32)
    acc_new = acc * alpha.transpose(0, 2, 1, 3) + pv     # (b,t,h,d)
    return acc_new, m_new, l_new


def _ring_step_gate(src, my_idx, *, t_local, causal, window):
    """Scalar: does this ring step's K/V block contribute at all? False
    for strictly-future blocks (causal) and blocks wholly outside the
    window band — the caller lax.cond's the WHOLE step compute away
    (einsum + softmax + PV), which is what makes causal ring O(T^2/2)
    and windowed ring O(T*W) per device instead of dense cost."""
    gate = jnp.bool_(True)
    if causal:
        gate &= src <= my_idx
    if window is not None:
        # overlap between [src*t, src*t+t-1] cols and the band of
        # [my*t, my*t+t-1] rows
        lo_ok = (src + 1) * t_local - 1 >= my_idx * t_local - (window - 1)
        in_band = lo_ok if causal else (
            lo_ok & (src * t_local <= (my_idx + 1) * t_local - 1
                     + (window - 1)))
        gate &= in_band
    return gate


def _ring_inner(q, k, v, km, seg, *, axis, causal, window, scale, n):
    b, t, h, d = q.shape  # local (sequence-sharded) shapes
    has_mask = km is not None
    has_segs = seg is not None
    my_idx = lax.axis_index(axis)
    perm = [(i, (i + 1) % n) for i in range(n)]
    qf = q  # native dtype into the MXU (see _ring_step_compute note)
    compute = jax.checkpoint(functools.partial(
        _ring_step_compute, t_local=t, causal=causal, window=window,
        scale=scale))

    def step(carry, t_step):
        acc, m, l, kc, vc, kmc, ksegc = carry
        src = (my_idx - t_step) % n  # origin rank of the K/V block we hold
        gate = _ring_step_gate(src, my_idx, t_local=t, causal=causal,
                               window=window)
        acc, m, l = lax.cond(
            gate,
            lambda a, mm, ll, kcc, vcc: compute(
                qf, a, mm, ll, kcc, vcc,
                kmc if has_mask else None,
                seg if has_segs else None,
                ksegc if has_segs else None, src, my_idx),
            lambda a, mm, ll, kcc, vcc: (a, mm, ll),
            acc, m, l, kc, vc)
        kc = lax.ppermute(kc, axis, perm)
        vc = lax.ppermute(vc, axis, perm)
        if has_mask:  # the keep-mask block travels with its K/V block
            kmc = lax.ppermute(kmc, axis, perm)
        if has_segs:  # so do the kv-side segment ids
            ksegc = lax.ppermute(ksegc, axis, perm)
        return (acc, m, l, kc, vc, kmc, ksegc), None

    acc0 = jnp.zeros((b, t, h, d), jnp.float32)
    m0 = jnp.full((b, h, t, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, t, 1), jnp.float32)
    # zeros placeholders keep the scan carry structure static when no
    # mask/ids are supplied (never read: has_* are trace-time consts)
    km0 = km if has_mask else jnp.zeros((b, t), jnp.bool_)
    seg0 = seg if has_segs else jnp.zeros((b, t), jnp.int32)
    # scan the first n-1 steps (compute + rotate); the last block's compute is
    # peeled out so the final rotation — whose result would be discarded —
    # never hits the ICI ring
    (acc, m, l, kc, vc, kmc, ksegc), _ = lax.scan(
        step, (acc0, m0, l0, k, v, km0, seg0), jnp.arange(n - 1))
    last_src = (my_idx - (n - 1)) % n
    acc, m, l = lax.cond(
        _ring_step_gate(last_src, my_idx, t_local=t, causal=causal,
                        window=window),
        lambda a, mm, ll, kcc, vcc: compute(
            qf, a, mm, ll, kcc, vcc,
            kmc if has_mask else None,
            seg if has_segs else None,
            ksegc if has_segs else None, last_src, my_idx),
        lambda a, mm, ll, kcc, vcc: (a, mm, ll),
        acc, m, l, kc, vc)
    o = acc / jnp.maximum(l.transpose(0, 2, 1, 3), 1e-37)
    return o.astype(q.dtype)


# --- ring attention on the flash kernel (VERDICT r4 #3) -------------------
#
# The einsum inner above materializes per-shard-pair (t x t) score blocks
# through XLA every hop — exactly the cost the flash kernel exists to
# kill, and the reason bert_long's SP config was bounded by the fallback.
# This path instead runs the Pallas flash FORWARD per hop (returning the
# block's output + logsumexp) and merges hops flash-decoding style:
#
#   lse' = logaddexp(lse, lse_hop)
#   o'   = o * exp(lse - lse') + o_hop * exp(lse_hop - lse')
#
# which is the online-softmax recurrence carried ACROSS ppermute hops —
# scores never leave VMEM. The backward is its own ring loop: each hop
# calls the flash backward kernel with the GLOBAL (ring-merged) lse and
# the FINAL output (delta = rowsum(do*o)), which makes every hop's
# (dq, dk, dv) the exact contribution of that (q rows, kv block) pair to
# the global gradients; dk/dv accumulators travel the ring with their
# block and arrive home after n hops. Causal runs skip strictly-future
# blocks entirely (lax.cond) and use the causal kernel variant only on
# the diagonal block, keeping the O(T^2/2) ring schedule.
#
# Handles kv_mask/segment_ids/causal AND GQA (kv blocks rotate with
# their fewer heads; the kernel shares them per group). Windowed runs
# stay on the einsum inner; dropout doesn't apply under SP. Dispatch in
# ring_attention.


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10, 11, 12, 13))
def _ring_flash(q, k, v, km, seg, axis, causal, scale, n, block_q,
                block_k, block_q_bwd, block_k_bwd, interpret):
    o, _ = _ring_flash_fwd(q, k, v, km, seg, axis, causal, scale, n,
                           block_q, block_k, block_q_bwd, block_k_bwd,
                           interpret)
    return o


def _ring_flash_fwd(q, k, v, km, seg, axis, causal, scale, n, block_q,
                    block_k, block_q_bwd, block_k_bwd, interpret):
    from ..ops.pallas.flash_attention import ring_fwd_block

    b, t, h, d = q.shape
    my_idx = lax.axis_index(axis)
    perm = [(i, (i + 1) % n) for i in range(n)]
    has_mask = km is not None
    has_segs = seg is not None

    def fwd_block(kc, vc, kmc, ksegc, blk_causal):
        return ring_fwd_block(
            q, kc, vc, kmc if has_mask else None,
            seg if has_segs else None, ksegc if has_segs else None,
            causal=blk_causal, scale=scale, block_q=block_q,
            block_k=block_k, interpret=interpret)

    def merge(o_acc, lse_acc, o_s, lse_s):
        lse_new = jnp.logaddexp(lse_acc, lse_s)          # (b, h, t)
        w = lambda x: jnp.exp(x - lse_new).transpose(0, 2, 1)[..., None]
        return (o_acc * w(lse_acc) + o_s.astype(jnp.float32) * w(lse_s),
                lse_new)

    def contribute(o_acc, lse_acc, kc, vc, kmc, ksegc, src):
        if causal:
            o_s, lse_s = lax.cond(
                src == my_idx,
                lambda: fwd_block(kc, vc, kmc, ksegc, True),
                lambda: fwd_block(kc, vc, kmc, ksegc, False))
        else:
            o_s, lse_s = fwd_block(kc, vc, kmc, ksegc, False)
        return merge(o_acc, lse_acc, o_s, lse_s)

    def step_body(o_acc, lse_acc, kc, vc, kmc, ksegc, src):
        if causal:  # strictly-future blocks contribute nothing at all
            return lax.cond(
                src > my_idx,
                lambda: (o_acc, lse_acc),
                lambda: contribute(o_acc, lse_acc, kc, vc, kmc, ksegc,
                                   src))
        return contribute(o_acc, lse_acc, kc, vc, kmc, ksegc, src)

    def step(carry, t_step):
        o_acc, lse_acc, kc, vc, kmc, ksegc = carry
        src = (my_idx - t_step) % n
        o_acc, lse_acc = step_body(o_acc, lse_acc, kc, vc, kmc, ksegc,
                                   src)
        kc = lax.ppermute(kc, axis, perm)
        vc = lax.ppermute(vc, axis, perm)
        if has_mask:
            kmc = lax.ppermute(kmc, axis, perm)
        if has_segs:
            ksegc = lax.ppermute(ksegc, axis, perm)
        return (o_acc, lse_acc, kc, vc, kmc, ksegc), None

    o0 = jnp.zeros((b, t, h, d), jnp.float32)
    lse0 = jnp.full((b, h, t), _NEG_INF, jnp.float32)
    km0 = km if has_mask else jnp.zeros((b, t), jnp.bool_)
    seg0 = seg if has_segs else jnp.zeros((b, t), jnp.int32)
    # scan the first n-1 hops (compute + rotate); the last hop's compute
    # is peeled so the final rotation never hits the ICI ring
    (o_acc, lse_acc, kc, vc, kmc, ksegc), _ = lax.scan(
        step, (o0, lse0, k, v, km0, seg0), jnp.arange(n - 1))
    last_src = (my_idx - (n - 1)) % n
    o_acc, lse_acc = step_body(o_acc, lse_acc, kc, vc, kmc, ksegc,
                               last_src)
    o = o_acc.astype(q.dtype)
    return o, (q, k, v, km, seg, o, lse_acc)


def _ring_flash_bwd(axis, causal, scale, n, block_q, block_k,
                    block_q_bwd, block_k_bwd, interpret, res, do):
    from ..ops.pallas.flash_attention import ring_bwd_block

    q, k, v, km, seg, o, lse = res
    b, t, h, d = q.shape
    my_idx = lax.axis_index(axis)
    perm = [(i, (i + 1) % n) for i in range(n)]
    has_mask = km is not None
    has_segs = seg is not None

    # hop-invariant: rowsum(do * o) against the FINAL output, computed
    # once here rather than inside each of the n hops' kernel calls
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)  # (b, t, h)

    def bwd_block(kc, vc, kmc, ksegc, blk_causal):
        return ring_bwd_block(
            q, kc, vc, kmc if has_mask else None,
            seg if has_segs else None, ksegc if has_segs else None,
            o, lse, do, causal=blk_causal, scale=scale,
            block_q=block_q_bwd, block_k=block_k_bwd,
            interpret=interpret, delta=delta)

    def contribute(dq, dkc, dvc, kc, vc, kmc, ksegc, src):
        if causal:
            dq_p, dk_p, dv_p = lax.cond(
                src == my_idx,
                lambda: bwd_block(kc, vc, kmc, ksegc, True),
                lambda: bwd_block(kc, vc, kmc, ksegc, False))
        else:
            dq_p, dk_p, dv_p = bwd_block(kc, vc, kmc, ksegc, False)
        return (dq + dq_p.astype(jnp.float32),
                dkc + dk_p.astype(jnp.float32),
                dvc + dv_p.astype(jnp.float32))

    def step_body(dq, dkc, dvc, kc, vc, kmc, ksegc, src):
        if causal:
            return lax.cond(
                src > my_idx,
                lambda: (dq, dkc, dvc),
                lambda: contribute(dq, dkc, dvc, kc, vc, kmc, ksegc,
                                   src))
        return contribute(dq, dkc, dvc, kc, vc, kmc, ksegc, src)

    def step(carry, t_step):
        dq, kc, vc, kmc, ksegc, dkc, dvc = carry
        src = (my_idx - t_step) % n
        dq, dkc, dvc = step_body(dq, dkc, dvc, kc, vc, kmc, ksegc, src)
        kc = lax.ppermute(kc, axis, perm)
        vc = lax.ppermute(vc, axis, perm)
        if has_mask:
            kmc = lax.ppermute(kmc, axis, perm)
        if has_segs:
            ksegc = lax.ppermute(ksegc, axis, perm)
        # the block's gradient accumulators travel WITH it
        dkc = lax.ppermute(dkc, axis, perm)
        dvc = lax.ppermute(dvc, axis, perm)
        return (dq, kc, vc, kmc, ksegc, dkc, dvc), None

    dq0 = jnp.zeros((b, t, h, d), jnp.float32)
    # GQA: accumulators match the (possibly fewer-headed) K/V blocks
    dk0 = jnp.zeros(k.shape, jnp.float32)
    dv0 = jnp.zeros(v.shape, jnp.float32)
    km0 = km if has_mask else jnp.zeros((b, t), jnp.bool_)
    seg0 = seg if has_segs else jnp.zeros((b, t), jnp.int32)
    (dq, kc, vc, kmc, ksegc, dkc, dvc), _ = lax.scan(
        step, (dq0, k, v, km0, seg0, dk0, dv0), jnp.arange(n - 1))
    last_src = (my_idx - (n - 1)) % n
    dq, dkc, dvc = step_body(dq, dkc, dvc, kc, vc, kmc, ksegc, last_src)
    # one final hop brings each block's accumulated dk/dv home (the k/v
    # blocks themselves are already discarded — no need to rotate them)
    dkc = lax.ppermute(dkc, axis, perm)
    dvc = lax.ppermute(dvc, axis, perm)
    return (dq.astype(q.dtype), dkc.astype(k.dtype),
            dvc.astype(v.dtype), None, None)


_ring_flash.defvjp(_ring_flash_fwd, _ring_flash_bwd)


def _ring_flash_inner(q, k, v, km, seg, *, axis, causal, scale, n,
                      blocks, interpret):
    return _ring_flash(q, k, v, km, seg, axis, causal, scale, n,
                       blocks[0], blocks[1], blocks[2], blocks[3],
                       interpret)


def ring_attention(q, k, v, *, causal: bool = False,
                   scale: Optional[float] = None, axis: str = "sp",
                   batch_axis: Optional[str] = "dp", mesh=None,
                   kv_mask=None, segment_ids=None,
                   window: Optional[int] = None,
                   use_flash: bool = True):
    """Sequence-parallel attention over global (B, T, H, D) arrays.

    ``q``/``k``/``v`` are sharded ``P(batch_axis, axis)`` over the mesh; T must
    divide by the ``axis`` size. Causal masking is in *global* positions.
    ``kv_mask``: optional global (B, T) keep-mask (the ragged-batch
    key-padding form); its blocks rotate around the ring with their K/V.
    ``segment_ids``: optional global (B, T) packed-batch ids (ids global
    per row, so a segment spanning a shard boundary keeps one id); the
    kv-side ids rotate with their block. ``window``: sliding-window band
    in GLOBAL positions (ring steps wholly outside the band keep their
    carries untouched).

    ``use_flash``: route each ring hop through the Pallas flash kernel
    (online-softmax carries merged ACROSS hops — scores never hit HBM)
    when the per-shard block shape is kernel-eligible; windowed runs and
    ineligible shapes keep the einsum inner. Same gating semantics as
    scaled_dot_product_attention's use_flash.

    GQA/MQA (r5): ``k``/``v`` may carry fewer heads than ``q``
    (``h % kv_heads == 0``) — on the flash path the smaller blocks
    rotate as-is and the kernel shares them per group (dk/dv come home
    group-summed); the einsum fallback expands them kv-major up front.
    """
    mesh = mesh or get_mesh()
    n = mesh.shape[axis]
    b, t, h, d = q.shape
    hkv = k.shape[2]
    enforce(t % n == 0, "seq len %s must divide sp size %s", t, n)
    enforce(k.shape == v.shape and k.shape[0] == b and k.shape[1] == t
            and k.shape[3] == d,
            "ring attention is self-attention shaped: k/v must be "
            "(%s, %s, kv_heads, %s), got k=%s v=%s", b, t, d, k.shape,
            v.shape)
    enforce(h % hkv == 0,
            "q heads %s must be a multiple of kv heads %s (GQA)", h, hkv)
    for name, arr in (("kv_mask", kv_mask), ("segment_ids", segment_ids)):
        if arr is not None:
            enforce(arr.shape == (b, t),
                    "%s must be (batch, seq) = (%s, %s), got %s",
                    name, b, t, arr.shape)
    enforce(window is None or window >= 1,
            "window must be >= 1, got %s", window)
    if scale is None:
        scale = d ** -0.5
    spec = P(batch_axis, axis, None, None)
    mspec = P(batch_axis, axis)
    t_local = t // n
    from ..ops.attention import flash_shape_ok

    if use_flash and window is None and flash_shape_ok(
            t_local, t_local, d, causal=causal):
        from ..ops.pallas.flash_attention import (_use_interpret,
                                                  resolve_block_sizes)

        blocks = resolve_block_sizes(t_local, t_local, d, causal)
        inner = functools.partial(
            _ring_flash_inner, axis=axis, causal=causal,
            scale=float(scale), n=n, blocks=blocks,
            interpret=_use_interpret())
    else:
        # the einsum inner handles GQA natively (grouped score einsum in
        # _ring_step_compute): kv blocks rotate with their fewer heads
        inner = functools.partial(_ring_inner, axis=axis, causal=causal,
                                  window=window, scale=float(scale), n=n)
    return _shard_with_optional(inner, mesh, spec, mspec, q, k, v,
                                kv_mask, segment_ids)


# ---------------------------------------------------------------------------
# Ulysses (all-to-all) sequence parallelism
# ---------------------------------------------------------------------------


def _ulysses_inner(q, k, v, km, seg, *, axis, causal, window, scale,
                   use_flash):
    from ..ops.attention import scaled_dot_product_attention

    # (b, t/sp, h, d) --a2a--> (b, t, h/sp, d): full sequence, head subset
    q = lax.all_to_all(q, axis, split_axis=2, concat_axis=1, tiled=True)
    k = lax.all_to_all(k, axis, split_axis=2, concat_axis=1, tiled=True)
    v = lax.all_to_all(v, axis, split_axis=2, concat_axis=1, tiled=True)
    mask = None
    if km is not None:
        # each shard holds (b, t/sp) of the keep-mask; after the a2a the
        # local attention sees the FULL sequence, so gather the mask
        # along sp (tiny: bools, no head/dim axes)
        full = lax.all_gather(km, axis, axis=1, tiled=True)  # (b, t)
        mask = full[:, None, None, :]
    seg_full = None
    if seg is not None:  # same gather for packed-batch segment ids
        seg_full = lax.all_gather(seg, axis, axis=1, tiled=True)
    o = scaled_dot_product_attention(q, k, v, mask=mask, causal=causal,
                                     scale=scale, use_flash=use_flash,
                                     segment_ids=seg_full, window=window)
    # back to sequence sharding
    return lax.all_to_all(o, axis, split_axis=1, concat_axis=2, tiled=True)


def ulysses_attention(q, k, v, *, causal: bool = False,
                      scale: Optional[float] = None, axis: str = "sp",
                      batch_axis: Optional[str] = "dp", mesh=None,
                      use_flash: bool = True, kv_mask=None,
                      segment_ids=None, window: Optional[int] = None):
    """DeepSpeed-Ulysses-style SP: a2a seq→head shard, local full attention
    (Pallas flash on TPU), a2a back. Requires heads % sp == 0.
    ``kv_mask``: optional global (B, T) keep-mask; all-gathered over sp
    for the full-sequence local attention (key-padding routes to the
    flash kernel's kv_mask path on TPU). ``segment_ids``: optional global
    (B, T) packed-batch ids, same gather (self-attention only).

    GQA/MQA (r5): supported when ``kv_heads % sp == 0`` — q's kv-major
    head order means each head shard then holds WHOLE groups, so the k/v
    all-to-alls split their own (fewer) heads and the local attention
    stays a valid GQA problem. Fewer kv heads than sp can't shard this
    way; use ``ring`` there."""
    mesh = mesh or get_mesh()
    n = mesh.shape[axis]
    b, t, h, d = q.shape
    hkv = k.shape[2]
    enforce(t % n == 0, "seq len %s must divide sp size %s", t, n)
    enforce(h % n == 0, "num heads %s must divide sp size %s (Ulysses)", h, n)
    enforce(h % hkv == 0,
            "q heads %s must be a multiple of kv heads %s (GQA)", h, hkv)
    enforce(hkv % n == 0,
            "kv heads %s must divide sp size %s (Ulysses GQA shards "
            "whole groups per device; use seq_parallel='ring' for "
            "kv_heads < sp)", hkv, n)
    if kv_mask is not None:
        # key-padding masks cover the KEY sequence: cross-attention under
        # Ulysses has tk != tq and the mask belongs to k/v, not q
        tk = k.shape[1]
        enforce(kv_mask.shape == (b, tk),
                "kv_mask must be (batch, key_seq) = (%s, %s), got %s",
                b, tk, kv_mask.shape)
    if segment_ids is not None:
        enforce(q.shape[1] == k.shape[1],
                "segment_ids requires self-attention shapes "
                "(tq=%s != tk=%s)", q.shape[1], k.shape[1])
        enforce(segment_ids.shape == (b, t),
                "segment_ids must be (batch, seq) = (%s, %s), got %s",
                b, t, segment_ids.shape)
    if scale is None:
        scale = d ** -0.5
    spec = P(batch_axis, axis, None, None)
    mspec = P(batch_axis, axis)
    enforce(window is None or window >= 1,
            "window must be >= 1, got %s", window)
    inner = functools.partial(_ulysses_inner, axis=axis, causal=causal,
                              window=window, scale=float(scale),
                              use_flash=use_flash)
    return _shard_with_optional(inner, mesh, spec, mspec, q, k, v,
                                kv_mask, segment_ids)


def context_parallel_attention(q, k, v, *, impl: str = "ring", **kw):
    """Dispatch helper: ``impl`` in {"ring", "ulysses"}."""
    if impl == "ring":
        return ring_attention(q, k, v, **kw)
    if impl == "ulysses":
        return ulysses_attention(q, k, v, **kw)
    raise ValueError(f"unknown context-parallel impl {impl!r}")


def sharded_flash_attention(q, k, v, *, mesh=None, batch_axis="dp",
                            head_axis=None, causal=False, scale=None,
                            kv_mask=None, segment_ids=None, window=None,
                            dropout_p=0.0, dropout_key=None):
    """Flash attention partitioned over batch and/or head mesh axes via
    EXPLICIT shard_map. Since round 4 the kernel itself registers a
    partitioning rule (jax.experimental.custom_partitioning, see
    ops/pallas/flash_attention.py) covering dense AND GQA heads (q
    crosses the boundary as (B, T, KV, GROUP, D) so kv heads shard with
    k/v), so plain pjit auto-sharding already runs it on local shards —
    this wrapper remains for explicit control of which axes shard
    independently of the operands' incoming shardings.

    Attention is embarrassingly parallel over batch and heads, so each
    device runs the kernel on its local (b/dp, t, h/tp, d) shard with no
    collectives. kv_mask/segment_ids shard over batch only. Dropout:
    each shard folds its mesh coordinates into the key, so masks are
    DISTINCT across devices (no cross-shard correlation) and
    deterministic per key — unlike the auto-partitioned path, whose
    per-(b,h) seeds make masks bit-identical to the unsharded call.

    The SP paths (ring/ulysses above) already run inside their own
    shard_map.
    """
    from ..ops.pallas.flash_attention import flash_attention

    mesh = mesh or get_mesh()
    b, t, h, d = q.shape
    axes = dict(mesh.shape)
    for name, ax in (("batch_axis", batch_axis), ("head_axis", head_axis)):
        enforce(ax is None or ax in axes,
                "%s %r is not a mesh axis (mesh has %s)", name, ax,
                sorted(axes))
    if batch_axis is not None:
        enforce(b % axes[batch_axis] == 0,
                "batch %s must divide %s axis size %s", b, batch_axis,
                axes[batch_axis])
    if head_axis is not None:
        enforce(h % axes[head_axis] == 0,
                "heads %s must divide %s axis size %s", h, head_axis,
                axes[head_axis])
        # GQA k/v shard with the same head spec: their (fewer) heads
        # must divide the axis too, or shard_map fails opaquely inside
        enforce(k.shape[2] % axes[head_axis] == 0,
                "kv heads %s must divide %s axis size %s (GQA under "
                "head sharding)", k.shape[2], head_axis, axes[head_axis])
    tk = k.shape[1]  # key-padding masks cover the KEY sequence
    for name, arr, length in (("kv_mask", kv_mask, tk),
                              ("segment_ids", segment_ids, t)):
        if arr is not None:
            enforce(arr.shape == (b, length),
                    "%s must be (batch, %s), got %s",
                    name, length, arr.shape)
    spec = P(batch_axis, None, head_axis, None)
    mspec = P(batch_axis, None)

    def inner(q, k, v, km, seg):
        key = dropout_key
        if key is not None:
            # distinct masks per shard: fold the mesh coordinates in
            for ax in (batch_axis, head_axis):
                if ax is not None:
                    key = jax.random.fold_in(key, lax.axis_index(ax))
        return flash_attention(q, k, v, causal=causal, scale=scale,
                               kv_mask=km, segment_ids=seg, window=window,
                               dropout_p=dropout_p, dropout_key=key)

    return _shard_with_optional(inner, mesh, spec, mspec, q, k, v,
                                kv_mask, segment_ids)
