"""Gradient compression — DGC + quantized collectives (reference:
paddle/fluid/operators/dgc_op.cc, framework/details/
sparse_all_reduce_op_handle.h:30 sparse allreduce, python
optimizer.py:640 DGCMomentumOptimizer; quantized allreduce follows the
EQuARX-style design referenced in PAPERS.md).

Deep Gradient Compression (Lin et al.): send only the top-k fraction of
gradient magnitudes each step; the rest accumulates locally (error
feedback) with momentum correction, preserving convergence at 100-1000x
compression.

TPU-native notes: the reference ships sparse (index, value) pairs over
NCCL. On TPU, dynamic sparse shapes fight XLA, so:
  - ``top_k_sparsify`` produces a *dense masked* tensor (static shape) —
    the error-feedback/momentum-correction math is identical;
  - the bandwidth win comes from ``quantized_allreduce``: int8
    reduce-scatter + all-gather over the dp axis (~4x less ICI traffic),
    composable with DGC's sparsification (zeros quantize to zero).
Both are shard_map-level tools: use inside a manually-sharded train step
where the gradient exchange is explicit.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..core.enforce import enforce
from ..utils import compat
from ..optimizer.optimizers import Momentum, Optimizer, tree_map


def top_k_sparsify(g, sparsity: float = 0.999) -> Tuple[jnp.ndarray,
                                                        jnp.ndarray]:
    """Keep the top-(1-sparsity) fraction of |g|; return (kept, residual)
    as dense tensors (kept + residual == g). reference: dgc_op.cc top-k
    threshold selection."""
    flat = jnp.abs(g.reshape(-1))
    k = max(int(round(flat.size * (1.0 - sparsity))), 1)
    # threshold = k-th largest |g|; lax.top_k is TPU-friendly
    thresh = lax.top_k(flat, k)[0][-1]
    mask = (jnp.abs(g) >= thresh).astype(g.dtype)
    kept = g * mask
    return kept, g - kept


class DGCMomentum(Optimizer):
    """Momentum with deep gradient compression (reference:
    optimizer.py:640 DGCMomentumOptimizer: momentum correction + local
    gradient accumulation + top-k sparsification, with a dense warmup
    period [rampup_begin_step]).

    Per-leaf state: velocity ``u`` (momentum-corrected accumulator) and
    error accumulator ``v``. Each step the locally-accumulated
    momentum-corrected gradient is sparsified; kept entries update the
    params, the residual stays local.
    """

    def __init__(self, learning_rate, momentum: float = 0.9,
                 sparsity: float = 0.999, rampup_begin_step: int = 0,
                 use_nesterov: bool = False, grad_clip=None,
                 regularization=None):
        super().__init__(learning_rate, grad_clip, regularization)
        self.momentum = momentum
        self.sparsity = sparsity
        self.rampup_begin_step = rampup_begin_step
        self.use_nesterov = use_nesterov

    def init_leaf(self, p):
        return {"u": jnp.zeros_like(p), "v": jnp.zeros_like(p)}

    def update_leaf(self, p, g, s, lr, step):
        # momentum correction (DGC paper alg. 1): accumulate velocity
        # locally, THEN sparsify the accumulated update; BOTH accumulators
        # are cleared at sent coordinates
        u = self.momentum * s["u"] + g
        if self.use_nesterov:
            u = self.momentum * u + g
        acc = s["v"] + u
        kept, residual = top_k_sparsify(acc, self.sparsity)
        sent = (kept != 0).astype(u.dtype)
        new_u = u * (1.0 - sent)
        # dense warmup: send everything, keep plain momentum, no residual
        dense = step < self.rampup_begin_step
        kept = jnp.where(dense, acc, kept)
        residual = jnp.where(dense, jnp.zeros_like(acc), residual)
        new_u = jnp.where(dense, u, new_u)
        new_p = p - lr * kept
        return new_p, {"u": new_u, "v": residual}


def quantized_allreduce(x, axis_name: str = "dp", bits: int = 8):
    """Bandwidth-reduced allreduce: int8 reduce-scatter + int8 all-gather
    (each phase quantized with a per-shard scale). ~4x less traffic than
    fp32 allreduce; error is bounded by the two quantization steps.

    Call inside shard_map with ``axis_name`` live. x must have a leading
    dim divisible by the axis size (pad first if needed)."""
    n = compat.axis_size(axis_name)
    qmax = float(2 ** (bits - 1) - 1)
    orig_shape = x.shape
    flat = x.reshape(-1)
    enforce(flat.size % n == 0,
            "quantized_allreduce needs size %% axis_size == 0 "
            "(got %s %% %s)", flat.size, n)
    chunks = flat.reshape(n, -1)  # row i -> destination device i

    def quant(v):
        scale = jnp.maximum(jnp.max(jnp.abs(v)), 1e-12)
        q = jnp.round(v * (qmax / scale)).astype(jnp.int8)
        return q, scale

    # phase 1: quantize chunks, exchange so device i holds every shard's
    # chunk i (reduce-scatter in int8): split rows across peers, row p of
    # the result is peer p's chunk destined for me
    q, scale = quant(chunks)  # (n, c) int8 + scalar scale
    recv = lax.all_to_all(q, axis_name, split_axis=0, concat_axis=0)
    scales = lax.all_gather(scale, axis_name)  # (n,)
    partial = jnp.sum(recv.astype(x.dtype) *
                      (scales / qmax)[:, None], axis=0)  # (c,) my chunk sum
    # phase 2: quantize the reduced chunk, all-gather back
    q2, scale2 = quant(partial)
    gathered = lax.all_gather(q2, axis_name)        # (n, c) int8
    scales2 = lax.all_gather(scale2, axis_name)     # (n,)
    out = (gathered.astype(x.dtype) * (scales2 / qmax)[:, None]).reshape(-1)
    return out.reshape(orig_shape)


def dgc_allreduce(grads, axis_name: str = "dp", sparsity: float = 0.999,
                  quantize: bool = True):
    """Compressed gradient exchange for shard_map DP steps: sparsify each
    leaf locally (caller owns the residual bookkeeping via DGCMomentum) and
    sum across the axis, optionally with the quantized path. Returns the
    summed (dense) gradients."""
    def reduce_leaf(g):
        kept, _ = top_k_sparsify(g, sparsity)
        if quantize and kept.size % compat.axis_size(axis_name) == 0:
            return quantized_allreduce(kept, axis_name)
        return lax.psum(kept, axis_name)

    return tree_map(reduce_leaf, grads)
