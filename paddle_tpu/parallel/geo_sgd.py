"""Geo-async training — the communicator capability (reference:
paddle/fluid/operators/distributed/communicator.h:160 Communicator — a
background thread batching gradient pushes to the parameter server every
``geo_sgd_need_push_nums`` steps, with trainers running on stale local
params between pushes).

TPU-native redesign: no RPC, no parameter server. Each data-parallel
worker holds its OWN param/optimizer replica (leaves stacked along a
leading worker axis, sharded ``P('dp')`` so every replica lives on its
own chips) and trains independently; every ``sync_every`` steps the
replicas synchronize by parameter averaging — one compiler-emitted
``pmean`` over ICI. This is local SGD / federated averaging, the
synchronous-hardware form of the reference's geo mode (push deltas every
K steps, train on stale params in between): communication drops to 1/K
of per-step DP traffic, exactly the reference's bandwidth contract,
without a server round trip.

Use::

    geo = GeoSGDTrainer(trainer, sync_every=16)
    for batch in loader:                 # batch sharded P('dp')
        loss = geo.train_step(batch)     # local step; auto-sync every 16
    geo.sync()                           # flush + write averaged params
                                         # back into the wrapped trainer
"""

from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.enforce import enforce
from ..utils.compat import shard_map


class GeoSGDTrainer:
    """Wrap a ``parallel.Trainer`` with per-worker replicas and K-step
    deferred parameter averaging over ``axis``."""

    def __init__(self, trainer, sync_every: int = 16, axis: str = "dp"):
        enforce(sync_every >= 1, "sync_every must be >= 1, got %s",
                sync_every)
        self.trainer = trainer
        self.sync_every = sync_every
        self.axis = axis
        self.mesh = trainer.mesh
        n = int(self.mesh.shape.get(axis, 0))
        enforce(n >= 1, "mesh has no %r axis", axis)
        self._n = n
        self._since_sync = 0

        def stack(tree):
            def put(x):
                y = jnp.broadcast_to(x[None], (n,) + x.shape)
                spec = P(axis, *([None] * x.ndim))
                return jax.device_put(y, NamedSharding(self.mesh, spec))

            return jax.tree_util.tree_map(put, tree)

        # per-worker replicas (the reference's per-trainer stale params)
        self._params = stack(trainer.params)
        self._buffers = stack(trainer.buffers)
        self._opt_state = stack(trainer.opt_state)
        self._jit_local = None
        self._jit_avg = None

    # -- jitted pieces ------------------------------------------------------

    def _specs(self, stacked):
        return jax.tree_util.tree_map(
            lambda x: P(self.axis, *([None] * (x.ndim - 1))), stacked)

    def _build(self, batch):
        tr, axis = self.trainer, self.axis

        def local(params, buffers, opt_state, rng, batch):
            """One UNSYNCED step per worker: inside shard_map over dp,
            each shard squeezes its replica and updates it with its own
            local batch — no cross-worker gradient traffic."""
            def inner(p, b, s, rng, bt):
                # state replicas carry a size-1 stacked dim per shard —
                # squeeze them; the batch shard does NOT (its leading dim
                # is this worker's B/n samples, all of which train)
                one = lambda t: jax.tree_util.tree_map(lambda x: x[0], t)
                p, b, s = one(p), one(b), one(s)
                sub = jax.random.fold_in(rng, lax.axis_index(axis))
                loss, _m, p, b, s = tr._step(p, b, s, sub, bt)
                ex = lambda t: jax.tree_util.tree_map(lambda x: x[None], t)
                return loss[None], ex(p), ex(b), ex(s)

            pspec, bspec, sspec = (self._specs(params),
                                   self._specs(buffers),
                                   self._specs(opt_state))
            batch_spec = jax.tree_util.tree_map(lambda _: P(axis), batch)
            return shard_map(
                inner, mesh=self.mesh,
                in_specs=(pspec, bspec, sspec, P(), batch_spec),
                out_specs=(P(axis), pspec, bspec, sspec),
                check_vma=False)(params, buffers, opt_state, rng, batch)

        def avg(params):
            """The geo sync: average replicas over dp (one ICI
            all-reduce — the batched-push replacement)."""
            def inner(p):
                return jax.tree_util.tree_map(
                    lambda x: lax.pmean(x, axis), p)

            spec = self._specs(params)
            return shard_map(inner, mesh=self.mesh, in_specs=(spec,),
                             out_specs=spec, check_vma=False)(params)

        self._jit_local = jax.jit(local)
        self._jit_avg = jax.jit(avg)

    # -- driver -------------------------------------------------------------

    def train_step(self, batch) -> Tuple[Any, dict]:
        """One local step per worker; every ``sync_every``-th call
        averages the replicas (the geo push/pull). Returns the mean of
        the per-worker losses."""
        if self._jit_local is None:
            self._build(batch)
        tr = self.trainer
        tr._rng, sub = jax.random.split(tr._rng)
        losses, self._params, self._buffers, self._opt_state = \
            self._jit_local(self._params, self._buffers, self._opt_state,
                            sub, batch)
        self._since_sync += 1
        if self._since_sync >= self.sync_every:
            self._params = self._jit_avg(self._params)
            self._since_sync = 0
        return jnp.mean(losses), {}

    def sync(self) -> None:
        """Flush: average now and write the consensus params, buffers,
        AND optimizer state back into the wrapped trainer so eval/resume
        see trained running stats and moments (reference: Communicator
        flush on barrier/save)."""
        if self._jit_avg is None and self._jit_local is None:
            return
        self._params = self._jit_avg(self._params)
        self._buffers = self._jit_avg(self._buffers)
        self._opt_state = self._jit_avg(self._opt_state)
        self._since_sync = 0
        rep = NamedSharding(self.mesh, P())
        unstack = lambda t: jax.tree_util.tree_map(
            lambda x: jax.device_put(x[0], rep), t)
        self.trainer.params = unstack(self._params)
        self.trainer.buffers = unstack(self._buffers)
        self.trainer.opt_state = unstack(self._opt_state)

    @property
    def divergence(self):
        """Max abs spread across replicas (0 right after a sync) — a
        staleness observability hook."""
        def spread(x):
            return jnp.max(jnp.abs(x - jnp.mean(x, axis=0, keepdims=True)))

        leaves = [spread(x) for x in
                  jax.tree_util.tree_leaves(self._params)]
        return jnp.max(jnp.stack(leaves))
