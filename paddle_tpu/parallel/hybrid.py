"""Composed 3D parallelism — dp x tp x pp on ONE mesh, one module.

The reference composes its parallel modes by program rewriting (data
parallelism via multi_devices_graph_pass, PS sharding via the
transpiler — reference: framework/ir/multi_devices_graph_pass/
multi_devices_graph_pass.cc:165, transpiler/distribute_transpiler.py:283);
a real cluster job stacks them. The TPU-native composition is one mesh
with named axes and one jitted training step:

- **dp**: the batch is sharded ``P('dp')``; GSPMD inserts the gradient
  all-reduce.
- **tp**: Megatron column/row sharding inside each block (weights
  ``P(..., 'tp')`` / ``P('tp', ...)``); GSPMD inserts the activation
  all-reduce.
- **pp**: the block stack is pipelined by :func:`~paddle_tpu.parallel.
  pipeline_apply`, whose ``shard_map`` is manual ONLY over 'pp'
  (``axis_names={'pp'}``) so the dp/tp shardings ride through the
  pipeline body as auto axes — all three collectives land in a single
  compiled module (all-reduce for dp/tp, collective-permute for pp).

``build_hybrid_transformer_step`` is the executable form of this recipe:
a tiny transformer-style stack whose single train step exercises every
axis. The multichip dryrun and tests/test_hybrid_parallel.py run it; it
is deliberately small enough to compile on an 8-device CPU simulation.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.enforce import enforce
from .pipeline import (microbatched_aux_fold, pipeline_apply,
                       ring_order_layers)
from .sharding import constraint, infer_param_spec


def build_hybrid_transformer_step(mesh, *, layers: int = 4, d_model: int = 16,
                                  d_ff: int = 32, num_classes: int = 8,
                                  batch: int = 8, num_microbatches: int = 2,
                                  lr: float = 0.1, seed: int = 0):
    """A full dp x tp x pp training step on ``mesh`` (axes 'dp','tp','pp').

    Returns ``(step, params, batch_xy)`` where ``step(params, x, y) ->
    (loss, new_params)`` is ready to jit with donation. Layer weights are
    stacked ``(L, ...)`` and placed ``P('pp', ..., 'tp')`` (column) /
    ``P('pp', 'tp', ...)`` (row) — Megatron inside each pipeline stage.
    """
    for ax in ("dp", "tp", "pp"):
        enforce(ax in mesh.shape, "hybrid mesh needs axis %r", ax)
    L, n_pp = layers, mesh.shape["pp"]
    enforce(L % n_pp == 0, "pp size %s must divide layer count %s", n_pp, L)
    div = num_microbatches * mesh.shape["dp"]
    enforce(batch % div == 0,
            "microbatches x dp (%s) must divide batch size %s", div, batch)

    import numpy as np

    rng = np.random.default_rng(seed)
    scale = d_model ** -0.5

    def put(a, spec):
        return jax.device_put(jnp.asarray(a), NamedSharding(mesh, spec))

    params = {
        # Megatron pair per layer: w1 column-parallel, w2 row-parallel,
        # both stacked over the pipeline's layer dim
        "w1": put(rng.normal(scale=scale, size=(L, d_model, d_ff))
                  .astype(np.float32), P("pp", None, "tp")),
        "w2": put(rng.normal(scale=scale, size=(L, d_ff, d_model))
                  .astype(np.float32), P("pp", "tp", None)),
        "head": put(rng.normal(scale=scale, size=(d_model, num_classes))
                    .astype(np.float32), P()),
    }
    x = put(rng.normal(size=(batch, d_model)).astype(np.float32), P("dp"))
    y = put(rng.integers(0, num_classes, size=(batch,)), P("dp"))

    def block_fn(p, h):
        # column-parallel matmul -> tp-sharded activation -> row-parallel
        # matmul whose contraction over the sharded dim becomes a GSPMD
        # all-reduce; residual keeps the signal well-conditioned
        h1 = jnp.tanh(h @ p["w1"])
        h1 = constraint(h1, P("dp", "tp"),
                        mesh=jax.sharding.get_abstract_mesh())
        return h + h1 @ p["w2"]

    def loss_fn(p, x, y):
        h = pipeline_apply(block_fn, {"w1": p["w1"], "w2": p["w2"]}, x,
                           num_microbatches=num_microbatches, mesh=mesh)
        h = constraint(h, P("dp"), mesh=mesh)
        logits = h @ p["head"]
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=1))

    def step(p, x, y):
        loss, grads = jax.value_and_grad(loss_fn)(p, x, y)
        new_p = jax.tree_util.tree_map(lambda w, g: w - lr * g, p, grads)
        return loss, new_p

    return step, params, (x, y)


def _sub(tree, prefix):
    """Strip ``prefix.`` from matching keys (functional_call feeding)."""
    pre = prefix + "."
    return {k[len(pre):]: v for k, v in tree.items()
            if k.startswith(pre)}


def _place_hybrid_params(mesh, stacked, rest, rules, ring, n_pp,
                         virtual_stages):
    """Shared placement: ring-order the stack when the interleaved
    schedule needs it, infer tp/ep specs for rest and the stacked
    leaves ('pp' on the layer dim, the rule shifted past it), and
    device_put everything."""
    if ring:
        stacked = ring_order_layers(stacked, n_pp, virtual_stages)
    rest_spec = infer_param_spec(rest, rules, mesh)
    stacked_spec = {
        name: P("pp", *spec)
        for name, spec in infer_param_spec(
            {n: v[0] for n, v in stacked.items()}, rules, mesh).items()}

    def put(tree, spec_map, default):
        return {n: jax.device_put(v, NamedSharding(
                    mesh, spec_map.get(n, default)))
                for n, v in tree.items()}

    return {"layers": put(stacked, stacked_spec, P("pp")),
            "rest": put(rest, rest_spec, P())}


def _stacked_blocks_runner(mesh, template, moe, num_microbatches,
                           pipeline_schedule, virtual_stages):
    """ONE definition of the hybrid block-stack execution shared by the
    BERT and GPT flagship builders: pipelined (both schedules, ring
    weight order, MoE aux riding the scan carry) vs the sequential
    oracle fold. Returns ``run(layers, x, pipelined) -> (h, aux)`` and
    the ring flag (callers ring-order their persistent stack with
    it)."""
    n_pp = mesh.shape["pp"]
    ring = pipeline_schedule == "interleaved" and virtual_stages > 1

    def block_fn(p_l, h):
        out, _ = template.functional_call(p_l, h, training=False)
        return out

    def block_fn_aux(p_l, h):
        out, nb = template.functional_call(p_l, h, training=False)
        # [load-balance, router-z]; kept_fraction stays a buffer-level
        # diagnostic — carrying it through every pipeline tick would be
        # dead payload the scan carry can't DCE
        return out, jnp.stack([nb["ffn.aux_loss"],
                               nb["ffn.router_z_loss"]])

    def run(layers, x, *, pipelined):
        aux = None
        if pipelined:
            h = pipeline_apply(block_fn_aux if moe else block_fn,
                               layers, x,
                               num_microbatches=num_microbatches,
                               mesh=mesh, schedule=pipeline_schedule,
                               virtual_stages=virtual_stages,
                               layers_in_ring_order=ring,
                               aux_size=2 if moe else 0)
            if moe:
                h, aux = h
            h = constraint(h, P("dp"), mesh=mesh)
        else:
            if ring:
                # the sequential oracle applies layers in LOGICAL order
                layers = ring_order_layers(layers, n_pp,
                                           virtual_stages, inverse=True)
            if moe:
                # per-MICROBATCH fold (MoE routing is microbatch-local
                # in the pipelined form): the SAME shared definition the
                # n == 1 pipeline path uses, so oracle and pipeline can
                # never diverge on the aux contract
                h, aux = microbatched_aux_fold(
                    block_fn_aux, layers, x,
                    num_microbatches=num_microbatches, aux_size=2,
                    remat=False)
            else:
                def one(hc, p_l):
                    return block_fn(p_l, hc), None

                h = jax.lax.scan(one, x, layers)[0]
        return h, aux

    return run, ring


def build_bert_hybrid_step(mesh, *, cfg=None, batch: int = 8,
                           seq_len: int = 16, num_microbatches: int = 2,
                           lr: float = 0.01, seed: int = 0,
                           vocab_chunk: int = 256,
                           pipeline_schedule: str = "gpipe",
                           virtual_stages: int = 1):
    """The FLAGSHIP composed-3D step: the real ``BertForPretraining``
    stack — MultiHeadAttention (flash path on TPU), post-norm encoder
    blocks, fused chunked linear-CE MLM head, NSP head — trained under
    ONE dp x tp x pp mesh.

    Decomposition (capability lineage: the reference ran its *benchmark
    models* distributed, reference: benchmark/fluid/fluid_benchmark.py:80
    + benchmark/fluid/models/; dp graph rewrite
    framework/ir/multi_devices_graph_pass/multi_devices_graph_pass.cc:165):

    - encoder layers: params stacked ``(L, ...)``, pipelined over 'pp' by
      :func:`pipeline_apply` (remat per stage — jax.checkpoint inside the
      pipeline tick, scan over the stage's layer chunk);
    - tp: Megatron specs from :func:`transformer_tp_rules` applied to the
      stacked leaves (shifted past the layer dim) and to the
      embedding/head params;
    - dp: batch sharded ``P('dp')``; GSPMD inserts the gradient
      all-reduce.

    Returns ``(step, ref_step, params, batch_feed)``: ``step`` is the
    pipelined hybrid train step (jit with donation at the call site);
    ``ref_step`` is the numerically-identical sequential form (plain
    scan over layers, no pipeline) for single-device loss-matching;
    both are ``(params, ids, mlm_labels, nsp_label) -> (loss,
    new_params)`` over the SAME params pytree.
    """
    for ax in ("dp", "tp", "pp"):
        enforce(ax in mesh.shape, "hybrid mesh needs axis %r", ax)

    import numpy as np

    from ..core.random import seed as set_seed
    from ..models.bert import BertConfig, BertForPretraining
    from ..nn.layer import stacked_parameters
    from ..ops import loss as L
    from ..ops.fused_loss import mean_linear_cross_entropy
    from .sharding import infer_param_spec, transformer_tp_rules

    if cfg is None:
        cfg = BertConfig(vocab_size=512, hidden_size=64, num_layers=4,
                         num_heads=4, intermediate_size=128,
                         max_position=64, dropout=0.0)
    n_pp, n_dp = mesh.shape["pp"], mesh.shape["dp"]
    enforce(cfg.num_layers % (n_pp * virtual_stages) == 0,
            "pp size x virtual stages (%s x %s) must divide num_layers %s",
            n_pp, virtual_stages, cfg.num_layers)
    enforce(batch % (num_microbatches * n_dp) == 0,
            "microbatches x dp (%s) must divide batch size %s",
            num_microbatches * n_dp, batch)
    enforce(cfg.dropout == 0.0,
            "hybrid BERT step needs dropout == 0 (deterministic "
            "loss-match contract)")

    set_seed(seed)
    model = BertForPretraining(cfg)
    template = model.bert.encoder.layers[0]
    # Switch-MoE blocks (cfg.moe_experts > 0): the per-layer load-balance
    # aux + router-z losses ride the pipeline's aux carry (aux_size=2,
    # microbatch-mean — see pipeline_apply) and fold into the objective
    # with the Switch-paper weights; experts shard over 'ep' when the
    # mesh has that axis, completing dp x tp x pp x ep (VERDICT r4 #4)
    moe = getattr(cfg, "moe_experts", 0) > 0
    moe_aux_w, moe_z_w = 0.01, 1e-3

    run_blocks, ring = _stacked_blocks_runner(
        mesh, template, moe, num_microbatches, pipeline_schedule,
        virtual_stages)
    # split: stacked encoder-layer params | everything else; the
    # persistent stack holds RING order under the interleaved schedule
    # (device-contiguous chunks — a logical-order 'pp'-sharded stack
    # would all-to-all every weight every step)
    stacked = stacked_parameters(model.bert.encoder.layers)
    rest = {k: v for k, v in model.named_parameters().items()
            if ".encoder.layers." not in k}
    rules = transformer_tp_rules()
    if moe and "ep" in mesh.shape:
        from ..nn.moe import expert_param_spec

        rules = rules + expert_param_spec("ep")
    params = _place_hybrid_params(mesh, stacked, rest, rules, ring,
                                  n_pp, virtual_stages)

    rng = np.random.default_rng(seed)
    ids = rng.integers(0, cfg.vocab_size, size=(batch, seq_len))
    mlm_labels = np.where(rng.random((batch, seq_len)) < 0.15,
                          rng.integers(0, cfg.vocab_size,
                                       size=(batch, seq_len)), -100)
    nsp_label = rng.integers(0, 2, size=(batch,))
    dsh = NamedSharding(mesh, P("dp"))
    feed = tuple(jax.device_put(jnp.asarray(a), dsh)
                 for a in (ids, mlm_labels, nsp_label))
    sub = _sub

    def loss_fn(p, ids, mlm_labels, nsp_label, *, pipelined):
        r = p["rest"]
        x, _ = model.bert.embeddings.functional_call(
            sub(r, "bert.embeddings"), ids, training=False)
        h, aux = run_blocks(p["layers"], x, pipelined=pipelined)
        pooled, _ = model.bert.pooler.functional_call(
            sub(r, "bert.pooler"), h[:, 0])
        hm, _ = model.mlm_transform.functional_call(
            sub(r, "mlm_transform"), h)
        hm, _ = model.mlm_norm.functional_call(sub(r, "mlm_norm"), hm)
        b, t, d = hm.shape
        mlm = mean_linear_cross_entropy(
            hm.reshape(b * t, d), r["mlm_decoder.weight"],
            r["mlm_decoder.bias"], mlm_labels.reshape(-1),
            chunk=vocab_chunk, ignore_index=-100)
        nsp_logits, _ = model.nsp.functional_call(sub(r, "nsp"), pooled)
        nsp = jnp.mean(L.softmax_with_cross_entropy(nsp_logits, nsp_label))
        loss = mlm + nsp
        if moe:
            # aux = microbatch-mean of per-layer sums: [load-balance,
            # router-z]
            loss = loss + moe_aux_w * aux[0] + moe_z_w * aux[1]
        return loss

    def _make_step(pipelined):
        def step(p, ids, mlm_labels, nsp_label):
            loss, grads = jax.value_and_grad(
                lambda p_: loss_fn(p_, ids, mlm_labels, nsp_label,
                                   pipelined=pipelined))(p)
            new_p = jax.tree_util.tree_map(lambda w, g: w - lr * g,
                                           p, grads)
            return loss, new_p

        return step

    return _make_step(True), _make_step(False), params, feed


def build_gpt_hybrid_step(mesh, *, cfg=None, batch: int = 8,
                          seq_len: int = 16, num_microbatches: int = 2,
                          lr: float = 0.01, seed: int = 0,
                          vocab_chunk: int = 256,
                          pipeline_schedule: str = "gpipe",
                          virtual_stages: int = 1):
    """The MODERN flagship composed-3D step: the real GPTForCausalLM
    stack — RoPE + GQA attention (flash path on TPU), RMSNorm pre-norm
    blocks, SwiGLU (or Switch-MoE) FFNs, tied-embedding fused chunked
    linear-CE next-token head — trained under ONE dp x tp x pp mesh,
    the decoder-LM sibling of :func:`build_bert_hybrid_step` (same
    decomposition, same return contract; feed is ``(ids,)``).

    tp notes: GQA's kv heads must divide the tp axis; the SwiGLU
    gate/up/down split and the ``embed`` vocab sharding come from
    :func:`transformer_tp_rules`; the TIED head reuses the 'tp'-sharded
    embedding transposed (row-sharded table -> column-parallel head —
    GSPMD inserts the same collectives Megatron's vocab-parallel head
    uses)."""
    for ax in ("dp", "tp", "pp"):
        enforce(ax in mesh.shape, "hybrid mesh needs axis %r", ax)

    import numpy as np

    from ..core.random import seed as set_seed
    from ..models.gpt import GPTConfig, GPTForCausalLM
    from ..nn.layer import stacked_parameters
    from ..ops.fused_loss import mean_linear_cross_entropy
    from .sharding import infer_param_spec, transformer_tp_rules

    if cfg is None:
        cfg = GPTConfig(vocab_size=512, hidden_size=64, num_layers=4,
                        num_heads=4, num_kv_heads=2,
                        intermediate_size=128, max_position=64)
    n_pp, n_dp = mesh.shape["pp"], mesh.shape["dp"]
    enforce(cfg.num_layers % (n_pp * virtual_stages) == 0,
            "pp size x virtual stages (%s x %s) must divide num_layers "
            "%s", n_pp, virtual_stages, cfg.num_layers)
    enforce(batch % (num_microbatches * n_dp) == 0,
            "microbatches x dp (%s) must divide batch size %s",
            num_microbatches * n_dp, batch)
    enforce(cfg.dropout == 0.0,
            "hybrid GPT step needs dropout == 0 (deterministic "
            "loss-match contract)")
    enforce(cfg.tie_embeddings,
            "hybrid GPT step assumes the tied head (embed.weight.T)")

    set_seed(seed)
    model = GPTForCausalLM(cfg)
    template = model.blocks[0]
    moe = cfg.moe_experts > 0
    moe_aux_w, moe_z_w = 0.01, 1e-3

    run_blocks, ring = _stacked_blocks_runner(
        mesh, template, moe, num_microbatches, pipeline_schedule,
        virtual_stages)
    stacked = stacked_parameters(list(model.blocks))
    rest = {k: v for k, v in model.named_parameters().items()
            if not k.startswith("blocks.")}
    rules = transformer_tp_rules()
    if moe and "ep" in mesh.shape:
        from ..nn.moe import expert_param_spec

        rules = rules + expert_param_spec("ep")
    params = _place_hybrid_params(mesh, stacked, rest, rules, ring,
                                  n_pp, virtual_stages)

    rng = np.random.default_rng(seed)
    ids = rng.integers(0, cfg.vocab_size, size=(batch, seq_len))
    feed = (jax.device_put(jnp.asarray(ids),
                           NamedSharding(mesh, P("dp"))),)

    def loss_fn(p, ids, *, pipelined):
        r = p["rest"]
        x = r["embed.weight"][ids]                # (B, T, D) gather
        h, aux = run_blocks(p["layers"], x, pipelined=pipelined)
        hn, _ = model.norm_f.functional_call(_sub(r, "norm_f"), h)
        labels = jnp.concatenate(
            [ids[:, 1:], jnp.full((ids.shape[0], 1), -100, ids.dtype)],
            axis=1)
        b, t, d = hn.shape
        loss = mean_linear_cross_entropy(
            hn.reshape(b * t, d), r["embed.weight"].T, None,
            labels.reshape(-1), chunk=vocab_chunk, ignore_index=-100)
        if moe:
            loss = loss + moe_aux_w * aux[0] + moe_z_w * aux[1]
        return loss

    def _make_step(pipelined):
        def step(p, ids):
            loss, grads = jax.value_and_grad(
                lambda p_: loss_fn(p_, ids, pipelined=pipelined))(p)
            new_p = jax.tree_util.tree_map(lambda w, g: w - lr * g,
                                           p, grads)
            return loss, new_p

        return step

    return _make_step(True), _make_step(False), params, feed
