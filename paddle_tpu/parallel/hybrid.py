"""Composed 3D parallelism — dp x tp x pp on ONE mesh, one module.

The reference composes its parallel modes by program rewriting (data
parallelism via multi_devices_graph_pass, PS sharding via the
transpiler — reference: framework/ir/multi_devices_graph_pass/
multi_devices_graph_pass.cc:165, transpiler/distribute_transpiler.py:283);
a real cluster job stacks them. The TPU-native composition is one mesh
with named axes and one jitted training step:

- **dp**: the batch is sharded ``P('dp')``; GSPMD inserts the gradient
  all-reduce.
- **tp**: Megatron column/row sharding inside each block (weights
  ``P(..., 'tp')`` / ``P('tp', ...)``); GSPMD inserts the activation
  all-reduce.
- **pp**: the block stack is pipelined by :func:`~paddle_tpu.parallel.
  pipeline_apply`, whose ``shard_map`` is manual ONLY over 'pp'
  (``axis_names={'pp'}``) so the dp/tp shardings ride through the
  pipeline body as auto axes — all three collectives land in a single
  compiled module (all-reduce for dp/tp, collective-permute for pp).

``build_hybrid_transformer_step`` is the executable form of this recipe:
a tiny transformer-style stack whose single train step exercises every
axis. The multichip dryrun and tests/test_hybrid_parallel.py run it; it
is deliberately small enough to compile on an 8-device CPU simulation.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.enforce import enforce
from .pipeline import pipeline_apply
from .sharding import constraint


def build_hybrid_transformer_step(mesh, *, layers: int = 4, d_model: int = 16,
                                  d_ff: int = 32, num_classes: int = 8,
                                  batch: int = 8, num_microbatches: int = 2,
                                  lr: float = 0.1, seed: int = 0):
    """A full dp x tp x pp training step on ``mesh`` (axes 'dp','tp','pp').

    Returns ``(step, params, batch_xy)`` where ``step(params, x, y) ->
    (loss, new_params)`` is ready to jit with donation. Layer weights are
    stacked ``(L, ...)`` and placed ``P('pp', ..., 'tp')`` (column) /
    ``P('pp', 'tp', ...)`` (row) — Megatron inside each pipeline stage.
    """
    for ax in ("dp", "tp", "pp"):
        enforce(ax in mesh.shape, "hybrid mesh needs axis %r", ax)
    L, n_pp = layers, mesh.shape["pp"]
    enforce(L % n_pp == 0, "pp size %s must divide layer count %s", n_pp, L)
    div = num_microbatches * mesh.shape["dp"]
    enforce(batch % div == 0,
            "microbatches x dp (%s) must divide batch size %s", div, batch)

    import numpy as np

    rng = np.random.default_rng(seed)
    scale = d_model ** -0.5

    def put(a, spec):
        return jax.device_put(jnp.asarray(a), NamedSharding(mesh, spec))

    params = {
        # Megatron pair per layer: w1 column-parallel, w2 row-parallel,
        # both stacked over the pipeline's layer dim
        "w1": put(rng.normal(scale=scale, size=(L, d_model, d_ff))
                  .astype(np.float32), P("pp", None, "tp")),
        "w2": put(rng.normal(scale=scale, size=(L, d_ff, d_model))
                  .astype(np.float32), P("pp", "tp", None)),
        "head": put(rng.normal(scale=scale, size=(d_model, num_classes))
                    .astype(np.float32), P()),
    }
    x = put(rng.normal(size=(batch, d_model)).astype(np.float32), P("dp"))
    y = put(rng.integers(0, num_classes, size=(batch,)), P("dp"))

    def block_fn(p, h):
        # column-parallel matmul -> tp-sharded activation -> row-parallel
        # matmul whose contraction over the sharded dim becomes a GSPMD
        # all-reduce; residual keeps the signal well-conditioned
        h1 = jnp.tanh(h @ p["w1"])
        h1 = constraint(h1, P("dp", "tp"),
                        mesh=jax.sharding.get_abstract_mesh())
        return h + h1 @ p["w2"]

    def loss_fn(p, x, y):
        h = pipeline_apply(block_fn, {"w1": p["w1"], "w2": p["w2"]}, x,
                           num_microbatches=num_microbatches, mesh=mesh)
        h = constraint(h, P("dp"), mesh=mesh)
        logits = h @ p["head"]
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=1))

    def step(p, x, y):
        loss, grads = jax.value_and_grad(loss_fn)(p, x, y)
        new_p = jax.tree_util.tree_map(lambda w, g: w - lr * g, p, grads)
        return loss, new_p

    return step, params, (x, y)
