"""Pipeline parallelism — stage-partitioned microbatch pipeline over 'pp'.

Green-field design (the reference has no pipeline parallelism at all,
SURVEY.md §2.5/§7: its only model-parallel-adjacent feature is PS-sharded
optimizer state, reference: transpiler/distribute_transpiler.py:702).

TPU-native shape: the repeated block's parameters are **stacked** along a
leading layer axis and sharded ``P('pp')`` so each pipeline stage holds a
contiguous chunk of layers in its HBM. One ``shard_map`` + ``lax.scan``
runs the classic GPipe schedule: at tick ``t`` every stage applies its
layers to the activation it holds, then the activations rotate one stage
forward via ``lax.ppermute`` (a single ICI hop — pipeline traffic never
leaves neighbouring chips). Stage 0 injects microbatch ``t``; the last
stage banks its result. ``n + m - 1`` ticks stream ``m`` microbatches
through ``n`` stages (bubble fraction ``(n-1)/(n+m-1)``).

Backward is pure autodiff: the transpose of ``ppermute`` is the reverse
rotation, so the gradient pipeline runs automatically in the opposite
direction — no hand-written 1F1B engine. Each stage application is wrapped
in ``jax.checkpoint`` so the backward recomputes block activations instead
of storing every tick's intermediates.

Two schedules (``schedule=`` on :func:`pipeline_apply`):

- ``"gpipe"`` — each device holds ONE contiguous chunk of ``L/n`` layers;
  ``n + m - 1`` ticks, bubble ``(n-1)/(n+m-1)``.
- ``"interleaved"`` — the Megatron-style virtual-stage schedule: each
  device holds ``v`` round-robin chunks of ``L/(n*v)`` layers (device
  ``d`` owns chunks ``d, n+d, 2n+d, …``) and microbatches circulate the
  ring ``v`` times, injected in bursts of ``n``. A tick now costs
  ``1/v`` of a GPipe tick, so the pipe fills/drains ``v×`` faster:
  bubble ``(n-1)/(m*v + n - 1)`` (for ``n | m``) vs GPipe's
  ``(n-1)/(m+n-1)`` — e.g. 16% vs 27% at n=4, m=8, v=2. The backward
  pipeline inherits the same interleaving through autodiff. Cost: ``v×``
  more ppermute hops of the same total activation traffic, still
  neighbour-only ICI.

Constraints (standard for this schedule): every block maps activations of
one uniform shape to the same shape (transformer blocks qualify); the
stacked layer count must divide ``n * virtual_stages``; microbatches all
share one shape.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.enforce import enforce
from ..core.mesh import get_mesh
from ..utils.compat import shard_map


def _stack_to_stages(stacked_params, n_stages: int):
    """(L, ...) leaves → (n_stages, L//n_stages, ...)."""

    def reshape(leaf):
        L = leaf.shape[0]
        return leaf.reshape(n_stages, L // n_stages, *leaf.shape[1:])

    return jax.tree_util.tree_map(reshape, stacked_params)


def _interleave_to_stages(stacked_params, n: int, v: int):
    """LOGICAL-order (L, ...) leaves → (n, v, L/(n*v), ...): device ``d``
    slot ``j`` holds chunk ``j*n + d`` — the round-robin layout the
    interleaved schedule walks (a microbatch's j-th ring pass applies
    chunks ``j*n .. j*n + n - 1`` in device order).

    NOTE: on a 'pp'-sharded stack this transpose is a cross-device
    RESHARD (XLA lowers it to all-to-alls of every weight, each step).
    Persistent training state should store the stack in RING ORDER
    (:func:`ring_order_layers`) and pass ``layers_in_ring_order=True`` so
    the per-step reshape stays device-local."""

    def reshape(leaf):
        L = leaf.shape[0]
        k = L // (n * v)
        a = leaf.reshape(v, n, k, *leaf.shape[1:])
        return jnp.swapaxes(a, 0, 1)

    return jax.tree_util.tree_map(reshape, stacked_params)


def _ring_to_stages(stacked_params, n: int, v: int):
    """RING-order (L, ...) leaves → (n, v, k, ...) by pure local reshape
    (ring order stores device d's chunks contiguously: rows
    [d*v*k, (d+1)*v*k) are chunks d, n+d, 2n+d, …)."""

    def reshape(leaf):
        L = leaf.shape[0]
        k = L // (n * v)
        return leaf.reshape(n, v, k, *leaf.shape[1:])

    return jax.tree_util.tree_map(reshape, stacked_params)


def ring_order_layers(stacked_params, n: int, v: int, *,
                      inverse: bool = False):
    """Permute a stacked (L, ...) pytree between LOGICAL layer order and
    the interleaved schedule's RING order (device-contiguous round-robin
    chunks). Apply once at parameter-placement time so each training
    step's stage reshape is local — leaving the stack logical would
    all-to-all every weight on every step. ``inverse=True`` maps ring
    order back to logical (the sequential-oracle path)."""

    def perm(leaf):
        L = leaf.shape[0]
        k = L // (n * v)
        if inverse:  # ring (n, v, k) layout -> logical (v, n, k)
            a = leaf.reshape(n, v, k, *leaf.shape[1:])
        else:        # logical (v, n, k) layout -> ring (n, v, k)
            a = leaf.reshape(v, n, k, *leaf.shape[1:])
        return jnp.swapaxes(a, 0, 1).reshape(L, *leaf.shape[1:])

    return jax.tree_util.tree_map(perm, stacked_params)


def gpipe_ticks(n: int, m: int) -> int:
    """GPipe schedule length in ticks (one tick = one L/n-layer stage)."""
    return n + m - 1


def interleaved_ticks(n: int, m: int, v: int) -> int:
    """Interleaved schedule length in ticks (one tick = one L/(n*v)-layer
    chunk — i.e. 1/v of a GPipe tick). Microbatches are injected in
    bursts of n; burst b starts at tick b*v*n."""
    bursts = -(-m // n)
    o_last = (m - 1) - (bursts - 1) * n
    return (bursts - 1) * v * n + o_last + (v - 1) * n + n


def bubble_fraction(n: int, m: int, schedule: str = "gpipe",
                    virtual_stages: int = 1) -> float:
    """Idle fraction of each device's timeline under the schedule —
    the quantity the interleaved schedule exists to shrink."""
    enforce(schedule in ("gpipe", "interleaved"),
            "schedule must be 'gpipe' or 'interleaved', got %r", schedule)
    if schedule == "interleaved":
        t = interleaved_ticks(n, m, virtual_stages)
        return 1.0 - (m * virtual_stages) / t
    enforce(virtual_stages == 1,
            "gpipe schedule has no virtual stages (got %s)", virtual_stages)
    return 1.0 - m / gpipe_ticks(n, m)


def _aux_block_step(block_fn):
    """Scan body applying one aux-carrying block: the SINGLE definition
    of the aux accumulation (f32, summed over layers) shared by both
    schedule inners and the sequential folds — the microbatch-mean aux
    contract must not be able to diverge between the pipelined paths and
    their loss-match oracles."""
    def one_block(carry, p):
        h, a = carry
        h, al = block_fn(p, h)
        return (h, a + al.astype(jnp.float32)), None

    return one_block


def microbatched_aux_fold(block_fn, stacked_params, x, *,
                          num_microbatches, aux_size, remat=True):
    """Sequential per-MICROBATCH fold of an aux-carrying block stack:
    ``(out (B, ...), aux_mean (aux_size,))`` with aux summed over layers
    per microbatch and averaged over microbatches — numerically the same
    definition every pipelined schedule computes (MoE routing state is
    microbatch-local, so a full-batch fold would differ). Used by the
    n == 1 pipeline short-circuit AND by sequential loss-match oracles
    (parallel/hybrid.py)."""
    body = _aux_block_step(block_fn)
    if remat:
        body = jax.checkpoint(body)
    B, m = x.shape[0], num_microbatches
    x_mb = x.reshape(m, B // m, *x.shape[1:])

    def per_mb(_, mb):
        out = lax.scan(body, (mb, jnp.zeros((aux_size,), jnp.float32)),
                       stacked_params)[0]
        return None, out

    _, (h_mb, a_mb) = lax.scan(per_mb, None, x_mb)
    return h_mb.reshape(B, *h_mb.shape[2:]), jnp.mean(a_mb, axis=0)


def _pipeline_inner(params_nk, x_mb, *, block_fn, axis, n, m, remat,
                    aux_size=0):
    # params_nk leaves: (1, k, ...) — this stage's chunk; squeeze the shard dim
    p_local = jax.tree_util.tree_map(lambda a: a[0], params_nk)
    idx = lax.axis_index(axis)
    has_aux = aux_size > 0
    # x_mb: (m, mb, ...) replicated — stage 0 reads, others ignore

    if has_aux:
        # aux contract: block_fn(p, h) -> (h, aux (A,)); each
        # microbatch's aux vector RIDES THE RING with its activation,
        # summed over the layers it passes through, and is banked by the
        # last stage next to the output (MoE load-balance/z losses —
        # VERDICT r4 #4)
        def stage_fn(p_k, h, a):
            return lax.scan(_aux_block_step(block_fn), (h, a), p_k)[0]
    else:
        def stage_fn(p_k, h):
            def one_block(h, p):
                return block_fn(p, h), None

            return lax.scan(one_block, h, p_k)[0]

    if remat:
        stage_fn = jax.checkpoint(stage_fn)

    mb_shape = x_mb.shape[1:]
    fwd_perm = [(i, i + 1) for i in range(n - 1)]

    def tick(carry, t):
        if has_aux:
            state, aux_state, outbuf, auxbuf = carry
        else:
            state, outbuf = carry
        # stage 0 injects microbatch t (clipped: past-the-end ticks feed
        # a dummy that never reaches the output window)
        mb = lax.dynamic_index_in_dim(x_mb, jnp.clip(t, 0, m - 1), 0,
                                      keepdims=False)
        inp = jnp.where(idx == 0, mb, state)
        if has_aux:
            a_in = jnp.where(idx == 0, jnp.zeros_like(aux_state),
                             aux_state)
            out, a_out = stage_fn(p_local, inp, a_in)
        else:
            out = stage_fn(p_local, inp)
        # last stage banks microbatch t-(n-1) once the pipe is full
        pos = t - (n - 1)
        write = jnp.logical_and(idx == n - 1, pos >= 0)
        upd = lax.dynamic_update_index_in_dim(
            outbuf, out.astype(outbuf.dtype), jnp.clip(pos, 0, m - 1), 0)
        outbuf = jnp.where(write, upd, outbuf)
        if has_aux:
            aupd = lax.dynamic_update_index_in_dim(
                auxbuf, a_out, jnp.clip(pos, 0, m - 1), 0)
            auxbuf = jnp.where(write, aupd, auxbuf)
        if n > 1:
            state = lax.ppermute(out, axis, fwd_perm)
            if has_aux:
                aux_state = lax.ppermute(a_out, axis, fwd_perm)
        else:
            state = out
            if has_aux:
                aux_state = a_out
        carry = ((state, aux_state, outbuf, auxbuf) if has_aux
                 else (state, outbuf))
        return carry, None

    state0 = jnp.zeros(mb_shape, x_mb.dtype)
    outbuf0 = jnp.zeros((m,) + mb_shape, jnp.result_type(x_mb.dtype))
    init = ((state0, jnp.zeros((aux_size,), jnp.float32), outbuf0,
             jnp.zeros((m, aux_size), jnp.float32)) if has_aux
            else (state0, outbuf0))
    carry, _ = lax.scan(tick, init, jnp.arange(n + m - 1))
    # only the last stage's buffer is real; mask+psum broadcasts it so the
    # result is replicated over 'pp' (loss/optimizer run identically on all
    # stages — the XLA partitioner then dedups what it can). n == 1 never
    # reaches here: pipeline_apply short-circuits to a sequential fold
    if has_aux:
        _, _, outbuf, auxbuf = carry
        outbuf = jnp.where(idx == n - 1, outbuf, jnp.zeros_like(outbuf))
        auxbuf = jnp.where(idx == n - 1, auxbuf, jnp.zeros_like(auxbuf))
        return lax.psum(outbuf, axis), lax.psum(auxbuf, axis)
    _, outbuf = carry
    outbuf = jnp.where(idx == n - 1, outbuf, jnp.zeros_like(outbuf))
    return lax.psum(outbuf, axis)


def _interleaved_inner(params_nvk, x_mb, *, block_fn, axis, n, m, v,
                       remat, aux_size=0):
    """One device's lockstep loop of the interleaved schedule.

    Tick arithmetic (s = t - device_index ≥ 0 inside the busy window):
    burst b = s // (v*n), r = s % (v*n), ring pass j = r // n, burst
    offset o = r % n, microbatch = b*n + o. Device d applies chunk
    j*n + d (local slot j) to the activation the ring just delivered;
    stage 0 overrides with a fresh injection when j == 0, the last stage
    banks after its j == v-1 application. The full ring permutation
    (n-1 → 0 wrap) carries activations into their next pass. With
    ``aux_size``, each microbatch's (A,) aux vector travels the full
    v-pass ring journey with its activation (see _pipeline_inner)."""
    p_local = jax.tree_util.tree_map(lambda a: a[0], params_nvk)  # (v,k,...)
    idx = lax.axis_index(axis)
    has_aux = aux_size > 0

    if has_aux:
        def chunk_fn(p_vk, j, h, a):
            p_k = jax.tree_util.tree_map(
                lambda arr: lax.dynamic_index_in_dim(arr, j, 0,
                                                     keepdims=False),
                p_vk)
            return lax.scan(_aux_block_step(block_fn), (h, a), p_k)[0]
    else:
        def chunk_fn(p_vk, j, h):
            p_k = jax.tree_util.tree_map(
                lambda a: lax.dynamic_index_in_dim(a, j, 0,
                                                   keepdims=False),
                p_vk)

            def one_block(h, p):
                return block_fn(p, h), None

            return lax.scan(one_block, h, p_k)[0]

    if remat:
        chunk_fn = jax.checkpoint(chunk_fn)

    mb_shape = x_mb.shape[1:]
    perm = [(i, (i + 1) % n) for i in range(n)]  # full ring: passes wrap

    def tick(carry, t):
        if has_aux:
            state, aux_state, outbuf, auxbuf = carry
        else:
            state, outbuf = carry
        s = jnp.maximum(t - idx, 0)  # pre-window ticks compute garbage
        r = s % (v * n)
        j = r // n
        mb = (s // (v * n)) * n + r % n
        inj = lax.dynamic_index_in_dim(x_mb, jnp.clip(mb, 0, m - 1), 0,
                                       keepdims=False)
        fresh = jnp.logical_and(idx == 0, j == 0)
        inp = jnp.where(fresh, inj, state)
        if has_aux:
            a_in = jnp.where(fresh, jnp.zeros_like(aux_state), aux_state)
            out, a_out = chunk_fn(p_local, j, inp, a_in)
        else:
            out = chunk_fn(p_local, j, inp)
        write = jnp.logical_and(
            jnp.logical_and(idx == n - 1, j == v - 1),
            jnp.logical_and(mb < m, t >= idx))
        upd = lax.dynamic_update_index_in_dim(
            outbuf, out.astype(outbuf.dtype), jnp.clip(mb, 0, m - 1), 0)
        outbuf = jnp.where(write, upd, outbuf)
        if has_aux:
            aupd = lax.dynamic_update_index_in_dim(
                auxbuf, a_out, jnp.clip(mb, 0, m - 1), 0)
            auxbuf = jnp.where(write, aupd, auxbuf)
        state = lax.ppermute(out, axis, perm) if n > 1 else out
        if has_aux:
            aux_state = (lax.ppermute(a_out, axis, perm) if n > 1
                         else a_out)
            return (state, aux_state, outbuf, auxbuf), None
        return (state, outbuf), None

    state0 = jnp.zeros(mb_shape, x_mb.dtype)
    outbuf0 = jnp.zeros((m,) + mb_shape, jnp.result_type(x_mb.dtype))
    T = interleaved_ticks(n, m, v)
    init = ((state0, jnp.zeros((aux_size,), jnp.float32), outbuf0,
             jnp.zeros((m, aux_size), jnp.float32)) if has_aux
            else (state0, outbuf0))
    carry, _ = lax.scan(tick, init, jnp.arange(T))
    # n == 1 never reaches here (pipeline_apply short-circuits)
    if has_aux:
        _, _, outbuf, auxbuf = carry
        outbuf = jnp.where(idx == n - 1, outbuf, jnp.zeros_like(outbuf))
        auxbuf = jnp.where(idx == n - 1, auxbuf, jnp.zeros_like(auxbuf))
        return lax.psum(outbuf, axis), lax.psum(auxbuf, axis)
    _, outbuf = carry
    outbuf = jnp.where(idx == n - 1, outbuf, jnp.zeros_like(outbuf))
    return lax.psum(outbuf, axis)


def pipeline_apply(block_fn: Callable, stacked_params, x, *,
                   num_microbatches: int, axis: str = "pp",
                   mesh=None, remat: bool = True,
                   schedule: str = "gpipe", virtual_stages: int = 1,
                   layers_in_ring_order: bool = False,
                   aux_size: int = 0):
    """Run ``x`` through ``L`` stacked layers as an ``n``-stage pipeline.

    - ``block_fn(params_l, h) -> h``: applies ONE layer (uniform shape).
    - ``stacked_params``: pytree whose leaves have leading dim ``L``
      (``L % n == 0``); stage ``s`` gets layers ``[s*L/n, (s+1)*L/n)``.
    - ``x``: global batch ``(B, ...)`` with ``B % num_microbatches == 0``.
    - ``schedule``: ``"gpipe"`` (contiguous chunks) or ``"interleaved"``
      (``virtual_stages`` round-robin chunks per device — lower bubble,
      see module docstring; requires ``L % (n * virtual_stages) == 0``).
    - ``layers_in_ring_order``: the stacked leaves were pre-permuted with
      :func:`ring_order_layers` (persistent 'pp'-sharded training state
      should be — the per-step stage split is then a LOCAL reshape;
      logical-order sharded stacks pay a weight all-to-all per step).
    - ``aux_size``: when > 0 the block contract widens to
      ``block_fn(params_l, h) -> (h, aux)`` with ``aux`` a float32
      ``(aux_size,)`` vector per layer (MoE load-balance/router-z losses
      — VERDICT r4 #4). Each microbatch's aux rides the pipeline ring
      with its activation, summed over all ``L`` layers, and the return
      becomes ``(out, aux_mean)`` where ``aux_mean`` is the
      MICROBATCH-MEAN of the per-microbatch layer sums — the pipelined
      aux definition (each microbatch routes independently, so a
      full-batch aux would not be computable without materializing every
      microbatch's router state).

    Returns the pipelined equivalent of folding ``block_fn`` over all ``L``
    layers, replicated over the 'pp' axis.
    """
    mesh = mesh or get_mesh()
    n = mesh.shape[axis]
    m = num_microbatches
    enforce(schedule in ("gpipe", "interleaved"),
            "schedule must be 'gpipe' or 'interleaved', got %r", schedule)
    v = int(virtual_stages)
    enforce(v >= 1, "virtual_stages must be >= 1, got %s", v)
    if schedule == "gpipe":
        enforce(v == 1, "gpipe schedule has no virtual stages; use "
                "schedule='interleaved' with virtual_stages=%s", v)
    leaves = jax.tree_util.tree_leaves(stacked_params)
    enforce(leaves, "stacked_params must be a non-empty pytree")
    L = leaves[0].shape[0]
    enforce(all(l.shape[0] == L for l in leaves),
            "all stacked_params leaves must share leading layer dim %s", L)
    enforce(L % (n * v) == 0,
            "layer count %s must divide pp size x virtual stages (%s x %s)",
            L, n, v)
    B = x.shape[0]
    enforce(B % m == 0,
            "num_microbatches %s must divide batch size %s", m, B)
    enforce(not layers_in_ring_order
            or (schedule == "interleaved" and v > 1),
            "layers_in_ring_order only applies to the interleaved "
            "schedule with virtual_stages > 1")
    if n == 1:
        # a 1-stage pipeline IS the sequential fold; skip the shard_map
        # entirely — the degenerate manual region would still wrap every
        # auto dp/tp collective in a size-1 manual subgroup, which the
        # SPMD partitioner rejects in MULTI-PROCESS compiles (seen with
        # the dcn_dp x dp x tp hybrid mesh, pp = 1)
        fold_params = (ring_order_layers(stacked_params, n, v,
                                         inverse=True)
                       if layers_in_ring_order else stacked_params)

        if aux_size > 0:
            # the pipelined aux is per-MICROBATCH (routing state is
            # microbatch-local); the degenerate fold must microbatch
            # identically, or its MoE capacity/queues — and therefore
            # its loss — would differ from every n > 1 configuration
            h, aux = microbatched_aux_fold(
                block_fn, fold_params, x, num_microbatches=m,
                aux_size=aux_size, remat=remat)
            return h.astype(jnp.result_type(x.dtype)), aux

        def fold(h, p_l):
            return block_fn(p_l, h), None

        body = jax.checkpoint(fold) if remat else fold
        # match the pipelined path's output dtype contract (outbuf is
        # result_type(x.dtype) there, whatever block_fn returns)
        return lax.scan(body, x, fold_params)[0].astype(
            jnp.result_type(x.dtype))
    x_mb = x.reshape(m, B // m, *x.shape[1:])

    if schedule == "interleaved" and v > 1:
        params_staged = (_ring_to_stages(stacked_params, n, v)
                         if layers_in_ring_order
                         else _interleave_to_stages(stacked_params, n, v))
    else:
        params_staged = _stack_to_stages(stacked_params, n)
    # jit is required: remat's closed_call can't evaluate eagerly inside
    # shard_map (and the production path is jitted anyway — no-op there).
    # Cached by configuration so eager per-step callers hit the XLA compile
    # cache instead of retracing a fresh closure every call.
    fn = _jitted_pipeline(block_fn, mesh, axis, n, m, remat, schedule, v,
                          aux_size)
    if aux_size > 0:
        out_mb, aux_mb = fn(params_staged, x_mb)
        return (out_mb.reshape(B, *out_mb.shape[2:]),
                jnp.mean(aux_mb, axis=0))
    out_mb = fn(params_staged, x_mb)
    return out_mb.reshape(B, *out_mb.shape[2:])


@functools.lru_cache(maxsize=64)
def _jitted_pipeline(block_fn, mesh, axis, n, m, remat, schedule="gpipe",
                     v=1, aux_size=0):
    if schedule == "interleaved" and v > 1:
        inner = functools.partial(_interleaved_inner, block_fn=block_fn,
                                  axis=axis, n=n, m=m, v=v, remat=remat,
                                  aux_size=aux_size)
    else:
        inner = functools.partial(_pipeline_inner, block_fn=block_fn,
                                  axis=axis, n=n, m=m, remat=remat,
                                  aux_size=aux_size)
    out_specs = (P(), P()) if aux_size > 0 else P()

    def wrapper(params_staged, x_mb):
        # specs are shape-independent, built from the pytree at trace time
        stage_spec = jax.tree_util.tree_map(
            lambda a: P(axis, *([None] * (a.ndim - 1))), params_staged)
        # manual ONLY over the pipeline axis: every other mesh axis stays
        # auto, so dp batch sharding and tp weight sharding compose with
        # the pipeline in ONE module (GSPMD inserts their collectives
        # around the manual ppermute ring)
        return shard_map(inner, mesh=mesh,
                         in_specs=(stage_spec, P()),
                         out_specs=out_specs,
                         axis_names=frozenset({axis}),
                         check_vma=False)(params_staged, x_mb)

    return jax.jit(wrapper)


def stage_param_sharding(stacked_params, n_stages: int, axis: str = "pp",
                         mesh=None):
    """NamedShardings that place each stage's layer-chunk on its device —
    apply with jax.device_put to hold only 1/n of the layers per chip."""
    mesh = mesh or get_mesh()

    def spec(leaf):
        return NamedSharding(mesh, P(axis, *([None] * (leaf.ndim - 1))))

    return jax.tree_util.tree_map(spec, _stack_to_stages(stacked_params,
                                                         n_stages))


class GPipe:
    """Layer-level convenience: pipeline a uniform stack of blocks.

    ``blocks`` must be structurally identical Layers (same param pytree);
    their params are stacked along a new leading axis and fed to
    :func:`pipeline_apply`.
    """

    def __init__(self, blocks, *, num_microbatches: int, axis: str = "pp",
                 mesh=None, remat: bool = True, schedule: str = "gpipe",
                 virtual_stages: int = 1):
        enforce(len(blocks) > 0, "GPipe needs at least one block")
        self.blocks = list(blocks)
        self.num_microbatches = num_microbatches
        self.axis = axis
        self.mesh = mesh
        self.remat = remat
        self.schedule = schedule
        self.virtual_stages = virtual_stages
        self._template = self.blocks[0]

        # one stable closure for the pipeline compile cache (a fresh
        # closure per __call__ would defeat _jitted_pipeline's lru_cache)
        def _block_fn(p, h, _t=self._template):
            out, _ = _t.functional_call(p, h)
            return out

        self._block_fn = _block_fn

    def stacked_params(self):
        from ..nn.layer import stacked_parameters

        return stacked_parameters(self.blocks)

    def __call__(self, x, stacked_params=None):
        params = (self.stacked_params() if stacked_params is None
                  else stacked_params)
        return pipeline_apply(self._block_fn, params, x,
                              num_microbatches=self.num_microbatches,
                              axis=self.axis, mesh=self.mesh,
                              remat=self.remat, schedule=self.schedule,
                              virtual_stages=self.virtual_stages)
