"""Declarative sharding plans — dp/fsdp/tp compilation for models bigger
than one chip.

The reference's distributed story rewrote the program per cluster role
(reference: transpiler/distribute_transpiler.py:164); the TensorFlow
paper's dataflow/placement split (PAPERS.md) is the design here: a
:class:`Plan` *declares* how state and data map onto a named mesh, and
:func:`compile_step` turns any pure step function into one partitioned
XLA executable — ``pjit`` with full ``in_shardings``/``out_shardings``/
``donate_argnums`` when the plan carries explicit shardings (the
Gemma-31B-on-TPU table-stakes setup), or a ``shard_map``-wrapped
``jax.jit`` for pure data parallelism (the SNIPPETS [1]-[3] pattern).

Axes (a plan mesh always carries the three core axes, degenerate sizes
included, so specs can name any axis regardless of the active
parallelism):

- ``dp``:   data parallel — batch split, params replicated
- ``fsdp``: fully-sharded data parallel — batch split AND params/opt
  moments sharded (ZeRO-3 style); the default rule shards each large
  param's largest divisible axis over ``fsdp``
- ``tp``:   tensor parallel — param dims split per explicit/pattern rules
  (``parallel.sharding.transformer_tp_rules`` compose directly)
- ``ep``:   embedding-table axis (opt-in: the mesh carries it only when
  ``ep > 1``) — params registered via ``tables=`` shard their ROWS
  (dim 0) over ``ep``, the parameter-server giant-table layout
  (reference: distribute_lookup_table.py) without a parameter server.
  Batch leaves never split over ``ep``; ids replicate across it and
  the lookup reduces over it (``parallel.sharded_embedding``).

Spec resolution per param name: **explicit map > pattern rules >
largest-axis-over-fsdp default > replicated.** Derived shardings:
buffers resolve through the same rules (default replicated), optimizer
moments inherit their param's spec (``zeros_like`` on a placed param —
ZeRO-style, never re-replicated), RNG keys and loss replicate, batch
leaves split their leading dim over ``(dp, fsdp)``.

Sharded-by-construction state: :meth:`Plan.place` stages each leaf from
HOST memory straight into its target sharding (``jax.device_put`` with a
``NamedSharding`` transfers only each device's shard), so an
fsdp-sharded init peaks per device at ~1/N of the replicated bytes —
the full array never materializes on any one device.
"""

from __future__ import annotations

import contextlib
import re
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import telemetry
from ..core.enforce import enforce

PLAN_AXES = ("dp", "fsdp", "tp")
# the opt-in table axis (present in the plan mesh only when ep > 1 so
# ep=1 plans keep the exact legacy 3-axis mesh)
TABLE_AXIS = "ep"

Rule = Tuple[str, P]


@telemetry.cached_instruments
def _plan_metrics(reg):
    """Plan instrument set (only reached when telemetry is on)."""
    return {
        "resharding_copies": reg.counter(
            "pt_resharding_copies_total",
            "device-to-device resharding copies caught by "
            "guard_no_resharding (a steady-state planned step must "
            "stay at 0 — a copy means in_shardings drifted from the "
            "live placement)"),
    }


class Plan:
    """Declarative sharding plan over a ``(dp, fsdp, tp)`` mesh.

    - ``rules``: ordered ``(regex, PartitionSpec)`` pattern rules (first
      match wins — ``parallel.sharding.transformer_tp_rules()`` slots in
      directly).
    - ``params``: explicit per-name spec map; beats every rule.
    - default: when ``fsdp > 1``, a param above ``min_shard_size``
      elements shards its largest fsdp-divisible axis over ``"fsdp"``;
      everything else replicates.
    - ``batch_axes``: mesh axes the batch leading dim splits over
      (default ``("dp", "fsdp")`` — the standard FSDP layout).
    - ``tables``: regex patterns naming embedding-table params whose
      ROWS shard over the ``ep`` axis (``P("ep", None)``) when
      ``ep > 1`` — resolved between the explicit map and the pattern
      rules, so a table registration beats ``transformer_tp_rules``
      but an explicit per-name spec still wins.

    A spec that names an axis the leaf's dim doesn't divide by is
    dropped to the next resolution tier (same divisibility contract as
    :func:`..sharding.infer_param_spec`).
    """

    def __init__(self, dp: int = 1, fsdp: int = 1, tp: int = 1, *,
                 ep: int = 1,
                 rules: Sequence[Rule] = (),
                 params: Optional[Dict[str, P]] = None,
                 tables: Sequence[str] = (),
                 min_shard_size: int = 1024,
                 batch_axes: Sequence[str] = ("dp", "fsdp"),
                 devices: Optional[Sequence[jax.Device]] = None,
                 mesh: Optional[Mesh] = None,
                 grad_compression: Optional[str] = None):
        for name, s in (("dp", dp), ("fsdp", fsdp), ("tp", tp),
                        (TABLE_AXIS, ep)):
            enforce(s >= 1, "plan axis %s must be >= 1, got %s", name, s)
        self.dp, self.fsdp, self.tp = int(dp), int(fsdp), int(tp)
        self.ep = int(ep)
        self.tables = [re.compile(pat) for pat in tables]
        # opt-in int8 gradient allreduce ("int8" | "int8_sr" stochastic
        # rounding): the Trainer compiles the quantized psum into the
        # pure-DP shard_map step / the wire-format round-trip into the
        # pjit reduce boundary (quant.collectives)
        from ..quant.collectives import check_mode

        self.grad_compression = check_mode(grad_compression)
        self.rules = [(re.compile(pat), spec) for pat, spec in rules]
        self.params = dict(params or {})
        self.min_shard_size = int(min_shard_size)
        for ax in batch_axes:
            enforce(ax in PLAN_AXES, "unknown batch axis %r (plan axes "
                    "are %s)", ax, PLAN_AXES)
        self.batch_axes = tuple(batch_axes)
        if mesh is not None:
            enforce(all(a in mesh.shape for a in PLAN_AXES),
                    "plan mesh must carry axes %s, got %s", PLAN_AXES,
                    tuple(mesh.axis_names))
            enforce(tuple(mesh.shape[a] for a in PLAN_AXES)
                    == (self.dp, self.fsdp, self.tp),
                    "mesh shape %s != plan (dp=%s, fsdp=%s, tp=%s)",
                    dict(mesh.shape), self.dp, self.fsdp, self.tp)
            # the ep axis is opt-in: an ep=1 plan accepts the legacy
            # 3-axis mesh; an ep>1 plan needs the table axis on it
            enforce(int(mesh.shape.get(TABLE_AXIS, 1)) == self.ep,
                    "mesh %s axis size %s != plan ep=%s", TABLE_AXIS,
                    int(mesh.shape.get(TABLE_AXIS, 1)), self.ep)
            self._mesh: Optional[Mesh] = mesh
        else:
            self._mesh = None
            self._devices = devices

    # -- mesh ----------------------------------------------------------------

    @property
    def mesh(self) -> Mesh:
        """The plan's mesh, built lazily over its devices (default: the
        first ``dp*fsdp*tp*ep`` of ``jax.devices()``). ``fsdp``/``tp``
        (and ``ep``, whose lookup psum is the hot collective) take the
        innermost (ICI-adjacent) positions, ``dp`` the outer (possibly
        DCN) one — the scaling-book layout. An ep=1 plan builds the
        exact legacy 3-axis mesh; the table axis appears only when
        ``ep > 1``."""
        if self._mesh is None:
            n = self.num_devices
            devices = self._devices
            if devices is None:
                devices = jax.devices()[:n]
            enforce(len(devices) == n,
                    "plan needs %s devices (dp=%s x fsdp=%s x tp=%s "
                    "x ep=%s), got %s", n, self.dp, self.fsdp, self.tp,
                    self.ep, len(devices))
            if self.ep > 1:
                self._mesh = Mesh(
                    np.asarray(devices).reshape(self.dp, self.fsdp,
                                                self.tp, self.ep),
                    axis_names=PLAN_AXES + (TABLE_AXIS,))
            else:
                self._mesh = Mesh(
                    np.asarray(devices).reshape(self.dp, self.fsdp,
                                                self.tp),
                    axis_names=PLAN_AXES)
        return self._mesh

    @property
    def num_devices(self) -> int:
        return self.dp * self.fsdp * self.tp * self.ep

    @property
    def explicit(self) -> bool:
        """True when the plan carries real shardings — fsdp/tp/ep axes
        or any per-param rule — and steps must compile through ``pjit``
        with full in/out shardings. A pure-DP plan (dp only) takes the
        ``shard_map`` fallback instead."""
        return (self.fsdp > 1 or self.tp > 1 or self.ep > 1
                or bool(self.rules) or bool(self.params))

    # -- spec resolution -----------------------------------------------------

    def is_table(self, name: str) -> bool:
        """True when ``name`` matches a registered ``tables=`` pattern
        — the leaves the ``ep`` axis row-shards (and the leaves
        ``analysis/shardcheck``'s PT-SHARD-204/205 table audits
        apply to)."""
        return any(pat.search(name) for pat in self.tables)

    def table_spec(self) -> P:
        """The row-sharded layout registered tables resolve to under an
        ``ep`` plan."""
        return P(TABLE_AXIS, None)

    def spec_for(self, name: str, value=None) -> P:
        """Resolve one param/buffer name: explicit > table > pattern >
        default.

        ``value`` (or anything with ``.shape``) gates divisibility and
        the default rule's size floor; without it, explicit/pattern
        specs are trusted as given and the default stays replicated
        (no shape to pick an axis from).
        """
        if name in self.params:
            spec = self.params[name]
            if self._divisible(value, spec):
                return spec
        if self.ep > 1 and self.is_table(name):
            spec = self.table_spec()
            if self._divisible(value, spec):
                return spec
            # indivisible vocab: fall through to rules/default (the
            # audit reports the drop as PT-SHARD-202/204)
        for pat, spec in self.rules:
            if pat.search(name):
                if self._divisible(value, spec):
                    return spec
                # first match wins even when undivisible (mirrors
                # infer_param_spec): the leaf falls to the default
                # tier below, which re-checks divisibility itself
                break
        return self._default_spec(value)

    def requested_spec(self, name: str) -> Optional[P]:
        """The spec the author *asked for* (explicit map, else table
        registration, else first matching rule) before any divisibility
        gating — ``None`` when only the default tier applies. Lives
        next to :meth:`spec_for` so the audit's notion of "requested"
        can never drift from the resolution order it checks
        (``analysis/shardcheck`` compares this against what
        :meth:`spec_for` actually resolves)."""
        if name in self.params:
            return self.params[name]
        if self.ep > 1 and self.is_table(name):
            return self.table_spec()
        for pat, spec in self.rules:
            if pat.search(name):
                return spec
        return None

    def _divisible(self, value, spec: P) -> bool:
        shape = getattr(value, "shape", None)
        if shape is None:
            return True
        for dim, axes in enumerate(spec):
            if axes is None or dim >= len(shape):
                continue
            axes = (axes,) if isinstance(axes, str) else axes
            n = 1
            for ax in axes:
                n *= int(self.mesh.shape.get(ax, 1))
            if n and shape[dim] % n:
                return False
        return True

    def _default_spec(self, value) -> P:
        """Largest-axis-over-fsdp default (ZeRO-3 style): shard the
        biggest fsdp-divisible dim; small/odd leaves replicate."""
        shape = getattr(value, "shape", None)
        if (self.fsdp <= 1 or shape is None or not len(shape)
                or int(np.prod(shape)) < self.min_shard_size):
            return P()
        order = sorted(range(len(shape)), key=lambda d: -int(shape[d]))
        for dim in order:
            if shape[dim] and shape[dim] % self.fsdp == 0:
                spec: List[Any] = [None] * len(shape)
                spec[dim] = "fsdp"
                return P(*spec)
        return P()

    # -- derived shardings ---------------------------------------------------

    def sharding_for(self, name: str, value=None) -> NamedSharding:
        return NamedSharding(self.mesh, self.spec_for(name, value))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def batch_sharding(self) -> NamedSharding:
        """Batch leaves: leading dim split over the active batch axes
        (degenerate axes dropped so a dp=1 fsdp=N plan still shards)."""
        axes = tuple(a for a in self.batch_axes
                     if int(self.mesh.shape[a]) > 1)
        return NamedSharding(self.mesh, P(axes) if axes else P())

    def param_shardings(self, params: Dict[str, Any]) -> Dict[str, NamedSharding]:
        return {name: self.sharding_for(name, value)
                for name, value in params.items()}

    # -- sharded-by-construction placement ----------------------------------

    def place(self, named: Dict[str, Any]) -> Dict[str, Any]:
        """Place a ``name -> leaf`` dict sharded-by-construction: each
        leaf is staged from host memory directly into its resolved
        sharding, so no device ever holds more than its shard (a leaf
        already on device is viewed host-side first — the CPU backend
        zero-copies that view, and other backends pay one D2H for the
        one-time init). Placed leaves are re-homed into runtime-owned
        buffers (:func:`..utils.memory.owned_on_device`) because every
        train step DONATES them — a cpu-backend zero-copy alias of the
        init-time host array would corrupt the heap on reuse."""
        from ..analysis.donation import note_transfer
        from ..utils.memory import owned_on_device

        out = {}
        for name, leaf in named.items():
            sh = self.sharding_for(name, leaf)
            host = np.asarray(leaf) if isinstance(leaf, jax.Array) else leaf
            placed = note_transfer(host, jax.device_put(host, sh))
            # note_transfer records the host-backed provenance of the
            # staging put; owned_on_device's copy is recorded owned —
            # so if the laundering were ever bypassed, the Trainer's
            # compile-time donation check (analysis/donation.py) flags
            # the placed state instead of the runtime corrupting later
            out[name] = owned_on_device(placed)
        return out

    def place_replicated(self, tree):
        """Re-place every leaf of an arbitrary pytree that is not
        already a mesh-placed array (optimizer step counters, loss-scale
        scalars, RNG key data) onto the plan mesh replicated. Leaves
        already carrying a ``NamedSharding`` on this mesh — e.g. opt
        moments born from ``zeros_like`` on a placed param — keep it."""
        rep = self.replicated()

        def put(leaf):
            sh = getattr(leaf, "sharding", None)
            if isinstance(sh, NamedSharding) and sh.mesh == self.mesh:
                return leaf
            return jax.device_put(leaf, rep)

        return jax.tree_util.tree_map(put, tree)

    # -- reporting -----------------------------------------------------------

    def describe(self, params: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        """Plan summary for ``/statusz`` and bench extras."""
        out: Dict[str, Any] = {
            "axes": {"dp": self.dp, "fsdp": self.fsdp, "tp": self.tp,
                     "ep": self.ep},
            "devices": self.num_devices,
            "batch_axes": list(self.batch_axes),
            "mode": "pjit" if self.explicit else "shard_map",
            "rules": len(self.rules),
            "explicit_params": len(self.params),
            "tables": len(self.tables),
            "grad_compression": self.grad_compression,
        }
        if params is not None:
            specs = {n: self.spec_for(n, v) for n, v in params.items()}
            sharded = {n: str(s) for n, s in specs.items() if s != P()}
            out["sharded_params"] = len(sharded)
            out["replicated_params"] = len(params) - len(sharded)
            out["param_specs"] = sharded
            # static plan audit (analysis/shardcheck): would-reshard /
            # dropped-spec / big-leaf-replicated findings ride along,
            # so /statusz's sharding section reports layout hazards
            # without any extra wiring
            from ..analysis.shardcheck import audit_plan, audit_summary

            out["audit"] = audit_summary(
                audit_plan(self, params, specs=specs))
        return out

    def __repr__(self):
        return (f"Plan(dp={self.dp}, fsdp={self.fsdp}, tp={self.tp}, "
                f"ep={self.ep}, rules={len(self.rules)}, "
                f"tables={len(self.tables)}, explicit={self.explicit})")


@contextlib.contextmanager
def host_init():
    """Build a model's eager init-time params in HOST memory.

    ``nn.Layer`` materializes parameters at construction on the default
    device — on a TPU runtime that is chip 0, so a model bigger than one
    chip's HBM could never even be built. Constructing it under this
    scope lands the arrays on the host cpu backend instead;
    :meth:`Plan.place` then stages host->shard and at no point does any
    chip hold more than its shard::

        with host_init():
            model = GPTForCausalLM(cfg)          # params in host RAM
        trainer = Trainer.supervised(model, opt, loss, plan=plan)

    A cpu-only runtime (tests, the 8-device sim) already inits on host;
    the scope is then inert.
    """
    try:
        cpu = jax.local_devices(backend="cpu")[0]
    except RuntimeError:
        yield  # no cpu backend exposed: nothing better to offer
        return
    with jax.default_device(cpu):
        yield


# ---------------------------------------------------------------------------
# per-device byte accounting (the OOM-gate evidence: planned per-device
# param+opt bytes ~= replicated / num_fsdp_shards)
# ---------------------------------------------------------------------------


def device_bytes(tree) -> Dict[int, int]:
    """Addressable bytes each device holds for ``tree`` (by device id).
    Replicated leaves count once per device; sharded leaves count each
    device's shard only — exactly the per-device HBM the state costs."""
    out: Dict[int, int] = {}
    for leaf in jax.tree_util.tree_leaves(tree):
        if not isinstance(leaf, jax.Array):
            continue
        for shard in leaf.addressable_shards:
            d = shard.device.id
            out[d] = out.get(d, 0) + int(shard.data.nbytes)
    return out


def max_device_bytes(tree) -> int:
    """Largest per-device footprint of ``tree`` (0 for an empty tree)."""
    per = device_bytes(tree)
    return max(per.values()) if per else 0


# ---------------------------------------------------------------------------
# resharding guard (tests + bench): steady-state planned steps must not
# pay device-to-device resharding copies
# ---------------------------------------------------------------------------


@contextlib.contextmanager
def guard_no_resharding():
    """Assert no implicit device-to-device resharding copy happens in
    the body (``jax.transfer_guard_device_to_device("disallow")``). A
    steady-state planned step whose ``in_shardings`` match the live
    placement triggers none; a mismatch raises here and bumps
    ``pt_resharding_copies_total`` — the signal tier-1 tests pin to 0.
    """
    try:
        with jax.transfer_guard_device_to_device("disallow"):
            yield
    except Exception as e:
        # count ONLY sharding/transfer violations — an unrelated error
        # in the body (OOM, a test assertion) must not read as
        # in_shardings drift on /metrics
        msg = str(e).lower()
        if telemetry.enabled() and ("device-to-device" in msg
                                    or "transfer" in msg
                                    or "sharding" in msg):
            _plan_metrics()["resharding_copies"].inc()
        raise


# ---------------------------------------------------------------------------
# step compilation — ONE path for plain jit / pjit / shard_map fallback
# ---------------------------------------------------------------------------


def compile_step(plan: Optional[Plan], fn: Callable, *,
                 in_shardings=None, out_shardings=None,
                 donate_argnums: Sequence[int] = (),
                 batch_argnum: int = -1,
                 static_argnums: Sequence[int] = ()):
    """Compile ``fn`` for the plan. Three regimes, one entry point:

    - ``plan`` is ``None`` (or a 1-device plan): plain
      ``jax.jit(fn, donate_argnums=...)`` — the single-chip path.
    - ``plan.explicit`` (fsdp/tp axes or param rules): ``pjit`` — i.e.
      ``jax.jit`` with full ``in_shardings`` / ``out_shardings`` /
      ``donate_argnums``, so XLA compiles against the declared layout
      and the steady-state step pays zero resharding copies.
    - pure-DP plan: ``shard_map``-wrapped ``jax.jit``. ``fn`` runs
      per-shard on the batch argument (``batch_argnum``) with all other
      arguments replicated, and MUST be collective-aware: reduce its
      loss/grads over ``jax.lax`` collectives on the batch axes (the
      Trainer threads ``pmean_axes`` for this). ``check_rep=False``
      because the post-``pmean`` replication is real but not statically
      inferable.

    The returned callable carries ``compiled_via`` in
    ``("jit", "pjit", "shard_map")`` so callers (and tests) can pin the
    selection.
    """
    donate = tuple(donate_argnums)
    if plan is None or plan.num_devices == 1:
        compiled = jax.jit(fn, donate_argnums=donate,
                           static_argnums=tuple(static_argnums))
        compiled.compiled_via = "jit"
        return compiled
    if plan.explicit or in_shardings is not None:
        enforce(in_shardings is not None and out_shardings is not None,
                "explicit plans compile via pjit and need both "
                "in_shardings and out_shardings (derive them from the "
                "placed state)")
        compiled = jax.jit(fn, in_shardings=in_shardings,
                           out_shardings=out_shardings,
                           donate_argnums=donate,
                           static_argnums=tuple(static_argnums))
        compiled.compiled_via = "pjit"
        return compiled

    # pure-DP fallback: shard_map keeps map-style collective ergonomics
    from jax.experimental.shard_map import shard_map

    enforce(not static_argnums,
            "static_argnums is not supported on the shard_map fallback "
            "(the static positions would be fed to shard_map as array "
            "operands) — close over the static values instead")
    mesh = plan.mesh
    batch_spec = plan.batch_sharding().spec

    def wrapped(*args):
        n = len(args)
        b = batch_argnum % n
        in_specs = tuple(batch_spec if i == b else P() for i in range(n))
        return shard_map(fn, mesh=mesh, in_specs=in_specs,
                         out_specs=P(), check_rep=False)(*args)

    compiled = jax.jit(wrapped, donate_argnums=donate)
    compiled.compiled_via = "shard_map"
    return compiled


def pmean_axes(plan: Optional[Plan]) -> Tuple[str, ...]:
    """The mesh axes a collective-aware step must reduce grads/loss
    over under the shard_map fallback (empty for explicit/absent plans,
    where GSPMD inserts the collectives itself)."""
    if plan is None or plan.explicit or plan.num_devices == 1:
        return ()
    return tuple(a for a in plan.batch_axes
                 if int(plan.mesh.shape[a]) > 1)
