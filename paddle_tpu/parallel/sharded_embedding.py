"""Sharded embedding tables — the TPU-native successor of the reference's
parameter-server sparse tables.

Capability lineage (SURVEY.md §2.5): the reference shards giant embedding
tables across parameter servers and prefetches rows over RPC
(reference: operators/distributed/parameter_prefetch.cc,
transpiler/distribute_lookup_table.py, framework/fleet/fleet_wrapper.h:55
PullSparseVarsSync) with SelectedRows sparse gradients
(reference: framework/selected_rows.h:32). On TPU the table is a dense
array row-sharded over a mesh axis ('ep'); lookup is a *local* gather of
the in-shard rows plus one ``psum`` over the axis (XLA lowers it onto the
ICI ring), and the "sparse gradient" is the transpose — a local
scatter-add into each shard — handled entirely by autodiff. No RPC, no
row cache, no id-dedup protocol.

Memory: each chip holds V/ep rows. Compute: every chip gathers B ids
against its shard (out-of-shard rows contribute zeros) — bandwidth-bound
on the (B, D) psum, the standard SPMD embedding recipe.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.enforce import InvalidArgumentError, enforce
from ..core.mesh import get_mesh
from ..nn.layer import Layer
from .. import initializer as I
from ..utils.compat import shard_map


def _lookup_inner(ids, table, *, axis, rows_per_shard):
    idx = lax.axis_index(axis)
    offset = idx * rows_per_shard
    local = ids - offset
    valid = (local >= 0) & (local < rows_per_shard)
    safe = jnp.clip(local, 0, rows_per_shard - 1)
    rows = jnp.take(table, safe, axis=0)
    rows = jnp.where(valid[..., None], rows, 0)
    return lax.psum(rows, axis)


def _check_ids_in_vocab(ids, vocab: int,
                        padding_idx: Optional[int] = None) -> None:
    """Typed out-of-vocab enforcement on CONCRETE ids (eager calls and
    the op-construction path). An id outside [0, V) used to psum to a
    silent all-zeros row — indistinguishable from a real zero embedding
    and the classic off-by-one-vocab data bug; now it raises
    :class:`..core.enforce.InvalidArgumentError`. ``padding_idx`` ids
    are exempt (an out-of-range pad like -1 is a legitimate
    convention). Traced ids (inside jit/pjit, shapes only) skip the
    check — the in-shard mask still yields zeros there, and the data
    pipeline owns validation."""
    if isinstance(ids, jax.core.Tracer) or getattr(ids, "size", 0) == 0:
        return
    import numpy as np

    # host-side numpy on the concrete ids: jnp ops here would STAGE
    # under an enclosing jit trace (constants become tracers) and the
    # int() coercion would blow up mid-trace
    check = np.asarray(ids)
    if padding_idx is not None:
        check = np.where(check == padding_idx, 0, check)
    lo, hi = int(check.min()), int(check.max())
    if lo < 0 or hi >= vocab:
        raise InvalidArgumentError(
            f"embedding ids span [{lo}, {hi}] but the table has "
            f"{vocab} rows — out-of-vocab ids are an error, not a "
            f"clip (hash or bucket ids upstream, or grow the table)")


def sharded_embedding_lookup(ids, table, *, axis: str = "ep",
                             batch_axis: Optional[str] = "dp", mesh=None,
                             padding_idx: Optional[int] = None):
    """Gather rows of a globally (V, D) table row-sharded over ``axis``.

    ``ids``: any int shape, batch-sharded over ``batch_axis`` (or
    replicated with ``batch_axis=None``). Returns ids.shape + (D,).
    ``padding_idx`` rows come back as exact zeros; concrete
    out-of-vocab ids raise :class:`..core.enforce.InvalidArgumentError`
    (see :func:`_check_ids_in_vocab`).
    """
    mesh = mesh or get_mesh()
    enforce(axis in mesh.shape, "mesh has no %r axis (axes: %s)", axis,
            tuple(mesh.shape))
    n = mesh.shape[axis]
    V, D = table.shape
    enforce(V % n == 0,
            "vocab %s must divide %s axis size %s (pad the table)", V, axis, n)
    _check_ids_in_vocab(ids, V, padding_idx)
    if batch_axis is not None and batch_axis not in mesh.shape:
        batch_axis = None  # user mesh without a batch axis: replicate ids
    if batch_axis is not None and ids.shape[0] % mesh.shape[batch_axis]:
        batch_axis = None  # odd batch (e.g. eval tail): replicate, still exact
    ids_spec = P(batch_axis, *([None] * (ids.ndim - 1)))
    inner = functools.partial(_lookup_inner, axis=axis,
                              rows_per_shard=V // n)
    fn = shard_map(inner, mesh=mesh,
                   in_specs=(ids_spec, P(axis, None)),
                   out_specs=P(batch_axis, *([None] * ids.ndim)),
                   check_vma=False)
    out = fn(ids, table)
    if padding_idx is not None:
        out = jnp.where((ids == padding_idx)[..., None], 0.0, out)
    return out


class ShardedEmbedding(Layer):
    """Embedding whose table is row-sharded over a mesh axis ('ep').

    Drop-in for :class:`paddle_tpu.nn.Embedding` at vocab sizes that don't
    fit one chip's HBM — the PSLib/Downpour giant-table capability
    (reference: distributed/downpour.py:24) without a parameter server.
    """

    def __init__(self, num_embeddings: int, embedding_dim: int,
                 axis: str = "ep", padding_idx: Optional[int] = None,
                 weight_init=None, dtype=None, mesh=None,
                 batch_axis: Optional[str] = "dp",
                 is_sparse: bool = False):
        super().__init__()
        self.axis = axis
        self.batch_axis = batch_axis
        self.padding_idx = padding_idx
        self._mesh = mesh
        # row-sparse gradient updates (see nn.Embedding.is_sparse): the
        # sparse step's scatter composes with the P(axis, None) placement
        # — GSPMD routes each unique row's update to its owning shard
        self.is_sparse = is_sparse
        self.create_parameter("weight", (num_embeddings, embedding_dim),
                              dtype, weight_init or I.XavierNormal())

    def weight_sharding(self, mesh=None) -> NamedSharding:
        """Row-sharded placement — device_put the weight with this (and use
        it as the param's sharding rule in the trainer)."""
        return NamedSharding(self._mesh or mesh or get_mesh(),
                             P(self.axis, None))

    def forward(self, ids):
        from ..nn.sparse import Capture, Inject, active

        ctx = active()
        if ctx is not None and ctx.handles(self):
            if isinstance(ctx, Capture):
                ctx.record(self, ids)
            else:
                assert isinstance(ctx, Inject)
                rows = ctx.pop(self)
                if self.padding_idx is not None:
                    rows = jnp.where((ids == self.padding_idx)[..., None],
                                     0.0, rows)
                return rows
        return sharded_embedding_lookup(
            ids, self.weight, axis=self.axis, mesh=self._mesh,
            batch_axis=self.batch_axis, padding_idx=self.padding_idx)


def embedding_ep_rules(model: Layer, axis: str = "ep"):
    """Sharding rules placing every ShardedEmbedding table in ``model`` on
    the ep axis (compose with transformer_tp_rules/zero_dp_rules in the
    trainer)."""
    import re

    rules = []
    for name, sub in model.named_sublayers():
        if isinstance(sub, ShardedEmbedding):
            rules.append((re.escape(f"{name}.weight") + "$", P(axis, None)))
    return rules
