"""Sharding rules — tensor parallelism as annotation, not program rewrite.

This is the capability successor of the reference's DistributeTranspiler
(reference: python/paddle/fluid/transpiler/distribute_transpiler.py:164,283 —
which rewrote the ProgramDesc op-by-op for a cluster role): here the "rewrite"
is a set of (param-name regex → PartitionSpec) rules; GSPMD partitions the
traced computation and inserts the collectives over ICI. Megatron-style
column/row parallel linear layers fall out of two specs:

  column-parallel (output dim sharded):  weight P(None, "tp"), bias P("tp")
  row-parallel    (input dim sharded):   weight P("tp", None) + psum (auto)

Rules are ordered; first match wins; unmatched params replicate.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Sequence, Tuple, Union

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.mesh import get_mesh

Rule = Tuple[str, P]


def infer_param_spec(params: Dict[str, object],
                     rules: Sequence[Rule],
                     mesh=None) -> Dict[str, P]:
    """Map each param name through the first matching rule (search, not
    fullmatch — anchor with $ where needed). Unmatched names are omitted
    (→ replicated), as are matches whose sharded dims don't divide the mesh
    axis (e.g. a 2-row segment-embedding table on tp=4)."""
    mesh = mesh or get_mesh()
    compiled = [(re.compile(pat), spec) for pat, spec in rules]
    out: Dict[str, P] = {}
    for name, value in params.items():
        for pat, spec in compiled:
            if pat.search(name):
                if _divisible(value, spec, mesh):
                    out[name] = spec
                break
    return out


def _divisible(value, spec: P, mesh) -> bool:
    shape = getattr(value, "shape", None)
    if shape is None:
        return True
    for dim, axes in enumerate(spec):
        if axes is None or dim >= len(shape):
            continue
        axes = (axes,) if isinstance(axes, str) else axes
        n = 1
        for ax in axes:
            n *= int(mesh.shape.get(ax, 1))
        if shape[dim] % n:
            return False
    return True


def shard_params(params: Dict[str, object], rules: Sequence[Rule],
                 mesh=None) -> Dict[str, object]:
    """Place params per rules (unmatched → replicated)."""
    mesh = mesh or get_mesh()
    spec_map = infer_param_spec(params, rules, mesh)
    out = {}
    for name, value in params.items():
        spec = spec_map.get(name, P())
        out[name] = jax.device_put(value, NamedSharding(mesh, spec))
    return out


def constraint(x, spec: P, mesh=None):
    """with_sharding_constraint pinned to the global mesh — activation
    sharding hints inside jitted code."""
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh or get_mesh(), spec))


# ---------------------------------------------------------------------------
# Standard rulesets
# ---------------------------------------------------------------------------


def transformer_tp_rules(axis: str = "tp") -> List[Rule]:
    """Megatron-style TP for nn.transformer-built models (BERT, NMT, GPT):
    attention QKV and FFN-in are column-parallel, attention-out and FFN-out
    are row-parallel, vocab projections and embedding tables shard the vocab
    dim. Head-count must divide the tp axis size."""
    col_w, col_b = P(None, axis), P(axis)
    row_w = P(axis, None)
    vocab_w = P(axis, None)  # (vocab, hidden) tables: shard vocab rows
    return [
        (r"(q_proj|k_proj|v_proj)\.weight$", col_w),
        (r"(q_proj|k_proj|v_proj)\.bias$", col_b),
        (r"out_proj\.weight$", row_w),
        (r"fc1\.weight$", col_w),
        (r"fc1\.bias$", col_b),
        (r"fc2\.weight$", row_w),
        (r"(generator|mlm_decoder)\.weight$", P(None, axis)),
        (r"(generator|mlm_decoder)\.bias$", P(axis)),
        # GPT-family SwiGLU FFN: gate/up column-parallel, down
        # row-parallel (the Megatron MLP split for gated FFNs);
        # attribute-anchored like 'embed' below (a module whose name
        # merely ENDS in gate/up/down must not inherit the split)
        (r"(^|\.)(gate|up)\.weight$", col_w),
        (r"(^|\.)down\.weight$", row_w),
        # attribute boundary: 'embed' must be the WHOLE attribute name
        # (GPT's token table), not a suffix of one — ViT's
        # patch_embed.weight is a 4D conv kernel that must replicate
        (r"(^|\.)(tok|seg|src_emb|tgt_emb|embed)\.weight$", vocab_w),
    ]


def zero_dp_rules(axis: str = "dp",
                  min_size: int = 2 ** 16) -> "OptStateRules":
    """ZeRO-style optimizer-state sharding over dp — the capability successor
    of PS-sharded optimizer state (reference:
    transpiler/distribute_transpiler.py:702 get_pserver_program runs optimizer
    blocks on each pserver's shard)."""
    return OptStateRules(axis=axis, min_size=min_size)


class OptStateRules:
    """Shard large optimizer-state leaves along their biggest divisible dim."""

    def __init__(self, axis: str = "dp", min_size: int = 2 ** 16):
        self.axis = axis
        self.min_size = min_size

    def spec_for(self, leaf, mesh=None) -> Optional[P]:
        mesh = mesh or get_mesh()
        n = int(mesh.shape.get(self.axis, 1))
        if n <= 1 or not hasattr(leaf, "shape") or leaf.size < self.min_size:
            return None
        for dim, s in enumerate(leaf.shape):
            if s % n == 0 and s >= n:
                spec = [None] * leaf.ndim
                spec[dim] = self.axis
                return P(*spec)
        return None

    def place(self, tree, mesh=None):
        mesh = mesh or get_mesh()

        def put(leaf):
            spec = self.spec_for(leaf, mesh)
            if spec is None:
                return leaf
            return jax.device_put(leaf, NamedSharding(mesh, spec))

        return jax.tree_util.tree_map(put, tree)
