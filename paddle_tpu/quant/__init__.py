"""Quantization subsystem — fake-quant op family + QAT/PTQ layer rewriting
(reference: operators/fake_quantize_op.cc, contrib/slim/quantization/)."""

from .ops import (MovingAverageState, RangeState, abs_max_scale,
                  absmax_decode, absmax_encode, dequantize,
                  fake_channel_wise_quantize_abs_max, fake_quantize_abs_max,
                  fake_quantize_moving_average_abs_max,
                  fake_quantize_range_abs_max, moving_average_abs_max_scale,
                  moving_average_state_init, quantize_dequantize,
                  quantize_to_int, range_state_init)
from .collectives import (compress_grads, quantized_pmean,
                          quantized_pmean_tree, quantized_psum,
                          quantized_psum_partitioned)
from .int8 import (Int8Conv2D, Int8Linear, int8_conv2d,
                   int8_linear, int8_swap)
from .weight_only import WeightOnlyLinear, apply_weight_only_int8
from .qat import (QuantConfig, QuantedLayer, calibrate, freeze,
                  quantize_model)

__all__ = [
    "MovingAverageState", "RangeState", "WeightOnlyLinear",
    "abs_max_scale", "absmax_decode", "absmax_encode",
    "apply_weight_only_int8", "compress_grads", "dequantize",
    "fake_channel_wise_quantize_abs_max", "fake_quantize_abs_max",
    "fake_quantize_moving_average_abs_max", "fake_quantize_range_abs_max",
    "moving_average_abs_max_scale", "moving_average_state_init",
    "quantize_dequantize", "quantize_to_int", "quantized_pmean",
    "quantized_pmean_tree", "quantized_psum",
    "quantized_psum_partitioned", "range_state_init",
    "QuantConfig", "QuantedLayer", "calibrate", "freeze", "quantize_model",
    "int8_linear", "int8_swap", "Int8Linear", "Int8Conv2D", "int8_conv2d",
]
