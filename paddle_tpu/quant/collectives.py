"""Compressed gradient collectives — int8 allreduce for the sharding
plan's dp/fsdp axes (EQuARX, PAPERS.md: quantized AllReduce inside the
collective at ~2x speedup; here the same design hand-written at the JAX
level for the plan's ``shard_map`` pure-DP path).

:func:`quantized_psum` is a hand-written ring allreduce over a named
mesh axis — reduce-scatter then all-gather via ``lax.ppermute`` — whose
per-hop payload is the int8 ``quant.ops.absmax_encode`` wire format
(per-``group`` abs-max scales ride along as float32, a ``4/group``
overhead). Partial sums are dequantized, accumulated in float32, and
requantized at each reduce-scatter hop exactly like EQuARX's in-XLA
pipeline; the all-gather phase forwards received payloads unchanged so
every device decodes bit-identical chunks — the replicated-update
invariant the shard_map trainer step relies on. Wire bytes per device:
``2*(n-1)/n * (size + 4*size/group)`` vs ``2*(n-1)/n * 4*size`` for the
fp32 ring — a ~3.98x payload reduction at the default group.

Safety rails baked in (the ``amp``-style contract — opt-in, parity
gated, never silently lossy in the failure modes that matter):

- **tiny leaves** (< ``MIN_COMPRESS_SIZE`` elements) and non-float
  leaves ride the plain fp32 ``lax.psum`` — scale overhead and
  quantization noise on a 10-element bias buys nothing;
- **scale-degenerate leaves**: an all-zero chunk encodes exactly (the
  eps floor), and a NON-FINITE leaf (inf/nan gradients) poisons the
  whole output with NaN via a 4-byte ``pmin``-reduced finite flag — the
  train loop's nan-guard must keep firing; a quantizer that launders
  inf into a finite int8 payload would silently corrupt training;
- **stochastic rounding** (``key=``): unbiased ``floor(y + u)``
  rounding so quantization bias cannot accumulate across steps.

:func:`quantized_psum_partitioned` is the same ring rebuilt as a
``jax.custom_partitioning``-wrapped collective for PJIT-LEVEL callers:
the stacked per-shard partials stay sharded over the named axis and the
int8 encode/exchange/accumulate lowers INSIDE the partitioned
computation (bit-identical to the shard_map form on the same mesh) —
no shard_map body to write, and GSPMD composes the op with everything
around it. Both forms funnel their dispatch through
``utils.compat.native_int8_allreduce()``: the moment the runtime
exposes a native int8 AllReduce (EQuARX proper), it swaps in under
both spellings with zero call-site changes.

The explicit (fsdp/tp) pjit path has no user-visible collective — GSPMD
owns the reduce schedule — so :func:`compress_grads` applies the SAME
int8 wire-format round-trip at the reduce boundary instead: numerics
(and therefore the parity gate) match the quantized wire exactly, and
the native-AllReduce seam above slots in underneath without an API
change when the backend grows one.

Byte accounting is host-side (``pt_collective_bytes_total{compressed=}``
— traced code cannot touch counters): leaf shapes are static, so the
per-step payload is computed once (:func:`tree_payload_bytes`) and the
trainer increments the counter per dispatched step.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import functools

import jax
import jax.numpy as jnp
from jax import lax

from .. import telemetry
from ..core.enforce import enforce
from ..utils import compat
from .ops import absmax_decode, absmax_encode

# per-group quantization granularity of the wire format (elements per
# f32 scale — 4/GROUP_SIZE relative overhead on the payload)
GROUP_SIZE = 1024
# leaves below this many elements ride the fp32 psum (biases, scalars:
# noise for no bandwidth win)
MIN_COMPRESS_SIZE = 2048

COMPRESSION_MODES = (None, "int8", "int8_sr")


def check_mode(mode: Optional[str]) -> Optional[str]:
    """Validate a ``grad_compression`` knob value (None | "int8" |
    "int8_sr" — the stochastic-rounding variant)."""
    enforce(mode in COMPRESSION_MODES,
            "grad_compression must be one of %s, got %r",
            COMPRESSION_MODES, mode)
    return mode


@telemetry.cached_instruments
def _comm_metrics(reg):
    """Collective byte counters (only reached when telemetry is on)."""
    return {
        "bytes_int8": reg.counter(
            "pt_collective_bytes_total",
            "per-device gradient-allreduce payload bytes moved by the "
            "hand-written plan collectives (int8 wire format incl. "
            "scales)", labels={"compressed": "int8"}),
        "bytes_fp32": reg.counter(
            "pt_collective_bytes_total",
            "per-device gradient-allreduce payload bytes moved by the "
            "hand-written plan collectives (fp32 payload)",
            labels={"compressed": "fp32"}),
    }


def record_payload_bytes(int8_bytes: int, fp32_bytes: int) -> None:
    """Host-side per-step counter bump (no-op when telemetry is off)."""
    if not telemetry.enabled():
        return
    m = _comm_metrics()
    if int8_bytes:
        m["bytes_int8"].inc(int8_bytes)
    if fp32_bytes:
        m["bytes_fp32"].inc(fp32_bytes)


# ---------------------------------------------------------------------------
# payload-byte accounting (static shapes -> computed once per trainer)
# ---------------------------------------------------------------------------


def _ring_chunk(size: int, n: int, group: int) -> int:
    """Per-device ring chunk in elements, padded to the group grid."""
    chunk = -(-size // n)
    return -(-chunk // group) * group


def leaf_payload_bytes(size: int, axis_size: int, *, compressed: bool,
                       group: int = GROUP_SIZE,
                       dtype_bytes: int = 4) -> int:
    """Ring-allreduce payload bytes ONE device moves (sends) for one
    leaf: 2*(n-1) hops of one chunk each (reduce-scatter + all-gather),
    int8 data + f32 per-group scales when compressed."""
    n = int(axis_size)
    if n <= 1:
        return 0
    if not compressed:
        # plain lax.pmean: ring chunk is ceil(size/n), no group grid
        return 2 * (n - 1) * (-(-int(size) // n)) * dtype_bytes
    chunk = _ring_chunk(int(size), n, group)
    return 2 * (n - 1) * (chunk + 4 * (chunk // group))


def tree_payload_bytes(tree, axis_size: int, *, compression: Optional[str],
                       min_size: int = MIN_COMPRESS_SIZE,
                       group: int = GROUP_SIZE) -> Tuple[int, int]:
    """(int8_bytes, fp32_bytes) one device moves per step reducing
    ``tree`` over an ``axis_size`` ring — the numbers
    ``pt_collective_bytes_total`` advances by. Compression applies per
    leaf exactly where :func:`quantized_pmean_tree` would compress."""
    i8 = f32 = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        size = int(leaf.size) if hasattr(leaf, "size") else 1
        itemsize = getattr(getattr(leaf, "dtype", None), "itemsize", 4)
        if compression and _compressible(leaf, min_size):
            i8 += leaf_payload_bytes(size, axis_size, compressed=True,
                                     group=group)
        else:
            f32 += leaf_payload_bytes(size, axis_size, compressed=False,
                                      dtype_bytes=itemsize)
    return i8, f32


def _compressible(leaf, min_size: int) -> bool:
    dt = getattr(leaf, "dtype", None)
    return (dt is not None and jnp.issubdtype(dt, jnp.floating)
            and int(leaf.size) >= min_size)


# ---------------------------------------------------------------------------
# the hand-written quantized ring psum (shard_map bodies only)
# ---------------------------------------------------------------------------


def _ring_perm(n: int):
    return [(i, (i + 1) % n) for i in range(n)]


def _encode_chunk(chunk, group: int, key=None):
    """Chunk -> (q (gpc, group) int8, scale (gpc, 1) f32)."""
    return absmax_encode(chunk.reshape(-1, group), axis=1, key=key)


def quantized_psum(x, axis_name: str, axis_size: int, *,
                   group: int = GROUP_SIZE, key=None):
    """int8 ring allreduce of ``x`` over ``axis_name`` — call inside a
    ``shard_map`` body (the plan's pure-DP step). Returns the summed
    array in ``x``'s dtype, identical on every device. ``key``: enables
    stochastic rounding of each hop's payload (per-device independent
    keys are fine — unbiasedness is per-element).

    The mean-loss gradient tolerance: each chunk's running sum is
    requantized per reduce-scatter hop, so worst-case error grows
    ~linearly in ``axis_size`` quantization steps (the EQuARX regime,
    <1% on gradient-scale data); the trajectory parity gate in
    ``tests/test_quant_comm.py`` pins the training-level consequence.
    """
    n = int(axis_size)
    enforce(n >= 2, "quantized_psum needs axis_size >= 2, got %s", n)
    native = compat.native_int8_allreduce()
    if native is not None and (
            key is None or not getattr(native, "partial_contract",
                                       False)):
        # the runtime grew an in-XLA int8 AllReduce (EQuARX proper):
        # route through it — same contract, the ring below becomes the
        # reference implementation. A partial-contract adapter (no
        # stochastic-rounding support) is refused for key= calls: SR
        # numerics must never silently degrade to nearest rounding.
        return native(x, axis_name=axis_name, axis_size=n, group=group,
                      key=key)
    shape, dt = x.shape, x.dtype
    flat = x.reshape(-1).astype(jnp.float32)
    size = flat.size
    chunk = _ring_chunk(size, n, group)
    gpc = chunk // group
    flat = jnp.pad(flat, (0, n * chunk - size))
    parts = flat.reshape(n, chunk)
    idx = lax.axis_index(axis_name)
    perm = _ring_perm(n)
    # non-finite leaves must POISON the result (nan-guard contract):
    # quantizing inf/nan would launder it into a finite payload
    ok_all = lax.pmin(jnp.isfinite(x).all().astype(jnp.int32), axis_name)

    # reduce-scatter: n-1 hops; hop s sends chunk (idx-s) mod n as int8
    # + scales, receiver dequantizes and accumulates in f32
    for s in range(n - 1):
        hop_key = None if key is None else jax.random.fold_in(key, s)
        q, sc = _encode_chunk(jnp.take(parts, (idx - s) % n, axis=0),
                              group, key=hop_key)
        q = lax.ppermute(q, axis_name, perm)
        sc = lax.ppermute(sc, axis_name, perm)
        recv = (idx - s - 1) % n
        upd = jnp.take(parts, recv, axis=0) \
            + absmax_decode(q, sc).reshape(chunk)
        parts = parts.at[recv].set(upd)

    # device idx now owns the fully-reduced chunk (idx+1) mod n; encode
    # it ONCE and all-gather the payload unchanged — every device
    # (owner included) decodes the same bytes, so outputs replicate
    # bit-identically
    own = (idx + 1) % n
    own_key = None if key is None else jax.random.fold_in(key, n - 1)
    q_own, s_own = _encode_chunk(jnp.take(parts, own, axis=0), group,
                                 key=own_key)
    out_q = jnp.zeros((n, gpc, group), jnp.int8).at[own].set(q_own)
    out_s = jnp.zeros((n, gpc, 1), jnp.float32).at[own].set(s_own)
    cur_q, cur_s = q_own, s_own
    for s in range(n - 1):
        cur_q = lax.ppermute(cur_q, axis_name, perm)
        cur_s = lax.ppermute(cur_s, axis_name, perm)
        recv = (idx - s) % n
        out_q = out_q.at[recv].set(cur_q)
        out_s = out_s.at[recv].set(cur_s)
    out = absmax_decode(out_q.reshape(-1, group),
                        out_s.reshape(-1, 1)).reshape(-1)[:size]
    out = jnp.where(ok_all > 0, out, jnp.nan)
    return out.reshape(shape).astype(dt)


def quantized_pmean(x, axis_name: str, axis_size: int, *,
                    group: int = GROUP_SIZE, key=None):
    """Mean form of :func:`quantized_psum` (what gradient reduction
    wants: mean over batch shards == grad of the global-mean loss)."""
    return quantized_psum(x, axis_name, axis_size, group=group,
                          key=key) / axis_size


# ---------------------------------------------------------------------------
# the custom-partitioned form (pjit-level callers — no shard_map body)
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _partitioned_psum(axis_name: str, group: int, has_key: bool):
    """Build (and cache per static config) the custom_partitioning
    wrapper around the int8 ring. The SPMD partitioners have no rule
    for a quantized collective — under plain pjit the stacked partials
    would all-gather and reduce in fp32, erasing the byte win. The
    registered partition keeps the input sharded over ``axis_name`` and
    lowers to a per-shard body that runs :func:`quantized_psum` over
    the SAME named axis: the int8 encode/exchange/accumulate executes
    INSIDE the partitioned computation (per-shard ring, fp32
    accumulation), not at its edges."""
    from jax.experimental.custom_partitioning import custom_partitioning
    from jax.sharding import NamedSharding, PartitionSpec as P

    compat.fix_custom_partitioning_static_args()

    def ref(x, *maybe_key):
        # global semantics (abstract eval + the no-mesh eager fallback):
        # the exact fp32 sum over the stacked partials. The partitioned
        # lowering replaces this with the quantized ring — single-shard
        # (and eager) calls are exact, multi-shard calls carry the
        # documented quantization-step bound.
        return x.astype(jnp.float32).sum(0).astype(x.dtype)

    wrapped = custom_partitioning(ref)

    def _arg_shardings(msh, ndim):
        xs = NamedSharding(msh, P(axis_name, *([None] * (ndim - 1))))
        if has_key:
            return (xs, NamedSharding(msh, P()))
        return (xs,)

    def partition(mesh, arg_shapes, result_shape):
        a_sh = arg_shapes[0].sharding
        msh = getattr(a_sh, "mesh", None) or mesh
        n = int(msh.shape[axis_name])
        ndim = len(arg_shapes[0].shape)

        def lower_fn(x_local, *maybe_key):
            # local partials fold first (any even sharding of the
            # leading dim is correct: sum of local sums == global sum),
            # then ONE ring over the named axis
            part = x_local.astype(jnp.float32).sum(0)
            k = maybe_key[0] if maybe_key else None
            if k is not None:
                # per-device independent draws (unbiasedness is
                # per-element; see quantized_psum's key contract)
                k = jax.random.fold_in(k, lax.axis_index(axis_name))
            if n < 2:
                out = part
            else:
                out = quantized_psum(part, axis_name, n, group=group,
                                     key=k)
            return out.astype(x_local.dtype)

        return (msh, lower_fn, NamedSharding(msh, P()),
                _arg_shardings(msh, ndim))

    def infer_sharding_from_operands(mesh, arg_shapes, shape):
        a_sh = arg_shapes[0].sharding
        msh = getattr(a_sh, "mesh", None) or mesh
        return NamedSharding(msh, P())

    compat.def_partition(
        wrapped, partition=partition,
        infer_sharding_from_operands=infer_sharding_from_operands)
    return wrapped


def quantized_psum_partitioned(x, axis_name: str, *,
                               group: int = GROUP_SIZE, key=None):
    """:func:`quantized_psum` as a ``jax.custom_partitioning``-wrapped
    collective — the pjit-level spelling (no shard_map body to write).
    ``x`` (n, ...) stacks the per-shard partials on dim 0, sharded over
    mesh axis ``axis_name``; returns the REPLICATED sum (...) in ``x``'s
    dtype. The lowered computation runs the identical hand-written int8
    ring (same wire format, same per-hop payload — byte accounting via
    :func:`leaf_payload_bytes` applies unchanged; same nan-poison and
    stochastic-rounding ``key=`` contracts), so results are
    bit-identical to the shard_map form on the same mesh. Outside a
    mesh/jit the exact fp32 sum runs instead (nothing to compress
    across). The runtime-native int8 AllReduce seam
    (``utils.compat.native_int8_allreduce``) applies inside the
    partitioned body exactly as it does inside shard_map bodies."""
    enforce(x.ndim >= 1,
            "quantized_psum_partitioned stacks per-shard partials on "
            "dim 0 — got a scalar")
    wrapped = _partitioned_psum(axis_name, int(group), key is not None)
    out = wrapped(x, key) if key is not None else wrapped(x)
    return out.astype(x.dtype)


def quantized_pmean_tree(tree, axis_name: str, axis_size: int, *,
                         min_size: int = MIN_COMPRESS_SIZE,
                         group: int = GROUP_SIZE, key=None):
    """Gradient-tree reduce for the shard_map step: float leaves >=
    ``min_size`` elements ride the int8 ring; everything else (tiny
    biases, int counters) the plain fp32 ``lax.pmean``. Each compressed
    leaf folds its flattened tree index into ``key`` so stochastic
    draws never repeat across leaves."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    out = []
    for i, leaf in enumerate(leaves):
        if _compressible(leaf, min_size):
            k = None if key is None else jax.random.fold_in(key, i)
            out.append(quantized_pmean(leaf, axis_name, axis_size,
                                       group=group, key=k))
        else:
            out.append(lax.pmean(leaf, axis_name))
    return jax.tree_util.tree_unflatten(treedef, out)


# ---------------------------------------------------------------------------
# the pjit/GSPMD boundary: wire-format round-trip (fsdp/tp plans)
# ---------------------------------------------------------------------------


def compress_grads(tree, *, min_size: int = MIN_COMPRESS_SIZE,
                   group: int = GROUP_SIZE, key=None):
    """int8 wire-format round-trip (encode -> decode, same per-group
    abs-max convention) over a gradient tree whose allreduce GSPMD owns
    (explicit fsdp/tp plans — no user-level collective to rewrite at
    the JAX level). Numerics match the quantized wire exactly, so the
    parity gate and the opt-in surface are uniform across plan shapes;
    the in-collective byte win lands when the runtime exposes an int8
    AllReduce (EQuARX) under the same boundary. Non-finite leaves pass
    through untouched — the nan-guard sees the original values."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    out = []
    for i, leaf in enumerate(leaves):
        if not _compressible(leaf, min_size):
            out.append(leaf)
            continue
        k = None if key is None else jax.random.fold_in(key, i)
        flat = leaf.reshape(-1).astype(jnp.float32)
        size = flat.size
        pad = -(-size // group) * group - size
        g = jnp.pad(flat, (0, pad)).reshape(-1, group)
        q, sc = absmax_encode(g, axis=1, key=k)
        deq = absmax_decode(q, sc).reshape(-1)[:size]
        ok = jnp.isfinite(leaf).all()
        deq = jnp.where(ok, deq, flat[:size])
        out.append(deq.reshape(leaf.shape).astype(leaf.dtype))
    return jax.tree_util.tree_unflatten(treedef, out)
