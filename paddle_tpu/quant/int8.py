"""int8 execution of frozen quantized layers (reference: the mkldnn int8
kernel role + contrib/int8_inference) over the Pallas quantized-matmul
kernel: weights live as int8 (from quant.freeze), activations quantize
per-tensor at the recorded act scale, the GEMM accumulates int32 on the
MXU and dequantizes in the kernel epilogue."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.enforce import enforce
from ..nn.layer import Layer as _Layer
from ..ops.pallas.quant_matmul import quant_matmul


def _as_int8_weight(w):
    # any wider integer could hold values that wrap mod 256 — reject loudly
    # (quant.freeze with weight_bits=8 emits int8 directly)
    enforce(w.dtype == jnp.int8,
            "int8 execution needs int8 frozen weights, got %s "
            "(weight_bits != 8?)", w.dtype)
    return w


def _quantize_acts(x, act_scale):
    """Per-tensor activation quantization at the recorded abs-max scale
    — the shared ``quant.ops.absmax_encode`` convention (one rounding
    rule with the KV-pool and collective quantizers)."""
    from .ops import absmax_encode

    return absmax_encode(x, absmax=act_scale)




def int8_linear(x, frozen_entry, bias=None, *, out_dtype=jnp.float32,
                use_pallas=None, interpret: bool = False):
    """Run a frozen Linear layer in int8: x (N, D) float; frozen_entry is
    one value of quant.freeze()'s dict ({"weight_int8" (D, O),
    "weight_scale" (O,), "act_scale" scalar})."""
    w_i8 = _as_int8_weight(frozen_entry["weight_int8"])
    x_i8, a_scale = _quantize_acts(x, frozen_entry["act_scale"])
    w_scale = jnp.asarray(frozen_entry["weight_scale"],
                          jnp.float32) / 127.0
    out = quant_matmul(x_i8, w_i8, a_scale, w_scale, out_dtype=out_dtype,
                       use_pallas=use_pallas, interpret=interpret)
    if bias is not None:
        out = out + bias
    return out


class Int8Linear(_Layer):
    """Frozen int8 Linear executor: weights are fixed int8 BUFFERS (from
    quant.freeze), never trainable — a proper Layer so train/eval/state
    traversal over a swapped model keeps working."""

    def __init__(self, frozen_entry, bias=None, act=None):
        super().__init__()
        self.register_buffer("weight_int8",
                             jnp.asarray(frozen_entry["weight_int8"]))
        self.register_buffer("weight_scale",
                             jnp.asarray(frozen_entry["weight_scale"],
                                         jnp.float32))
        self.register_buffer("act_scale",
                             jnp.asarray(frozen_entry["act_scale"],
                                         jnp.float32))
        if bias is not None:
            self.register_buffer("linear_bias", jnp.asarray(bias))
        self.has_bias = bias is not None
        self.act = act

    def forward(self, x):
        entry = {"weight_int8": self.weight_int8,
                 "weight_scale": self.weight_scale,
                 "act_scale": self.act_scale}
        out = int8_linear(x, entry,
                          bias=self.linear_bias if self.has_bias else None)
        from ..nn.layers import _apply_act  # same resolver as nn.Linear

        return _apply_act(out, self.act)


def int8_swap(model, frozen):
    """Swap every frozen QuantedLayer-wrapped Linear and Conv2D —
    including grouped/depthwise, dilated, and NHWC convs (VERDICT r1 #7)
    — for Int8Linear/Int8Conv2D so ``model(x)`` inference runs the int8
    kernel path (the QuantizationFreezePass → int8 runtime handoff).
    Non-8-bit freezes keep the fake-quant float path; any skipped layer
    is reported loudly on stderr. Returns the number of layers swapped."""
    import sys as _sys

    from .qat import QuantedLayer

    swapped = 0
    for path, sub in list(model.named_sublayers()):
        if not isinstance(sub, QuantedLayer) or path not in frozen:
            continue
        if frozen[path].get("bits", 8) != 8:
            print(f"int8_swap: {path} skipped "
                  f"({frozen[path].get('bits')}-bit freeze stays on "
                  "the fake-quant float path)", file=_sys.stderr)
            continue  # int8 runtime only; 16-bit freezes stay float
        inner = sub.inner
        tname = type(inner).__name__
        if tname == "Linear":
            repl = Int8Linear(frozen[path],
                              bias=inner._params.get("bias"),
                              act=getattr(inner, "act", None))
        elif tname == "Conv2D":
            repl = Int8Conv2D(
                frozen[path], bias=inner._params.get("bias"),
                act=getattr(inner, "act", None),
                stride=getattr(inner, "stride", 1),
                padding=getattr(inner, "padding", 0),
                dilation=getattr(inner, "dilation", 1),
                groups=getattr(inner, "groups", 1),
                data_format=getattr(inner, "data_format", "NCHW"))
        else:
            print(f"int8_swap: {path} ({tname}) has no int8 executor — "
                  "stays on the fake-quant float path", file=_sys.stderr)
            continue
        # locate the parent and rebind the attribute/sublayer slot
        parent = model
        parts = path.split(".")
        for p in parts[:-1]:
            parent = parent._sublayers[p]
        parent._sublayers[parts[-1]] = repl
        object.__setattr__(parent, parts[-1], repl)
        swapped += 1
    return swapped


from ..ops.nn import _pair  # noqa: E402  (shared, enforce-validated)


def _im2col_nchw(x, kh: int, kw: int, stride, padding, dilation=1):
    """(B, C, H, W) -> (B*OH*OW, kh*kw*C) patches, (i, j, c) inner order —
    integer-safe (slicing only), so int8 activations stay int8. Supports
    rectangular stride/padding and dilated sampling."""
    sh, sw = _pair(stride)
    ph, pw = _pair(padding)
    dh, dw = _pair(dilation)
    b, c, h, w = x.shape
    if ph or pw:
        x = jnp.pad(x, ((0, 0), (0, 0), (ph, ph), (pw, pw)))
    oh = (h + 2 * ph - dh * (kh - 1) - 1) // sh + 1
    ow = (w + 2 * pw - dw * (kw - 1) - 1) // sw + 1
    cols = []
    for i in range(kh):
        for j in range(kw):
            cols.append(x[:, :,
                          i * dh:i * dh + (oh - 1) * sh + 1:sh,
                          j * dw:j * dw + (ow - 1) * sw + 1:sw])
    # (kh*kw, B, C, OH, OW) -> (B, OH, OW, kh*kw, C)
    stacked = jnp.stack(cols, axis=0)
    patches = jnp.transpose(stacked, (1, 3, 4, 0, 2))
    return patches.reshape(b * oh * ow, kh * kw * c), (b, oh, ow)


def int8_conv2d(x, frozen_entry, bias=None, *, stride=1, padding=0,
                dilation=1, groups: int = 1, data_format: str = "NCHW",
                out_dtype=jnp.float32, use_pallas=None,
                interpret: bool = False):
    """Frozen int8 Conv2D covering the full conv set (VERDICT r1 #7):

    - ``groups == 1``: quantize activations, im2col (int8 slicing — no
      float copy), ONE int8 GEMM on the MXU via the Pallas quantized
      matmul, dequant epilogue — the mkldnn int8-conv role (reference:
      paddle/fluid/operators/fused/conv2d_fusion_op.cc:1 + mkldnn int8
      kernels).
    - ``groups > 1`` (incl. depthwise): integer ``conv_general_dilated``
      with int32 accumulation — exact int8 arithmetic without G tiny
      GEMMs (depthwise is bandwidth-bound; the MXU GEMM wins nothing).
    - ``data_format="NHWC"``: edge transposes (XLA fuses them into the
      surrounding layout pipeline on TPU).

    x float -> float, same layout in and out.
    """
    w_i8 = _as_int8_weight(frozen_entry["weight_int8"])
    o, cpg, kh, kw = w_i8.shape  # weight layout OIHW (C-per-group)
    if data_format == "NHWC":
        x = jnp.transpose(x, (0, 3, 1, 2))
    x_i8, a_scale = _quantize_acts(x, frozen_entry["act_scale"])
    w_scale = jnp.asarray(frozen_entry["weight_scale"],
                          jnp.float32) / 127.0      # per-out-channel (O,)

    if groups == 1:
        patches, (b, oh, ow) = _im2col_nchw(x_i8, kh, kw, stride, padding,
                                            dilation)
        # weight -> (kh*kw*C, O) in the SAME (i, j, c) order as patches
        w_mat = jnp.transpose(w_i8, (2, 3, 1, 0)).reshape(kh * kw * cpg, o)
        out = quant_matmul(patches, w_mat, a_scale, w_scale,
                           out_dtype=out_dtype, use_pallas=use_pallas,
                           interpret=interpret)  # kernel pads internally
        out = jnp.transpose(out.reshape(b, oh, ow, o), (0, 3, 1, 2))
    else:
        sh, sw = _pair(stride)
        ph, pw = _pair(padding)
        dh, dw = _pair(dilation)
        acc = jax.lax.conv_general_dilated(
            x_i8.astype(jnp.int32), w_i8.astype(jnp.int32),
            window_strides=(sh, sw), padding=((ph, ph), (pw, pw)),
            rhs_dilation=(dh, dw), feature_group_count=groups,
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
            preferred_element_type=jnp.int32)
        out = (acc.astype(jnp.float32) * a_scale *
               w_scale.reshape(1, -1, 1, 1)).astype(out_dtype)
    if bias is not None:
        out = out + bias.reshape(1, -1, 1, 1)
    if data_format == "NHWC":
        out = jnp.transpose(out, (0, 2, 3, 1))
    return out


class Int8Conv2D(_Layer):
    """Frozen int8 Conv2D executor (int8 weight buffers; see Int8Linear)."""

    def __init__(self, frozen_entry, bias=None, act=None, stride=1,
                 padding=0, dilation=1, groups: int = 1,
                 data_format: str = "NCHW"):
        super().__init__()
        self.register_buffer("weight_int8",
                             jnp.asarray(frozen_entry["weight_int8"]))
        self.register_buffer("weight_scale",
                             jnp.asarray(frozen_entry["weight_scale"],
                                         jnp.float32))
        self.register_buffer("act_scale",
                             jnp.asarray(frozen_entry["act_scale"],
                                         jnp.float32))
        if bias is not None:
            self.register_buffer("conv_bias", jnp.asarray(bias))
        self.has_bias = bias is not None
        self.act = act
        self.stride, self.padding = stride, padding
        self.dilation, self.groups = dilation, groups
        self.data_format = data_format

    def forward(self, x):
        entry = {"weight_int8": self.weight_int8,
                 "weight_scale": self.weight_scale,
                 "act_scale": self.act_scale}
        out = int8_conv2d(x, entry,
                          bias=self.conv_bias if self.has_bias else None,
                          stride=self.stride, padding=self.padding,
                          dilation=self.dilation, groups=self.groups,
                          data_format=self.data_format)
        from ..nn.layers import _apply_act

        return _apply_act(out, self.act)
