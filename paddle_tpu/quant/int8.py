"""int8 execution of frozen quantized layers (reference: the mkldnn int8
kernel role + contrib/int8_inference) over the Pallas quantized-matmul
kernel: weights live as int8 (from quant.freeze), activations quantize
per-tensor at the recorded act scale, the GEMM accumulates int32 on the
MXU and dequantizes in the kernel epilogue."""

from __future__ import annotations

import jax.numpy as jnp

from ..core.enforce import enforce
from ..nn.layer import Layer as _Layer
from ..ops.pallas.quant_matmul import quant_matmul


def int8_linear(x, frozen_entry, bias=None, *, out_dtype=jnp.float32,
                use_pallas=None, interpret: bool = False):
    """Run a frozen Linear layer in int8: x (N, D) float; frozen_entry is
    one value of quant.freeze()'s dict ({"weight_int8" (D, O),
    "weight_scale" (O,), "act_scale" scalar})."""
    w_i8 = frozen_entry["weight_int8"]
    enforce(w_i8.dtype == jnp.int8 or w_i8.dtype == jnp.int32,
            "frozen weight must be integer, got %s", w_i8.dtype)
    w_i8 = w_i8.astype(jnp.int8)
    a_scale = jnp.maximum(jnp.asarray(frozen_entry["act_scale"],
                                      jnp.float32) / 127.0, 1e-10)
    x_i8 = jnp.clip(jnp.round(x / a_scale), -127, 127).astype(jnp.int8)
    w_scale = jnp.asarray(frozen_entry["weight_scale"],
                          jnp.float32) / 127.0
    out = quant_matmul(x_i8, w_i8, a_scale, w_scale, out_dtype=out_dtype,
                       use_pallas=use_pallas, interpret=interpret)
    if bias is not None:
        out = out + bias
    return out


class Int8Linear(_Layer):
    """Frozen int8 Linear executor: weights are fixed int8 BUFFERS (from
    quant.freeze), never trainable — a proper Layer so train/eval/state
    traversal over a swapped model keeps working."""

    def __init__(self, frozen_entry, bias=None, act=None):
        super().__init__()
        self.register_buffer("weight_int8",
                             jnp.asarray(frozen_entry["weight_int8"]))
        self.register_buffer("weight_scale",
                             jnp.asarray(frozen_entry["weight_scale"],
                                         jnp.float32))
        self.register_buffer("act_scale",
                             jnp.asarray(frozen_entry["act_scale"],
                                         jnp.float32))
        if bias is not None:
            self.register_buffer("linear_bias", jnp.asarray(bias))
        self.has_bias = bias is not None
        self.act = act

    def forward(self, x):
        entry = {"weight_int8": self.weight_int8,
                 "weight_scale": self.weight_scale,
                 "act_scale": self.act_scale}
        out = int8_linear(x, entry,
                          bias=self.linear_bias if self.has_bias else None)
        from ..nn.layers import _apply_act  # same resolver as nn.Linear

        return _apply_act(out, self.act)


def int8_swap(model, frozen):
    """Swap every frozen QuantedLayer-wrapped Linear for an Int8Linear so
    plain ``model(x)`` inference runs the int8 kernel path (the
    QuantizationFreezePass → int8 runtime handoff). Conv layers keep the
    fake-quant float path (int8 conv lowering is a further step). Returns
    the number of layers swapped."""
    from .qat import QuantedLayer

    swapped = 0
    for path, sub in list(model.named_sublayers()):
        if not isinstance(sub, QuantedLayer) or path not in frozen:
            continue
        inner = sub.inner
        if type(inner).__name__ != "Linear":
            continue
        repl = Int8Linear(frozen[path],
                          bias=inner._params.get("bias"),
                          act=getattr(inner, "act", None))
        # locate the parent and rebind the attribute/sublayer slot
        parent = model
        parts = path.split(".")
        for p in parts[:-1]:
            parent = parent._sublayers[p]
        parent._sublayers[parts[-1]] = repl
        object.__setattr__(parent, parts[-1], repl)
        swapped += 1
    return swapped
