"""int8 execution of frozen quantized layers (reference: the mkldnn int8
kernel role + contrib/int8_inference) over the Pallas quantized-matmul
kernel: weights live as int8 (from quant.freeze), activations quantize
per-tensor at the recorded act scale, the GEMM accumulates int32 on the
MXU and dequantizes in the kernel epilogue."""

from __future__ import annotations

import jax.numpy as jnp

from ..core.enforce import enforce
from ..ops.pallas.quant_matmul import quant_matmul


def int8_linear(x, frozen_entry, bias=None, *, out_dtype=jnp.float32,
                use_pallas=None, interpret: bool = False):
    """Run a frozen Linear layer in int8: x (N, D) float; frozen_entry is
    one value of quant.freeze()'s dict ({"weight_int8" (D, O),
    "weight_scale" (O,), "act_scale" scalar})."""
    w_i8 = frozen_entry["weight_int8"]
    enforce(w_i8.dtype == jnp.int8 or w_i8.dtype == jnp.int32,
            "frozen weight must be integer, got %s", w_i8.dtype)
    w_i8 = w_i8.astype(jnp.int8)
    a_scale = jnp.maximum(jnp.asarray(frozen_entry["act_scale"],
                                      jnp.float32) / 127.0, 1e-10)
    x_i8 = jnp.clip(jnp.round(x / a_scale), -127, 127).astype(jnp.int8)
    w_scale = jnp.asarray(frozen_entry["weight_scale"],
                          jnp.float32) / 127.0
    out = quant_matmul(x_i8, w_i8, a_scale, w_scale, out_dtype=out_dtype,
                       use_pallas=use_pallas, interpret=interpret)
    if bias is not None:
        out = out + bias
    return out
