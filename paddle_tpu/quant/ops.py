"""Fake-quantization ops — capability parity with the reference's quant op
family (reference: paddle/fluid/operators/fake_quantize_op.cc —
fake_quantize_abs_max, fake_channel_wise_quantize_abs_max,
fake_quantize_range_abs_max, fake_quantize_moving_average_abs_max,
fake_quantize_dequantize_moving_average_abs_max, moving_average_abs_max_scale
— and fake_dequantize_op.cc).

All ops simulate int-k quantization in float (quantize→round→dequantize) so
training stays on the MXU in bf16/f32; gradients use the straight-through
estimator exactly like the reference's grad kernels (identity inside the
clipping range). Stateful scale trackers (range / moving-average) are
functional: they take and return their state, JAX-style, instead of mutating
in/out vars.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from ..core.enforce import enforce


def _qmax(bit_length: int) -> float:
    return float((1 << (bit_length - 1)) - 1)  # 127 for int8


@jax.custom_vjp
def _ste_round(x):
    return jnp.round(x)


def _ste_round_fwd(x):
    return jnp.round(x), None


def _ste_round_bwd(_, g):
    return (g,)


_ste_round.defvjp(_ste_round_fwd, _ste_round_bwd)


def quantize_dequantize(x, scale, bit_length: int = 8):
    """Simulated quantization: clip to [-scale, scale], round to int-k grid,
    return float. STE gradient: identity inside the clip range, zero outside
    (matches FakeQuantizeAbsMaxGradKernel semantics)."""
    qmax = _qmax(bit_length)
    scale = jnp.maximum(jnp.asarray(scale, x.dtype), 1e-8)
    inv = qmax / scale
    clipped = jnp.clip(x, -scale, scale)  # clip grad handles out-of-range zeroing
    return _ste_round(clipped * inv) / inv


def abs_max_scale(x, axis=None):
    """Current abs-max of a tensor (per-channel when ``axis`` is given)."""
    if axis is None:
        return jnp.max(jnp.abs(x))
    reduce_axes = tuple(i for i in range(x.ndim) if i != axis)
    return jnp.max(jnp.abs(x), axis=reduce_axes)


def fake_quantize_abs_max(x, bit_length: int = 8):
    """reference: fake_quantize_abs_max — scale = abs-max of this tensor.
    Returns (quantized x, scale)."""
    scale = abs_max_scale(x)
    return quantize_dequantize(x, scale, bit_length), scale


def fake_channel_wise_quantize_abs_max(x, bit_length: int = 8,
                                       channel_axis: int = 0):
    """reference: fake_channel_wise_quantize_abs_max — one scale per output
    channel (weights)."""
    scale = abs_max_scale(x, axis=channel_axis)
    shape = [1] * x.ndim
    shape[channel_axis] = x.shape[channel_axis]
    return (quantize_dequantize(x, scale.reshape(shape), bit_length), scale)


class MovingAverageState(NamedTuple):
    scale: jnp.ndarray  # scalar running scale
    accum: jnp.ndarray
    state: jnp.ndarray


def moving_average_state_init(dtype=jnp.float32) -> MovingAverageState:
    return MovingAverageState(jnp.asarray(0.0, dtype),
                              jnp.asarray(0.0, dtype),
                              jnp.asarray(0.0, dtype))


def moving_average_abs_max_scale(x, st: MovingAverageState,
                                 moving_rate: float = 0.9
                                 ) -> Tuple[jnp.ndarray, MovingAverageState]:
    """reference: moving_average_abs_max_scale op — EMA of abs-max with
    bias-corrected accumulators (accum/state pair)."""
    cur = abs_max_scale(x).astype(st.scale.dtype)
    accum = st.accum * moving_rate + cur
    state = st.state * moving_rate + 1.0
    scale = accum / state
    return scale, MovingAverageState(scale, accum, state)


def fake_quantize_moving_average_abs_max(x, st: MovingAverageState,
                                         bit_length: int = 8,
                                         moving_rate: float = 0.9,
                                         is_test: bool = False):
    """reference: fake_quantize_moving_average_abs_max (and the fused
    fake_quantize_dequantize_ variant — identical here since all fake quant
    is quantize+dequantize). Returns (quantized, new_state)."""
    if is_test:
        return quantize_dequantize(x, st.scale, bit_length), st
    scale, new_st = moving_average_abs_max_scale(x, st, moving_rate)
    return quantize_dequantize(x, scale, bit_length), new_st


class RangeState(NamedTuple):
    scale: jnp.ndarray       # current scale
    scales_window: jnp.ndarray  # (window,) recent abs-max ring buffer
    step: jnp.ndarray        # int32 counter


def range_state_init(window_size: int = 10000,
                     dtype=jnp.float32) -> RangeState:
    return RangeState(jnp.asarray(0.0, dtype),
                      jnp.zeros((window_size,), dtype),
                      jnp.asarray(0, jnp.int32))


def fake_quantize_range_abs_max(x, st: RangeState, bit_length: int = 8,
                                is_test: bool = False):
    """reference: fake_quantize_range_abs_max — scale = max of a sliding
    window of recent abs-max values. Returns (quantized, new_state)."""
    if is_test:
        return quantize_dequantize(x, st.scale, bit_length), st
    cur = abs_max_scale(x).astype(st.scale.dtype)
    idx = st.step % st.scales_window.shape[0]
    window = st.scales_window.at[idx].set(cur)
    scale = jnp.max(window)
    return (quantize_dequantize(x, scale, bit_length),
            RangeState(scale, window, st.step + 1))


# ---------------------------------------------------------------------------
# THE shared abs-max int-k encode/decode (one rounding convention for
# every real-int8 quantizer in the tree: int8 activation execution,
# the quantized paged-KV pool, and the compressed gradient collectives
# — three conventions drifting apart is a parity bug waiting to happen)
# ---------------------------------------------------------------------------


def absmax_encode(x, axis: Optional[int] = None, *, absmax=None,
                  bit_length: int = 8, eps: float = 1e-10, key=None):
    """Quantize ``x`` onto the symmetric int-k grid at an abs-max scale.

    Convention (the ONE every caller shares): ``scale = max(absmax /
    qmax, eps)``; ``q = clip(round(x / scale), -qmax, qmax)`` as int8
    (int16 above 8 bits); dequant is ``q * scale`` (:func:`absmax_decode`).

    - ``axis``: reduction axis the abs-max is taken over (``None`` =
      whole tensor, scalar scale). The returned scale keeps the reduced
      axis with size 1, so ``q * scale`` broadcasts back.
    - ``absmax``: externally-recorded abs-max (calibrated activation
      scales) — skips the local reduction; ``axis`` is ignored.
    - ``key``: optional PRNG key switching nearest-even rounding to
      STOCHASTIC rounding (``floor(y + u)``, ``u ~ U[0, 1)`` —
      unbiased: ``E[q] = y``), the gradient-compression option where
      rounding bias would accumulate across steps.

    Returns ``(q, scale)`` with ``scale`` float32.
    """
    qmax = _qmax(bit_length)
    if absmax is None:
        absmax = jnp.max(jnp.abs(x), axis=axis,
                         keepdims=axis is not None)
    scale = jnp.maximum(jnp.asarray(absmax, jnp.float32) / qmax, eps)
    y = x.astype(jnp.float32) / scale
    if key is not None:
        y = jnp.floor(y + jax.random.uniform(key, y.shape))
    else:
        y = jnp.round(y)
    dtype = jnp.int8 if bit_length <= 8 else jnp.int16
    return jnp.clip(y, -qmax, qmax).astype(dtype), scale


def absmax_decode(q, scale):
    """Map an :func:`absmax_encode` payload back to float32:
    ``q * scale`` (scale broadcasts — the reduced axis was kept)."""
    return q.astype(jnp.float32) * scale


def dequantize(q, scale, bit_length: int = 8, quant_axis: Optional[int] = None):
    """reference: fake_dequantize_max_abs / channel-wise variant — map an
    int-k grid tensor back to float: q * scale / qmax."""
    qmax = _qmax(bit_length)
    scale = jnp.asarray(scale, jnp.float32)
    if quant_axis is not None and scale.ndim == 1:
        shape = [1] * q.ndim
        shape[quant_axis] = q.shape[quant_axis]
        scale = scale.reshape(shape)
    return q.astype(jnp.float32) * scale / qmax


def quantize_to_int(x, scale, bit_length: int = 8):
    """Real int quantization for export (reference: operators/quantize_op.cc
    role): returns int8/int16 values on the int-k grid."""
    qmax = _qmax(bit_length)
    scale = jnp.maximum(jnp.asarray(scale, x.dtype), 1e-8)
    q = jnp.round(jnp.clip(x, -scale, scale) * (qmax / scale))
    dtype = jnp.int8 if bit_length <= 8 else jnp.int16
    return q.astype(dtype)
