"""Weight-only int8 quantization (W8A16) — the LM-serving memory-
bandwidth lever: weights store as per-output-channel symmetric int8
(half of bf16, a quarter of fp32 HBM bytes) and dequantize in-register
at matmul time (XLA fuses the convert+scale into the operand read), so
the bandwidth-bound decode loop streams half the weight bytes while
activations and accumulation stay high-precision.

Different trade than quant/int8.py's full int8 execution (QAT/PTQ +
int8 GEMM kernel): that path quantizes ACTIVATIONS too and needs
calibration; this one is a pure post-training weight transform — no
data, no retraining, accuracy within bf16 noise for typical LMs.

Reference niche: the int8 serving capability family
(/root/reference/paddle/fluid/inference/api/mkldnn_quantizer.cc role);
weight-only is its modern decode-serving variant.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence

import jax.numpy as jnp

from ..core.dtypes import get_policy
from ..core.enforce import enforce
from ..nn.layer import Layer
from ..nn.layers import Linear, _apply_act


class WeightOnlyLinear(Layer):
    """A Linear whose weight lives as int8 + per-out-channel fp32
    scales (buffers — this is a serving transform, nothing trains).
    Same forward contract (bias, act, AMP policy) as the Linear it
    replaces."""

    def __init__(self, inner: Linear):
        super().__init__()
        enforce(isinstance(inner, Linear),
                "WeightOnlyLinear wraps nn.Linear, got %s",
                type(inner).__name__)
        self.in_features = inner.in_features
        self.out_features = inner.out_features
        self.act = inner.act
        self.has_bias = inner.has_bias
        from .ops import abs_max_scale, quantize_to_int

        # the package-wide convention (quant/ops.py): scale = per-channel
        # abs-max, int grid = round(w * 127 / scale), dequant = q *
        # scale / 127 — so this buffer interoperates with
        # quant.dequantize(q, scale, quant_axis=1)
        w = inner.weight.astype(jnp.float32)          # (in, out)
        scale = jnp.maximum(abs_max_scale(w, axis=1), 1e-8)
        q = quantize_to_int(w, scale[None, :])
        self.register_buffer("qweight", q)
        self.register_buffer("scale", scale)
        if inner.has_bias:
            self.register_buffer("bias", inner.bias)

    def forward(self, x):
        pol = get_policy()
        xc = pol.cast_to_compute(x)
        # dequant in the compute dtype: int8 -> bf16 mul fuses into the
        # matmul operand read; the int8 bytes are what HBM streams
        w = (self.qweight.astype(xc.dtype)
             * (self.scale / 127.0).astype(xc.dtype))
        out = jnp.matmul(xc, w)
        if self.has_bias:
            out = out + pol.cast_to_compute(self.bias)
        return _apply_act(pol.cast_to_output(out), self.act)

    def dequantized_weight(self):
        from .ops import dequantize

        return dequantize(self.qweight, self.scale, quant_axis=1)


def apply_weight_only_int8(model: Layer,
                           targets: Optional[Sequence[str]] = None,
                           predicate: Optional[
                               Callable[[str, Layer], bool]] = None,
                           min_features: int = 0) -> List[str]:
    """Replace matching Linear sublayers with WeightOnlyLinear in place
    (the quantize_model/apply_lora rewrite idiom); returns the wrapped
    paths. ``targets``: attribute-name suffixes (None = every Linear);
    ``min_features``: skip layers smaller than this on BOTH dims (tiny
    heads gain nothing and lose the most precision)."""
    from ..nn.rewrite import rewrite_linears

    def big_enough(path, sub):
        return (max(sub.in_features, sub.out_features) >= min_features
                and (predicate is None or predicate(path, sub)))

    return rewrite_linears(
        model, WeightOnlyLinear, targets=targets, predicate=big_enough,
        skip=lambda sub: isinstance(sub, WeightOnlyLinear),
        what="apply_weight_only_int8")
