"""paddle_tpu.resilience — the fault-tolerance plane.

The layer that turns failures into recoverable events (the diagnostics
plane of PR 4 can *see* a failure; this one *survives* it):

- ``preemption``: SIGTERM/SIGINT grace handler — opted into by
  ``TrainLoop.run(preemption=...)`` and
  ``serving.BatchedDecoder.run(preemption=...)``; the loop finishes the
  in-flight step, writes a final checkpoint / drains in-flight
  requests, and exits with a ``preempted`` status instead of dying
  mid-save.
- ``retry``: capped exponential backoff + seeded jitter for transient
  I/O (``pt_retry_total``), deadline-bounded — checkpoint save/restore
  wrap every file op in it.
- ``integrity``: per-file checksums (crc32c when native, else crc32)
  recorded in the checkpoint manifest and verified on restore.
- ``faults``: seeded deterministic :class:`FaultInjector` with named
  injection points (``ckpt.write``, ``ckpt.manifest``, ``ckpt.stage``,
  ``ckpt.commit``, ``restore.read``, ``step.nan``, ``io.slow``,
  ``fleet.notice``) — the substrate of the chaos test suite. Off by
  default with zero hot-path cost.
- ``reliability``: the request reliability plane — end-to-end
  :class:`Deadline` budgets (minted at ``Router.submit``, propagated
  via ``X-PT-Deadline`` beside the trace header and through
  ``KVHandoff``), SRE-style :class:`RetryBudget` token buckets,
  adaptive hedged dispatch, and per-replica gray-failure circuit
  breakers (:class:`ReplicaHealth`: closed → open → half-open probe).
- ``controller``: the elastic fleet controller —
  :class:`FleetController` agrees "preempt at step N" across ranks
  over the coordination transport, makes every PERIODIC save a
  step-agreed two-phase transaction ("all hosts save step N or none" —
  the ``ckpt.staged.<rank>`` / global ``ckpt.committed`` protocol
  CheckpointManager drives through its ``coordinator=`` seam), agrees
  on ONE fleet-held restore step at resume, watches a metadata notice
  source ahead of SIGTERM, aggregates per-rank health into ``/podz``
  (including ``last_committed_global`` commit-drift rows), and (with
  ``launch.py --elastic``) lets the job respawn on N-1 hosts from the
  last committed checkpoint. :class:`BarrierTimeoutError` is the typed
  diagnostic every coordination wait raises on expiry — naming the
  missing ranks on the coordination-service path too, not just the
  shared-FS fallback.

Everything here is opt-in: with no handler installed and no injector
armed, the training/serving hot paths execute no resilience code (the
telemetry-off discipline, pinned by test).
"""

from __future__ import annotations

from typing import Any, Dict

from . import controller, faults, integrity, preemption, reliability, retry
from .controller import (BarrierTimeoutError, FileNotice,
                         FleetController, HttpNotice)
from .faults import POINTS, FaultError, FaultInjector
from .integrity import ChecksumError, checksum_bytes, verify_bytes
from .preemption import PreemptionHandler
from .reliability import (DEADLINE_HEADER, Deadline, DeadlineExceededError,
                          LatencyTracker, ReliabilityConfig,
                          ReliabilityPlane, ReplicaHealth, RetryBudget,
                          RetryBudgetExhaustedError)
from .retry import DEFAULT_POLICY, RetryPolicy, retry_io

__all__ = [
    "BarrierTimeoutError", "ChecksumError", "DEADLINE_HEADER",
    "DEFAULT_POLICY", "Deadline", "DeadlineExceededError", "FaultError",
    "FaultInjector", "FileNotice", "FleetController", "HttpNotice",
    "LatencyTracker", "POINTS", "PreemptionHandler", "ReliabilityConfig",
    "ReliabilityPlane", "ReplicaHealth", "RetryBudget",
    "RetryBudgetExhaustedError", "RetryPolicy", "checksum_bytes",
    "controller", "faults", "integrity", "preemption", "reliability",
    "retry", "retry_io", "statusz", "verify_bytes",
]


def statusz() -> Dict[str, Any]:
    """Resilience section for the debug server's /statusz: ambient
    preemption-handler state + armed-injector schedule (both usually
    absent — that absence is itself the signal)."""
    out: Dict[str, Any] = {}
    handler = preemption.active()
    out["preemption"] = (handler.statusz() if handler is not None
                        else {"installed": False})
    inj = faults.active()
    out["faults"] = (inj.statusz() if inj is not None
                     else {"armed": False})
    ctl = controller.active()
    out["controller"] = (ctl.statusz() if ctl is not None
                         else {"active": False})
    return out
