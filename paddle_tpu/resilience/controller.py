"""Elastic fleet controller — the control plane that coordinates hosts.

PR 5 made ONE process preemption-safe: each rank reacts to its own
SIGTERM, finishes the in-flight step, and checkpoints wherever it
stands. Multi-host that is not enough — ranks receive the signal at
different steps, a desynced rank fails the final cooperative save
loudly, and a rank that dies outright kills the whole job. This module
is the missing coordinator, split out of the data plane the way the
TensorFlow paper separates control-plane RPC from tensor traffic
(PAPERS.md): a tiny key-value protocol over the job's coordination
transport agrees on ONE "preempt at step N" for the whole fleet.

The pieces:

- :class:`FleetController` — one per rank, woven through
  ``TrainLoop.run(controller=...)``. On a preemption notice (its
  :class:`~.preemption.PreemptionHandler`'s SIGTERM flag, a metadata
  watcher, or a peer's published ack) the rank publishes
  ``preempt.ack.<rank> = <own step>`` and HOLDS; once every live rank's
  ack is in, the agreed step is ``max(acks)`` — held ranks catch up to
  it, every rank commits the SAME step, and a commit-confirmation wait
  keeps any rank from reporting a clean exit before the whole fleet's
  checkpoint is on disk.
- Coordination transports — :class:`ClientTransport` rides the JAX
  coordination service (``checkpoint._barrier``'s client) when the job
  brought one up; :class:`FileTransport` is the shared-filesystem
  fallback the CI rig and coordinator-less jobs use (same stance as the
  checkpoint file-barrier fallback). Keys are namespaced by a per-job
  ``run_id`` so an elastic restart never reads a dead attempt's state.
- A metadata **watcher** thread — polls a pluggable
  :class:`NoticeSource` (the GCE/TPU maintenance-event metadata URL, or
  a file stub for CI) and raises the preempt flag AHEAD of SIGTERM for
  a longer grace window.
- ``/podz`` — pod-level aggregation: the controller publishes each
  rank's debug-server endpoint through the transport, and any rank's
  ``/podz`` fans out to every worker's ``/healthz`` + ``/statusz`` +
  ``/memz`` and renders one fleet view (per-rank heartbeat age, last
  committed step, preempt state).
- :class:`BarrierTimeoutError` — the typed diagnostic every
  coordination wait (checkpoint barriers included) raises on expiry,
  naming the ranks that never arrived instead of an opaque timeout.

``launch.py --elastic`` closes the loop: a dead worker is marked
``dead.<rank>`` through the transport (survivors drop it from
agreement and exit clean within the grace window) and the job respawns
on the surviving hosts from the last COMMITTED checkpoint.

Zero-cost when unused: no controller, no code on the hot path — the
loop resolves ``controller`` once per run, and ``check()`` is an Event
peek plus a time-throttled transport poll.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from .. import telemetry
from ..core.enforce import EnforceError, enforce
from ..telemetry import tracing as _tracing
from ..utils.atomic import atomic_write_text
from . import faults as _faults
from .preemption import PreemptionHandler
from .retry import RetryPolicy, retry_io

__all__ = [
    "BarrierTimeoutError", "ClientTransport", "FileNotice",
    "FleetController", "HttpNotice", "active", "auto_transport",
    "notice_source_from_env",
]

_ACTIVE: Optional["FleetController"] = None

# Transport KV writes ride the shared transient-I/O retry machinery: a
# shared-FS blip (OSError) or a coordination-service RPC hiccup
# (RuntimeError — jax's client surfaces gRPC faults as XlaRuntimeError)
# costs a short bounded backoff instead of tearing a save or an
# agreement. Deadline-bounded: an op that keeps failing raises inside
# 10s, it never wedges a commit.
_KV_POLICY = RetryPolicy(max_attempts=4, base_delay_s=0.02,
                         max_delay_s=0.5, deadline_s=10.0,
                         retry_on=(OSError, RuntimeError))

# env protocol (set by launch.py for every worker; overridable):
ENV_FLEET_DIR = "PT_FLEET_DIR"       # FileTransport root (shared FS)
ENV_RUN_ID = "PT_FLEET_RUN_ID"       # per-attempt namespace for keys
ENV_NOTICE = "PT_PREEMPT_NOTICE"     # notice source: http(s) URL | path


@telemetry.cached_instruments
def _fleet_metrics(reg):
    return {
        "agreements": reg.counter(
            "pt_fleet_preempt_agreements_total",
            "coordinated preempt-at-step agreements reached"),
        "notices": reg.counter(
            "pt_fleet_preempt_notices_total",
            "preemption notices raised by the metadata watcher"),
        "barrier_timeouts": reg.counter(
            "pt_barrier_timeouts_total",
            "coordination barrier / fleet-agreement waits that "
            "timed out"),
        "commit_lag": reg.gauge(
            "pt_checkpoint_commit_lag_steps",
            "steps this rank's newest staged checkpoint is ahead of "
            "the fleet's newest globally-committed step (commit "
            "drift; 0 = the whole fleet is caught up)"),
    }


def note_barrier_timeout() -> None:
    """Bump ``pt_barrier_timeouts_total`` (shared with checkpoint's
    barrier paths — one counter for every coordination-wait expiry)."""
    if telemetry.enabled():
        _fleet_metrics()["barrier_timeouts"].inc()


class BarrierTimeoutError(EnforceError):
    """A coordination wait (checkpoint barrier, preempt agreement,
    commit confirmation) expired. Unlike the opaque transport error it
    replaces, this names the ranks that never arrived — the first thing
    an operator needs when one host of a pod wedges. An
    :class:`~..core.enforce.EnforceError`: drive loops propagate it
    (a half-agreed fleet must fail loudly, never be 'recovered' into
    silent divergence)."""

    def __init__(self, tag: str, *, missing: Optional[List[int]] = None,
                 world: Optional[int] = None,
                 timeout_s: Optional[float] = None,
                 detail: Optional[str] = None):
        self.tag = tag
        self.missing = sorted(missing) if missing else []
        self.world = world
        self.timeout_s = timeout_s
        who = (f"missing ranks {self.missing}" if self.missing
               else "missing ranks unknown (coordination-service "
                    "barrier)")
        msg = (f"barrier/agreement '{tag}' timed out after "
               f"{timeout_s}s ({who}, world={world})")
        if detail:
            msg += f": {detail}"
        super().__init__(msg)


# ---------------------------------------------------------------------------
# Coordination transports
# ---------------------------------------------------------------------------

class FileTransport:
    """Shared-filesystem key-value fallback (the file-barrier stance:
    jobs without a coordination service rendezvous through the
    checkpoint FS). One file per key, atomic-published; keys are
    namespaced ``<run_id>.<key>`` so a crash-restarted or elastic
    successor run never reads a dead attempt's acks as live state."""

    kind = "file"

    def __init__(self, root: str, run_id: str = "r0",
                 stale_age_s: float = 120.0):
        self.root = root
        self.run_id = run_id
        self.stale_age_s = stale_age_s

    def _path(self, key: str) -> str:
        return os.path.join(self.root, f"{self.run_id}.{key}")

    def put(self, key: str, value: str) -> None:
        os.makedirs(self.root, exist_ok=True)
        atomic_write_text(self._path(key), value)

    def get(self, key: str) -> Optional[str]:
        try:
            with open(self._path(key)) as f:
                return f.read()
        except OSError:
            return None

    def delete(self, key: str) -> None:
        try:
            os.unlink(self._path(key))
        except OSError:
            pass  # already gone (a peer reclaimed it first)

    def sweep(self) -> int:
        """GC other-run litter past the stale age. Prefix namespacing
        already makes foreign keys invisible to :meth:`get`; this just
        keeps the root from accumulating forever across elastic
        restarts into the same directory."""
        prefix = self.run_id + "."
        try:
            names = os.listdir(self.root)
        except OSError:
            return 0
        removed = 0
        now = time.time()
        for name in names:
            if name.startswith(prefix):
                continue
            path = os.path.join(self.root, name)
            try:
                if now - os.path.getmtime(path) > self.stale_age_s:
                    os.unlink(path)
                    removed += 1
            except OSError:
                pass  # a peer swept it first
        return removed


class ClientTransport:
    """The JAX coordination-service KV store (``checkpoint._barrier``'s
    client) — the production transport whenever the job brought the
    service up (``fleet.init`` multi-process)."""

    kind = "client"

    def __init__(self, client, run_id: str = "r0"):
        self._client = client
        self.run_id = run_id

    def _key(self, key: str) -> str:
        return f"pt_fleet/{self.run_id}/{key}"

    def put(self, key: str, value: str) -> None:
        # allow_overwrite: the protocol's shared keys (preempt.flag,
        # the global ckpt.committed.<N> marker) are written by EVERY
        # rank with the same idempotent value — the service's default
        # rejects the second writer, which would tear a commit that
        # actually succeeded
        try:
            self._client.key_value_set(self._key(key), value,
                                       allow_overwrite=True)
        except TypeError:
            # old clients without the kwarg: tolerate the duplicate
            # publish (same-value rewrites are harmless by design)
            try:
                self._client.key_value_set(self._key(key), value)
            except Exception as e:
                if "already exists" not in str(e).lower():
                    raise

    def get(self, key: str) -> Optional[str]:
        try_get = getattr(self._client, "key_value_try_get", None)
        try:
            if try_get is not None:
                return try_get(self._key(key))
            # old clients: a blocking get with a tiny deadline is the
            # only non-blocking probe available
            return self._client.blocking_key_value_get(
                self._key(key), 50)
        except Exception:
            return None  # NotFound surfaces as an error on both paths

    def delete(self, key: str) -> None:
        try:
            self._client.key_value_delete(self._key(key))
        except Exception:
            pass  # already gone / old client without delete

    def sweep(self) -> int:
        return 0  # the service dies with the job; nothing persists


def coordination_client():
    """The live JAX coordination-service client, or None (single
    process / ``fleet.init(connect=False)`` / plain tests)."""
    try:
        from jax._src import distributed as _dist

        return getattr(_dist.global_state, "client", None)
    except Exception:
        return None


def auto_transport(*, run_id: Optional[str] = None,
                   root: Optional[str] = None):
    """Pick the transport the way ``checkpoint._barrier`` picks its
    rendezvous: the coordination client when the job has one, else the
    shared-filesystem fallback (root: explicit > ``PT_FLEET_DIR`` >
    ``./.pt_fleet``)."""
    run_id = run_id or os.environ.get(ENV_RUN_ID) or "r0"
    client = coordination_client()
    if client is not None:
        return ClientTransport(client, run_id)
    root = (root or os.environ.get(ENV_FLEET_DIR)
            or os.path.join(os.getcwd(), ".pt_fleet"))
    return FileTransport(root, run_id)


# ---------------------------------------------------------------------------
# Preemption notice sources (the metadata watcher's pluggable input)
# ---------------------------------------------------------------------------

class FileNotice:
    """CI / orchestrator stub: the notice is a file appearing at
    ``path`` (an init-container or test touches it)."""

    def __init__(self, path: str):
        self.path = path

    def poll(self) -> bool:
        return os.path.exists(self.path)

    def describe(self) -> str:
        return f"file:{self.path}"


class HttpNotice:
    """GCE/TPU metadata poller. The default URL is the instance
    maintenance-event endpoint; any body other than ``NONE`` (or a
    configured ``trigger`` substring match) is a preemption notice —
    delivered minutes before the SIGTERM, which is the whole point:
    the fleet agrees and commits on the LONG grace window."""

    DEFAULT_URL = ("http://metadata.google.internal/computeMetadata/v1/"
                   "instance/maintenance-event")

    def __init__(self, url: Optional[str] = None,
                 trigger: Optional[str] = None,
                 timeout_s: float = 2.0):
        self.url = url or self.DEFAULT_URL
        self.trigger = trigger
        self.timeout_s = timeout_s

    def poll(self) -> bool:
        import urllib.request

        req = urllib.request.Request(
            self.url, headers={"Metadata-Flavor": "Google"})
        with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
            body = r.read().decode("utf-8", "replace").strip()
        if self.trigger is not None:
            return self.trigger in body
        return body not in ("", "NONE")

    def describe(self) -> str:
        return f"http:{self.url}"


def notice_source_from_env(env=None):
    """Build the notice source ``PT_PREEMPT_NOTICE`` names: an
    ``http(s)://`` URL → :class:`HttpNotice`, anything else → a
    :class:`FileNotice` path. None when unset."""
    env = os.environ if env is None else env
    spec = env.get(ENV_NOTICE)
    if not spec:
        return None
    if spec.startswith("http://") or spec.startswith("https://"):
        return HttpNotice(spec)
    return FileNotice(spec)


def _fetch_json(url: str, timeout_s: float = 2.0):
    import urllib.request

    try:
        with urllib.request.urlopen(url, timeout=timeout_s) as r:
            return json.loads(r.read().decode("utf-8"))
    except Exception as e:  # per-rank rows degrade, /podz never 500s
        return {"error": repr(e)}


# ---------------------------------------------------------------------------
# The controller
# ---------------------------------------------------------------------------

class FleetController:
    """One rank's view of the fleet control plane.

    Protocol (symmetric — no special coordinator rank, so killing ANY
    rank mid-agreement degrades the same way):

    1. A rank notices preemption: its handler's SIGTERM flag,
       :meth:`request` (metadata watcher / API), or — sampled every
       ``poll_interval_s`` — a peer's published ack.
    2. It publishes ``preempt.ack.<rank> = <its step>`` and holds,
       polling until every LIVE rank's ack is present (ranks marked
       ``dead.<rank>`` by the launcher are dropped from agreement —
       survivors never hang on a corpse). Timeout ⇒
       :class:`BarrierTimeoutError` naming the missing ranks.
    3. Agreed step = ``max(acks)``: no rank ever rewinds; held ranks
       resume and train UP TO the agreed step, then every rank commits
       the same step and confirms through ``committed.<rank>``.

    ``TrainLoop.run(controller=...)`` drives all of this; the only
    methods loops call are :meth:`check` (per step),
    :meth:`confirm_committed` (after the final save), and
    :meth:`note_checkpoint` (after periodic saves, for /podz rows).
    """

    def __init__(self, *, rank: Optional[int] = None,
                 world: Optional[int] = None,
                 transport=None,
                 handler: Optional[PreemptionHandler] = None,
                 notice_source=None,
                 coordination_dir: Optional[str] = None,
                 run_id: Optional[str] = None,
                 poll_interval_s: float = 0.25,
                 hold_poll_s: float = 0.02,
                 watch_interval_s: float = 2.0,
                 agree_timeout_s: float = 60.0,
                 commit_timeout_s: float = 300.0,
                 ckpt_timeout_s: float = 300.0,
                 dead_grace_s: float = 5.0,
                 podz_fetch_timeout_s: float = 2.0):
        env = os.environ
        if rank is None:
            rank = int(env.get("PADDLE_TRAINER_ID",
                               env.get("JAX_PROCESS_ID", 0)))
        if world is None:
            world = int(env.get("PADDLE_TRAINERS_NUM",
                                env.get("JAX_NUM_PROCESSES", 1)))
        enforce(0 <= rank < world,
                "rank %s out of range for world size %s", rank, world)
        self.rank = rank
        self.world = world
        self.run_id = run_id or env.get(ENV_RUN_ID) or "r0"
        if transport is None and world > 1:
            transport = auto_transport(run_id=self.run_id,
                                       root=coordination_dir)
        self.transport = transport
        # the launcher is transport-agnostic: its dead-rank markers
        # always land on the shared file root. When the primary
        # transport is the coordination service, still consult the
        # file markers — otherwise a crashed rank would hold the
        # agreement for the full timeout while the launcher's grace
        # kill lands first
        self._marker_transport = None
        if transport is not None and \
                getattr(transport, "kind", "") != "file":
            root = coordination_dir or os.environ.get(ENV_FLEET_DIR)
            if root:
                self._marker_transport = FileTransport(root,
                                                       self.run_id)
        self.handler = handler if handler is not None \
            else PreemptionHandler()
        if notice_source is None:
            notice_source = notice_source_from_env()
        self.notice_source = notice_source
        self.poll_interval_s = poll_interval_s
        self.hold_poll_s = hold_poll_s
        self.watch_interval_s = watch_interval_s
        self.agree_timeout_s = agree_timeout_s
        self.commit_timeout_s = commit_timeout_s
        self.ckpt_timeout_s = ckpt_timeout_s
        self.dead_grace_s = dead_grace_s
        self.podz_fetch_timeout_s = podz_fetch_timeout_s
        # agreement state
        self.acked_step: Optional[int] = None
        self.agreed_step: Optional[int] = None
        self.last_checkpoint_step: Optional[int] = None
        self.last_committed_step: Optional[int] = None
        # step-agreed periodic save state (two-phase global commit).
        # The ledger is touched from every async writer thread running
        # a coordinated save — guard it.
        self._staged_steps: List[int] = []  # own staged-key ledger
        self._staged_lock = threading.Lock()
        self.last_staged_step: Optional[int] = None
        self.last_global_commit_step: Optional[int] = None
        self.last_commit_barrier_s: Optional[float] = None
        self.agreed_restore_step: Optional[int] = None
        self.committed_view: Optional[Dict[int, int]] = None
        self.last_wait_s: Optional[float] = None
        # guards request_reason/_notice/_watch_error: the metadata
        # watcher thread and the training loop both WRITE them
        # (request() from the watcher, _requested()'s signal-reason
        # stamp from the loop) — the flag reads stay lock-free (the
        # publication pattern; the lock serializes the writers)
        self._req_mu = threading.Lock()
        self.request_reason: Optional[str] = None
        self._notice = False
        self._own_endpoint: Optional[str] = None
        # throttle clock starts NOW: the first transport peek waits a
        # full interval, so a controller on the hot path costs zero
        # transport IO until one elapses
        self._last_peek = time.monotonic()
        self._watch_error: Optional[str] = None
        self._watcher: Optional[threading.Thread] = None
        self._stop_evt = threading.Event()
        self._started = False

    # -- lifecycle ----------------------------------------------------------

    @property
    def started(self) -> bool:
        return self._started

    def start(self) -> "FleetController":
        """Register as the process's active controller (the /statusz
        'controller' section), sweep dead-run transport litter, and
        start the metadata watcher when a notice source is
        configured."""
        global _ACTIVE
        if self._started:
            return self
        self._started = True
        _ACTIVE = self
        if self.transport is not None:
            self.transport.sweep()
        if self.notice_source is not None:
            self._stop_evt.clear()
            self._watcher = threading.Thread(
                target=self._watch, daemon=True,
                name="pt-fleet-watcher")
            self._watcher.start()
        return self

    def stop(self) -> None:
        global _ACTIVE
        self._stop_evt.set()
        if self._watcher is not None:
            self._watcher.join(timeout=5)
            self._watcher = None
        if _ACTIVE is self:
            _ACTIVE = None
        self._started = False

    def __enter__(self) -> "FleetController":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- preemption notice --------------------------------------------------

    def request(self, reason: str = "api") -> None:
        """Raise the preempt flag without a signal (metadata watcher,
        orchestrator RPC, tests). The next :meth:`check` starts the
        agreement."""
        with self._req_mu:
            self.request_reason = self.request_reason or reason
            self._notice = True
        if telemetry.enabled():
            # preempt-agreement breadcrumbs on the trace ring: the
            # fleet /tracez fan-in shows request → per-rank ack →
            # agreement on each rank's lane next to its step spans
            _tracing.event("fleet.preempt.request", rank=self.rank,
                           reason=reason)
        self.handler.request()

    def _requested(self) -> bool:
        if self._notice:
            return True
        if self.handler.requested():
            with self._req_mu:
                self.request_reason = self.request_reason or "signal"
            return True
        return False

    def _watch(self) -> None:
        """Metadata watcher: poll the notice source (and the seeded
        ``fleet.notice`` injection point — a ``corrupt`` rule is a
        synthetic notice, a raising rule a flaky metadata endpoint)
        until a notice lands, then raise the flag once and exit."""
        while not self._stop_evt.wait(self.watch_interval_s):
            try:
                inj = _faults.active()
                fired = (inj is not None
                         and bool(inj.fire("fleet.notice")))
                if fired or self.notice_source.poll():
                    if telemetry.enabled():
                        _fleet_metrics()["notices"].inc()
                    self.request(reason="notice")
                    return
            except Exception as e:
                # a flaky metadata endpoint must never kill the watcher
                with self._req_mu:
                    self._watch_error = repr(e)

    # -- the agreement ------------------------------------------------------

    def _marker(self, key: str) -> Optional[str]:
        """A key on the primary transport OR the launcher's file-marker
        root (dead/done records can originate from either side)."""
        v = (self.transport.get(key)
             if self.transport is not None else None)
        if v is None and self._marker_transport is not None:
            v = self._marker_transport.get(key)
        return v

    def _live_ranks(self) -> List[int]:
        """Every rank still PARTICIPATING in coordination: not marked
        dead by the launcher and not cleanly done (a rank whose data
        stream ran dry publishes ``done.<rank>`` on exit — without it,
        survivors would hold the agreement for a rank that finished
        and left). Self always counts — we are provably alive."""
        if self.transport is None:
            return [self.rank]
        return [r for r in range(self.world)
                if r == self.rank
                or (self._marker(f"dead.{r}") is None
                    and self._marker(f"done.{r}") is None)]

    def _peer_ack_seen(self) -> bool:
        # ONE well-known key, not a per-peer scan: the hot-path sample
        # stays O(1) transport reads at any world size (an old-client
        # blocking-get fallback costs one bounded probe, not world-1)
        if self.transport is None:
            return False
        return self.transport.get("preempt.flag") is not None

    def _wait_all_raw(self, prefix: str, *, timeout_s: float,
                      what: str) -> Dict[int, str]:
        """Gather ``<prefix>.<rank>``: WAIT only on live ranks, but
        collect EVERY published value — a rank that acked and then
        died still contributed its step, so every survivor computes
        the same max no matter when the dead marker landed relative
        to its own wait (values are persistent on both transports).
        On expiry, the typed diagnostic names whoever never arrived."""
        deadline = time.monotonic() + timeout_s
        t0 = time.monotonic()
        while True:
            vals: Dict[int, str] = {}
            for r in range(self.world):
                v = self.transport.get(f"{prefix}.{r}")
                if v is not None:
                    vals[r] = v
            missing = [r for r in self._live_ranks()
                       if r not in vals]
            if not missing:
                self.last_wait_s = round(time.monotonic() - t0, 3)
                return vals
            if time.monotonic() >= deadline:
                note_barrier_timeout()
                raise BarrierTimeoutError(
                    what, missing=missing, world=self.world,
                    timeout_s=timeout_s)
            time.sleep(self.hold_poll_s)

    def _wait_all(self, prefix: str, *, timeout_s: float,
                  what: str) -> Dict[int, int]:
        return {r: int(v) for r, v in self._wait_all_raw(
            prefix, timeout_s=timeout_s, what=what).items()}

    def check(self, step: int) -> Optional[int]:
        """The per-step drive. Returns the agreed preempt step once one
        exists (the loop commits when ``step >= agreed``), else None.
        Cheap until a preemption is in flight: one Event peek plus a
        transport sample at most every ``poll_interval_s``."""
        if self.agreed_step is not None:
            return self.agreed_step
        requested = self._requested()
        if not requested and self.world > 1:
            now = time.monotonic()
            if now - self._last_peek >= self.poll_interval_s:
                self._last_peek = now
                if self._peer_ack_seen():
                    requested = True
                    with self._req_mu:
                        self.request_reason = (self.request_reason
                                               or "peer")
        if not requested:
            return None
        return self._agree(step)

    def _agree(self, step: int) -> int:
        if self.world <= 1 or self.transport is None:
            self.agreed_step = int(step)
        else:
            if self.acked_step is None:
                # publish-then-hold: our ack freezes our step, so
                # max(acks) is an upper bound no rank has passed. The
                # shared preempt.flag is what peers' O(1) hot-path
                # sample watches (first writer wins; rewrites are
                # harmless)
                self.acked_step = int(step)
                self.transport.put(f"preempt.ack.{self.rank}",
                                   str(int(step)))
                self.transport.put("preempt.flag", str(self.rank))
                if telemetry.enabled():
                    _tracing.event("fleet.preempt.ack",
                                   rank=self.rank, step=int(step))
            acks = self._wait_all("preempt.ack",
                                  timeout_s=self.agree_timeout_s,
                                  what="preempt-agreement")
            self.agreed_step = max(acks.values())
        if telemetry.enabled():
            _fleet_metrics()["agreements"].inc()
            _tracing.event("fleet.preempt.agreed", rank=self.rank,
                           step=int(self.agreed_step))
        return self.agreed_step

    def confirm_committed(self, step: int) -> Dict[int, int]:
        """Publish this rank's committed step and wait for every live
        rank's — no rank reports a clean preempted exit until the whole
        fleet's checkpoints are on disk. Returns {rank: step}."""
        step = int(step)
        if self.world <= 1 or self.transport is None:
            self.last_committed_step = step
            self.committed_view = {self.rank: step}
            return dict(self.committed_view)
        self.transport.put(f"committed.{self.rank}", str(step))
        vals = self._wait_all("committed",
                              timeout_s=self.commit_timeout_s,
                              what="commit-confirmation")
        self.last_committed_step = step
        self.committed_view = vals
        if telemetry.enabled():
            _tracing.event("fleet.commit.confirmed", rank=self.rank,
                           step=step)
        return vals

    def note_checkpoint(self, step: int) -> None:
        """Record the newest step a save targeted (the /podz per-rank
        'last committed step' row; async writes may still be in
        flight — the COMMITTED marker on disk is the truth)."""
        self.last_checkpoint_step = int(step)
        self._update_commit_lag()

    def note_done(self, step: int) -> None:
        """Announce a CLEAN exit (data stream exhausted / num_steps
        reached) through the transport: peers drop this rank from
        future agreements instead of timing out on a rank that
        finished and left. Best-effort — the launcher's dead marker
        and the grace kill bound the failure modes either way."""
        if self.transport is None:
            return
        try:
            self.transport.put(f"done.{self.rank}", str(int(step)))
        except Exception:
            pass  # a failed announce degrades to the agree timeout

    # -- step-agreed periodic saves (two-phase global commit) ---------------
    #
    # The preempt agreement above coordinates the FINAL save; these
    # methods make EVERY periodic save a fleet-level transaction
    # (orbax's "all hosts save step N or none"): each rank stages its
    # step-N checkpoint locally, publishes ``ckpt.staged.<N>.<rank>``,
    # and the single global ``ckpt.committed.<N>`` marker lands only
    # once every LIVE rank has staged — dead-rank markers keep a
    # crashed rank from wedging the commit, and a wait that expires
    # raises the typed BarrierTimeoutError naming the missing ranks.
    # CheckpointManager drives this through its ``coordinator=`` seam
    # and records the durable per-step GLOBAL_COMMITTED marker (the
    # transport's state dies with the job; the disk record is what a
    # restarted fleet trusts).

    def _kv_put(self, key: str, value: str) -> None:
        """Transport put under the bounded transport retry policy —
        every KV op on the save/agreement path is deadline-bounded,
        never a single-shot RPC that tears a commit on one blip."""
        enforce(self.transport is not None,
                "no coordination transport (world=%s)", self.world)
        retry_io(lambda: self.transport.put(key, value),
                 policy=_KV_POLICY, what="fleet.kv_put")

    def note_stage(self, step: int) -> None:
        """Phase 1: announce this rank's step-``step`` checkpoint is
        fully staged (locally committed on disk)."""
        step = int(step)
        self._kv_put(f"ckpt.staged.{step}.{self.rank}", str(step))
        with self._staged_lock:
            self._staged_steps.append(step)
        self.last_staged_step = step
        self._update_commit_lag()
        if telemetry.enabled():
            _tracing.event("fleet.ckpt.staged", rank=self.rank,
                           step=step)

    def wait_global_commit(self, step: int) -> Optional[float]:
        """Phase 2: hold until every live rank staged ``step``, then
        land the global commit marker (every rank writes the same
        idempotent value — no special coordinator rank, so killing ANY
        rank mid-commit degrades the same way). Returns the barrier
        wait in seconds (the ``commit_barrier_ms`` bench column) — or
        None when the commit DEFERS to an in-flight preempt agreement.

        The deferral closes a deadlock: once some rank publishes the
        preempt flag it HOLDS in the ack-wait and will not stage this
        step until the agreement resolves — and a rank blocking inside
        a synchronous coordinated save is exactly what keeps the
        agreement from resolving (its ack publishes on the loop's next
        check). So while a preemption is in flight and unagreed, the
        commit backs off: the step stays staged-but-uncommitted, which
        is safe (fleet GC never prunes at/above the global floor, and
        the restore agreement reconciles common stage-only steps), and
        the final preempt save commits coordinated at the agreed step.

        Dead-rank semantics differ by phase, on purpose. BEFORE any
        preempt agreement, a rank marked ``dead.<rank>`` fails the
        commit FAST with the typed error naming it (never a hang) — a
        crashed rank can never stage, and committing the step globally
        WITHOUT its copy would let retention GC prune the fleet's last
        common step, leaving a restarted fleet with no consistent
        restore point at all (the job is being torn down by the
        launcher's fail-fast anyway). AFTER an agreement resolved, the
        fleet itself already dropped the corpse from the live set — the
        survivors' FINAL coordinated save commits among the live, which
        is what the elastic N-1 restart resumes from. Ranks that
        announced ``done.<rank>`` (clean data exhaustion) are always
        dropped: their exit was coordinated and they will never save
        this step."""
        step = int(step)
        what = f"ckpt-commit step {step}"
        t0 = time.monotonic()
        deadline = t0 + self.ckpt_timeout_s
        prefix = f"ckpt.staged.{step}"
        dead_seen_at: Optional[float] = None
        while True:
            missing: List[int] = []
            dead: List[int] = []
            for r in range(self.world):
                if r == self.rank:
                    continue
                if self.transport.get(f"{prefix}.{r}") is not None:
                    continue
                if self._marker(f"done.{r}") is not None:
                    continue
                if self._marker(f"dead.{r}") is not None:
                    if self.agreed_step is not None:
                        continue  # agreement already dropped the corpse
                    dead.append(r)
                else:
                    missing.append(r)
            if not missing and not dead:
                break  # every live rank staged: commit now
            if self.transport.get(f"ckpt.committed.{step}") \
                    is not None:
                # a peer already landed the global commit — and may
                # have begun reclaiming its staged keys, so "missing"
                # can be a cleanup mirage on overlapped async saves.
                # The persistent marker IS the transaction's outcome.
                break
            if self.agreed_step is None and (
                    self._requested()
                    or self.transport.get("preempt.flag") is not None):
                return None  # defer to the forming preempt agreement
            if dead:
                # a corpse before any agreement. The launcher's
                # fail-fast marks dead FIRST and SIGTERMs survivors
                # right after — give that teardown ``dead_grace_s`` to
                # reach us (the deferral above then routes this save
                # into the coordinated preempt exit). A dead marker
                # with no teardown following means a torn fleet with
                # nobody driving it down: fail typed, never commit.
                if dead_seen_at is None:
                    dead_seen_at = time.monotonic()
                if time.monotonic() - dead_seen_at >= \
                        self.dead_grace_s:
                    note_barrier_timeout()
                    raise BarrierTimeoutError(
                        what, missing=dead + missing,
                        world=self.world,
                        timeout_s=self.ckpt_timeout_s,
                        detail=f"rank(s) {dead} died mid-commit")
                time.sleep(self.hold_poll_s)
                continue
            if time.monotonic() >= deadline:
                note_barrier_timeout()
                raise BarrierTimeoutError(
                    what, missing=missing, world=self.world,
                    timeout_s=self.ckpt_timeout_s)
            time.sleep(self.hold_poll_s)
        self._kv_put(f"ckpt.committed.{step}", str(step))
        wait_s = time.monotonic() - t0
        if self.last_global_commit_step is None or \
                step > self.last_global_commit_step:
            self.last_global_commit_step = step
        # transport hygiene: a global commit of N proves every live
        # rank staged N, hence finished every save below it — staged
        # keys for older steps are dead weight (one key per step per
        # rank, forever, on the shared-FS transport). Each rank
        # reclaims its OWN; overlapped async waits on an older step
        # stay safe because the wait loop above breaks on the PERSISTED
        # ckpt.committed marker (which is why the committed markers
        # themselves are never reclaimed — they are the durable
        # transaction outcome a late waiter falls back to).
        with self._staged_lock:
            reclaim = [s for s in self._staged_steps if s < step]
            self._staged_steps = [s for s in self._staged_steps
                                  if s >= step]
        for s in reclaim:
            self.transport.delete(f"ckpt.staged.{s}.{self.rank}")
        self.last_commit_barrier_s = round(wait_s, 4)
        self._update_commit_lag()
        if telemetry.enabled():
            _tracing.event("fleet.ckpt.global_commit", rank=self.rank,
                           step=step)
        return wait_s

    def global_commit_seen(self, step: int) -> bool:
        """Whether the fleet-wide commit marker for ``step`` is visible
        on the transport (a rank that timed out can re-check before
        declaring the step dead)."""
        if self.transport is None:
            return False
        return self.transport.get(f"ckpt.committed.{int(step)}") \
            is not None

    def agree_restore_step(self, local_steps) -> Optional[int]:
        """Restore-time agreement: every rank publishes the steps it
        can restore locally (its committed step dirs) and the fleet
        restores the NEWEST step every live rank has — one consistent
        step on every rank, never each rank's own newest. Returns None
        when the fleet shares no restorable step (a consistent cold
        start on every rank). Runs at attempt start, before training:
        the rank set is the launcher's spawned set, so the published
        lists and the live set agree on every rank."""
        steps = sorted({int(s) for s in local_steps})
        if self.world <= 1 or self.transport is None:
            agreed = steps[-1] if steps else None
        else:
            self._kv_put(f"restore.steps.{self.rank}",
                         json.dumps(steps))
            if not steps:
                # nothing restorable locally: the fleet intersection
                # is empty no matter what peers hold — cold start NOW,
                # and the published empty list lets every peer reach
                # the same conclusion without holding for this rank
                agreed = None
            else:
                vals = self._wait_all_raw(
                    "restore.steps", timeout_s=self.agree_timeout_s,
                    what="restore-agreement")
                common: Optional[set] = None
                for v in vals.values():
                    s = set(json.loads(v))
                    common = s if common is None else (common & s)
                agreed = max(common) if common else None
        self.agreed_restore_step = agreed
        if agreed is not None and (
                self.last_global_commit_step is None
                or agreed > self.last_global_commit_step):
            # the agreed step IS fleet-held (the caller promotes it):
            # seed the global-commit view so the commit-lag gauge
            # reports DRIFT after a resume, not the absolute step
            self.last_global_commit_step = agreed
            self._update_commit_lag()
        return agreed

    def _update_commit_lag(self) -> None:
        if not telemetry.enabled():
            return
        local = self.last_staged_step
        if local is None:
            local = self.last_checkpoint_step
        if local is None:
            return
        _fleet_metrics()["commit_lag"].set(
            max(0, local - (self.last_global_commit_step or 0)))

    # -- pod-level aggregation (/podz) --------------------------------------

    def publish_endpoint(self, host: str, port: int) -> None:
        """Announce this rank's debug-server address through the
        transport so any rank's /podz can fan out to it. The debug
        server binds loopback by default, which a REMOTE aggregator
        cannot reach — on a real multi-host fleet set
        ``PT_PODZ_ADVERTISE_HOST`` (this host's routable name) or bind
        the server on one; the single-host rig needs neither."""
        host = os.environ.get("PT_PODZ_ADVERTISE_HOST") or host
        self._own_endpoint = f"{host}:{port}"
        if self.transport is not None:
            self.transport.put(f"debug.{self.rank}",
                               self._own_endpoint)

    def _podz_row(self, r: int) -> Dict[str, Any]:
        if r == self.rank and self._own_endpoint:
            ep = self._own_endpoint
        elif self.transport is not None:
            ep = self.transport.get(f"debug.{r}")
        else:
            ep = None
        dead = self._marker(f"dead.{r}") is not None
        done = self._marker(f"done.{r}")
        row: Dict[str, Any] = {"rank": r, "endpoint": ep,
                               "dead": dead,
                               "done_at_step": (int(done)
                                                if done else None)}
        if ep and not dead:
            t = self.podz_fetch_timeout_s
            h = _fetch_json(f"http://{ep}/healthz", t)
            row["healthz"] = h
            if isinstance(h, dict):
                row["heartbeat_age_s"] = h.get("last_step_age_s")
            s = _fetch_json(f"http://{ep}/statusz", t)
            if isinstance(s, dict) and "error" not in s:
                row["backend"] = s.get("backend")
                res = s.get("resilience")
                view = (res.get("controller")
                        if isinstance(res, dict) else None)
                if isinstance(view, dict):
                    row["last_checkpoint_step"] = view.get(
                        "last_checkpoint_step")
                    row["last_committed_step"] = view.get(
                        "last_committed_step")
                    # fleet-wide commit next to the local one: a rank
                    # whose local step runs ahead of the global commit
                    # is the one wedging (or outpacing) the fleet —
                    # commit drift is visible at a glance
                    row["last_committed_global"] = view.get(
                        "last_global_commit_step")
                    row["last_staged_step"] = view.get(
                        "last_staged_step")
                    row["preempt"] = {
                        k: view.get(k)
                        for k in ("preempt_requested", "acked_step",
                                  "agreed_preempt_step")}
            else:
                row["statusz_error"] = (s.get("error")
                                        if isinstance(s, dict)
                                        else repr(s))
            m = _fetch_json(f"http://{ep}/memz", t)
            if isinstance(m, dict):
                row["peak_mem_bytes"] = m.get("peak_mem_bytes")
        return row

    def podz(self) -> Dict[str, Any]:
        """One fleet view: fan out to every rank's /healthz + /statusz
        + /memz and distill per-rank heartbeat age, last committed
        step, and preempt state. Unreachable ranks degrade to an error
        row — /podz renders whatever the fleet can still tell it.
        Ranks fetch CONCURRENTLY: a scrape of a partially-wedged fleet
        is bounded near one rank's fetch budget, not world x timeouts."""
        from concurrent.futures import ThreadPoolExecutor

        if self.world <= 1:
            rows = [self._podz_row(0)]
        else:
            with ThreadPoolExecutor(
                    max_workers=min(8, self.world),
                    thread_name_prefix="pt-podz-fetch") as ex:
                rows = list(ex.map(self._podz_row,
                                   range(self.world)))
        return {"world_size": self.world,
                "aggregator_rank": self.rank,
                "run_id": self.run_id,
                "preempt_requested": self._requested(),
                "agreed_preempt_step": self.agreed_step,
                "last_committed_global": self.last_global_commit_step,
                "ranks": {str(row["rank"]): row for row in rows}}

    def tracez_fanout(self,
                      trace_id: Optional[str] = None) -> Dict[str, Any]:
        """/podz-style TRACE aggregation for training fleets (mounted
        on the debug server's ``/tracez?trace_id=`` when a controller
        is attached): fan out to every rank's /tracez, align each
        rank's spans via its clock-offset handshake, and merge ONE
        chrome-trace — per-rank lanes carrying the rank-tagged
        ``train.step`` spans and the preempt-agreement events.
        Unreachable/dead ranks degrade to error rows."""
        from concurrent.futures import ThreadPoolExecutor

        collections: List[Dict[str, Any]] = []
        rows: Dict[str, Any] = {}
        # ``local=1`` forces each rank's LOCAL ring: every rank mounts
        # this same fan-out on its own /tracez, so without it two
        # ranks' aggregators would recurse into each other
        q = (f"?trace_id={trace_id}&local=1" if trace_id
             else "?local=1")

        def fetch(r: int):
            if r == self.rank:
                return r, _tracing.collection(trace_id,
                                              proc=f"rank{r}"), "local"
            if self._marker(f"dead.{r}") is not None:
                return r, None, "dead"
            ep = (self.transport.get(f"debug.{r}")
                  if self.transport is not None else None)
            if not ep:
                return r, None, "no endpoint published"
            j = _fetch_json(f"http://{ep}/tracez{q}",
                            self.podz_fetch_timeout_s)
            if isinstance(j, dict) and "trace_spans" in j:
                j["proc"] = f"rank{r}"
                return r, j, ep
            return r, None, (j.get("error") if isinstance(j, dict)
                             else repr(j))

        with ThreadPoolExecutor(
                max_workers=min(8, max(1, self.world)),
                thread_name_prefix="pt-tracez-fetch") as ex:
            for r, j, info in ex.map(fetch, range(self.world)):
                if j is not None:
                    collections.append(j)
                    rows[str(r)] = {
                        "rank": r, "source": info,
                        "spans": len(j.get("trace_spans",
                                           j.get("spans", [])))}
                else:
                    rows[str(r)] = {"rank": r, "error": info}
        return {"world_size": self.world,
                "aggregator_rank": self.rank,
                "trace_id": trace_id,
                "ranks": rows,
                "trace": _tracing.merge_chrome_trace(collections)}

    # -- introspection ------------------------------------------------------

    def statusz(self) -> Dict[str, Any]:
        """The /statusz 'resilience.controller' section — the per-rank
        row /podz aggregates: agreement state, notice source, and the
        last coordination-barrier latency."""
        out: Dict[str, Any] = {
            "active": self._started,
            "rank": self.rank,
            "world_size": self.world,
            "run_id": self.run_id,
            "transport": (getattr(self.transport, "kind", None)
                          if self.transport is not None else None),
            "notice_source": (self.notice_source.describe()
                              if self.notice_source is not None
                              else None),
            "watcher_alive": (self._watcher is not None
                              and self._watcher.is_alive()),
            "watch_error": self._watch_error,
            "preempt_requested": self._requested(),
            "request_reason": self.request_reason,
            "acked_step": self.acked_step,
            "agreed_preempt_step": self.agreed_step,
            "last_checkpoint_step": self.last_checkpoint_step,
            "last_committed_step": self.last_committed_step,
            "last_staged_step": self.last_staged_step,
            "last_global_commit_step": self.last_global_commit_step,
            "last_commit_barrier_s": self.last_commit_barrier_s,
            "agreed_restore_step": self.agreed_restore_step,
            "last_agreement_wait_s": self.last_wait_s,
        }
        try:  # lazy: checkpoint pulls jax; /statusz must render anyway
            from .. import checkpoint as _ckpt

            bs = _ckpt.barrier_stats()
            out["last_barrier_latency_s"] = bs["last_latency_s"]
            out["barrier_timeouts"] = bs["timeouts"]
        except Exception:
            out["last_barrier_latency_s"] = None
        return out


def active() -> Optional[FleetController]:
    """The process's started controller, or None (the /statusz hook)."""
    return _ACTIVE
