"""Deterministic fault injection — the substrate the chaos test suite
drives to PROVE the preemption and checkpoint-integrity pillars (kill
mid-save at every injection point → resume always lands on the last
committed step).

A :class:`FaultInjector` holds per-point rules (raise / corrupt / delay
on a seeded, repeatable schedule) and is armed process-globally with
``inj.arm()`` / ``with inj:``. Instrumented call-sites resolve
:func:`active` ONCE per operation and pass every I/O through
:meth:`FaultInjector.fire`; with no injector armed the call-sites see
``None`` and execute nothing — zero hot-path cost, the telemetry-off
discipline applied to chaos tooling.

Injection points (:data:`POINTS`):

- ``ckpt.write``    each checkpoint leaf/shard file write
- ``ckpt.manifest`` the manifest write
- ``ckpt.stage``    the coordinated save's stage phase: fired after the
  local step dir committed, BEFORE ``staged.<rank>`` is published
  through the fleet transport (delay rules widen the mid-stage
  SIGKILL window; raising rules model a transport put failing)
- ``ckpt.commit``   the coordinated save's commit phase: fired after
  every live rank staged, BEFORE the durable ``GLOBAL_COMMITTED``
  marker lands on disk (delay rules widen the mid-commit kill window)
- ``restore.read``  each checkpoint file read
- ``step.nan``      the training step's loss (corrupt → NaN)
- ``io.slow``       any checkpoint file I/O (delay rules widen the
  kill window for the SIGKILL e2e and exercise retry deadlines)
- ``fleet.notice``  the fleet controller's metadata-watcher poll (a
  ``corrupt`` rule injects a synthetic preemption notice; a raising
  rule models a flaky metadata endpoint)
- ``router.dispatch`` the serving router's per-request dispatch to a
  replica (``path`` = the replica name, so ``match=`` targets one
  replica) — a raising rule models a replica dying mid-dispatch and
  drives the router's retry-on-surviving-replica path deterministically
- ``lock.acquire`` a :class:`~paddle_tpu.telemetry.lockwatch.
  WatchedLock` acquisition (``path`` = the lock's name; fired only
  while the lock-order watchdog is enabled). A seeded ``delay_s`` rule
  matched to ONE lock stretches its acquire window so two racing
  threads interleave deterministically — the chaos suite uses it to
  force a real lock-order inversion the watchdog must catch with both
  witness stacks
- ``autoscale.spawn`` the scaler's scale-up attempt, fired before the
  spawn fn runs — a raising rule models a worker that dies mid-boot
  and drives the spawn-failure/retry path deterministically
- ``autoscale.drain`` the scaler's scale-down, fired before the drain
  begins (``path`` = the victim replica's name) — delay rules widen
  the SIGKILL-mid-drain window for the chaos e2e
- ``router.latency`` the router's per-request replica submit (``path``
  = the replica name) — a seeded ``delay_s`` rule matched to ONE
  replica simulates a gray (slow-but-alive) replica deterministically;
  the delay lands inside the router's dispatch-latency measurement, so
  hedging and quarantine see it exactly like a real stall
- ``replica.wedge`` the local replica's serve-loop tick (``path`` =
  the replica name) — a ``delay_s`` rule freezes the decode loop
  mid-stream, the in-process stand-in for SIGSTOP
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, Optional

from .. import telemetry
from ..core.enforce import enforce

POINTS = ("ckpt.write", "ckpt.manifest", "ckpt.stage", "ckpt.commit",
          "restore.read", "step.nan", "io.slow", "fleet.notice",
          "router.dispatch", "lock.acquire", "autoscale.spawn",
          "autoscale.drain", "router.latency", "replica.wedge")

_ACTIVE: Optional["FaultInjector"] = None
_LOCK = threading.Lock()


@telemetry.cached_instruments
def _fault_metrics(reg):
    return {
        "fired": reg.counter("pt_faults_injected_total",
                             "faults fired by an armed FaultInjector"),
    }


class FaultError(OSError):
    """Default injected error — an OSError subclass, so the retry layer
    treats it as the transient I/O fault it simulates."""


class FaultInjector:
    """Seeded, deterministic fault schedule over named injection points.

    Rules (one per point, latest :meth:`on` wins):

    - ``at=(3, 5)``: fire on those 1-based call indices of the point —
      fully deterministic, independent of the seed.
    - ``prob=0.2``: fire per call with that probability, drawn from the
      injector's own seeded RNG — repeatable for a fixed seed and call
      order.
    - ``times=N``: total fire budget for the rule (None = unlimited).
      ``times=1`` with the default error models a transient fault the
      retry layer absorbs; ``times`` >= the retry budget models a hard
      fault that tears the save.

    Effects (combinable): ``error=`` raise it (class or instance;
    default :class:`FaultError`), ``delay_s=`` sleep first,
    ``corrupt=True`` flip one byte of the payload instead of raising
    (for ``step.nan``: poison the loss).
    """

    def __init__(self, seed: int = 0):
        self.seed = seed
        self._rng = random.Random(seed)
        self._rules: Dict[str, Dict[str, Any]] = {}
        self.calls: Dict[str, int] = {p: 0 for p in POINTS}
        self.fired: Dict[str, int] = {p: 0 for p in POINTS}

    def on(self, point: str, *, error=None, prob: float = 0.0,
           at=(), times: Optional[int] = None,
           delay_s: float = 0.0, corrupt: bool = False,
           match: Optional[str] = None) -> "FaultInjector":
        """Install the rule for ``point`` (returns self for chaining).
        ``match``: only fire when the call-site's ``path`` contains this
        substring (target one shard file, spare the manifest, ...)."""
        enforce(point in POINTS, "unknown injection point %r (have %s)",
                point, ", ".join(POINTS))
        enforce(0.0 <= prob <= 1.0, "prob must be in [0, 1], got %s",
                prob)
        self._rules[point] = {
            "error": error, "prob": float(prob),
            "at": frozenset(int(i) for i in at),
            "times": times, "delay_s": float(delay_s),
            "corrupt": bool(corrupt), "match": match,
        }
        return self

    # -- arming ------------------------------------------------------------

    def arm(self) -> "FaultInjector":
        """Make this the process's active injector (one at a time —
        overlapping schedules would destroy determinism)."""
        global _ACTIVE
        with _LOCK:
            enforce(_ACTIVE is None or _ACTIVE is self,
                    "another FaultInjector is already armed")
            _ACTIVE = self
        return self

    def disarm(self) -> None:
        global _ACTIVE
        with _LOCK:
            if _ACTIVE is self:
                _ACTIVE = None

    def __enter__(self) -> "FaultInjector":
        return self.arm()

    def __exit__(self, *exc) -> None:
        self.disarm()

    # -- firing ------------------------------------------------------------

    def _should_fire(self, rule, n: int) -> bool:
        if rule["times"] is not None and rule["times"] <= 0:
            return False
        if rule["at"]:
            return n in rule["at"]
        if rule["prob"] > 0.0:
            return self._rng.random() < rule["prob"]
        # no schedule (bare `on(point, ...)`) fires on every call —
        # the "this path is broken, period" mode; bound with times=
        return True

    def fire(self, point: str, *, data: Optional[bytes] = None,
             path: Optional[str] = None):
        """Run ``point``'s rule for this call.

        Returns ``data`` (possibly one byte flipped, when the rule says
        ``corrupt``) if ``data`` was given, else True/False = fired.
        Raising rules raise instead. Call order is the schedule clock:
        every call increments the point's index whether or not a rule
        fires, so ``at=`` indices are stable across rule edits."""
        self.calls[point] = n = self.calls.get(point, 0) + 1
        rule = self._rules.get(point)
        if rule is None:
            return data if data is not None else False
        if rule["match"] is not None and (path is None
                                          or rule["match"] not in path):
            return data if data is not None else False
        if not self._should_fire(rule, n):
            return data if data is not None else False
        if rule["times"] is not None:
            rule["times"] -= 1
        self.fired[point] = self.fired.get(point, 0) + 1
        if telemetry.enabled():
            _fault_metrics()["fired"].inc()
        if rule["delay_s"] > 0.0:
            time.sleep(rule["delay_s"])
        if rule["corrupt"]:
            if data is not None:
                # flip one byte in the middle: deterministic, always
                # lands inside the payload (npy data follows the
                # header). bytes() first: call-sites may hand a
                # zero-copy memoryview, which doesn't concatenate
                data = bytes(data)
                i = len(data) // 2
                return data[:i] + bytes([data[i] ^ 0xFF]) + data[i + 1:]
            return True
        if rule["error"] is not None or not rule["delay_s"]:
            err = rule["error"]
            if err is None:
                err = FaultError(f"injected fault at {point} "
                                 f"(call {n}, path={path})")
            elif isinstance(err, type):
                err = err(f"injected fault at {point} (call {n})")
            raise err
        return data if data is not None else True

    def statusz(self) -> Dict[str, Any]:
        return {"seed": self.seed,
                "points": sorted(self._rules),
                "calls": {k: v for k, v in self.calls.items() if v},
                "fired": {k: v for k, v in self.fired.items() if v}}


def active() -> Optional[FaultInjector]:
    """The armed injector, or None (the common case — call-sites gate
    every fire() behind this None-check)."""
    return _ACTIVE
