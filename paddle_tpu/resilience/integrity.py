"""Checkpoint payload checksums.

Every checkpoint file gets a checksum recorded at write time and
verified at restore time — a torn or bit-flipped shard becomes a loud
:class:`ChecksumError` (and, through ``CheckpointManager.restore``, a
fallback to the previous committed step) instead of a crash or silently
corrupted weights.

Algorithm: crc32c (Castagnoli — the checksum TFRecord/tensorstore use)
when a native implementation is importable, else zlib's crc32. The
algorithm NAME travels with the value (``"crc32c:9a7f..."`` /
``"crc32:..."``), so restore always verifies with the writer's
algorithm; no dependency is required and none may be installed here
(container constraint) — a pure-python crc32c would be ~1000x slower
than C zlib on multi-MB shards, which is the wrong trade for an
integrity check that runs on every save.

Inputs may be any bytes-like object, including memoryviews — large
leaves checksum CHUNKED (the native crc32c binding only accepts
``bytes``, and a whole-payload conversion would double peak host
memory for a multi-GB shard).
"""

from __future__ import annotations

import zlib

_CHUNK = 1 << 20

# (kind, fns...): "google" exposes value()+extend() for incremental
# use; the "crc32c" package's crc32c(data, crc) is incremental itself
_IMPL = None
try:
    import google_crc32c as _g

    _IMPL = ("google", _g.value, _g.extend)
except ImportError:
    try:
        import crc32c as _c

        _IMPL = ("crc32c", _c.crc32c)
    except ImportError:
        _IMPL = None


class ChecksumError(RuntimeError):
    """A checkpoint file's bytes do not match its recorded checksum."""


_PP_TABLE = None
_pp_warned = False


def _crc32c_pure(data) -> int:
    """Last-resort pure-python crc32c (table-driven, ~MB/s): VERIFY
    crc32c-tagged checkpoints written on a machine with native support
    when this one has none — slow beats unrestorable. New saves here
    never take this path (checksum_bytes falls back to zlib crc32)."""
    global _PP_TABLE, _pp_warned
    if not _pp_warned:
        _pp_warned = True
        import sys

        print("[resilience] no native crc32c module: verifying a "
              "crc32c-tagged checkpoint with the pure-python fallback "
              "(slow)", file=sys.stderr)
    if _PP_TABLE is None:
        table = []
        for n in range(256):
            c = n
            for _ in range(8):
                c = (c >> 1) ^ 0x82F63B78 if c & 1 else c >> 1
            table.append(c)
        _PP_TABLE = table
    crc = 0xFFFFFFFF
    for b in memoryview(data):
        crc = _PP_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _crc32c_value(data) -> int:
    """crc32c over any bytes-like, chunked so a memoryview never needs
    a second full ``bytes`` copy."""
    if _IMPL[0] == "google":
        _, value, extend = _IMPL
        if isinstance(data, bytes):
            return value(data)
        mv = memoryview(data)
        crc = 0
        for i in range(0, len(mv), _CHUNK):
            crc = extend(crc, bytes(mv[i:i + _CHUNK]))
        return crc
    fn = _IMPL[1]
    if isinstance(data, bytes):
        return fn(data)
    mv = memoryview(data)
    crc = 0
    for i in range(0, len(mv), _CHUNK):
        crc = fn(bytes(mv[i:i + _CHUNK]), crc)
    return crc


def checksum_bytes(data) -> str:
    """``"<algo>:<hex>"`` tag for ``data`` (bytes or memoryview; crc32c
    when native support exists, else crc32)."""
    if _IMPL is not None:
        return f"crc32c:{_crc32c_value(data) & 0xffffffff:08x}"
    return f"crc32:{zlib.crc32(data) & 0xffffffff:08x}"


def verify_bytes(data, tag: str, *, name: str = "<data>") -> None:
    """Raise :class:`ChecksumError` unless ``data`` matches ``tag``
    (computed with the algorithm the tag names). Unknown algorithms
    raise too — silently skipping verification would turn a reader/
    writer version skew into unverified restores."""
    algo, _, want = tag.partition(":")
    if algo == "crc32c" and _IMPL is not None:
        got = f"{_crc32c_value(data) & 0xffffffff:08x}"
    elif algo == "crc32":
        got = f"{zlib.crc32(data) & 0xffffffff:08x}"
    elif algo == "crc32c":
        # written elsewhere with native crc32c, verified here without:
        # the pure-python fallback keeps the checkpoint restorable
        got = f"{_crc32c_pure(data) & 0xffffffff:08x}"
    else:
        raise ChecksumError(
            f"{name}: unknown checksum algorithm {algo!r}")
    if got != want:
        raise ChecksumError(
            f"{name}: checksum mismatch — recorded {tag}, "
            f"computed {algo}:{got} (torn or bit-flipped file)")
