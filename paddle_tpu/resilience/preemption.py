"""Preemption-safe shutdown — the SIGTERM/SIGINT grace handler.

TPU preemption delivers SIGTERM with a grace window; without a handler
the process dies wherever it stands — mid-step, mid-checkpoint-save —
and the run loses everything since the last snapshot (the dominant
failure mode for long pod jobs per the Gemma-on-TPU report, PAPERS.md).

:class:`PreemptionHandler` converts the signal into a *checked flag*:
drive loops that opt in (``TrainLoop.run(preemption=...)``,
``BatchedDecoder.run(preemption=...)``, ``Executor.train_from_dataset``
via the ambient handler) finish the in-flight step, write a final
checkpoint / drain in-flight requests, and exit cleanly with a
``preempted`` status. Nothing is interrupted mid-save — the signal
handler only sets an Event.

Zero-cost when unused: no handler is ever installed unless asked, and
loops resolve :func:`active` once, outside the hot path.
"""

from __future__ import annotations

import signal
import threading
from typing import Optional, Sequence

from .. import telemetry

_ACTIVE: Optional["PreemptionHandler"] = None


@telemetry.cached_instruments
def _preempt_metrics(reg):
    return {
        "signals": reg.counter(
            "pt_preemptions_total",
            "preemption signals received by the grace handler"),
        "clean_exits": reg.counter(
            "pt_preempt_clean_exits_total",
            "drive loops that exited cleanly after a preemption "
            "signal (final checkpoint written / requests drained)"),
    }


class PreemptionHandler:
    """Grace handler for ``signals`` (default SIGTERM + SIGINT).

    ``install()`` swaps the process handlers in (main thread only — a
    CPython constraint on ``signal.signal``) and registers this handler
    as the process-ambient one (:func:`active`); ``uninstall()``
    restores exactly what was there before. On signal the handler
    records which signal arrived and sets the flag — ``requested()`` is
    what drive loops poll between steps. ``request()`` sets the flag
    programmatically (external preemption notices, e.g. a GCE metadata
    watcher, and tests)."""

    def __init__(self, signals: Sequence[int] = (signal.SIGTERM,
                                                 signal.SIGINT)):
        self.signals = tuple(signals)
        self.received_signal: Optional[int] = None
        self._requested = threading.Event()
        self._counted = False
        self._prev: Optional[dict] = None
        self._prev_active: Optional["PreemptionHandler"] = None

    # -- lifecycle ---------------------------------------------------------

    def install(self) -> "PreemptionHandler":
        global _ACTIVE
        if self._prev is not None:
            return self  # already installed (idempotent)
        prev = {s: signal.getsignal(s) for s in self.signals}
        for s in self.signals:
            signal.signal(s, self._on_signal)
        self._prev = prev
        self._prev_active = _ACTIVE  # restored on uninstall: a nested
        _ACTIVE = self               # run-scoped handler must hand the
        return self                  # ambient slot back to the outer one

    def uninstall(self) -> None:
        global _ACTIVE
        if self._prev is None:
            return
        for s, h in self._prev.items():
            signal.signal(s, h)
        self._prev = None
        if _ACTIVE is self:
            _ACTIVE = self._prev_active
        self._prev_active = None

    @property
    def installed(self) -> bool:
        return self._prev is not None

    def __enter__(self) -> "PreemptionHandler":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()

    # -- the flag ----------------------------------------------------------

    def _on_signal(self, signum, frame) -> None:
        # STRICTLY async-signal-safe: set the Event and record the
        # signum, nothing else. Telemetry counters take non-reentrant
        # locks the interrupted main thread may already hold (or a
        # second nested signal would re-enter) — the count happens
        # lazily in requested(), which runs in ordinary thread context.
        self.received_signal = signum
        self._requested.set()

    def request(self) -> None:
        """Flag a preemption without a signal (metadata watchers,
        tests)."""
        self._requested.set()

    def requested(self) -> bool:
        r = self._requested.is_set()
        if r and not self._counted and telemetry.enabled():
            # deferred from _on_signal: safe to take locks here
            self._counted = True
            _preempt_metrics()["signals"].inc()
        return r

    def clear(self) -> None:
        """Reset the flag (a new run after a handled preemption)."""
        self._requested.clear()
        self.received_signal = None
        self._counted = False

    def statusz(self) -> dict:
        return {"installed": self.installed,
                "requested": self.requested(),
                "received_signal": self.received_signal,
                "signals": [int(s) for s in self.signals]}


def active() -> Optional[PreemptionHandler]:
    """The installed ambient handler, or None. Drive loops resolve this
    once per run — never per step."""
    return _ACTIVE
