"""Request reliability plane: deadlines, retry budgets, hedging, quarantine.

The serving fleet already survives *crash* failures (SIGKILL chaos, death
failover, elastic restart).  This module covers the harder case: a replica
that is merely **slow or wedged** — SIGSTOP, GC stall, compile storm, a bad
host.  Four classic tail-tolerance mechanisms, all deterministic on CPU via
the FaultInjector (``router.latency`` / ``replica.wedge`` points):

**End-to-end deadlines.**  A :class:`Deadline` is minted at ``Router.submit``
from the request's SLO class and propagated on every hop: in-process via a
contextvar (:func:`bind` / :func:`current`, same shape as trace binding),
cross-process via the ``X-PT-Deadline`` header beside ``X-PT-Trace``, and
through the ``KVHandoff`` npz wire for disaggregated prefill.  Expired work
is dropped with a typed :class:`DeadlineExceededError` — a cause-labeled
shed, never silently computed.  Deadlines are *absolute wall-clock* epochs
(``time.time``) so they survive process boundaries; skew between hosts on
one box is negligible versus second-scale budgets.

**Retry budgets.**  Router retries draw from a token bucket
(:class:`RetryBudget`) refilled as a fraction of successful requests —
the SRE "retry budget" pattern.  When the bucket is dry a failed request
degrades to a single typed :class:`RetryBudgetExhaustedError` instead of
amplifying a replica failure into a retry storm.

**Hedged dispatch.**  Short requests stuck past an adaptive p95 latency
threshold (:class:`LatencyTracker`) get a second dispatch on another
replica; the first result wins and the loser's result is discarded.

**Gray-failure quarantine.**  Per-replica :class:`ReplicaHealth` scores —
dispatch-latency EWMA vs the fleet median, queue-depth outliers, and
consecutive timeouts — drive a circuit breaker (closed → open → half-open
probe with a cheap warmed request).  Quarantined replicas leave placement
and affinity but keep draining in-flight work; the autoscaler reads
quarantine as capacity loss.

Zero-cost when disabled: ``Router(reliability=None)`` (the default) leaves
only ``is None`` checks on the hot path, the same discipline as telemetry.
"""

from __future__ import annotations

import contextlib
import contextvars
import threading
import time

from ..core import EnforceError

__all__ = [
    "DEADLINE_HEADER",
    "Deadline",
    "DeadlineExceededError",
    "RetryBudgetExhaustedError",
    "RetryBudget",
    "LatencyTracker",
    "ReplicaHealth",
    "ReliabilityConfig",
    "ReliabilityPlane",
    "bind",
    "current",
    "statusz_section",
]

# Kept in sync with telemetry.tracing.TRACE_HEADER ("X-PT-Trace") — the
# deadline rides beside the trace context on every HTTP hop.
DEADLINE_HEADER = "X-PT-Deadline"


class DeadlineExceededError(EnforceError):
    """Request's end-to-end deadline expired before it could complete."""

    http_status = 504


class RetryBudgetExhaustedError(EnforceError):
    """Retry budget is dry: the failure is surfaced instead of retried."""

    http_status = 503


class Deadline:
    """Absolute wall-clock deadline carried with one request end-to-end.

    ``t_end`` is a ``time.time()`` epoch so the value means the same thing
    in the router process, an HTTP replica worker, and a prefill worker.
    """

    __slots__ = ("t_end",)

    def __init__(self, t_end):
        self.t_end = float(t_end)

    @classmethod
    def after(cls, budget_s):
        """Mint a deadline ``budget_s`` seconds from now."""
        return cls(time.time() + float(budget_s))

    def remaining(self):
        """Seconds left (negative once expired)."""
        return self.t_end - time.time()

    def expired(self):
        return time.time() >= self.t_end

    def check(self, what="request"):
        """Raise :class:`DeadlineExceededError` if expired."""
        over = time.time() - self.t_end
        if over >= 0.0:
            raise DeadlineExceededError(
                f"deadline exceeded for {what}: {over * 1e3:.1f} ms past budget"
            )

    def to_header(self):
        return repr(self.t_end)

    @classmethod
    def from_header(cls, header):
        """Parse an ``X-PT-Deadline`` header value; None on garbage."""
        try:
            return cls(float(header))
        except (TypeError, ValueError):
            return None

    def __repr__(self):  # pragma: no cover - debugging aid
        return f"Deadline(remaining={self.remaining():+.3f}s)"


# -- in-process propagation (mirrors telemetry.tracing bind/current) ---------

_current: contextvars.ContextVar = contextvars.ContextVar(
    "pt_deadline", default=None
)


@contextlib.contextmanager
def bind(deadline):
    """Bind ``deadline`` as the ambient deadline for the enclosed work."""
    tok = _current.set(deadline)
    try:
        yield deadline
    finally:
        _current.reset(tok)


def current():
    """The ambient :class:`Deadline`, or None when unbound/disabled."""
    return _current.get()


# -- retry budget ------------------------------------------------------------


class RetryBudget:
    """Token bucket bounding retry amplification, SRE-style.

    Each retry spends one token; each *successful* request refills
    ``refill_fraction`` of a token (so sustained retries are bounded to
    roughly that fraction of successful traffic).  Starts full: a burst of
    up to ``capacity`` retries is always available after quiet periods.
    """

    def __init__(self, capacity=10.0, refill_fraction=0.1):
        self.capacity = float(capacity)
        self.refill_fraction = float(refill_fraction)
        self.tokens = float(capacity)
        self.spent = 0
        self.exhausted = 0
        self._mu = threading.Lock()

    def take(self):
        """Spend one token; False (and counted) when the bucket is dry."""
        with self._mu:
            if self.tokens >= 1.0:
                self.tokens -= 1.0
                self.spent += 1
                return True
            self.exhausted += 1
            return False

    def note_success(self):
        with self._mu:
            self.tokens = min(self.capacity, self.tokens + self.refill_fraction)

    def snapshot(self):
        with self._mu:
            return {
                "tokens": round(self.tokens, 3),
                "capacity": self.capacity,
                "spent": self.spent,
                "exhausted": self.exhausted,
            }


# -- adaptive hedge threshold ------------------------------------------------


class LatencyTracker:
    """Ring buffer of request latencies exposing an adaptive quantile.

    Used for the hedge trigger: a request older than ``threshold()``
    (fleet p95 by default) is presumed stuck and worth hedging.
    """

    def __init__(self, window=256, min_samples=20, quantile=0.95):
        self.window = int(window)
        self.min_samples = int(min_samples)
        self.quantile = float(quantile)
        self._buf = [0.0] * self.window
        self._n = 0
        self._i = 0
        self._mu = threading.Lock()

    def observe(self, seconds):
        with self._mu:
            self._buf[self._i] = float(seconds)
            self._i = (self._i + 1) % self.window
            if self._n < self.window:
                self._n += 1

    def threshold(self):
        """Current quantile latency, or None until warm."""
        with self._mu:
            if self._n < self.min_samples:
                return None
            vals = sorted(self._buf[: self._n])
        k = min(len(vals) - 1, int(self.quantile * len(vals)))
        return vals[k]

    def count(self):
        with self._mu:
            return self._n


# -- per-replica circuit breaker --------------------------------------------


class ReplicaHealth:
    """Gray-failure score + circuit breaker for one replica.

    States: ``closed`` (healthy) → ``open`` (quarantined; no placement)
    → ``half_open`` (one cheap probe in flight) → ``closed`` on probe
    success or back to ``open`` on failure.
    """

    def __init__(self, name, alpha=0.3):
        self.name = name
        self.alpha = float(alpha)
        self.state = "closed"
        self.latency_ewma = None  # dispatch→first-result latency, seconds
        self.queue_ewma = None  # replica-reported queue depth
        self.timeouts = 0  # consecutive timeouts/errors
        self.samples = 0
        self.t_open = 0.0  # monotonic time the breaker opened
        self.opened_count = 0
        self.last_reason = None

    def note_latency(self, seconds):
        s = float(seconds)
        if self.latency_ewma is None:
            self.latency_ewma = s
        else:
            self.latency_ewma += self.alpha * (s - self.latency_ewma)
        self.samples += 1
        self.timeouts = 0

    def note_queue(self, depth):
        d = float(depth)
        if self.queue_ewma is None:
            self.queue_ewma = d
        else:
            self.queue_ewma += self.alpha * (d - self.queue_ewma)

    def note_timeout(self):
        self.timeouts += 1

    def trip(self, reason):
        self.state = "open"
        self.t_open = time.monotonic()
        self.opened_count += 1
        self.last_reason = reason
        self.timeouts = 0

    def probe_due(self, cooldown_s, now=None):
        if self.state != "open":
            return False
        now = time.monotonic() if now is None else now
        return (now - self.t_open) >= cooldown_s

    def half_open(self):
        self.state = "half_open"

    def close(self):
        self.state = "closed"
        self.latency_ewma = None
        self.queue_ewma = None
        self.timeouts = 0
        self.samples = 0

    def reopen(self):
        """Failed half-open probe: back to open, cooldown restarts."""
        self.state = "open"
        self.t_open = time.monotonic()

    def snapshot(self):
        return {
            "state": self.state,
            "latency_ewma_s": (
                round(self.latency_ewma, 6) if self.latency_ewma is not None else None
            ),
            "queue_ewma": (
                round(self.queue_ewma, 3) if self.queue_ewma is not None else None
            ),
            "timeouts": self.timeouts,
            "samples": self.samples,
            "opened": self.opened_count,
            "reason": self.last_reason,
        }


# -- plane -------------------------------------------------------------------


class ReliabilityConfig:
    """Knobs for the reliability plane.  All times in seconds."""

    def __init__(
        self,
        deadline_s=None,
        deadline_factor=10.0,
        retry_budget=10.0,
        retry_refill=0.1,
        hedge=True,
        hedge_factor=1.0,
        hedge_min_samples=20,
        hedge_max_new=64,
        outlier_factor=3.0,
        min_outlier_latency_s=0.05,
        consecutive_timeouts=3,
        quarantine_cooldown_s=2.0,
        probe_timeout_s=5.0,
        ewma_alpha=0.3,
    ):
        # Default request budget; None → deadline_factor × the SLO class
        # target TTFT (and no deadline at all when neither is set).
        self.deadline_s = deadline_s
        self.deadline_factor = float(deadline_factor)
        self.retry_budget = float(retry_budget)
        self.retry_refill = float(retry_refill)
        self.hedge = bool(hedge)
        self.hedge_factor = float(hedge_factor)
        self.hedge_min_samples = int(hedge_min_samples)
        self.hedge_max_new = int(hedge_max_new)
        self.outlier_factor = float(outlier_factor)
        # Ignore outlier math below this absolute latency: a 3x outlier on
        # a 2 ms fleet median is noise, not gray failure.
        self.min_outlier_latency_s = float(min_outlier_latency_s)
        self.consecutive_timeouts = int(consecutive_timeouts)
        self.quarantine_cooldown_s = float(quarantine_cooldown_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self.ewma_alpha = float(ewma_alpha)


class ReliabilityPlane:
    """Aggregate reliability state for one Router.

    Owns the retry budget, the fleet latency tracker feeding the hedge
    threshold, and per-replica breakers.  The Router consults it at
    submit/dispatch/requeue/poll time; everything here is thread-safe and
    cheap (no locks held across I/O).
    """

    def __init__(self, config=None):
        self.config = config if config is not None else ReliabilityConfig()
        self.budget = RetryBudget(
            capacity=self.config.retry_budget,
            refill_fraction=self.config.retry_refill,
        )
        self.latency = LatencyTracker(min_samples=self.config.hedge_min_samples)
        self._health = {}
        self._mu = threading.Lock()
        # Counters mirrored into telemetry when enabled; kept locally so
        # /statusz works (and tests can assert) with telemetry off.
        self.deadline_exceeded = 0
        self.hedges = 0
        self.hedge_wins = 0
        self.quarantines = 0

    # -- deadlines ----------------------------------------------------------

    def deadline_for(self, target_ttft_s=None, budget_s=None):
        """Mint the Deadline for a new request, or None when unbudgeted.

        Priority: explicit per-class ``budget_s`` → config ``deadline_s``
        → ``deadline_factor`` × the SLO target TTFT.
        """
        if budget_s is None:
            budget_s = self.config.deadline_s
        if budget_s is None and target_ttft_s:
            budget_s = self.config.deadline_factor * float(target_ttft_s)
        if budget_s is None:
            return None
        return Deadline.after(budget_s)

    # -- per-replica health --------------------------------------------------

    def health(self, name):
        with self._mu:
            h = self._health.get(name)
            if h is None:
                h = self._health[name] = ReplicaHealth(
                    name, alpha=self.config.ewma_alpha
                )
            return h

    def drop(self, name):
        with self._mu:
            self._health.pop(name, None)

    def fleet_median_latency(self):
        """Median dispatch-latency EWMA across closed replicas, or None.

        Even-sized fleets take the LOWER middle: in a 2-replica fleet
        the upper middle IS the slow replica, which would make its own
        outlier test vacuous.
        """
        with self._mu:
            vals = sorted(
                h.latency_ewma
                for h in self._health.values()
                if h.latency_ewma is not None and h.state == "closed"
            )
        if not vals:
            return None
        return vals[(len(vals) - 1) // 2]

    def quarantine_reason(self, health, fleet_median=None):
        """Why ``health`` should trip now, or None if it looks fine.

        Signals, in priority order: consecutive timeouts, dispatch-latency
        EWMA outlier vs the fleet median, queue-depth outlier vs fleet
        median queue depth.  Outlier math requires ≥ 2 scored replicas so a
        lone replica can never self-quarantine.
        """
        cfg = self.config
        if health.state != "closed":
            return None
        if health.timeouts >= cfg.consecutive_timeouts:
            return f"timeouts={health.timeouts}"
        if fleet_median is None:
            fleet_median = self.fleet_median_latency()
        if (
            fleet_median is not None
            and health.latency_ewma is not None
            and health.samples >= 3
            and health.latency_ewma >= cfg.min_outlier_latency_s
            and health.latency_ewma > cfg.outlier_factor * fleet_median
        ):
            with self._mu:
                n_scored = sum(
                    1 for h in self._health.values() if h.latency_ewma is not None
                )
            if n_scored >= 2:
                return (
                    f"latency_outlier ewma={health.latency_ewma:.3f}s "
                    f"median={fleet_median:.3f}s"
                )
        q_med = self._fleet_median_queue()
        if (
            q_med is not None
            and health.queue_ewma is not None
            and health.queue_ewma >= 2.0
            and health.queue_ewma > cfg.outlier_factor * max(q_med, 1.0)
        ):
            return f"queue_outlier ewma={health.queue_ewma:.1f} median={q_med:.1f}"
        return None

    def _fleet_median_queue(self):
        with self._mu:
            vals = sorted(
                h.queue_ewma
                for h in self._health.values()
                if h.queue_ewma is not None and h.state == "closed"
            )
        if len(vals) < 2:
            return None
        return vals[(len(vals) - 1) // 2]

    # -- hedging -------------------------------------------------------------

    def hedge_threshold(self):
        """Adaptive hedge trigger in seconds, or None while cold/disabled."""
        if not self.config.hedge:
            return None
        t = self.latency.threshold()
        if t is None:
            return None
        return t * self.config.hedge_factor

    # -- introspection -------------------------------------------------------

    def statusz(self):
        with self._mu:
            health = {n: h.snapshot() for n, h in sorted(self._health.items())}
        return {
            "budget": self.budget.snapshot(),
            "hedge_threshold_s": self.hedge_threshold(),
            "latency_samples": self.latency.count(),
            "deadline_exceeded": self.deadline_exceeded,
            "hedges": self.hedges,
            "hedge_wins": self.hedge_wins,
            "quarantines": self.quarantines,
            "replicas": health,
        }


def statusz_section():
    """Placeholder-free /statusz hook: reliability state lives per-Router
    (see ``Router.stats()``); this module-level section only documents the
    header contract so operators can discover it."""
    return {"deadline_header": DEADLINE_HEADER}
