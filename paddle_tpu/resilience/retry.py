"""Bounded retry for transient I/O — capped exponential backoff with
deterministic seeded jitter, deadline-bounded, counted.

Checkpoint save/restore wrap every file operation in :func:`retry_io`:
a transient filesystem hiccup (shared-FS blip, NFS timeout — surfacing
as ``OSError``) costs a short backoff instead of aborting the save
outright. Deterministic errors (checksum mismatches, enforce failures)
are NOT retryable and propagate immediately.

``pt_retry_total`` counts absorbed faults; ``pt_retry_exhausted_total``
counts operations that failed even after the budget — both only while
telemetry is enabled.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Optional, Tuple, Type, TypeVar

from .. import telemetry
from ..core.enforce import enforce

T = TypeVar("T")


@telemetry.cached_instruments
def _retry_metrics(reg):
    return {
        "retries": reg.counter(
            "pt_retry_total",
            "transient I/O errors absorbed by resilience.retry"),
        "exhausted": reg.counter(
            "pt_retry_exhausted_total",
            "operations that failed after the full retry budget"),
    }


class RetryPolicy:
    """Retry shape: up to ``max_attempts`` tries, sleeping
    ``base_delay_s * 2^k`` (capped at ``max_delay_s``) plus up to
    ``jitter`` fraction of that, never sleeping past ``deadline_s``
    total. The jitter RNG is seeded — two runs with the same policy and
    failure schedule back off identically (the determinism the
    fault-injection harness needs)."""

    def __init__(self, max_attempts: int = 4, base_delay_s: float = 0.05,
                 max_delay_s: float = 2.0, deadline_s: float = 30.0,
                 retry_on: Tuple[Type[BaseException], ...] = (OSError,),
                 jitter: float = 0.5, seed: int = 0,
                 sleep: Callable[[float], None] = time.sleep):
        enforce(max_attempts >= 1, "max_attempts must be >= 1, got %s",
                max_attempts)
        enforce(deadline_s > 0, "deadline_s must be > 0, got %s",
                deadline_s)
        self.max_attempts = max_attempts
        self.base_delay_s = base_delay_s
        self.max_delay_s = max_delay_s
        self.deadline_s = deadline_s
        self.retry_on = tuple(retry_on)
        self.jitter = jitter
        self.seed = seed
        self._rng = random.Random(seed)
        self._sleep = sleep

    def backoff_s(self, attempt: int) -> float:
        """Sleep before retry ``attempt`` (1-based)."""
        base = min(self.base_delay_s * (2.0 ** (attempt - 1)),
                   self.max_delay_s)
        return base * (1.0 + self.jitter * self._rng.random())


DEFAULT_POLICY = RetryPolicy()


def retry_io(fn: Callable[[], T], *,
             policy: Optional[RetryPolicy] = None,
             what: str = "io") -> T:
    """Run ``fn`` under ``policy`` (default :data:`DEFAULT_POLICY`).

    Retries only ``policy.retry_on`` errors; re-raises the last error
    once attempts are exhausted or the next backoff would cross the
    deadline. ``what`` names the operation in telemetry-off-safe log
    lines."""
    policy = policy or DEFAULT_POLICY
    t0 = time.monotonic()
    attempt = 0
    while True:
        try:
            return fn()
        except policy.retry_on as e:
            attempt += 1
            delay = policy.backoff_s(attempt)
            out_of_budget = attempt >= policy.max_attempts
            past_deadline = (time.monotonic() - t0 + delay
                             > policy.deadline_s)
            if out_of_budget or past_deadline:
                if telemetry.enabled():
                    _retry_metrics()["exhausted"].inc()
                raise
            if telemetry.enabled():
                _retry_metrics()["retries"].inc()
            policy._sleep(delay)
