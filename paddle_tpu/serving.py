"""Continuous-batching LM serving loop (slot-based, static shapes).

A fixed arena of ``slots`` KV caches decodes in lockstep — every jitted
step advances ALL active slots one token, each at its OWN cursor (the
per-row machinery speculative decoding uses: vmapped single-row
attention with per-slot positions). Requests queue host-side; when a
slot finishes (eos or its max_len), the next prompt is prefilled into
that slot between steps and the batch keeps moving — no padding the
whole batch to the slowest request, no recompiles (prompt lengths pad
to fixed buckets; everything else is static).

This is the serving-runtime capstone over the decode stack: generate()
semantics per request (greedy or temperature/top-k/top-p sampling, eos
freezing), the KV-cache mixin underneath, and it composes with
quant.apply_weight_only_int8 (buffers ride the same functional step).
Opt-in refinements: paged KV (pages=N, vLLM-style page pool + prefix
caching), CHUNKED PREFILL (prefill_chunk=C — C prompt tokens per
serving tick instead of whole-prompt admission stalls), and
SPECULATIVE DECODING over the arena (draft=model, gamma=g — per-row
draft steps + ONE per-row verify chunk per round; greedy mode matches
the plain arena up to near-tie argmax flips — the verify chunk and the
step loop reduce in different orders, so a near-tie can break either
way; ``TestSpeculativeArena`` pins exactly this).

Telemetry (``paddle_tpu.telemetry``, off by default): TTFT and
per-token decode latency histograms, queue depth / page-pool occupancy
gauges, admission rejections, speculative accept rate, and recompile
tracking of the step + per-bucket prefill signatures. All host-side
scalars recorded outside jit; every hook short-circuits on the enabled
flag.

Green-field vs the reference (its serving is the one-request-at-a-time
predictor, /root/reference/paddle/fluid/inference/api/api_impl.cc role;
continuous batching is the modern LM-serving analog of that
capability).
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from . import telemetry
from .core.enforce import enforce

__all__ = ["BatchedDecoder", "PagedKVPool", "Request", "KVHandoff",
           "TokenStream", "reject_cause"]
from .nn.layer import inject_state
from .resilience import reliability as _reliability
from .ops import paged_kv as paged_ops
from .ops.sampling import sample_from_logits
from .telemetry import costs as _costs
from .telemetry import profiling as _profiling
from .telemetry import recompile as _recompile
from .telemetry import server as _dbg_server
from .telemetry import tracing as _tracing

# reusable inert context manager: span call-sites gate on
# telemetry.enabled() (the zero-cost contract — a disabled run must
# execute NO tracing code, pinned by test) and fall back to this
_NULL_CM = contextlib.nullcontext()


@telemetry.cached_instruments
def _serving_metrics(reg):
    """Serving instrument set, memoized against the registry generation
    (run() touches this every tick — rebuilding 12 get-or-create
    lookups per tick is pure waste). Only reached when telemetry is
    enabled."""
    return {
        "requests": reg.counter(
            "pt_serving_requests_total", "requests submitted"),
        "completed": reg.counter(
            "pt_serving_completed_total", "requests completed"),
        "tokens": reg.counter(
            "pt_serving_tokens_total", "tokens emitted"),
        "ttft": reg.histogram(
            "pt_serving_ttft_seconds",
            "submit-to-first-token latency (includes queue wait)",
            unit="s"),
        "decode_latency": reg.histogram(
            "pt_serving_decode_latency_seconds",
            "per-token decode latency (dispatch wall time / tokens "
            "emitted that dispatch)", unit="s"),
        "queue_depth": reg.gauge(
            "pt_serving_queue_depth", "requests waiting for a slot"),
        "rejections": reg.counter(
            "pt_serving_admission_rejections_total",
            "admissions rejected or deferred (all causes; see the "
            "cause-labeled series for the split)"),
        # cause-labeled split of the same total (unlabeled series kept
        # for dashboard compat): pool_exhausted = paged admission
        # deferred on page exhaustion, capacity = hard queue-depth cap,
        # shed = SLO load-shed (router-side policy), deadline =
        # end-to-end deadline expired before/while serving (the
        # reliability plane's typed drop — never silently computed)
        "rejections_by_cause": {
            cause: reg.counter(
                "pt_serving_admission_rejections_total",
                "admissions rejected or deferred, by cause",
                labels={"cause": cause})
            for cause in ("pool_exhausted", "capacity", "shed",
                          "deadline")},
        "page_occupancy": reg.gauge(
            "pt_serving_page_occupancy_ratio",
            "allocated fraction of the KV page pool"),
        "kv_pool_bytes": reg.gauge(
            "pt_serving_kv_pool_bytes",
            "device bytes held by the paged KV pools (all blocks, "
            "K+V, scales included for kv_dtype=int8) — the "
            "concurrent-session HBM denominator"),
        "kv_pool_live_bytes": reg.gauge(
            "pt_serving_kv_pool_live_bytes",
            "KV pool bytes backing ALLOCATED pages (occupancy x pool "
            "bytes)"),
        "spec_rounds": reg.counter(
            "pt_serving_spec_row_rounds_total",
            "speculative verify rounds (per active row)"),
        "spec_accepted": reg.counter(
            "pt_serving_spec_accepted_total",
            "draft tokens accepted by target verify"),
        "spec_accept_rate": reg.gauge(
            "pt_serving_spec_accept_rate",
            "mean accepted draft tokens per verify round (0..gamma)"),
        "streams": reg.counter(
            "pt_serving_streams_total",
            "requests served with a per-token stream attached"),
        "stream_stalled": reg.counter(
            "pt_stream_stalled_seconds",
            "cumulative seconds streams spent stalled on a full "
            "client buffer (the backpressure that pauses a stream, "
            "never the arena tick)", unit="s"),
    }


class PagedKVPool:
    """Shared page pool for paged-KV attention (vLLM-style): K and V
    live in (pages, page_size, kv_heads, head_dim) pools shared by all
    requests; each request owns a PAGE TABLE (its logical cache = the
    page sequence), so memory scales with live tokens, not
    slots x max-capacity. The attention side is
    ops.pallas.flash_decode.flash_decode_paged (the scalar-prefetched
    table drives the page DMA) with an XLA gather fallback.

    Host-side alloc/free here; the pools are functional arrays — step
    functions thread them like any cache (write_rows/write_chunk return
    updated pools). Serving integration (BatchedDecoder paged mode) is
    the round-6 hook; the building blocks are tested now
    (tests/test_paged_kv.py)."""

    def __init__(self, pages: int, page_size: int, kv_heads: int,
                 head_dim: int, dtype=None, arrays: bool = True,
                 kv_dtype=None):
        enforce(page_size in (64, 128, 256),
                "page_size must be one of (64, 128, 256), got %s",
                page_size)
        enforce(pages >= 1, "pages must be >= 1, got %s", pages)
        from .core.dtypes import default_dtype

        # kv_dtype="int8": QUANTIZED pools (ops.paged_kv.QuantizedPool
        # — int8 values + per-vector f32 scales, quantize-on-append /
        # dequantize-in-attention). ~(1 + 4/head_dim)/itemsize the
        # bytes per cached token of the float pool, which is what sets
        # max concurrent sessions at a fixed page-pool HBM budget.
        enforce(kv_dtype in (None, "int8", jnp.int8),
                'kv_dtype must be None or "int8", got %r', kv_dtype)
        self.quantized = kv_dtype is not None
        self.kv_dtype = "int8" if self.quantized else None
        self.dtype = dtype or default_dtype()
        self.shape = (pages, page_size, kv_heads, head_dim)
        # arrays=False: allocator-only (callers that thread their own
        # functional pools — BatchedDecoder — must not pin two extra
        # pool-sized device buffers here for the decoder's lifetime)
        self.kpool = self.empty_pool() if arrays else None
        self.vpool = self.empty_pool() if arrays else None
        self.page_size = page_size
        self.pages = pages
        self._free = list(range(pages - 1, -1, -1))
        self._free_set = set(self._free)
        # reference counts (prefix caching: a page shared by N live
        # requests + the registry has ref N+1 and only returns to the
        # free list at 0)
        self._ref = np.zeros(pages, np.int64)

    @property
    def free_pages(self) -> int:
        return len(self._free)

    def empty_pool(self):
        """Mint one zeroed functional pool array in this pool's storage
        form (float array, or QuantizedPool when ``kv_dtype="int8"``) —
        what BatchedDecoder threads per block."""
        if self.quantized:
            return paged_ops.QuantizedPool(
                jnp.zeros(self.shape, jnp.int8),
                jnp.zeros(self.shape[:3], jnp.float32))
        return jnp.zeros(self.shape, self.dtype)

    @property
    def pool_nbytes(self) -> int:
        """Device bytes ONE pool array costs (K or V side) — the
        serving-density denominator: sessions/HBM scales with
        1/pool_nbytes at fixed pages."""
        if self.quantized:
            return paged_ops.quantized_pool_nbytes(self.shape)
        return int(np.prod(self.shape)) * jnp.dtype(self.dtype).itemsize

    def alloc(self, n: int) -> np.ndarray:
        """Claim n pages (typed error when exhausted — the admission
        backpressure signal)."""
        enforce(n <= len(self._free),
                "page pool exhausted: want %s, free %s", n,
                len(self._free))
        got = [self._free.pop() for _ in range(n)]
        self._free_set.difference_update(got)
        for i in got:
            self._ref[i] = 1
        return np.asarray(got, np.int32)

    def share(self, ids) -> None:
        """Take an extra reference on live pages (prefix caching)."""
        for i in np.asarray(ids).reshape(-1):
            i = int(i)
            enforce(0 <= i < self.pages,
                    "page id %s outside pool (%s pages)", i, self.pages)
            enforce(self._ref[i] > 0,
                    "share of unallocated page %s", i)
            self._ref[i] += 1

    def free(self, ids) -> None:
        """Drop one reference per page; a page returns to the free list
        at refcount 0. Over-freeing would hand the same physical page
        to two requests (silent KV cross-contamination), so it is a
        typed error instead."""
        for i in np.asarray(ids).reshape(-1):
            i = int(i)
            enforce(0 <= i < self.pages,
                    "page id %s outside pool (%s pages)", i, self.pages)
            enforce(i not in self._free_set and self._ref[i] > 0,
                    "double free of page %s", i)
            self._ref[i] -= 1
            if self._ref[i] == 0:
                self._free.append(i)
                self._free_set.add(i)

    # --- functional array ops (jit-safe; thread the returned pools;
    # ONE definition in ops/paged_kv.py, re-exported here) ------------

    write_rows = staticmethod(paged_ops.write_rows)
    write_chunk = staticmethod(paged_ops.write_chunk)
    attend = staticmethod(paged_ops.attend)


def _row_apply(caches, s, fn):
    """Slice slot ``s`` of each layer's (slots, ...) K/V cache pair as
    a batch-1 row, run ``fn(row) -> (result, new_row)``, write the row
    back (dtype-cast) — the ONE definition of the per-slot
    slice/run/write-back boilerplate every contiguous prefill piece
    (full, chunk, restep, draft) shares. jit-safe: callers close over
    it inside their traced functions."""
    row = [(lax.dynamic_slice_in_dim(ck, s, 1, axis=0),
            lax.dynamic_slice_in_dim(cv, s, 1, axis=0))
           for ck, cv in caches]
    out, row = fn(row)
    new = []
    for (ck, cv), (rk, rv) in zip(caches, row):
        new.append((lax.dynamic_update_slice_in_dim(
            ck, rk.astype(ck.dtype), s, axis=0),
            lax.dynamic_update_slice_in_dim(
                cv, rv.astype(cv.dtype), s, axis=0)))
    return out, new


def reject_cause(cause: str) -> None:
    """Bump the admission-rejection counters (unlabeled total + the
    cause-labeled series) — the ONE place the split is recorded, shared
    by the arena's pool backpressure and the router's shed policy.
    No-op while telemetry is disabled."""
    if not telemetry.enabled():
        return
    m = _serving_metrics()
    m["rejections"].inc()
    by = m["rejections_by_cause"].get(cause)
    if by is not None:
        by.inc()


class TokenStream:
    """Bounded per-client token buffer — the per-token streaming sink.

    Tokens leave the arena the TICK they are sampled (not at request
    completion): the arena's host loop calls :meth:`offer` with the
    request's emitted-token list each tick, and records append from the
    stream's own high-water index while the buffer has room. ``offer``
    NEVER blocks — a stalled client (full buffer) pauses ITS OWN stream
    (stall seconds accumulate on ``pt_stream_stalled_seconds``) and the
    stream catches back up from the same list on a later tick once the
    client drains; the arena tick cadence is never throttled by any one
    consumer (pinned by test).

    The router's fan-in pump feeds a CLIENT-side instance through
    :meth:`put`, which MAY wait (bounded) for room — the pump is a
    per-request thread, so client backpressure propagates upstream to
    the replica-side buffer, never to the arena.

    Records are dicts. Tokens: ``{"i": index, "tok": id, "t":
    perf_counter-or-None}``. Control records ride the same queue and
    bypass the cap (they are O(retries), not O(tokens)):
    ``{"event": "resume", "retries": n, ...}`` (replica died mid-stream,
    the request re-dispatched on a survivor — same trace id, already-
    delivered tokens stay valid), ``{"event": "end", "n": total}``,
    ``{"event": "error", "error": repr}`` (typed terminal failure —
    a client NEVER sees a silent stall). Consume via :meth:`get` or
    iteration; ``None`` from ``get`` means timeout (stream still live)
    — iteration ends only at end/error."""

    def __init__(self, maxlen: int = 256):
        enforce(maxlen >= 1, "stream maxlen must be >= 1, got %s",
                maxlen)
        self.maxlen = int(maxlen)
        self._buf: List[Dict[str, Any]] = []
        self._cond = threading.Condition()
        self._src = 0                 # next emitted index to buffer
        self._final = None            # completion record's token array
        self._end_sent = False
        self.closed = False
        self.error: Optional[BaseException] = None
        self.stalled_s = 0.0
        self._stall_t0: Optional[float] = None

    # -- producer side ------------------------------------------------------

    def _note_stall_end(self, now: float) -> None:
        if self._stall_t0 is not None:
            d = max(0.0, now - self._stall_t0)
            self.stalled_s += d
            self._stall_t0 = None
            if d and telemetry.enabled():
                _serving_metrics()["stream_stalled"].inc(d)

    def offer(self, toks, now: Optional[float] = None) -> None:
        """Arena side: buffer token records for ``toks[src:]`` while
        the client buffer has room. Never blocks (see class doc)."""
        if now is None:
            now = time.perf_counter()
        with self._cond:
            if self.closed:
                return
            progressed = False
            while self._src < len(toks) and len(self._buf) < self.maxlen:
                self._buf.append({"i": self._src,
                                  "tok": int(toks[self._src]),
                                  "t": now})
                self._src += 1
                progressed = True
            if self._src < len(toks):
                if self._stall_t0 is None:
                    self._stall_t0 = now   # stall starts
            else:
                self._note_stall_end(now)
            if progressed:
                self._cond.notify_all()

    def put(self, rec: Dict[str, Any],
            timeout: Optional[float] = None) -> bool:
        """Pump side: append ONE record, waiting (bounded) for room.
        Returns False when the stream closed or the wait expired —
        the caller's signal that the client went away."""
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        with self._cond:
            while len(self._buf) >= self.maxlen and not self.closed:
                w = 0.05
                if deadline is not None:
                    w = min(w, deadline - time.monotonic())
                    if w <= 0:
                        return False
                t0 = time.monotonic()
                self._cond.wait(w)
                d = time.monotonic() - t0
                self.stalled_s += d
                if d and telemetry.enabled():
                    _serving_metrics()["stream_stalled"].inc(d)
            if self.closed:
                return False
            if "i" in rec and int(rec["i"]) < self._src:
                # already delivered — a finish()-driven tail (or an
                # earlier pump) outran this record; a lagging pump
                # near completion must not hand the client the same
                # index twice. Dropped-as-delivered, not a failure.
                return True
            self._buf.append(dict(rec))
            if "i" in rec:
                # keep the high-water index in sync so a later
                # finish() serves only the not-yet-forwarded tail
                self._src = max(self._src, int(rec["i"]) + 1)
            self._cond.notify_all()
            return True

    def control(self, event: str, **kv: Any) -> None:
        """Append a control record (resume markers and the like) —
        bypasses the cap so backpressure can't delay the very record
        that explains the stream's state."""
        with self._cond:
            if self.closed:
                return
            self._buf.append({"event": event, **kv})
            self._cond.notify_all()

    def finish(self, result, now: Optional[float] = None) -> None:
        """Producer epilogue: the request completed with ``result``
        tokens. Any tokens a stalled client has not buffered yet are
        served CONSUMER-driven from this record (no producer thread
        lingers for a slow reader), then the typed end record."""
        if now is None:
            now = time.perf_counter()
        with self._cond:
            self._note_stall_end(now)
            self._final = np.asarray(result, np.int32)
            self._cond.notify_all()

    def fail(self, err: BaseException) -> None:
        """Terminal failure: the typed error record, then closed —
        a consumer blocked in ``get`` wakes to it immediately."""
        with self._cond:
            self._note_stall_end(time.perf_counter())
            self.error = err
            self._buf.append({"event": "error", "error": repr(err)})
            self.closed = True
            self._cond.notify_all()

    # -- consumer side ------------------------------------------------------

    @property
    def done(self) -> bool:
        with self._cond:
            return (not self._buf
                    and (self.closed
                         or (self._final is not None and self._end_sent
                             and self._src >= len(self._final))))

    def get(self, timeout: Optional[float] = None):
        """Next record, or None on timeout (stream still live) or when
        the stream is fully drained after end/error."""
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        with self._cond:
            while True:
                if self._buf:
                    rec = self._buf.pop(0)
                    self._cond.notify_all()   # room freed: wake put()
                    return rec
                if self._final is not None:
                    if self._src < len(self._final):
                        i = self._src
                        self._src += 1
                        return {"i": i, "tok": int(self._final[i]),
                                "t": None}
                    if not self._end_sent:
                        self._end_sent = True
                        self.closed = True
                        return {"event": "end",
                                "n": int(len(self._final))}
                if self.closed:
                    return None
                w = 0.1
                if deadline is not None:
                    w = min(w, deadline - time.monotonic())
                    if w <= 0:
                        return None
                self._cond.wait(w)

    def __iter__(self):
        """Yield records until the end/error record has been consumed
        (the end/error record itself IS yielded)."""
        while True:
            rec = self.get(timeout=1.0)
            if rec is None:
                if self.done:
                    return
                continue
            yield rec
            if rec.get("event") in ("end", "error"):
                return


class KVHandoff:
    """Prefilled KV pages + next-token logits for ONE prompt — the
    prefill→decode disaggregation wire unit. A dedicated prefill worker
    produces it (:meth:`BatchedDecoder.prefill_export`), a decode
    replica consumes it (:meth:`BatchedDecoder.inject_prefilled`), so a
    long prompt's whole-prompt prefill never runs inside a decode
    replica's serving loop.

    ``blocks`` holds one ``(k_payload, v_payload)`` per transformer
    block: ``(m, page_size, kv_heads, head_dim)`` float arrays, or
    ``(q, scale)`` tuples for int8 pools (the storage form crosses the
    wire intact — no dequant/requant round trip). ``to_bytes`` /
    ``from_bytes`` are the npz wire format the HTTP handoff uses."""

    def __init__(self, prompt, plen: int, logits, blocks,
                 page_size: int, kv_dtype=None, trace=None,
                 deadline=None):
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        self.plen = int(plen)
        self.logits = np.asarray(logits, np.float32)
        self.blocks = blocks
        self.page_size = int(page_size)
        self.kv_dtype = kv_dtype
        # trace context (telemetry.tracing.TraceContext) riding the
        # wire form: in-process disaggregation hands the producer's
        # context straight to the decode replica — no HTTP header hop
        self.trace = trace
        # end-to-end deadline (resilience.reliability.Deadline) riding
        # the same wire: the decode replica inherits the REQUEST's
        # remaining budget, not a fresh per-hop one
        self.deadline = deadline

    @property
    def pages(self) -> int:
        """Pages per block the payload covers."""
        first = self.blocks[0][0]
        return (first[0] if isinstance(first, tuple)
                else first).shape[0]

    @property
    def nbytes(self) -> int:
        n = 0
        for kp, vp in self.blocks:
            for p in (kp, vp):
                arrs = p if isinstance(p, tuple) else (p,)
                n += sum(int(a.nbytes) for a in arrs)
        return n

    def to_bytes(self) -> bytes:
        import io

        quant = self.kv_dtype is not None

        def stack(side):
            if quant:
                return (np.stack([np.asarray(b[side][0])
                                  for b in self.blocks]),
                        np.stack([np.asarray(b[side][1])
                                  for b in self.blocks]))
            return (np.stack([np.asarray(b[side])
                              for b in self.blocks]),)

        arrays = {"prompt": self.prompt,
                  "logits": self.logits,
                  "meta": np.asarray([self.plen, self.page_size,
                                      int(quant)], np.int64)}
        if self.trace is not None:
            # the trace context crosses the wire in header form
            arrays["trace"] = np.asarray(self.trace.to_header())
        if self.deadline is not None:
            # absolute wall-clock epoch — meaningful across processes
            arrays["deadline"] = np.asarray(self.deadline.to_header())
        for side, name in ((0, "k"), (1, "v")):
            payload = stack(side)
            if quant:
                arrays[name + "q"], arrays[name + "s"] = payload
            else:
                arrays[name] = payload[0]
        buf = io.BytesIO()
        np.savez(buf, **arrays)
        return buf.getvalue()

    @staticmethod
    def from_bytes(data: bytes) -> "KVHandoff":
        import io

        z = np.load(io.BytesIO(data))
        plen, page_size, quant = (int(x) for x in z["meta"])
        blocks = []
        if quant:
            n = z["kq"].shape[0]
            blocks = [((z["kq"][i], z["ks"][i]),
                       (z["vq"][i], z["vs"][i])) for i in range(n)]
        else:
            blocks = [(z["k"][i], z["v"][i])
                      for i in range(z["k"].shape[0])]
        trace = (_tracing.from_header(str(z["trace"]))
                 if "trace" in z.files else None)
        deadline = (_reliability.Deadline.from_header(str(z["deadline"]))
                    if "deadline" in z.files else None)
        return KVHandoff(z["prompt"], plen, z["logits"], blocks,
                         page_size, "int8" if quant else None,
                         trace=trace, deadline=deadline)


class Request:
    """One generation request; ``result`` is filled on completion."""

    def __init__(self, rid: int, prompt_ids, max_new: int):
        self.rid = rid
        self.prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        self.max_new = int(max_new)
        self.result: Optional[np.ndarray] = None
        self.t_submit = 0.0   # stamped at submit (always — the router
        self.t_first = 0.0    # latency accounting reads these even
        self.t_done = 0.0     # with telemetry off; three float stores)
        self.t_tokens: List[float] = []  # per-token emission stamps
        self.handoff: Optional[KVHandoff] = None  # pre-filled KV pages
        self.trace = None  # TraceContext (telemetry on + traced hop)
        self.stream: Optional[TokenStream] = None  # per-token sink
        self.deadline = None  # reliability.Deadline (router-minted)
        self.deadline_exceeded = False  # dropped typed, never computed


class BatchedDecoder:
    """Slot-based continuous batching over a causal LM (GPT-family:
    anything exposing ``_step_logits``/``_chunk_logits`` and
    ``blocks[*].self_attn.init_cache``).

    ``submit()`` enqueues; ``run()`` drives to completion and returns
    {request_id: np.ndarray of generated ids (prompt excluded)}.
    Sampling params apply to every request (temperature=0 = greedy);
    eos_id ends a request early. Per-(slot-generation, position) keys
    derive by fold_in, so a request's draw stream is independent of
    which slot served it only via the admission counter — deterministic
    for a fixed submission order.
    """

    def __init__(self, model, slots: int, capacity: int, *,
                 eos_id: Optional[int] = None, key=None,
                 temperature: float = 0.0, top_k: int = 0,
                 top_p: float = 1.0, prompt_bucket: int = 16,
                 pages: Optional[int] = None, page_size: int = 128,
                 prefix_cache: bool = False, kv_dtype=None,
                 prefill_chunk: Optional[int] = None,
                 draft=None, gamma: int = 4, decode_steps: int = 1):
        enforce(slots >= 1, "slots must be >= 1, got %s", slots)
        enforce(capacity >= prompt_bucket,
                "capacity %s < prompt bucket %s", capacity,
                prompt_bucket)
        self.model = model
        # CHUNKED PREFILL (opt-in): admission only ALLOCATES; the
        # prompt then prefills prefill_chunk tokens per serving-loop
        # tick (one chunk per tick across all admitting slots), so
        # active slots keep emitting at decode cadence instead of
        # stalling for a whole long-prompt prefill (Sarathi-style
        # throughput smoothing). Token-identical to monolithic
        # prefill: chunk boundaries don't change the attention math.
        self.prefill_chunk = prefill_chunk
        if prefill_chunk is not None:
            enforce(prefill_chunk >= 1, "prefill_chunk must be >= 1")
            enforce(prefill_chunk <= capacity,
                    "prefill_chunk %s > capacity %s", prefill_chunk,
                    capacity)
            if pages is not None:
                # the chunk grid must never overrun the allocated
                # pages into an unallocated table entry (= physical
                # page 0): with C | page_size, the padded chunk
                # frontier (smallest multiple of C >= plen) is <= the
                # page demand ceil((plen+max_new)/ps)*ps
                enforce(page_size % prefill_chunk == 0,
                        "prefill_chunk %s must divide page_size %s",
                        prefill_chunk, page_size)
        # MULTI-TOKEN DECODE STEPS (opt-in, decode_steps=k): the jitted
        # step scans k single-token steps with the token picks moved
        # IN-DEVICE, so every dispatch advances all slots k tokens —
        # the steps-per-call lever applied to serving. On high-latency
        # links (the axon relay: one ~RTT per dispatch) this multiplies
        # arena throughput by ~k. Semantics: token-identical to k=1
        # (same fold_in key chain); admission/eos granularity coarsens
        # to k (a row hitting eos mid-window discards the tail
        # host-side and never emits past eos or its budget).
        self.decode_steps = int(decode_steps)
        enforce(self.decode_steps >= 1,
                "decode_steps must be >= 1, got %s", decode_steps)
        # SPECULATIVE DECODING over the arena (opt-in): a small draft
        # model proposes ``gamma`` tokens per round at every slot's own
        # cursor; the target verifies all gamma+1 in ONE per-row chunk
        # (_chunk_logits_rows / _chunk_logits_paged_rows) and a
        # modified rejection test accepts a prefix — output tokens are
        # distributed EXACTLY as the target's own sampling chain
        # (greedy mode matches the plain arena up to near-tie argmax
        # flips; see the module docstring). The
        # draft keeps a contiguous (slots, capacity) cache arena of
        # its own; in paged mode only the TARGET is paged.
        self.draft = draft
        self.gamma = int(gamma)
        if draft is not None:
            enforce(gamma >= 1, "gamma must be >= 1, got %s", gamma)
            enforce(model.cfg.vocab_size == draft.cfg.vocab_size,
                    "vocab mismatch: target %s vs draft %s",
                    model.cfg.vocab_size, draft.cfg.vocab_size)
            enforce(self.decode_steps == 1,
                    "decode_steps composes with the plain arena only; "
                    "speculative rounds already emit multiple tokens "
                    "per dispatch")
        # overrun margin budgeted at admission: spec verify-chunks
        # write up to cursor+gamma; a decode_steps window can write up
        # to k-1 positions past a mid-window finish. Without the
        # margin those writes would scatter into UNALLOCATED table
        # entries (= physical page 0) in paged mode, or clamp-corrupt
        # the contiguous row tail
        self._extra = (self.gamma if draft is not None
                       else self.decode_steps - 1)
        self.slots, self.capacity = slots, capacity
        self.eos_id = eos_id
        self.temperature, self.top_k, self.top_p = temperature, top_k, top_p
        self.sampled = float(temperature) != 0.0
        if self.sampled:
            enforce(key is not None,
                    "temperature > 0 samples and needs a PRNG key")
        self.key = key if key is not None else jax.random.key(0)
        self.bucket = prompt_bucket
        # PAGED mode (pages=N): K/V live in per-block SHARED page pools
        # + one page table — memory scales with live tokens (pages
        # actually allocated), not slots x capacity; admission
        # backpressures on pool exhaustion. Contiguous mode (default):
        # per-block (slots, cap, h_kv, hd) arenas.
        self.paged = pages is not None
        if self.paged:
            enforce(capacity % page_size == 0,
                    "capacity %s not divisible by page_size %s",
                    capacity, page_size)
            enforce(page_size % prompt_bucket == 0,
                    "page_size %s must be a multiple of prompt_bucket "
                    "%s (bucket round-up must never overrun the "
                    "allocated pages into another request's page 0)",
                    page_size, prompt_bucket)
            attn0 = model.blocks[0].self_attn
            # kv_dtype="int8": quantized page pools (quantize-on-append
            # K/V, dequantize-in-attention) — ~(4*hd)/(hd+4) more pages
            # per HBM byte than fp32, which is the max-sessions lever
            self._allocator = PagedKVPool(
                pages, page_size, attn0.num_kv_heads, attn0.head_dim,
                arrays=False, kv_dtype=kv_dtype)
            self.page_size = page_size
            self.n_log = capacity // page_size
            al = self._allocator
            self.pools = [(al.empty_pool(), al.empty_pool())
                          for _ in model.blocks]
            self.table = np.zeros((slots, self.n_log), np.int32)
            self._slot_pages: List[Optional[np.ndarray]] = \
                [None] * slots
            # prefix caching (opt-in): completed requests REGISTER
            # their page-aligned prompt-prefix pages (one registry
            # reference via the allocator's refcounts); a later request
            # sharing that exact token prefix reuses the pages and
            # prefills only its suffix. Insertion-ordered dict = LRU
            # (hits re-insert); eviction frees registry references when
            # admission runs dry. K/V in a shared page are a pure
            # function of (tokens, positions, weights), so reuse is
            # exact.
            self.prefix_cache = prefix_cache
            self._prefix_registry: Dict[tuple, np.ndarray] = {}
            self.prefix_hits = 0
            self.prefix_lookups = 0  # admissions that consulted it
        else:
            enforce(not prefix_cache,
                    "prefix_cache requires paged mode (pages=N)")
            enforce(kv_dtype is None,
                    "kv_dtype requires paged mode (pages=N) — the "
                    "contiguous arena has no quantized form")
            self.caches = [blk.self_attn.init_cache(slots, capacity)
                           for blk in model.blocks]
        if draft is not None:
            self.caches_d = [blk.self_attn.init_cache(slots, capacity)
                             for blk in draft.blocks]
        self.tok = jnp.zeros((slots,), jnp.int32)      # last token/slot
        # cursors: paged mode parks EVERY not-yet-admitted slot past
        # capacity — an idle slot's table row is zeros, and a cursor of
        # 0 would scatter its junk K/V into physical page 0, which the
        # allocator hands to the first real request (write_rows drops
        # OOB cursors instead). Contiguous slots own private rows, so
        # 0 is harmless there.
        self.t = jnp.full((slots,),
                          capacity if self.paged else 0, jnp.int32)
        self.active = np.zeros((slots,), bool)         # host-side
        self.budget = np.zeros((slots,), np.int64)     # tokens left
        self.owner: List[Optional[Request]] = [None] * slots
        # per-slot trace context of the ACTIVE request (None unless
        # telemetry was on at submit and the request is traced) — the
        # decode tick's span/exemplar source; one list store per
        # activation, so the disabled path never touches tracing
        self._slot_trace: List[Optional[Any]] = [None] * slots
        self.emitted: List[List[int]] = [[] for _ in range(slots)]
        self.gen_count = 0                             # admission counter
        self._slot_gen = np.zeros((slots,), np.int64)
        self.queue: List[Request] = []
        self.done: Dict[int, Request] = {}
        self._next_rid = 0
        self._prefill_cache: Dict[int, object] = {}
        # jitted arena steps keyed by tokens-per-dispatch k: degraded
        # mode drops to k=1 without retracing the k=decode_steps fn
        self._step_fns: Dict[int, object] = {}
        self._spec_fn = None
        # SLO degrade lever (router-driven): forces decode_steps=1 and
        # bypasses speculative rounds until cleared — see set_degraded
        self.degraded = False
        # readiness (router placement signal, distinct from liveness):
        # False until the serving step has dispatched once (jit warm),
        # False again while draining on preemption
        self._warmed = False
        # tick accounting (plain counters, harness-readable without
        # telemetry): ticks run, tokens actually emitted, and the
        # token capacity (slots x k per tick) — the serving goodput
        # ratio is tick_tokens / tick_capacity
        self.tick_count = 0
        self.tick_tokens = 0
        self.tick_capacity = 0
        self._weights_fp = None  # stamped per run() when telemetry on
        # weights/buffers snapshot, passed to every jitted fn as REAL
        # arguments (inject_state): compiled programs stay weight-free,
        # which remote-compile relays require (HTTP 413 otherwise) and
        # which also lets all prefill buckets + the step share one
        # on-device copy of the weights
        self._mstate = (dict(model.named_parameters()),
                        dict(model.named_buffers()))
        self._dstate = (None if draft is None else
                        (dict(draft.named_parameters()),
                         dict(draft.named_buffers())))
        # spec-mode stats: mean accepted per target verify per row =
        # spec_accepted / spec_row_rounds; tokens per target call =
        # 1 + that (the real-pair speedup formula)
        self.spec_rounds = 0
        self.spec_row_rounds = 0
        self.spec_accepted = 0
        # chunked-prefill state: slot -> {padded, plen, off, request};
        # _pf_order is admission-FIFO so ticks are fair
        self._pf: List[Optional[dict]] = [None] * slots
        self._pf_order: List[int] = []
        self.debug_server = None  # last run(debug_port=)'s server
        # (live during that run; kept stopped afterwards for port/
        # status inspection)
        self.preempted = False  # last run() exited on a grace signal
        # (in-flight drained; self.queue holds the unserved remainder)
        # slot-resident requests carrying a deadline: the per-tick
        # expiry sweep is gated on this count, so an undeadlined run
        # (reliability off) executes no deadline code per tick
        self._dl_active = 0

    # ----- host API --------------------------------------------------------

    def submit(self, prompt_ids, max_new: int,
               stream: Optional[TokenStream] = None) -> int:
        """Enqueue one request. ``stream=`` attaches a
        :class:`TokenStream`: tokens leave the arena the tick they are
        sampled (offered per serving tick) instead of only at
        completion — the per-token streaming sink."""
        enforce(len(np.asarray(prompt_ids).reshape(-1)) >= 1,
                "empty prompt")
        enforce(max_new >= 1, "max_new must be >= 1, got %s", max_new)
        enforce(stream is None or isinstance(stream, TokenStream),
                "stream= takes a serving.TokenStream, got %s",
                type(stream).__name__)
        r = Request(self._next_rid, prompt_ids, max_new)
        r.stream = stream
        # spec/multi-step modes reserve extra positions (see _extra):
        # overrun writes past an unreserved capacity would corrupt K/V
        # below a live cursor (contiguous clamp) or another request's
        # pages (paged unallocated-entry scatter)
        enforce(len(r.prompt) + max_new + self._extra <= self.capacity,
                "prompt %s + max_new %s (+%s speculative/window margin) "
                "exceeds slot capacity %s",
                len(r.prompt), max_new, self._extra, self.capacity)
        if self.paged:
            # a demand beyond the WHOLE pool could never be admitted —
            # _admit would re-queue it forever (silent run() hang)
            need = ((len(r.prompt) + max_new + self._extra
                     + self.page_size - 1) // self.page_size)
            enforce(need <= self._allocator.pages,
                    "request needs %s pages but the pool only has %s",
                    need, self._allocator.pages)
        self._next_rid += 1
        r.t_submit = time.perf_counter()
        # ambient end-to-end deadline (the router's dispatch / the
        # debug server's POST edge binds it — one contextvar read, the
        # reliability analog of the telemetry enabled-flag gate)
        r.deadline = _reliability.current()
        if telemetry.enabled():
            _serving_metrics()["requests"].inc()
            if stream is not None:
                _serving_metrics()["streams"].inc()
            # request-scoped tracing: adopt the caller's bound context
            # (the router's dispatch / the debug server's POST edge
            # binds it) so the whole decode life of this request lands
            # on ONE trace
            r.trace = _tracing.current()
            # /healthz last-request age (owner-scoped while run() has
            # our server up; submits outside a live run broadcast — a
            # stopped server kept for post-run inspection must not
            # swallow the heartbeat)
            srv = self.debug_server
            if srv is not None and srv.running:
                srv.note("request")
            else:
                _dbg_server.note("request")
        self.queue.append(r)
        return r.rid

    def run(self, debug_port: Optional[int] = None,
            flight_recorder=None,
            preemption=None) -> Dict[int, np.ndarray]:
        """Drive until every submitted request completes.

        Live diagnostics (opt-in): ``debug_port=P`` serves the debug
        endpoints (/metrics /healthz /statusz /tracez /memz) on
        127.0.0.1:P for the duration of the drive (0 = ephemeral;
        ``self.debug_server`` holds the running server; starting it
        enables telemetry; the thread is joined before run() returns).
        ``flight_recorder=`` records one entry per serving tick
        (tick wall time, queue depth, active slots) into a
        :class:`telemetry.diag.FlightRecorder` — its ``step_stall``
        watch catches a wedged arena; policy ``halt`` raises
        :class:`telemetry.diag.AnomalyHalt`, ``skip_step`` downgrades to ``record``
        (a serving tick is not an optimizer update; there is nothing
        to roll back). Only consulted while telemetry is enabled.

        Preemption grace (opt-in, ``resilience``): ``preemption=True``
        installs a SIGTERM/SIGINT handler for the drive (or pass an
        existing :class:`resilience.PreemptionHandler`). On signal the
        arena stops ADMITTING queued requests but keeps ticking until
        every in-flight request (active or mid-prefill) completes —
        drained results are returned, ``self.preempted`` is True, and
        unserved requests stay in ``self.queue`` for a successor
        process. Default ``preemption=None``: no handler, no per-tick
        resilience code (the zero-cost contract)."""
        # refresh the weight snapshot: the jitted fns take weights as
        # REAL arguments, so post-construction mutation of the model
        # (quant.apply_weight_only_int8, a LoRA merge, a hot-swapped
        # checkpoint) must be re-snapshotted here or it would be
        # silently ignored by every step. Unchanged weights rebuild a
        # dict of the SAME arrays — no retrace, no transfer.
        self._mstate = (dict(self.model.named_parameters()),
                        dict(self.model.named_buffers()))
        if self.draft is not None:
            self._dstate = (dict(self.draft.named_parameters()),
                            dict(self.draft.named_buffers()))
        if telemetry.enabled():
            # fingerprint the weight pytrees ONCE per run (they only
            # change here): per-tick records pass the hash as an Opaque
            # token, so a quant/LoRA swap between runs still registers
            # as a retrace without re-walking every leaf per dispatch
            self._weights_fp = _recompile.Opaque(hash(
                telemetry.fingerprint(
                    (self._mstate, getattr(self, "_dstate", None)))))
        self.debug_server = None
        if debug_port is not None:
            self.debug_server = _dbg_server.DebugServer(
                port=debug_port, owned=True,
                run_config={"role": "serving", "slots": self.slots,
                            "capacity": self.capacity,
                            "paged": self.paged,
                            "kv_dtype": (self._allocator.kv_dtype
                                         if self.paged else None),
                            "spec": self.draft is not None,
                            "decode_steps": self.decode_steps}).start()
            self.debug_server.add_status("serving", self._statusz)
            # on-demand bounded device capture (404->409->200 state
            # machine; one concurrent capture, hard duration cap)
            self.debug_server.add_post(
                "/profilez", _profiling.make_profilez())
            # readiness is distinct from liveness: a draining or
            # not-yet-warmed arena answers ready=false on /healthz +
            # /readyz so a router stops PLACING sessions here without
            # concluding the process is dead
            self.debug_server.set_ready(lambda: self.ready)
            if self.queue or self._pf_order or self.active.any():
                # requests submitted before the server came up: seed the
                # last-request clock now (a lower bound on the true age)
                self.debug_server.note("request")
        # preemption grace (resolved once — zero per-tick cost when
        # None): on signal, stop admitting and drain in-flight slots
        pre = None
        own_pre = False
        self.preempted = False
        if preemption is not None and preemption is not False:
            from .resilience.preemption import PreemptionHandler

            pre = (PreemptionHandler() if preemption is True
                   else preemption)
            if not pre.installed:
                pre.install()
                own_pre = True
        tick = 0
        try:
            while self.queue or self._pf_order or self.active.any():
                if pre is not None and not self.preempted \
                        and pre.requested():
                    self.preempted = True
                if self.preempted and not (self._pf_order
                                           or self.active.any()):
                    # in-flight work drained; queued requests stay in
                    # self.queue for a successor process
                    break
                telem = telemetry.enabled()
                if telem:
                    m = _serving_metrics()
                    m["queue_depth"].set(len(self.queue))
                    if self.paged:
                        al = self._allocator
                        occ = (al.pages - al.free_pages) / al.pages
                        m["page_occupancy"].set(occ)
                        pool_b = (2 * len(self.pools)
                                  * al.pool_nbytes)
                        m["kv_pool_bytes"].set(pool_b)
                        m["kv_pool_live_bytes"].set(occ * pool_b)
                    t_tick = time.perf_counter()
                if not self.preempted:
                    self._admit()
                self._prefill_tick()
                self._step()
                if telem:
                    tick += 1
                    # stamp OUR server when we own one (owner-scoped
                    # heartbeat — see telemetry.server.note)
                    if self.debug_server is not None:
                        self.debug_server.note("step")
                    else:
                        _dbg_server.note("step")
                    if flight_recorder is not None:
                        action = flight_recorder.record_step(
                            tick,
                            step_time=time.perf_counter() - t_tick,
                            queue_depth=len(self.queue),
                            active_slots=int(self.active.sum()))
                        if action == "halt":
                            raise flight_recorder.halt_error(
                                f"serving tick {tick}")
        finally:
            if own_pre:
                pre.uninstall()
            if self.debug_server is not None:
                self.debug_server.stop()
        if self.preempted and telemetry.enabled():
            from .resilience.preemption import _preempt_metrics

            _preempt_metrics()["clean_exits"].inc()
        out = {rid: r.result for rid, r in self.done.items()}
        self.done = {}
        return out

    def _statusz(self) -> Dict[str, Any]:
        """Arena view for /statusz (host-side fields only — reading it
        mid-tick may tear across fields, fine for monitoring)."""
        st = {"slots": self.slots, "capacity": self.capacity,
              "active_slots": int(self.active.sum()),
              "queue_depth": len(self.queue),
              "completed": len(self.done),
              "prefilling": len(self._pf_order),
              "preempted": self.preempted}
        if self.paged:
            al = self._allocator
            st["pages"] = al.pages
            st["free_pages"] = al.free_pages
            st["kv_dtype"] = al.kv_dtype or str(al.dtype)
            st["kv_pool_bytes"] = 2 * len(self.pools) * al.pool_nbytes
            if self.prefix_cache:
                st["prefix_hits"] = self.prefix_hits
        if self.draft is not None:
            st["spec_rounds"] = self.spec_rounds
            st["spec_accepted"] = self.spec_accepted
        st["ready"] = self.ready
        st["degraded"] = self.degraded
        return st

    # ----- router surface (readiness, degrade, KV handoff) -----------------

    @property
    def ready(self) -> bool:
        """Readiness (placement signal): True once the arena has
        dispatched a step (jit warm) and it is not draining. Liveness
        stays /healthz's heartbeat clocks — a not-ready replica is
        healthy, just not placeable."""
        return self._warmed and not self.preempted

    def warm_step(self) -> None:
        """EXPLICIT arena warmup: compile + dispatch the decode step
        executable once over the (idle) arena and mark the replica
        warmed — no sacrificial decode required. Replaces the old
        "max_new=2 warmup" workaround (a max_new=1 request finishes at
        activation without ever dispatching the arena step, and a
        2-token one burned a decode tick just to touch the
        executable). Safe on an idle arena: paged cursors are parked
        past capacity so the junk writes DROP (write_rows' OOB
        semantics); contiguous junk lands at positions a later prefill
        fully overwrites and no attention ever reads (nothing is
        active, and prefill rewrites [0, bucket) wholesale)."""
        kd = 1 if self.degraded else self.decode_steps
        step_fn = self._step_fns.get(kd)
        if step_fn is None:
            step_fn = self._step_fns[kd] = self._build_multi_step(kd)
        gens = jnp.asarray(self._slot_gen.astype(np.uint32))
        if self.paged:
            self.pools, toks = step_fn(
                self._mstate, self.pools, jnp.asarray(self.table),
                self.tok, self.t, gens)
        else:
            self.caches, toks = step_fn(
                self._mstate, self.caches, self.tok, self.t, gens)
        jax.block_until_ready(toks)
        if self.draft is not None and not self.degraded:
            # spec arenas serve through the spec round: warm that
            # executable too (same idle-arena safety argument; the
            # draft cache junk is likewise overwritten at prefill)
            if self._spec_fn is None:
                self._spec_fn = self._build_spec_step()
            if self.paged:
                out = self._spec_fn(self._mstate, self._dstate,
                                    self.pools, jnp.asarray(self.table),
                                    self.caches_d, self.tok, self.t,
                                    gens)
                self.pools, self.caches_d = out[0], out[1]
            else:
                out = self._spec_fn(self._mstate, self._dstate,
                                    self.caches, None, self.caches_d,
                                    self.tok, self.t, gens)
                self.caches, self.caches_d = out[0], out[1]
            jax.block_until_ready(out[2])
        self._warmed = True

    def set_degraded(self, on: bool) -> None:
        """SLO degrade lever (the router's load-shed precursor): while
        on, every dispatch emits ONE token (decode_steps forced to 1 —
        eos/budget granularity tightens, so no mid-window tail is ever
        computed just to be discarded) and speculative rounds are
        bypassed (no draft steps, no gamma+1 verify chunk per tick).
        Output correctness is unaffected either way: the plain step
        emits the target's own picks, and on re-enable the rejection
        test keeps outputs target-distributed even against a stale
        draft cache (stale drafts only lower the accept rate)."""
        self.degraded = bool(on)

    def prefill_export(self, prompt_ids) -> KVHandoff:
        """Run the bucketed prefill for ``prompt_ids`` and EXPORT the
        resulting KV pages + next-token logits instead of activating a
        slot — the prefill-worker half of prefill/decode
        disaggregation. Pages are allocated, written, gathered to host,
        and freed again, so a prefill worker's pool only ever holds
        in-flight prompts. Requires paged mode (the page payload IS the
        wire format; contiguous arenas chunk-prefill locally instead)."""
        enforce(self.paged, "prefill_export requires paged mode "
                "(pages=N) — the handoff payload is KV pages")
        # deadline check BEFORE the prefill compute: an expired request
        # must never burn device work (the typed-drop contract)
        dl = _reliability.current()
        if dl is not None and dl.expired():
            reject_cause("deadline")
            dl.check("prefill export")  # raises DeadlineExceededError
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        plen = len(prompt)
        enforce(plen >= 1, "empty prompt")
        enforce(plen <= self.capacity,
                "prompt %s exceeds prefill capacity %s", plen,
                self.capacity)
        # weights may have been swapped since construction (LoRA/quant)
        self._mstate = (dict(self.model.named_parameters()),
                        dict(self.model.named_buffers()))
        ps = self.page_size
        m = (plen + ps - 1) // ps
        ids = self._allocator.alloc(m)  # typed error when exhausted
        telem = telemetry.enabled()
        ctx = _tracing.current() if telem else None
        cm = (_tracing.span("serve.prefill.export", ctx=ctx,
                            plen=plen, pages=int(m))
              if telem else _NULL_CM)
        try:
            with cm:
                row = np.zeros((self.n_log,), np.int32)
                row[:m] = ids
                lb = self._bucket_len(plen)
                padded = np.zeros((lb,), np.int32)
                padded[:plen] = prompt
                if telem:
                    _recompile.record("serving.prefill", padded)
                self.pools, logits = self._prefill_fn_paged(lb)(
                    self._mstate, self.pools, jnp.asarray(row),
                    jnp.asarray(padded), plen)
                al = self._allocator
                blocks = []
                for kp, vp in self.pools:
                    payload = []
                    for pool in (kp, vp):
                        got = paged_ops.export_pages(pool,
                                                     jnp.asarray(ids))
                        payload.append(
                            tuple(np.asarray(a) for a in got)
                            if al.kv_dtype else np.asarray(got))
                    blocks.append(tuple(payload))
                return KVHandoff(prompt, plen, np.asarray(logits),
                                 blocks, ps, al.kv_dtype, trace=ctx,
                                 deadline=dl)
        finally:
            self._allocator.free(ids)

    def inject_prefilled(self, handoff: KVHandoff, max_new: int,
                         stream: Optional[TokenStream] = None) -> int:
        """Admit a request whose prompt KV arrives PRE-FILLED (a
        :class:`KVHandoff` from a prefill worker): the decode replica
        allocates pages, imports the payload, and activates the slot
        from the handoff's logits — no prompt token ever runs through
        this replica's prefill, so whole-prompt admission can't stall a
        decode tick. Queues like :meth:`submit` (paged backpressure
        applies); returns the request id."""
        enforce(self.paged, "inject_prefilled requires paged mode "
                "(pages=N) on the decode replica")
        enforce(isinstance(handoff, KVHandoff),
                "inject_prefilled takes a KVHandoff, got %s",
                type(handoff).__name__)
        enforce(handoff.page_size == self.page_size,
                "handoff page_size %s != replica page_size %s",
                handoff.page_size, self.page_size)
        al = self._allocator
        enforce(handoff.kv_dtype == al.kv_dtype,
                "handoff kv_dtype %r != replica kv_dtype %r — the "
                "storage form crosses the wire intact",
                handoff.kv_dtype, al.kv_dtype)
        enforce(len(handoff.blocks) == len(self.pools),
                "handoff has %s blocks, replica model has %s",
                len(handoff.blocks), len(self.pools))
        enforce(max_new >= 1, "max_new must be >= 1, got %s", max_new)
        r = Request(self._next_rid, handoff.prompt, max_new)
        enforce(len(r.prompt) + max_new + self._extra <= self.capacity,
                "prompt %s + max_new %s (+%s speculative/window margin) "
                "exceeds slot capacity %s",
                len(r.prompt), max_new, self._extra, self.capacity)
        need = ((len(r.prompt) + max_new + self._extra
                 + self.page_size - 1) // self.page_size)
        enforce(need <= al.pages,
                "request needs %s pages but the pool only has %s",
                need, al.pages)
        enforce(stream is None or isinstance(stream, TokenStream),
                "stream= takes a serving.TokenStream, got %s",
                type(stream).__name__)
        r.handoff = handoff
        r.stream = stream
        self._next_rid += 1
        r.t_submit = time.perf_counter()
        # the handoff carries the REQUEST's deadline (absolute epoch —
        # remaining budget, not a per-hop reset); a bound ambient
        # deadline wins, same precedence as the trace context below
        r.deadline = _reliability.current() or handoff.deadline
        if telemetry.enabled():
            _serving_metrics()["requests"].inc()
            if stream is not None:
                _serving_metrics()["streams"].inc()
            # the handoff carries its producer's context (in-process
            # disaggregation); an HTTP hop's bound header context wins
            # — both are the same trace when the router did its job
            r.trace = _tracing.current() or handoff.trace
            srv = self.debug_server
            if srv is not None and srv.running:
                srv.note("request")
            else:
                _dbg_server.note("request")
        self.queue.append(r)
        return r.rid

    def _import_handoff(self, s: int, r: Request) -> None:
        """Write the handoff payload into this slot's freshly allocated
        pages and activate from the handoff logits (admission epilogue
        for pre-filled requests)."""
        h = r.handoff
        plen = h.plen
        cm = (_tracing.span("serve.handoff.import", ctx=r.trace,
                            plen=plen, slot=s)
              if telemetry.enabled() else _NULL_CM)
        with cm:
            m = (plen + self.page_size - 1) // self.page_size
            ids = jnp.asarray(self._slot_pages[s][:m])
            pools = []
            for (kp, vp), (pk, pv) in zip(self.pools, h.blocks):
                pools.append((paged_ops.import_pages(kp, ids, pk),
                              paged_ops.import_pages(vp, ids, pv)))
            self.pools = pools
            self._activate(s, r, jnp.asarray(h.logits), plen)

    # ----- internals -------------------------------------------------------

    def _bucket_len(self, n: int) -> int:
        b = self.bucket
        # clamp to capacity: bucket rounding past the arena would hand
        # forward_chunk a write window it silently clamps (its
        # documented caller contract); any admissible prompt fits since
        # submit enforces plen + max_new <= capacity
        return min(max(b, ((n + b - 1) // b) * b), self.capacity)

    def _prefill_fn(self, lb: int):
        """Jitted prefill for bucket length lb: run the padded prompt
        through the model cache-only at positions [0, plen), writing
        slot ``s`` of the arena. One compile per bucket."""
        fn = self._prefill_cache.get(lb)
        if fn is not None:
            return fn
        model = self.model

        def prefill(mstate, caches, padded, plen, s):
            # chunk-run the FULL bucket (static shape) CACHE-ONLY —
            # positions >= plen write garbage above the cursor, masked
            # + overwritten later. The (lb, vocab) head projection
            # would be the dominant prefill FLOP and all but one row
            # is discarded, so the next-token logits come from a
            # one-position re-step of the LAST prompt token instead
            # (idempotent K/V rewrite at plen-1, single-row head).
            def body(row):
                _, row = model._chunk_logits(padded[None], row, 0,
                                             head=False)
                last = lax.dynamic_index_in_dim(padded, plen - 1,
                                                keepdims=False)
                return model._step_logits(last[None], row, plen - 1)

            with inject_state((model, *mstate)):
                logits, new = _row_apply(caches, s, body)
            return new, logits[0]

        fn = jax.jit(prefill)
        self._prefill_cache[lb] = fn
        return fn

    def _prefill_fn_paged(self, lb: int):
        """Jitted paged prefill for bucket length lb: chunk-write the
        prompt into the row's pages cache-only, then one re-step of the
        last token for the next-token logits."""
        fn = self._prefill_cache.get(("paged", lb))
        if fn is not None:
            return fn
        model = self.model

        def prefill(mstate, pools, table_row, padded, plen):
            with inject_state((model, *mstate)):
                _, pools = model._chunk_logits_paged(
                    padded[None], pools, table_row, 0, head=False)
                last = lax.dynamic_index_in_dim(padded, plen - 1,
                                                keepdims=False)
                logits, pools = model._step_logits_paged(
                    last[None], pools, table_row[None],
                    jnp.full((1,), plen - 1, jnp.int32))
            return pools, logits[0]

        fn = jax.jit(prefill)
        self._prefill_cache[("paged", lb)] = fn
        return fn

    def _suffix_fns(self, lb: int):
        """Prefix-hit prefill pieces: cache-only chunk of the SUFFIX at
        a page-aligned offset (one compile per bucket) and the
        lb-independent last-token re-step (compiled ONCE; also used
        alone when the whole prompt is cached)."""
        model = self.model
        chunk_fn = self._prefill_cache.get(("suffix", lb))
        if chunk_fn is None:
            def chunk(mstate, pools, table_row, padded, t0):
                with inject_state((model, *mstate)):
                    _, pools = model._chunk_logits_paged(
                        padded[None], pools, table_row, t0, head=False)
                return pools

            chunk_fn = jax.jit(chunk)
            self._prefill_cache[("suffix", lb)] = chunk_fn
        restep_fn = self._prefill_cache.get(("restep",))
        if restep_fn is None:
            def restep(mstate, pools, table_row, tok, pos):
                with inject_state((model, *mstate)):
                    logits, pools = model._step_logits_paged(
                        tok[None], pools, table_row[None],
                        jnp.full((1,), pos, jnp.int32))
                return pools, logits[0]

            restep_fn = jax.jit(restep)
            self._prefill_cache[("restep",)] = restep_fn
        return chunk_fn, restep_fn

    def _chunk_fn_contig(self, c: int):
        """Jitted cache-only contiguous-prefill piece: run chunk tokens
        (c,) at [t0, t0+c) through slot ``s``'s row (one compile per
        chunk size — the chunk size is fixed, so one total)."""
        fn = self._prefill_cache.get(("cchunk", c))
        if fn is not None:
            return fn
        model = self.model

        def chunk(mstate, caches, toks, t0, s):
            with inject_state((model, *mstate)):
                _, new = _row_apply(
                    caches, s, lambda row: model._chunk_logits(
                        toks[None], row, t0, head=False))
            return new

        fn = jax.jit(chunk)
        self._prefill_cache[("cchunk", c)] = fn
        return fn

    def _restep_contig(self):
        """Jitted last-token re-step for slot ``s`` (chunked-prefill
        finish): idempotent K/V rewrite at pos, single-row head."""
        fn = self._prefill_cache.get(("crestep",))
        if fn is not None:
            return fn
        model = self.model

        def restep(mstate, caches, tok, pos, s):
            with inject_state((model, *mstate)):
                logits, new = _row_apply(
                    caches, s,
                    lambda row: model._step_logits(tok[None], row, pos))
            return new, logits[0]

        fn = jax.jit(restep)
        self._prefill_cache[("crestep",)] = fn
        return fn

    def _prefill_tick(self):
        """Advance chunked prefill by ONE chunk (FIFO across admitting
        slots) — bounds the prefill work added to any serving-loop
        iteration, so active slots keep their decode cadence. On the
        final chunk the slot activates via the last-token re-step."""
        if not self._pf_order:
            return
        s = self._pf_order[0]
        st = self._pf[s]
        padded, plen, off, r = (st["padded"], st["plen"], st["off"],
                                st["r"])
        c = self.prefill_chunk
        if off < plen:
            t0 = off
            if t0 + c > self.capacity:
                # slide the final chunk back so the write can't clamp
                # below the frontier (the overlap re-writes the same
                # real tokens — idempotent); paged mode never triggers
                # this (page demand >= the chunk frontier)
                t0 = self.capacity - c
            toks = jnp.asarray(padded[t0:t0 + c])
            if self.paged:
                chunk_fn, _ = self._suffix_fns(c)
                self.pools = chunk_fn(
                    self._mstate, self.pools,
                    jnp.asarray(self.table[s]), toks, t0)
            else:
                self.caches = self._chunk_fn_contig(c)(
                    self._mstate, self.caches, toks,
                    jnp.asarray(t0, jnp.int32),
                    jnp.asarray(s, jnp.int32))
            st["off"] = t0 + c
            if st["off"] < plen:
                return
        # all chunks written: re-step the last prompt token for the
        # next-token logits and go live
        last = jnp.asarray(int(padded[plen - 1]), jnp.int32)
        if self.paged:
            _, restep_fn = self._suffix_fns(self.bucket)
            self.pools, logits = restep_fn(
                self._mstate, self.pools, jnp.asarray(self.table[s]),
                last, plen - 1)
        else:
            self.caches, logits = self._restep_contig()(
                self._mstate, self.caches, last,
                jnp.asarray(plen - 1, jnp.int32),
                jnp.asarray(s, jnp.int32))
        self._pf[s] = None
        self._pf_order.pop(0)
        self._activate(s, r, logits, plen)

    def _prefix_key(self, prompt: np.ndarray, n: int) -> bytes:
        return np.ascontiguousarray(prompt[:n], np.int32).tobytes()

    def _lookup_prefix(self, prompt: np.ndarray):
        """Longest registered page-aligned prefix of ``prompt`` ->
        (pages, cached_len); LRU-touches the hit. Keys are the raw
        token bytes (one memcpy + C-level hash, not per-int boxing)."""
        if not self._prefix_registry:
            return None, 0
        ps = self.page_size
        for k in range(min(len(prompt) // ps, self.n_log), 0, -1):
            key_t = self._prefix_key(prompt, k * ps)
            e = self._prefix_registry.pop(key_t, None)
            if e is not None:
                self._prefix_registry[key_t] = e      # LRU re-insert
                return e, k * ps
        return None, 0

    def _evict_prefixes(self, want: int):
        """Drop oldest registry entries until ``want`` pages are free
        (pages still referenced by live requests stay allocated)."""
        while (self._prefix_registry
               and self._allocator.free_pages < want):
            key_t = next(iter(self._prefix_registry))
            self._allocator.free(self._prefix_registry.pop(key_t))

    def _try_alloc_paged(self, s: int, r: Request):
        """Paged admission allocation (prefix lookup + pin + evict +
        alloc); installs the slot's table row. Returns the cached
        prefix length, or None when the pool can't satisfy the demand
        yet (caller requeues — backpressure)."""
        plen = len(r.prompt)
        # handoff requests never take a prefix hit: their payload is
        # IMPORTED over the allocated pages, and importing onto pages
        # shared with the registry (or a live request) would corrupt
        # every other holder's KV
        if self.prefix_cache and r.handoff is None:
            self.prefix_lookups += 1
            hit, cached = self._lookup_prefix(r.prompt)
        else:
            hit, cached = None, 0
        if hit is not None:
            # PIN before any eviction: _evict_prefixes may drop the
            # hit's own registry entry, and an unpinned hit would be
            # freed and handed straight back by alloc() — the same
            # physical page twice in one table (silent KV corruption)
            self._allocator.share(hit)
        need = ((plen + r.max_new + self._extra + self.page_size - 1)
                // self.page_size)
        need_new = need - cached // self.page_size
        if need_new > self._allocator.free_pages:
            self._evict_prefixes(need_new)
        if need_new > self._allocator.free_pages:
            if hit is not None:
                self._allocator.free(hit)       # unpin
            return None                         # wait for completions
        new_ids = self._allocator.alloc(need_new)
        if hit is not None:
            self.prefix_hits += 1
            ids = np.concatenate([hit, new_ids])
        else:
            ids = new_ids
        row = np.zeros((self.n_log,), np.int32)
        row[:need] = ids
        self.table[s] = row
        self._slot_pages[s] = ids
        return cached

    def _draft_prefill_fn(self, lb: int):
        """Jitted cache-only draft prefill for bucket lb (spec mode):
        the draft arena needs the prompt's K/V at [0, plen) — the spec
        round's first draft step feeds the last emitted token, so no
        restep/logits here."""
        fn = self._prefill_cache.get(("draft", lb))
        if fn is not None:
            return fn
        draft = self.draft

        def prefill(dstate, caches, padded, s):
            with inject_state((draft, *dstate)):
                _, new = _row_apply(
                    caches, s, lambda row: draft._chunk_logits(
                        padded[None], row, 0, head=False))
            return new

        fn = jax.jit(prefill)
        self._prefill_cache[("draft", lb)] = fn
        return fn

    def _activate(self, s: int, r: Request, logits, plen: int):
        """Shared admission epilogue: first-token pick + slot live."""
        self.active[s] = True
        self._slot_trace[s] = r.trace
        tok = self._pick(logits[None], s, plen)[0]
        self.emitted[s] = [int(tok)]
        r.t_first = time.perf_counter()
        r.t_tokens.append(r.t_first)
        if telemetry.enabled():
            m = _serving_metrics()
            traced = r.trace is not None and r.trace.sampled
            if r.t_submit:
                # TTFT exemplar: a traced sample stamps its trace id
                # onto the bucket it lands in — the p99 row's link to
                # the cross-process timeline that produced it
                m["ttft"].observe(
                    r.t_first - r.t_submit,
                    exemplar=r.trace.trace_id if traced else None)
            if traced:
                _tracing.event("serve.first_token", ctx=r.trace,
                               rid=r.rid, slot=s)
            m["tokens"].inc()
        self.budget[s] = r.max_new - 1
        self.tok = self.tok.at[s].set(int(tok))
        self.t = self.t.at[s].set(plen)
        if r.stream is not None:
            # the first token leaves the arena at activation, not at
            # completion — the streaming-TTFT edge
            r.stream.offer(self.emitted[s], r.t_first)
        self._maybe_finish(s)

    def _admit(self):
        """Fill every free slot from the queue. Monolithic mode runs
        the whole prefill (+ first token) here; chunked mode
        (prefill_chunk=C) only allocates and queues the slot for
        _prefill_tick. Paged mode backpressures: a request whose page
        demand exceeds the free pool stays queued until completions
        free pages."""
        for s in range(self.slots):
            if (self.active[s] or self._pf[s] is not None
                    or not self.queue):
                continue
            r = self.queue.pop(0)
            # a request that expired while QUEUED is dropped typed
            # before any prefill work — never silently computed
            while r.deadline is not None and r.deadline.expired():
                self._expire_request(r, where="queue")
                if not self.queue:
                    r = None
                    break
                r = self.queue.pop(0)
            if r is None:
                break
            plen = len(r.prompt)
            lb = self._bucket_len(plen)
            padded = np.zeros((lb,), np.int32)
            padded[:plen] = r.prompt
            cached = 0
            if self.paged:
                cached = self._try_alloc_paged(s, r)
                if cached is None:
                    reject_cause("pool_exhausted")
                    self.queue.insert(0, r)
                    break
            if r.deadline is not None:
                # slot-resident from here on: the per-tick expiry
                # sweep (gated on this count) owns the deadline now
                self._dl_active += 1
            self.owner[s] = r
            self._slot_gen[s] = self.gen_count
            self.gen_count += 1
            if self.draft is not None:
                # draft cache needs the FULL prompt regardless of the
                # target's prefix hit (prefix pages cache only the
                # target's K/V); draft prefill is the cheap side
                self.caches_d = self._draft_prefill_fn(lb)(
                    self._dstate, self.caches_d, jnp.asarray(padded),
                    jnp.asarray(s, jnp.int32))
            if r.handoff is not None:
                # pre-filled KV arrived with the request: import the
                # pages and go live — no local prefill work at all
                # (chunked-prefill deferral included)
                self._import_handoff(s, r)
                continue
            if self.prefill_chunk is not None:
                # defer: chunk grid starts at the cached frontier
                # (page-aligned, hence chunk-aligned); park the cursor
                # so arena steps can't land junk below the frontier.
                # The tick reads fixed-size chunks, so pad the prompt
                # to the CHUNK grid (not the prompt bucket)
                c = self.prefill_chunk
                grid = np.zeros((max(1, -(-plen // c)) * c,), np.int32)
                grid[:plen] = r.prompt
                self._pf[s] = {"padded": grid, "plen": plen,
                               "off": cached, "r": r}
                self._pf_order.append(s)
                self.t = self.t.at[s].set(self.capacity)
                continue
            telem = telemetry.enabled()
            if telem:
                # one compile per prompt bucket: a new padded shape
                # here IS a new monolithic-prefill executable. Chunked
                # mode bailed out above — it compiles per CHUNK size,
                # so recording the bucket there would count compiles
                # that never happen
                _recompile.record("serving.prefill", padded)
            pf_cm = (_tracing.span("serve.prefill", ctx=r.trace,
                                   plen=plen, slot=s, cached=cached)
                     if telem else _NULL_CM)
            with pf_cm:
                if self.paged:
                    row = self.table[s]
                    if cached == 0:
                        pf = self._prefill_fn_paged(lb)
                        self.pools, logits = pf(
                            self._mstate, self.pools, jnp.asarray(row),
                            jnp.asarray(padded), plen)
                        if telem:
                            _costs.ensure_program(
                                f"serving.prefill[paged,{lb}]", pf,
                                (self._mstate, self.pools,
                                 jnp.asarray(row), jnp.asarray(padded),
                                 plen), origin="serving")
                    else:
                        # prefill only the uncached suffix (page-aligned
                        # t0), then the usual last-token re-step for the
                        # next-token logits — handles a fully-cached
                        # prompt (empty suffix) too
                        suf = r.prompt[cached:]
                        if len(suf):
                            slb = self._bucket_len(len(suf))
                            spad = np.zeros((slb,), np.int32)
                            spad[:len(suf)] = suf
                            chunk_fn, restep_fn = self._suffix_fns(slb)
                            self.pools = chunk_fn(
                                self._mstate, self.pools,
                                jnp.asarray(row),
                                jnp.asarray(spad), cached)
                        else:
                            _, restep_fn = self._suffix_fns(self.bucket)
                        self.pools, logits = restep_fn(
                            self._mstate, self.pools, jnp.asarray(row),
                            jnp.asarray(r.prompt[plen - 1], jnp.int32),
                            plen - 1)
                else:
                    pf = self._prefill_fn(lb)
                    self.caches, logits = pf(
                        self._mstate, self.caches, jnp.asarray(padded),
                        plen, s)
                    if telem:
                        _costs.ensure_program(
                            f"serving.prefill[{lb}]", pf,
                            (self._mstate, self.caches,
                             jnp.asarray(padded), plen, s),
                            origin="serving")
                self._activate(s, r, logits, int(plen))

    def _pick(self, logits, s: int, pos: int):
        """Admission-time single-row pick (the steady-state loop picks
        batched in _step); caller sets _slot_gen[s] first."""
        if not self.sampled:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        k = jax.random.fold_in(
            jax.random.fold_in(self.key, int(self._slot_gen[s])), pos)
        return sample_from_logits(logits, k, self.temperature,
                                  self.top_k, self.top_p).astype(jnp.int32)

    def _build_multi_step(self, kd: int):
        """decode_steps=k jitted step: scan k single-token steps with
        the picks IN-DEVICE (same fold_in key chain as the host picks,
        so outputs are token-identical to k=1) — every dispatch
        advances all slots k tokens, amortizing the per-dispatch
        round trip exactly like the training benches' steps-per-call.
        Inactive/parked rows compute junk the host discards; their
        writes drop (paged) or land above any attended position.
        ``kd`` is a parameter (not ``self.decode_steps``) so the SLO
        degrade lever can hold a k=1 executable next to the full-k one."""
        model = self.model
        sampled, temp = self.sampled, self.temperature
        top_k, top_p, key = self.top_k, self.top_p, self.key
        paged = self.paged

        def pick(logits, gens, poss):
            if not sampled:
                return jnp.argmax(logits, axis=-1).astype(jnp.int32)
            keys = jax.vmap(lambda g, p: jax.random.fold_in(
                jax.random.fold_in(key, g), p))(
                gens, poss.astype(jnp.uint32))
            return jax.vmap(lambda lg, kk: sample_from_logits(
                lg[None], kk, temp, top_k,
                top_p)[0])(logits, keys).astype(jnp.int32)

        if paged:
            def step(mstate, pools, table, tok, t, gens):
                with inject_state((model, *mstate)):
                    def body(c, _):
                        pools, tok, t = c
                        logits, pools = model._step_logits_paged(
                            tok, pools, table, t)
                        nxt = pick(logits, gens, t + 1)
                        return (pools, nxt, t + 1), nxt

                    (pools, _, _), toks = lax.scan(
                        body, (pools, tok, t), None, length=kd)
                return pools, jnp.swapaxes(toks, 0, 1)   # (B, k)
        else:
            def step(mstate, caches, tok, t, gens):
                with inject_state((model, *mstate)):
                    def body(c, _):
                        caches, tok, t = c
                        logits, caches = model._step_logits_rows(
                            tok, caches, t, decode_kernel=True)
                        nxt = pick(logits, gens, t + 1)
                        return (caches, nxt, t + 1), nxt

                    (caches, _, _), toks = lax.scan(
                        body, (caches, tok, t), None, length=kd)
                return caches, jnp.swapaxes(toks, 0, 1)

        return jax.jit(step)

    def _step_multi(self):
        """decode_steps host side: append each row's k tokens in order
        with per-TOKEN budget/eos finishing (nothing emits past eos or
        budget; a mid-window finish discards the tail). Degraded mode
        dispatches the k=1 executable instead (separate cache entry —
        no retrace when toggling)."""
        if not self.active.any():
            return
        kd = 1 if self.degraded else self.decode_steps
        step_fn = self._step_fns.get(kd)
        if step_fn is None:
            step_fn = self._step_fns[kd] = self._build_multi_step(kd)
        was_active = self.active.copy()
        telem = telemetry.enabled()
        if telem:
            # the weight token participates: run()'s weight re-snapshot
            # means a post-construction quant/LoRA swap changes the
            # weight pytree and genuinely retraces — a fingerprint of
            # just (tok, t) would never see it
            _recompile.record("serving.step", self.tok, self.t,
                              weights=self._weights_fp)
            t_dispatch = time.perf_counter()
        # per-decode-tick span: one dispatch advances every active
        # slot, so the tick rides the first SAMPLED slot's context
        # (an unsampled context must not shadow a sampled neighbor —
        # it would starve that request's timeline of its decode ticks)
        tick_ctx = (next((c for c in self._slot_trace
                          if c is not None and c.sampled), None)
                    if telem else None)
        tick_cm = (_tracing.span("serve.decode.tick", ctx=tick_ctx,
                                 k=kd,
                                 n_active=int(was_active.sum()))
                   if telem and tick_ctx is not None else _NULL_CM)
        with tick_cm:
            gens = jnp.asarray(self._slot_gen.astype(np.uint32))
            if self.paged:
                self.pools, toks = step_fn(
                    self._mstate, self.pools, jnp.asarray(self.table),
                    self.tok, self.t, gens)
            else:
                self.caches, toks = step_fn(
                    self._mstate, self.caches, self.tok, self.t, gens)
            toks = np.asarray(jax.device_get(toks)).astype(np.int32)
        self._warmed = True
        if telem:
            # cost-ledger registration, once per step variant (set
            # lookup after the first tick): lower() only reads avals,
            # so the post-dispatch arrays — donated or not — are fine
            prog = f"serving.step[k={kd}]"
            if self.paged:
                _costs.ensure_program(
                    prog, step_fn,
                    (self._mstate, self.pools, jnp.asarray(self.table),
                     self.tok, self.t, gens), origin="serving")
            else:
                _costs.ensure_program(
                    prog, step_fn,
                    (self._mstate, self.caches, self.tok, self.t, gens),
                    origin="serving")
        now = time.perf_counter()
        n_emitted = 0
        for s in range(self.slots):
            if not was_active[s]:
                continue
            r = self.owner[s]
            for j in range(kd):
                self.emitted[s].append(int(toks[s, j]))
                r.t_tokens.append(now)
                n_emitted += 1
                self.budget[s] -= 1
                self._maybe_finish(s)
                if not self.active[s]:
                    break
            if r.stream is not None and r.result is None:
                # per-tick streaming: this tick's tokens leave NOW
                # (completion already streamed via finish above)
                r.stream.offer(self.emitted[s], now)
        # tick accounting (plain ints — the bench harness reads these
        # without enabling telemetry)
        self.tick_count += 1
        self.tick_tokens += n_emitted
        self.tick_capacity += self.slots * kd
        if telem and n_emitted:
            m = _serving_metrics()
            m["tokens"].inc(n_emitted)
            itl = (time.perf_counter() - t_dispatch) / n_emitted
            m["decode_latency"].observe(
                itl,
                exemplar=(tick_ctx.trace_id
                          if tick_ctx is not None else None))
            # serving goodput (active-slot-tokens vs capacity) + the
            # ITL regression sentinel; a degraded arena (router SLO
            # lever / CPU-fallback run) never feeds a baseline
            _profiling.goodput().note_tick(n_emitted, self.slots * kd)
            _profiling.sentinel().observe(
                f"serving.step[k={kd}]", self._backend(), itl,
                kind="itl",
                degraded=self.degraded or bool(os.environ.get(
                    "PT_BENCH_CPU_FALLBACK")))
        # retired rows keep what _maybe_finish left (paged parking)
        keep = was_active & self.active
        cur_t = np.asarray(self.t)
        self.tok = jnp.asarray(np.where(
            keep, toks[:, -1], np.asarray(self.tok)).astype(np.int32))
        self.t = jnp.asarray(np.where(
            keep, cur_t + kd, cur_t).astype(np.int32))

    def _build_spec_step(self):
        """One speculative ROUND over the whole arena, jitted: gamma
        per-row draft steps (lax.scan), ONE per-row target verify
        chunk, and the Leviathan/Chen modified rejection test — all at
        per-row cursors, fixed shapes. Greedy mode (temperature=0) is
        token-identical to the plain arena step loop; sampled mode
        draws from the target's own filtered distribution (the same
        construction models/speculative.py pins with a frequency
        test). Inactive/parked rows compute junk that the host
        discards; their writes drop (paged) or land above any
        attended position (contiguous clamp)."""
        from .ops.sampling import filter_logits

        model, draft, gamma = self.model, self.draft, self.gamma
        sampled, temp = self.sampled, self.temperature
        top_k, top_p, key = self.top_k, self.top_p, self.key
        paged = self.paged

        def _flp(logits):
            return jax.nn.log_softmax(
                filter_logits(logits, temp, top_k, top_p), axis=-1)

        def spec(tstate, table, caches_d, tok, t, gens):
            # per-row key chain: (admission generation, round nonce=t —
            # strictly increasing per slot-generation, so draws never
            # collide across rounds)
            kb = jax.vmap(lambda g, tt: jax.random.fold_in(
                jax.random.fold_in(key, g), tt))(
                gens, t.astype(jnp.uint32))

            def draft_step(c, i):
                tokc, cd = c
                logits, cd = draft._step_logits_rows(tokc, cd, t + i)
                if sampled:
                    lq = _flp(logits)                        # (B, V)
                    ki = jax.vmap(
                        lambda kk: jax.random.fold_in(kk, i))(kb)
                    d = jax.vmap(jax.random.categorical)(ki, lq)
                    q = jnp.exp(lq)
                else:
                    d = jnp.argmax(logits, axis=-1)
                    q = jnp.zeros_like(logits, jnp.float32)
                d = d.astype(jnp.int32)
                return (d, cd), (d, q)

            (_, caches_d), (drafts, q_all) = lax.scan(
                draft_step, (tok, caches_d), jnp.arange(gamma))
            # cache d_{gamma-1}'s K/V at t+gamma (logits unused): on a
            # fully-accepted round no later write covers that position
            # before draft queries attend it (models/speculative.py's
            # argument, per row here)
            _, caches_d = draft._step_logits_rows(
                drafts[-1], caches_d, t + gamma)

            # target scores [last, d_0..d_{gamma-1}] per row in ONE
            # per-row chunk: logits for positions t+1 .. t+gamma+1
            drafts_b = jnp.swapaxes(drafts, 0, 1)      # (B, gamma)
            chunk = jnp.concatenate([tok[:, None], drafts_b], axis=1)
            if paged:
                logits_t, tstate = model._chunk_logits_paged_rows(
                    chunk, tstate, table, t)
            else:
                logits_t, tstate = model._chunk_logits_rows(
                    chunk, tstate, t)

            if sampled:
                p_all = jnp.exp(_flp(logits_t))    # (B, gamma+1, V)
                q_b = jnp.swapaxes(q_all, 0, 1)    # (B, gamma, V)
                pi = jnp.take_along_axis(
                    p_all[:, :gamma], drafts_b[..., None],
                    axis=2)[..., 0]
                qi = jnp.take_along_axis(
                    q_b, drafts_b[..., None], axis=2)[..., 0]
                ku = jax.vmap(
                    lambda kk: jax.random.fold_in(kk, gamma))(kb)
                u = jax.vmap(
                    lambda kk: jax.random.uniform(kk, (gamma,)))(ku)
                accept = u * qi < pi           # u < p/q without the /0
                n = jnp.sum(jnp.cumprod(accept.astype(jnp.int32),
                                        axis=1), axis=1)
                # residual max(p_n - q_n, 0) normalized; at n == gamma
                # q is all-zero so this IS the bonus draw from p_gamma
                p_n = jnp.take_along_axis(
                    p_all, n[:, None, None], axis=1)[:, 0]
                q_n = jnp.take_along_axis(
                    q_b, jnp.minimum(n, gamma - 1)[:, None, None],
                    axis=1)[:, 0]
                q_n = jnp.where((n < gamma)[:, None], q_n, 0.0)
                res = jnp.clip(p_n - q_n, 0.0, None)
                norm = jnp.sum(res, axis=1, keepdims=True)
                res = jnp.where(norm > 0, res / norm, p_n)
                kc = jax.vmap(
                    lambda kk: jax.random.fold_in(kk, gamma + 1))(kb)
                corr = jax.vmap(jax.random.categorical)(
                    kc, jnp.where(res > 0, jnp.log(res), -jnp.inf))
            else:
                tgt = jnp.argmax(logits_t, axis=-1)  # (B, gamma+1)
                accept = drafts_b == tgt[:, :gamma]
                n = jnp.sum(jnp.cumprod(accept.astype(jnp.int32),
                                        axis=1), axis=1)
                corr = jnp.take_along_axis(tgt, n[:, None],
                                           axis=1)[:, 0]
            corr = corr.astype(jnp.int32)
            slot = jnp.arange(gamma + 1)[None, :]
            ext = jnp.concatenate([drafts_b, drafts_b[:, -1:]],
                                  axis=1)
            emitted = jnp.where(
                slot < n[:, None], ext,
                jnp.where(slot == n[:, None], corr[:, None],
                          0)).astype(jnp.int32)
            return tstate, caches_d, emitted, n, corr, t + n + 1

        def spec_injected(mstate, dstate, tstate, table, caches_d, tok,
                          t, gens):
            with inject_state((model, *mstate), (draft, *dstate)):
                return spec(tstate, table, caches_d, tok, t, gens)

        return jax.jit(spec_injected)

    def _step_spec(self):
        """One speculative round (host side): run the jitted round,
        then append each row's accepted prefix + correction in order —
        budget/eos finishing applies per TOKEN, so a row never emits
        past its budget or beyond eos."""
        if not self.active.any():
            return
        if self._spec_fn is None:
            self._spec_fn = self._build_spec_step()
        was_active = self.active.copy()
        telem = telemetry.enabled()
        if telem:
            _recompile.record("serving.spec_step", self.tok, self.t,
                              weights=self._weights_fp)
            t_dispatch = time.perf_counter()
        gens = jnp.asarray(self._slot_gen.astype(np.uint32))
        if self.paged:
            (self.pools, self.caches_d, emitted, n, new_tok,
             new_t) = self._spec_fn(self._mstate, self._dstate,
                                    self.pools,
                                    jnp.asarray(self.table),
                                    self.caches_d, self.tok, self.t,
                                    gens)
        else:
            (self.caches, self.caches_d, emitted, n, new_tok,
             new_t) = self._spec_fn(self._mstate, self._dstate,
                                    self.caches, None, self.caches_d,
                                    self.tok, self.t, gens)
        # ONE batched transfer for the round's four host-side scalars
        # (per-array device_get would pay four sync round trips in the
        # serving hot loop)
        emitted, n_np, new_tok, new_t = jax.device_get(
            (emitted, n, new_tok, new_t))
        self._warmed = True
        now = time.perf_counter()
        self.spec_rounds += 1
        self.spec_row_rounds += int(was_active.sum())
        self.spec_accepted += int(n_np[was_active].sum())
        n_emitted = 0
        for s in range(self.slots):
            if not was_active[s]:
                continue
            r = self.owner[s]
            for j in range(int(n_np[s]) + 1):
                self.emitted[s].append(int(emitted[s, j]))
                r.t_tokens.append(now)
                n_emitted += 1
                self.budget[s] -= 1
                self._maybe_finish(s)
                if not self.active[s]:
                    break
            if r.stream is not None and r.result is None:
                r.stream.offer(self.emitted[s], now)
        if telem:
            m = _serving_metrics()
            m["spec_rounds"].inc(int(was_active.sum()))
            m["spec_accepted"].inc(int(n_np[was_active].sum()))
            if self.spec_row_rounds:
                m["spec_accept_rate"].set(
                    self.spec_accepted / self.spec_row_rounds)
            if n_emitted:
                # first SAMPLED slot (same rule as the plain tick)
                spec_ctx = next((c for c in self._slot_trace
                                 if c is not None and c.sampled), None)
                m["tokens"].inc(n_emitted)
                m["decode_latency"].observe(
                    (time.perf_counter() - t_dispatch) / n_emitted,
                    exemplar=(spec_ctx.trace_id
                              if spec_ctx is not None else None))
        # retired rows keep what _maybe_finish left (paged parking);
        # live rows advance by their accepted count + 1
        keep = was_active & self.active
        self.tok = jnp.asarray(
            np.where(keep, new_tok, np.asarray(self.tok)))
        self.t = jnp.asarray(
            np.where(keep, new_t, np.asarray(self.t)).astype(np.int32))

    def _step(self):
        if self._dl_active:
            # per-decode-tick deadline check (tentpole contract): an
            # expired slot is torn down BEFORE the next dispatch, so
            # no device tick is ever spent on a request nobody is
            # waiting for. Gated on the count — zero per-tick cost
            # while no slot-resident request carries a deadline.
            self._expire_slots()
        if self.draft is not None and not self.degraded:
            return self._step_spec()
        # k == 1 rides the same generalized scan path (length-1 scan,
        # in-device pick — pinned token-identical to the historical
        # host-pick loop by TestMultiStepDecode): ONE epilogue for
        # emit/budget/eos and one key chain, never two copies to keep
        # in lockstep
        return self._step_multi()

    def _backend(self) -> str:
        """First device's platform, resolved once (sentinel key)."""
        name = getattr(self, "_backend_name", None)
        if name is None:
            devs = jax.devices()
            name = devs[0].platform if devs else "unknown"
            self._backend_name = name
        return name

    def _expire_request(self, r: Request, where: str = "queue") -> None:
        """Drop an expired request TYPED (cause-labeled shed): a done
        record with ``deadline_exceeded`` set and no tokens — the drain
        wire carries the flag so the router fails the ticket with
        :class:`~paddle_tpu.resilience.reliability.DeadlineExceededError`
        instead of inventing a result."""
        reject_cause("deadline")
        r.result = None
        r.deadline_exceeded = True
        r.t_done = time.perf_counter()
        self.done[r.rid] = r
        if r.stream is not None:
            r.stream.fail(_reliability.DeadlineExceededError(
                f"request {r.rid} deadline expired in {where}"))
        if (telemetry.enabled() and r.trace is not None
                and r.trace.sampled):
            _tracing.event("serve.deadline_exceeded", ctx=r.trace,
                           rid=r.rid, where=where)

    def _expire_slots(self) -> None:
        """Tear down every slot-resident request whose deadline passed
        (active slots AND parked chunked-prefill slots)."""
        now = time.time()
        for s in range(self.slots):
            st = self._pf[s]
            r = st["r"] if st is not None else self.owner[s]
            if r is None or r.deadline is None:
                continue
            if now < r.deadline.t_end:
                continue
            self._expire_request(
                r, where="prefill" if st is not None else "decode")
            self._dl_active -= 1
            if st is not None:
                self._pf[s] = None
                self._pf_order.remove(s)
            self.owner[s] = None
            self._slot_trace[s] = None
            self.active[s] = False
            self.emitted[s] = []
            if self.paged and self._slot_pages[s] is not None:
                # freed pages may be REALLOCATED: park the cursor past
                # capacity so the retired slot's stale writes drop
                # (same argument as _maybe_finish's teardown)
                self._allocator.free(self._slot_pages[s])
                self._slot_pages[s] = None
                self.t = self.t.at[s].set(self.capacity)

    def _maybe_finish(self, s: int):
        r = self.owner[s]
        hit_eos = (self.eos_id is not None
                   and self.emitted[s][-1] == self.eos_id)
        if hit_eos or self.budget[s] <= 0:
            r.result = np.asarray(self.emitted[s], np.int32)
            r.t_done = time.perf_counter()
            self.done[r.rid] = r
            if r.stream is not None:
                # remaining un-buffered tokens serve consumer-driven
                # from the completion record; then the typed end mark
                r.stream.finish(r.result, r.t_done)
            if telemetry.enabled():
                _serving_metrics()["completed"].inc()
                if r.trace is not None and r.trace.sampled:
                    _tracing.event("serve.done", ctx=r.trace,
                                   rid=r.rid,
                                   n_tokens=len(r.result),
                                   eos=bool(hit_eos))
            if r.deadline is not None:
                self._dl_active -= 1
            self.owner[s] = None
            self._slot_trace[s] = None
            self.active[s] = False
            self.emitted[s] = []
            if self.paged and self._slot_pages[s] is not None:
                if self.prefix_cache:
                    # register this prompt's page-aligned prefix for
                    # reuse (one registry reference; idempotent when
                    # the key is already present)
                    ps_ = self.page_size
                    m = len(r.prompt) // ps_
                    if m >= 1:
                        key_t = self._prefix_key(r.prompt, m * ps_)
                        if key_t not in self._prefix_registry:
                            pref = self._slot_pages[s][:m]
                            self._allocator.share(pref)
                            self._prefix_registry[key_t] = \
                                np.asarray(pref)
                # freed pages may be REALLOCATED to another request, so
                # the retired slot's stale step-writes must DROP: park
                # its cursor past capacity (write_rows' OOB semantics)
                self._allocator.free(self._slot_pages[s])
                self._slot_pages[s] = None
                self.t = self.t.at[s].set(self.capacity)
