"""Production serving plane: a multi-replica router over
``serving.BatchedDecoder`` arenas — the millions-of-users story on top
of the single-replica serving runtime.

Three levers, each a tail-latency lever real TPU serving deployments
win on (cf. the Gemma-on-TPU serving study, PAPERS.md):

- **Multi-replica routing.** A :class:`Router` spreads sessions over N
  replicas (in-process :class:`LocalReplica` threads or
  :class:`HttpReplica` worker processes), health-checked through each
  replica's existing ``/healthz`` + the new ``/readyz`` readiness
  split, with LEAST-LOADED placement driven by the same occupancy/
  queue gauges /statusz already serves, and SESSION AFFINITY so a
  multi-turn conversation lands where its prefix-cache KV lives.

- **Prefill/decode disaggregation.** Dedicated prefill workers run the
  bucketed prefill and hand the resulting KV pages (float or int8
  ``QuantizedPool`` pages alike) to a decode replica as a
  :class:`serving.KVHandoff` — whole-prompt admission never stalls a
  decode tick. Chunked prefill remains the single-replica fallback;
  the router only disaggregates prompts past ``disagg_min_tokens``.

- **SLO-aware admission + load shedding.** An :class:`SLOPolicy` fed
  by the router's live in-flight count and the observed TTFT EWMA
  degrades first (``BatchedDecoder.set_degraded``: decode_steps→1,
  speculative rounds off) and SHEDS before p99 TTFT blows through
  target — shed admissions bump the cause-labeled
  ``pt_serving_admission_rejections_total{cause="shed"}`` next to the
  arena's own ``pool_exhausted`` series.

Resilience: a replica that dies mid-stream (health-check failures or a
dispatch error — chaos point ``router.dispatch``) has its in-flight
requests retried on a surviving replica; requests are only lost to a
typed :class:`NoReplicasError` when EVERY replica is down.

Process bring-up: ``python -m paddle_tpu.serving_router --worker``
runs one replica/prefill worker (model from ``--spec module:fn``);
:func:`spawn_replicas` forks N of them; ``python -m paddle_tpu.launch
--serve`` is the one-command front end.

Green-field vs the reference (its serving is a one-request-at-a-time
predictor per process; cross-replica routing/disaggregation is the
modern LM-serving analog of its multi-instance deployment story).
"""

from __future__ import annotations

import contextlib
import json
import os
import queue
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from . import telemetry
from .core.enforce import EnforceError, enforce
from .serving import BatchedDecoder, KVHandoff, reject_cause
from .telemetry import server as _dbg_server
from .telemetry import tracing as _tracing

_NULL_CM = contextlib.nullcontext()


def _trace_headers(base: Dict[str, str]) -> Dict[str, str]:
    """Stamp the bound trace context onto outbound HTTP headers — the
    ONE helper every cross-process hop in this file rides (pt-lint
    PT-LINT-306 flags HTTP POSTs here that skip it). No-op when
    telemetry is off or no sampled context is bound."""
    if telemetry.enabled():
        ctx = _tracing.current()
        if ctx is not None and ctx.sampled:
            base[_tracing.TRACE_HEADER] = ctx.to_header()
    return base

__all__ = ["Router", "SLOPolicy", "LocalReplica", "HttpReplica",
           "Ticket", "NoReplicasError", "RequestShedError",
           "spawn_replicas", "serve_main", "main"]


class NoReplicasError(EnforceError):
    """Every replica is down (or none was ever ready): the one
    condition under which the router LOSES a request. Anything short
    of this retries on a survivor."""


class RequestShedError(EnforceError):
    """Raised (opt-in, ``submit(raise_on_shed=True)``) when the SLO
    policy sheds the admission; default is a ``Ticket`` with
    ``shed=True`` so open-loop callers count sheds without exception
    overhead."""


@telemetry.cached_instruments
def _router_metrics(reg):
    return {
        "requests": reg.counter(
            "pt_router_requests_total", "requests routed"),
        "shed": reg.counter(
            "pt_router_shed_total",
            "admissions shed by the SLO policy"),
        "retries": reg.counter(
            "pt_router_retries_total",
            "in-flight requests re-dispatched after a replica "
            "failure"),
        "replica_deaths": reg.counter(
            "pt_router_replica_deaths_total",
            "replicas marked dead by the health loop"),
        "disagg": reg.counter(
            "pt_router_disagg_prefills_total",
            "prompts prefilled on a dedicated worker and handed "
            "off as KV pages"),
        "healthy": reg.gauge(
            "pt_router_replicas_healthy", "replicas alive and ready"),
        "degraded": reg.gauge(
            "pt_router_degraded",
            "1 while the SLO policy holds the fleet degraded"),
        "ttft": reg.histogram(
            "pt_router_ttft_seconds",
            "router-side submit-to-first-token latency", unit="s"),
        "queue_wait": reg.histogram(
            "pt_router_dispatch_wait_seconds",
            "router submit-to-replica-dispatch wait", unit="s"),
    }


# ---------------------------------------------------------------------------
# SLO policy
# ---------------------------------------------------------------------------

class SLOPolicy:
    """Deadline/queue-depth admission policy.

    Decision inputs: ``in_flight`` (router-tracked dispatched+queued
    requests), ``slots`` (live replica capacity), and the router's TTFT
    EWMA. Two ladders, most-degraded wins:

    - load factor = in_flight / slots: ``>= degrade_at`` → degrade
      (decode_steps=1, spec off), ``>= shed_at`` → shed. Queue growth
      is the EARLY signal — it predicts TTFT before TTFT blows.
    - ``target_ttft_s`` (optional): estimated wait (load factor x
      observed per-request TTFT EWMA) past the target → shed; past
      half the target → degrade. The deadline side of the policy.

    Pure function of its inputs (no clock, no I/O) — the unit tests pin
    the ladder deterministically."""

    def __init__(self, target_ttft_s: Optional[float] = None,
                 degrade_at: float = 1.5, shed_at: float = 3.0):
        enforce(shed_at >= degrade_at,
                "shed_at %s < degrade_at %s (shedding is the deeper "
                "degradation)", shed_at, degrade_at)
        self.target_ttft_s = target_ttft_s
        self.degrade_at = float(degrade_at)
        self.shed_at = float(shed_at)

    def admit(self, in_flight: int, slots: int,
              ewma_ttft_s: Optional[float] = None) -> str:
        """-> "admit" | "degrade" | "shed" for one arriving request."""
        if slots <= 0:
            return "shed"
        lf = in_flight / slots
        est = lf * ewma_ttft_s if ewma_ttft_s else None
        if lf >= self.shed_at or (
                self.target_ttft_s and est is not None
                and est > self.target_ttft_s):
            return "shed"
        if lf >= self.degrade_at or (
                self.target_ttft_s and est is not None
                and est > 0.5 * self.target_ttft_s):
            return "degrade"
        return "admit"


# ---------------------------------------------------------------------------
# Replicas
# ---------------------------------------------------------------------------

class LocalReplica:
    """One in-process replica: a :class:`serving.BatchedDecoder` driven
    by a background serve thread (admit → prefill tick → step, exactly
    ``run()``'s loop body) with a lock around every arena touch, so
    router dispatch threads and the serve loop interleave safely.

    Also the PREFILL-worker form: a replica that only ever receives
    :meth:`prefill` calls ticks nothing and just runs bucketed prefills
    under the same lock. ``warmup()`` drives one tiny request to
    compile the step + prefill bucket before the replica reports
    ready.

    Each in-process replica needs its OWN model instance (same seed =
    identical weights): the jitted arena passes weights via
    ``inject_state``, which temporarily rebinds the model's parameters
    — two replicas tracing one shared model from different threads
    would leak tracers into each other. Worker processes get this
    isolation for free."""

    def __init__(self, decoder: BatchedDecoder, name: str = "replica0",
                 idle_s: float = 0.002):
        self.decoder = decoder
        self.name = name
        self.idle_s = idle_s
        self._mu = threading.RLock()
        self._done: Dict[int, Dict[str, Any]] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "LocalReplica":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name=f"pt-replica-{self.name}")
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None

    def close(self) -> None:
        self.stop()

    def warmup(self, vocab_hint: int = 8) -> None:
        """Compile the serving step (and smallest prefill bucket) by
        driving one 2-token request to completion — a replica warms
        BEFORE it reports ready, so the router never places a real
        session onto a cold jit cache. max_new=2 on purpose: a 1-token
        request finishes at ACTIVATION without ever dispatching the
        arena step, which would leave the step executable cold (and
        ``ready`` false forever)."""
        rid = self.submit(np.asarray([1, min(2, vocab_hint - 1)],
                                     np.int32), 2)
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline:
            if rid in self.drain_results(keep=True):
                return
            if self._thread is None:  # not started: tick inline
                with self._mu:
                    self._tick_locked()
            else:
                time.sleep(0.005)
        raise EnforceError(f"replica {self.name} warmup timed out")

    # -- serving API (router-facing) ----------------------------------------

    def submit(self, prompt, max_new: int,
               session: Optional[str] = None) -> int:
        with self._mu:
            return self.decoder.submit(prompt, max_new)

    def inject(self, handoff: KVHandoff, max_new: int,
               session: Optional[str] = None) -> int:
        with self._mu:
            return self.decoder.inject_prefilled(handoff, max_new)

    def prefill(self, prompt) -> KVHandoff:
        with self._mu:
            return self.decoder.prefill_export(prompt)

    def drain_results(self, keep: bool = False) -> Dict[int, Dict]:
        """Completed requests since the last drain:
        ``{rid: {tokens, ttft_s, itl_p99_s, t_first, t_done}}``.
        ``keep=True`` peeks without consuming (warmup)."""
        with self._mu:
            out = dict(self._done)
            if not keep:
                self._done.clear()
            return out

    def set_degraded(self, on: bool) -> None:
        with self._mu:
            self.decoder.set_degraded(on)

    def healthz(self) -> Dict[str, Any]:
        return {"status": "ok", "ready": self.decoder.ready,
                "pid": os.getpid()}

    def load(self) -> Dict[str, Any]:
        d = self.decoder
        with self._mu:
            out = {"queue_depth": len(d.queue),
                   "active_slots": int(d.active.sum()),
                   "prefilling": len(d._pf_order),
                   "slots": d.slots}
            if d.paged:
                out["free_pages"] = d._allocator.free_pages
            return out

    # -- serve loop ---------------------------------------------------------

    def _tick_locked(self) -> bool:
        """One serving tick (caller holds the lock). Returns True when
        any work happened (idle loops back off otherwise)."""
        d = self.decoder
        busy = bool(d.queue or d._pf_order or d.active.any())
        if not busy:
            return False
        d._admit()
        d._prefill_tick()
        d._step()
        if d.done:
            for rid, r in d.done.items():
                ts = r.t_tokens
                itl = np.diff(ts) if len(ts) > 1 else np.asarray([0.0])
                self._done[rid] = {
                    "tokens": r.result,
                    "ttft_s": r.t_first - r.t_submit,
                    "itl_p99_s": float(np.quantile(itl, 0.99)),
                    "t_first": r.t_first, "t_done": r.t_done,
                    "n_tokens": len(r.result),
                }
            d.done.clear()
        return True

    def _loop(self) -> None:
        while not self._stop.is_set():
            with self._mu:
                busy = self._tick_locked()
            if not busy:
                time.sleep(self.idle_s)


class HttpReplica:
    """Client handle for one replica WORKER PROCESS (the
    ``--worker`` CLI below): the serving API over the worker's debug
    server port — ``/healthz``/``/readyz``/``/statusz`` for placement,
    POST ``/submit`` ``/inject`` ``/prefill`` ``/drain`` ``/config``
    for the data path. Transport errors raise ``OSError`` — the
    router's failover signal."""

    def __init__(self, url: str, name: Optional[str] = None,
                 timeout_s: float = 60.0,
                 proc: Optional[subprocess.Popen] = None):
        self.url = url.rstrip("/")
        self.name = name or url
        self.timeout_s = timeout_s
        self.proc = proc  # when spawn_replicas owns the process

    def _get(self, path: str) -> Dict[str, Any]:
        with urllib.request.urlopen(self.url + path,
                                    timeout=self.timeout_s) as r:
            return json.loads(r.read().decode())

    def _post(self, path: str, body: bytes,
              ctype: str = "application/json") -> bytes:
        req = urllib.request.Request(
            self.url + path, data=body, method="POST",
            headers=_trace_headers({"Content-Type": ctype}))
        try:
            with urllib.request.urlopen(req,
                                        timeout=self.timeout_s) as r:
                return r.read()
        except urllib.error.HTTPError as e:
            # 400 = the handler rejected the REQUEST (typed enforce
            # error worker-side); surface it as such, not as replica
            # death
            detail = e.read().decode(errors="replace")
            raise EnforceError(
                f"replica {self.name} rejected {path}: {detail}") \
                from None

    def _post_json(self, path: str, obj: Any) -> Dict[str, Any]:
        return json.loads(self._post(
            path, json.dumps(obj).encode()).decode())

    def submit(self, prompt, max_new: int,
               session: Optional[str] = None) -> int:
        out = self._post_json("/submit", {
            "prompt": np.asarray(prompt, np.int32).tolist(),
            "max_new": int(max_new)})
        return int(out["rid"])

    def inject(self, handoff: KVHandoff, max_new: int,
               session: Optional[str] = None) -> int:
        # wire layout: 8-byte big-endian max_new, then the npz payload
        # (the npz body is opaque bytes; max_new can't ride inside it
        # without a second parse, and the stdlib handler drops query
        # strings before dispatch)
        body = int(max_new).to_bytes(8, "big") + handoff.to_bytes()
        out = json.loads(self._post(
            "/inject", body, "application/octet-stream").decode())
        return int(out["rid"])

    def prefill(self, prompt) -> KVHandoff:
        body = self._post("/prefill", json.dumps({
            "prompt": np.asarray(prompt, np.int32).tolist()}).encode())
        return KVHandoff.from_bytes(body)

    def drain_results(self) -> Dict[int, Dict]:
        out = self._post_json("/drain", {})
        return {int(rid): {**rec, "tokens": np.asarray(
            rec["tokens"], np.int32)}
            for rid, rec in out["done"].items()}

    def set_degraded(self, on: bool) -> None:
        self._post_json("/config", {"degraded": bool(on)})

    def healthz(self) -> Dict[str, Any]:
        return self._get("/healthz")

    def load(self) -> Dict[str, Any]:
        # the dedicated lightweight endpoint — the health poll hits
        # this tens of times a second, and the full /statusz renders
        # device inventory + recompile report per scrape
        return self._post_json("/load", {})

    def close(self) -> None:
        if self.proc is not None and self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                self.proc.kill()


# ---------------------------------------------------------------------------
# Router
# ---------------------------------------------------------------------------

class Ticket:
    """One routed request. ``shed=True`` = never dispatched (SLO
    policy); otherwise ``wait()``/``Router.wait`` fills ``tokens`` and
    the latency fields, or ``error`` when every replica died."""

    def __init__(self, rid: int, prompt, max_new: int,
                 session: Optional[str]):
        self.rid = rid
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        self.max_new = int(max_new)
        self.session = session
        self.trace = None  # TraceContext minted at admission
        self.shed = False
        self.t_submit = time.perf_counter()
        self.t_dispatched = 0.0
        self.replica: Optional[str] = None
        self.replica_rid: Optional[int] = None
        self.retries = 0
        self.disaggregated = False
        self.tokens: Optional[np.ndarray] = None
        self.ttft_s: Optional[float] = None
        self.itl_p99_s: Optional[float] = None
        self.error: Optional[BaseException] = None
        self.done = threading.Event()

    @property
    def ok(self) -> bool:
        return self.tokens is not None

    def wait(self, timeout: Optional[float] = None) -> "Ticket":
        if not self.done.wait(timeout):
            raise TimeoutError(
                f"request {self.rid} still in flight after {timeout}s "
                f"(replica={self.replica})")
        if self.error is not None:
            raise self.error
        return self


class _ReplicaState:
    def __init__(self, replica):
        self.replica = replica
        self.alive = True
        self.ready = False
        self.fails = 0
        self.load: Dict[str, Any] = {"queue_depth": 0,
                                     "active_slots": 0, "slots": 1}
        self.inflight: Dict[int, Ticket] = {}  # replica_rid -> ticket
        # results drained before their dispatcher registered the rid
        # (the fast-completion race) park here until the registration
        # catches up; bounded, insertion-ordered (oldest evicted)
        self.orphans: Dict[int, Dict] = {}


class Router:
    """Spread sessions over N replicas; health-check, shed, fail over.

    ``replicas``: :class:`LocalReplica` / :class:`HttpReplica` handles
    (started/spawned by the caller — the router routes, it does not own
    model processes unless asked to ``close(replicas=True)``).
    ``prefill_workers``: replicas whose only job is
    :meth:`~LocalReplica.prefill`; prompts of at least
    ``disagg_min_tokens`` tokens are prefilled there and handed off as
    KV pages. ``policy``: an :class:`SLOPolicy` (None = admit always).

    Submission is NON-blocking (open-loop): ``submit`` sheds or
    enqueues; dispatcher threads place the request (running the
    disaggregated prefill when eligible); a poll loop drains completed
    results and health-checks replicas, retrying the in-flight load of
    a dead replica on the survivors."""

    def __init__(self, replicas: Sequence, prefill_workers: Sequence = (),
                 policy: Optional[SLOPolicy] = None,
                 session_affinity: bool = True,
                 disagg_min_tokens: Optional[int] = 64,
                 poll_interval_s: float = 0.05,
                 health_fails: int = 2,
                 dispatchers: Optional[int] = None,
                 max_in_flight: Optional[int] = None,
                 trace_sample: Optional[float] = None,
                 textfile_path: Optional[str] = None,
                 textfile_interval_s: float = 5.0):
        enforce(len(replicas) >= 1, "router needs >= 1 replica")
        self._replicas: Dict[str, _ReplicaState] = {}
        for r in replicas:
            enforce(r.name not in self._replicas,
                    "duplicate replica name %r", r.name)
            self._replicas[r.name] = _ReplicaState(r)
        self._prefill = list(prefill_workers)
        self._pf_rr = 0
        self.policy = policy
        self.session_affinity = session_affinity
        self.disagg_min_tokens = disagg_min_tokens
        self.poll_interval_s = poll_interval_s
        self.health_fails = int(health_fails)
        # hard queue-depth cap, independent of the SLO policy: past it
        # admissions reject with cause="capacity" (the policy's
        # load-factor shed keeps cause="shed" — the /metrics split)
        self.max_in_flight = max_in_flight
        # head-based trace sampling for requests admitted HERE (None =
        # the process-wide telemetry.tracing rate, default 1.0); the
        # decision rides the context to every replica/worker hop
        self.trace_sample = trace_sample
        # node-exporter textfile sink: the poll loop re-writes the
        # whole registry (pt_router_* included) every
        # textfile_interval_s — the scrape-less deployment path
        # (env PT_ROUTER_TEXTFILE works for the CLI bring-up)
        self._textfile = (textfile_path
                          or os.environ.get("PT_ROUTER_TEXTFILE"))
        self._textfile_interval_s = float(textfile_interval_s)
        self._textfile_t = 0.0
        self._mu = threading.RLock()
        self._affinity: Dict[str, str] = {}
        self._tickets: Dict[int, Ticket] = {}
        self._next_rid = 0
        self._queued = 0            # accepted, not yet dispatched
        self._degraded = False
        self._ewma_ttft: Optional[float] = None
        self._shed_count = 0
        self._served_count = 0
        self._retry_count = 0
        self._stop = threading.Event()
        self._dispatch_q: "queue.Queue[Optional[Ticket]]" = queue.Queue()
        self._threads: List[threading.Thread] = []
        self._probe_all()
        if dispatchers is None:
            # a dispatcher BLOCKS for the whole synchronous prefill of
            # a disaggregated request: without a lane per prefill
            # worker, two long prompts in a row would park every
            # dispatcher and short requests would queue behind a
            # prefill — the exact tail disaggregation exists to remove
            dispatchers = 2 + len(self._prefill)
        for i in range(max(1, int(dispatchers))):
            t = threading.Thread(target=self._dispatch_loop,
                                 daemon=True,
                                 name=f"pt-router-dispatch-{i}")
            t.start()
            self._threads.append(t)
        t = threading.Thread(target=self._poll_loop, daemon=True,
                             name="pt-router-poll")
        t.start()
        self._threads.append(t)
        self.server: Optional[_dbg_server.DebugServer] = None

    # -- public API ---------------------------------------------------------

    def submit(self, prompt, max_new: int,
               session: Optional[str] = None,
               raise_on_shed: bool = False) -> Ticket:
        """Route one request (non-blocking). SLO shed returns a
        ``shed=True`` ticket (or raises :class:`RequestShedError` when
        asked); :class:`NoReplicasError` when no replica is alive."""
        with self._mu:
            t = Ticket(self._next_rid, prompt, max_new, session)
            self._next_rid += 1
        if telemetry.enabled():
            _router_metrics()["requests"].inc()
            # the trace is MINTED here — admission is the one edge
            # every request crosses exactly once, so the head-based
            # sampling draw happens here and nowhere else
            t.trace = _tracing.new_trace(rate=self.trace_sample)
            _tracing.event("router.admit", ctx=t.trace, rid=t.rid,
                           session=session, plen=int(t.prompt.size),
                           max_new=t.max_new)
        if not self._alive_names():
            self._probe_all()
            if not self._alive_names():
                raise NoReplicasError(
                    "no replica alive to place the request on")
        cause = None
        if self.max_in_flight is not None:
            with self._mu:
                if self._in_flight_locked() >= self.max_in_flight:
                    cause = "capacity"  # hard queue-depth cap
        if cause is None and self._policy_action() == "shed":
            cause = "shed"
        if cause is not None:
            t.shed = True
            t.done.set()
            with self._mu:
                self._shed_count += 1
            if telemetry.enabled():
                _router_metrics()["shed"].inc()
                _tracing.event("router.shed", ctx=t.trace,
                               rid=t.rid, cause=cause)
            reject_cause(cause)
            if raise_on_shed:
                raise RequestShedError(
                    f"admission rejected ({cause}: "
                    + ("hard in-flight cap reached"
                       if cause == "capacity"
                       else "SLO load factor past shed_at") + ")")
            return t
        with self._mu:
            self._tickets[t.rid] = t
            self._queued += 1
        self._dispatch_q.put(t)
        return t

    def wait(self, tickets: Sequence[Ticket],
             timeout: Optional[float] = None) -> Dict[int, Ticket]:
        """Block until every non-shed ticket completes (or ``timeout``
        per ticket); raises the first ticket error (NoReplicasError
        when the fleet died under the request)."""
        out = {}
        for t in tickets:
            if not t.shed:
                t.wait(timeout)
            out[t.rid] = t
        return out

    def stats(self) -> Dict[str, Any]:
        with self._mu:
            alive = self._alive_names()
            return {
                "replicas": len(self._replicas),
                "alive": len(alive),
                "prefill_workers": len(self._prefill),
                "in_flight": self._in_flight_locked(),
                "served": self._served_count,
                "shed": self._shed_count,
                "retries": self._retry_count,
                "degraded": self._degraded,
                "ewma_ttft_s": self._ewma_ttft,
                "affinity_sessions": len(self._affinity),
            }

    def replicaz(self) -> Dict[str, Any]:
        """Per-replica fan-out (the /podz pattern over serving
        replicas): live health + load + in-flight, one row each."""
        rows = {}
        for name, st in list(self._replicas.items()):
            row: Dict[str, Any] = {"alive": st.alive,
                                   "ready": st.ready,
                                   "inflight": len(st.inflight)}
            if st.alive:
                try:
                    row["healthz"] = st.replica.healthz()
                    row["load"] = st.replica.load()
                except Exception as e:
                    row["error"] = repr(e)
            rows[name] = row
        return {"replicas": rows, "router": self.stats()}

    def trace_fanin(self,
                    trace_id: Optional[str] = None) -> Dict[str, Any]:
        """Fleet trace aggregation — the ``/tracez?trace_id=`` payload
        on the router's debug server: collect matching spans from this
        process's own ring (router spans + any in-process replicas)
        and every worker process's /tracez, align timestamps via each
        process's clock-offset handshake, and merge into ONE
        chrome-trace with per-process lanes. Unreachable workers
        degrade to ``errors`` rows — a dead replica never fails the
        merge of what the fleet can still tell us."""
        from concurrent.futures import ThreadPoolExecutor

        collections: List[Dict[str, Any]] = [
            _tracing.collection(trace_id, proc="router")]
        sources = ["router"]
        errors: Dict[str, str] = {}
        peers = [(n, st.replica)
                 for n, st in list(self._replicas.items())]
        peers += [(getattr(w, "name", f"prefill{i}"), w)
                  for i, w in enumerate(list(self._prefill))]
        seen = set()
        targets = []
        for name, rep in peers:
            url = getattr(rep, "url", None)
            if url is None or url in seen:
                continue  # in-process replica: spans ride OUR ring
            seen.add(url)
            targets.append((name, url))
        # ``local=1``: ask each peer for its LOCAL ring, never its own
        # fan-in (aggregators must not recurse into each other)
        q = (f"?trace_id={trace_id}&local=1" if trace_id
             else "?local=1")

        def fetch(target):
            name, url = target
            try:
                with urllib.request.urlopen(url + "/tracez" + q,
                                            timeout=2) as r:
                    j = json.loads(r.read().decode())
                j["proc"] = name
                return name, j, None
            except Exception as e:
                return name, None, repr(e)

        if targets:
            # CONCURRENT fan-out: a scrape of a partially-wedged fleet
            # is bounded near ONE peer's timeout, not peers x timeout
            # serialized on the debug-server handler thread
            with ThreadPoolExecutor(
                    max_workers=min(8, len(targets)),
                    thread_name_prefix="pt-tracez-fetch") as ex:
                for name, j, err in ex.map(fetch, targets):
                    if j is not None:
                        collections.append(j)
                        sources.append(name)
                    else:
                        errors[name] = err
        merged = _tracing.merge_chrome_trace(collections)
        return {"trace_id": trace_id, "sources": sources,
                "errors": errors, "trace": merged}

    def start_server(self, port: int = 0,
                     host: str = "127.0.0.1") -> _dbg_server.DebugServer:
        """Serve the router's own debug plane: /statusz gains a
        ``router`` section, /podz fans out over the replicas (the
        fleet-controller pattern reused), /tracez?trace_id= merges the
        fleet's spans for one request, /readyz = any replica
        placeable."""
        srv = _dbg_server.DebugServer(
            port=port, host=host,
            run_config={"role": "router",
                        "replicas": sorted(self._replicas)})
        srv.add_status("router", self.stats)
        srv.set_fleet(self.replicaz)
        srv.set_trace_fanin(self.trace_fanin)
        srv.set_ready(lambda: bool(self._alive_names()))
        srv.add_post("/submit", self._http_submit)
        srv.add_post("/drain", self._http_drain)
        self.server = srv.start()
        return self.server

    def close(self, replicas: bool = False) -> None:
        self._stop.set()
        for _ in self._threads:
            self._dispatch_q.put(None)
        for t in self._threads:
            t.join(timeout=10)
        self._threads = []
        if self.server is not None:
            self.server.stop()
            self.server = None
        if replicas:
            for st in self._replicas.values():
                try:
                    st.replica.close()
                except Exception:
                    pass
            for w in self._prefill:
                try:
                    w.close()
                except Exception:
                    pass

    def __enter__(self) -> "Router":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- router HTTP front-end (start_server) -------------------------------

    def _http_submit(self, body: bytes) -> Dict[str, Any]:
        req = json.loads(body.decode() or "{}")
        t = self.submit(np.asarray(req["prompt"], np.int32),
                        int(req["max_new"]),
                        session=req.get("session"))
        return {"rid": t.rid, "shed": t.shed}

    def _http_drain(self, body: bytes) -> Dict[str, Any]:
        done = {}
        with self._mu:
            for rid, t in list(self._tickets.items()):
                if t.done.is_set():
                    done[rid] = {
                        "tokens": (t.tokens.tolist() if t.ok else None),
                        "ttft_s": t.ttft_s,
                        "itl_p99_s": t.itl_p99_s,
                        "shed": t.shed,
                        "error": repr(t.error) if t.error else None}
                    del self._tickets[rid]
        return {"done": done}

    # -- policy -------------------------------------------------------------

    def _alive_names(self) -> List[str]:
        return [n for n, st in self._replicas.items() if st.alive]

    def _in_flight_locked(self) -> int:
        return self._queued + sum(len(st.inflight)
                                  for st in self._replicas.values())

    def _policy_action(self) -> str:
        if self.policy is None:
            return "admit"
        with self._mu:
            in_flight = self._in_flight_locked()
            slots = sum(st.load.get("slots", 1)
                        for st in self._replicas.values() if st.alive)
            ewma = self._ewma_ttft
        action = self.policy.admit(in_flight, slots, ewma)
        want_degraded = action in ("degrade", "shed")
        if want_degraded != self._degraded:
            # hysteresis-free toggle is fine: set_degraded is
            # idempotent and cheap (a bool; the k=1 step fn caches)
            self._degraded = want_degraded
            if telemetry.enabled():
                _router_metrics()["degraded"].set(int(want_degraded))
            for st in list(self._replicas.values()):
                if st.alive:
                    try:
                        st.replica.set_degraded(want_degraded)
                    except Exception:
                        pass  # health loop will catch a dead replica
        return action

    # -- placement + dispatch -----------------------------------------------

    def _pick_replica(self, t: Ticket) -> Optional[_ReplicaState]:
        with self._mu:
            if (self.session_affinity and t.session is not None):
                # affinity holds only while the replica is PLACEABLE
                # (alive AND ready) — a draining home replica loses the
                # session to least-loaded placement
                name = self._affinity.get(t.session)
                if name is not None:
                    st = self._replicas.get(name)
                    if st is not None and st.alive and st.ready:
                        return st

            def pick(require_ready: bool):
                best, best_load = None, None
                for st in self._replicas.values():
                    if not st.alive or (require_ready and not st.ready):
                        continue
                    load = (len(st.inflight)
                            + st.load.get("queue_depth", 0)
                            + st.load.get("prefilling", 0))
                    if best_load is None or load < best_load:
                        best, best_load = st, load
                return best

            # ready replicas first; an all-cold fleet (nothing warmed
            # yet) still places on an alive one rather than failing
            return pick(True) or pick(False)

    def _dispatch_loop(self) -> None:
        while True:
            t = self._dispatch_q.get()
            if t is None:
                return
            if self._stop.is_set():
                # closing: a silently dropped ticket would hang its
                # waiter — fail it typed and keep draining the queue
                with self._mu:
                    self._queued = max(0, self._queued - 1)
                t.error = NoReplicasError(
                    f"router closed before request {t.rid} was "
                    "dispatched")
                t.done.set()
                continue
            self._dispatch(t)

    def _dispatch(self, t: Ticket) -> None:
        st = self._pick_replica(t)
        if st is None:
            with self._mu:
                self._queued = max(0, self._queued - 1)
            t.error = NoReplicasError(
                "all replicas down; request cannot be placed")
            t.done.set()
            return
        telem = telemetry.enabled()
        # bind the request's context for the whole placement: every
        # hop below (prefill-worker POST, replica submit/inject —
        # HTTP header or in-process thread-local alike) parents onto
        # this dispatch span, and a retry re-enters here with the
        # SAME trace id (retry count annotated)
        cm_bind = _tracing.bind(t.trace) if telem else _NULL_CM
        cm_span = (_tracing.span("router.dispatch", ctx=t.trace,
                                 rid=t.rid,
                                 replica=st.replica.name,
                                 retry=t.retries)
                   if telem else _NULL_CM)
        with cm_bind, cm_span:
            self._dispatch_on(t, st, telem)

    def _dispatch_on(self, t: Ticket, st: "_ReplicaState",
                     telem: bool) -> None:
        from .resilience import faults as _faults

        try:
            inj = _faults.active()
            if inj is not None:
                inj.fire("router.dispatch", path=st.replica.name)
            handoff = None
            if (self._prefill and self.disagg_min_tokens is not None
                    and len(t.prompt) >= self.disagg_min_tokens):
                # a prefill-worker failure must not be blamed on the
                # decode replica picked above: drop the worker from the
                # rotation and FALL BACK to in-replica prefill (chunked
                # prefill / monolithic — the documented fallback path)
                with self._mu:
                    workers = list(self._prefill)
                    # round-robin cursor under the lock: two racing
                    # dispatchers must not pick the SAME worker and
                    # serialize on its replica lock while another
                    # worker idles
                    if workers:
                        worker = workers[self._pf_rr % len(workers)]
                        self._pf_rr += 1
                if workers:
                    pf_cm = (_tracing.span("router.disagg_prefill",
                                           ctx=t.trace,
                                           worker=worker.name,
                                           plen=int(t.prompt.size))
                             if telem else _NULL_CM)
                    try:
                        with pf_cm:
                            handoff = worker.prefill(t.prompt)
                        t.disaggregated = True
                        if telem:
                            _router_metrics()["disagg"].inc()
                    except EnforceError:
                        raise  # typed rejection: the REQUEST's fault
                    except Exception:
                        with self._mu:
                            if worker in self._prefill:
                                self._prefill.remove(worker)
            if handoff is not None:
                rid = st.replica.inject(handoff, t.max_new,
                                        session=t.session)
            else:
                rid = st.replica.submit(t.prompt, t.max_new,
                                        session=t.session)
        except EnforceError:
            # typed replica-side rejection (bad request): the caller's
            # error, not a replica death
            with self._mu:
                self._queued = max(0, self._queued - 1)
            t.error = sys.exc_info()[1]
            t.done.set()
            return
        except Exception:
            # transport/dispatch failure: fail the replica over and
            # retry the request on a survivor
            self._fail_replica(st, reason=repr(sys.exc_info()[1]))
            self._requeue(t)
            return
        t.t_dispatched = time.perf_counter()
        t.replica, t.replica_rid = st.replica.name, rid
        with self._mu:
            self._queued = max(0, self._queued - 1)
            # the poll thread may have drained this rid's result
            # BEFORE we registered it (a request can finish at its
            # first serve tick) — the parked orphan record completes
            # the ticket right here instead of hanging its waiter
            rec = st.orphans.pop(rid, None)
            if rec is None:
                st.inflight[rid] = t
            if self.session_affinity and t.session is not None:
                self._affinity[t.session] = st.replica.name
        if rec is not None:
            self._finish(t, rec)
        if telemetry.enabled():
            _router_metrics()["queue_wait"].observe(
                t.t_dispatched - t.t_submit,
                exemplar=(t.trace.trace_id
                          if t.trace is not None and t.trace.sampled
                          else None))

    def _requeue(self, t: Ticket) -> None:
        """Re-dispatch after a replica failure — the request survives
        as long as ANY replica does."""
        t.retries += 1
        prev = t.replica
        t.replica = t.replica_rid = None
        with self._mu:
            self._retry_count += 1
        if telemetry.enabled():
            _router_metrics()["retries"].inc()
            # the retry stays on the SAME trace id — the merged
            # timeline shows the death and the re-dispatch as one
            # request's story, annotated here
            _tracing.event("router.retry", ctx=t.trace, rid=t.rid,
                           retries=t.retries, failed_replica=prev)
        if not self._alive_names():
            with self._mu:
                self._queued = max(0, self._queued - 1)
            t.error = NoReplicasError(
                f"request {t.rid} lost: all replicas down "
                f"(after {t.retries} retries)")
            t.done.set()
            return
        self._dispatch_q.put(t)

    # -- health + results ---------------------------------------------------

    def _probe_all(self) -> None:
        for st in list(self._replicas.values()):
            self._probe(st)
        if telemetry.enabled():
            _router_metrics()["healthy"].set(len(self._alive_names()))

    def _probe(self, st: _ReplicaState) -> None:
        try:
            hz = st.replica.healthz()
            st.load = st.replica.load()
            st.fails = 0
            # ready=False is NOT death: placement stops (pick requires
            # ready) but in-flight work keeps draining and nothing is
            # retried — a draining replica finishes what it holds
            st.ready = bool(hz.get("ready", True))
            if not st.alive:
                st.alive = True  # answered again: recovered
        except Exception:
            st.fails += 1
            if st.fails >= self.health_fails and st.alive:
                self._fail_replica(st, reason="health check failed "
                                   f"{st.fails}x")

    def _fail_replica(self, st: _ReplicaState, reason: str = "") -> None:
        with self._mu:
            if not st.alive and not st.inflight:
                return
            st.alive = False
            orphans = list(st.inflight.values())
            st.inflight.clear()
            for s, name in list(self._affinity.items()):
                if name == st.replica.name:
                    del self._affinity[s]
        if telemetry.enabled():
            _router_metrics()["replica_deaths"].inc()
            _router_metrics()["healthy"].set(len(self._alive_names()))
        for t in orphans:
            with self._mu:
                self._queued += 1  # back to pre-dispatch accounting
            self._requeue(t)

    def _finish(self, t: Ticket, rec: Dict) -> None:
        """Complete a ticket from its replica-side result record."""
        t.tokens = np.asarray(rec["tokens"], np.int32)
        # replica-side TTFT is measured from ITS submit; add the
        # router-side dispatch wait so the number is end-to-end
        wait = max(0.0, t.t_dispatched - t.t_submit)
        t.ttft_s = float(rec["ttft_s"]) + wait
        t.itl_p99_s = float(rec.get("itl_p99_s") or 0.0)
        with self._mu:
            self._served_count += 1
            a = 0.2  # EWMA over recent completions
            self._ewma_ttft = (t.ttft_s if self._ewma_ttft is None
                               else (1 - a) * self._ewma_ttft
                               + a * t.ttft_s)
        if telemetry.enabled():
            _router_metrics()["ttft"].observe(
                t.ttft_s,
                exemplar=(t.trace.trace_id
                          if t.trace is not None and t.trace.sampled
                          else None))
        t.done.set()

    def _harvest(self, st: _ReplicaState) -> None:
        if not st.inflight:
            return
        try:
            done = st.replica.drain_results()
        except Exception:
            return  # the probe path owns failure counting
        for rid, rec in done.items():
            with self._mu:
                t = st.inflight.pop(rid, None)
                if t is None:
                    # drained before the dispatcher registered the rid
                    # (fast completion) or a stale record (warmup, a
                    # retried duplicate's original): park it for the
                    # registration to claim; bound the buffer so stale
                    # entries can't accumulate
                    st.orphans[rid] = rec
                    while len(st.orphans) > 256:
                        st.orphans.pop(next(iter(st.orphans)))
                    continue
            self._finish(t, rec)

    def _poll_once(self) -> None:
        """One health+results sweep (the poll loop's body; tests drive
        it directly for deterministic schedules). Probes EVERY replica
        — including dead ones, so a transient failure (GC pause, slow
        compile) recovers the replica on its next successful answer
        instead of removing it from the fleet forever."""
        for st in list(self._replicas.values()):
            self._probe(st)
            if st.inflight:
                self._harvest(st)
        if telemetry.enabled():
            _router_metrics()["healthy"].set(len(self._alive_names()))
            if self._textfile:
                # node-exporter textfile path: re-write the whole
                # registry (pt_router_* included) on a bounded cadence
                # — scrape-less deployments read the same series a
                # /metrics scrape would
                now = time.monotonic()
                if now - self._textfile_t >= self._textfile_interval_s:
                    self._textfile_t = now
                    try:
                        telemetry.write_textfile(self._textfile)
                    except Exception:
                        pass  # a full disk must not kill the poll loop

    def _poll_loop(self) -> None:
        while not self._stop.wait(self.poll_interval_s):
            self._poll_once()


# ---------------------------------------------------------------------------
# Worker process + spawning
# ---------------------------------------------------------------------------

def _resolve_spec(spec: str, spec_kw: Optional[dict]):
    """``module:fn`` → the BatchedDecoder the callable builds (the
    worker-process model contract: the function must be importable in
    a FRESH process and return a ready-to-serve decoder)."""
    mod, _, fn = spec.partition(":")
    enforce(mod and fn, "--spec must be module:function, got %r", spec)
    import importlib

    f = getattr(importlib.import_module(mod), fn)
    dec = f(**(spec_kw or {}))
    enforce(isinstance(dec, BatchedDecoder),
            "spec %r must return a serving.BatchedDecoder, got %s",
            spec, type(dec).__name__)
    return dec


def run_worker(spec: str, role: str = "decode", port: int = 0,
               port_file: Optional[str] = None,
               spec_kw: Optional[dict] = None, warm: bool = True,
               _ready_evt: Optional[threading.Event] = None) -> None:
    """One replica worker: build the decoder from ``spec``, serve the
    router API + debug endpoints on ``port``, run until SIGTERM/SIGINT.
    ``role="prefill"``: no serve loop — the worker only answers
    /prefill (and reports ready after its prefill bucket warms)."""
    import signal as _signal

    decoder = _resolve_spec(spec, spec_kw)
    name = f"{role}-{os.getpid()}"
    rep = LocalReplica(decoder, name=name)
    if role == "decode":
        rep.start()
    srv = _dbg_server.DebugServer(
        port=port, owned=True,
        run_config={"role": f"serving-{role}", "spec": spec,
                    "slots": decoder.slots,
                    "capacity": decoder.capacity,
                    "paged": decoder.paged})
    srv.add_status("serving", decoder._statusz)
    srv.set_ready(lambda: decoder.ready)
    if role == "decode":
        # arena endpoints only where a serve loop actually ticks — a
        # /submit accepted by a prefill worker would enqueue into an
        # arena nothing drives (silent forever-pending instead of 404)
        def _submit(b: bytes) -> Dict[str, Any]:
            req = json.loads(b.decode())
            return {"rid": rep.submit(
                np.asarray(req["prompt"], np.int32),
                int(req["max_new"]))}

        srv.add_post("/submit", _submit)
        srv.add_post("/drain", lambda b: {"done": {
            rid: {**rec, "tokens": np.asarray(rec["tokens"]).tolist()}
            for rid, rec in rep.drain_results().items()}})
        srv.add_post("/inject", _make_inject(rep))
    srv.add_post("/config", lambda b: _worker_config(rep, b))
    srv.add_post("/load", lambda b: rep.load())
    srv.add_post("/prefill", lambda b: (
        "application/octet-stream",
        rep.prefill(np.asarray(
            json.loads(b.decode())["prompt"], np.int32)).to_bytes()))
    srv.start()
    if port_file:
        tmp = port_file + ".tmp"
        with open(tmp, "w") as f:
            f.write(str(srv.port))
        os.replace(tmp, port_file)
    if warm:
        if role == "prefill":
            # compile the prefill bucket so the first real handoff
            # isn't a cold trace, then report ready
            decoder.prefill_export(np.asarray([1, 2], np.int32))
            decoder._warmed = True
        else:
            rep.warmup()
    stop = threading.Event()
    for sig in (_signal.SIGTERM, _signal.SIGINT):
        try:
            _signal.signal(sig, lambda *a: stop.set())
        except ValueError:
            pass  # not the main thread (in-process tests)
    if _ready_evt is not None:
        _ready_evt.set()
    try:
        while not stop.wait(0.1):
            pass
    finally:
        rep.stop()
        srv.stop()


def _worker_config(rep: LocalReplica, body: bytes) -> Dict[str, Any]:
    cfg = json.loads(body.decode() or "{}")
    if "degraded" in cfg:
        rep.set_degraded(bool(cfg["degraded"]))
    return {"ok": True, "degraded": rep.decoder.degraded}


def _make_inject(rep: LocalReplica):
    """/inject POST handler: the npz handoff payload carries everything
    but max_new, which rides a leading 8-byte header (the stdlib
    handler gives us only the body)."""
    def handler(body: bytes) -> Dict[str, Any]:
        enforce(len(body) > 8, "inject body too short")
        max_new = int.from_bytes(body[:8], "big")
        h = KVHandoff.from_bytes(body[8:])
        return {"rid": rep.inject(h, max_new)}

    return handler


def spawn_replicas(spec: str, n: int, role: str = "decode",
                   spec_kw: Optional[dict] = None,
                   log_dir: Optional[str] = None,
                   env: Optional[dict] = None,
                   timeout_s: float = 300.0,
                   warm: bool = True) -> List[HttpReplica]:
    """Fork ``n`` replica worker processes (``--worker`` CLI) and wait
    until each is serving (and warm, unless ``warm=False``). Returns
    connected :class:`HttpReplica` handles owning their process
    (``close()`` terminates it)."""
    import tempfile

    workdir = log_dir or tempfile.mkdtemp(prefix="pt-router-")
    os.makedirs(workdir, exist_ok=True)
    procs = []
    for i in range(n):
        pf = os.path.join(workdir, f"{role}{i}.port")
        if os.path.exists(pf):
            os.remove(pf)
        log = open(os.path.join(workdir, f"{role}{i}.log"), "w")
        cmd = [sys.executable, "-m", "paddle_tpu.serving_router",
               "--worker", "--spec", spec, "--role", role,
               "--port", "0", "--port-file", pf]
        if spec_kw:
            cmd += ["--spec-kw", json.dumps(spec_kw)]
        if not warm:
            cmd += ["--no-warm"]
        wenv = dict(os.environ if env is None else env)
        wenv.setdefault("JAX_PLATFORMS", "cpu")
        procs.append((subprocess.Popen(
            cmd, env=wenv, stdout=log, stderr=subprocess.STDOUT), pf,
            log))
    out = []
    try:
        for i, (p, pf, log) in enumerate(procs):
            # per-WORKER deadline: the workers boot in parallel, so by
            # the time worker i's wait starts, it has been warming all
            # along — a shared deadline would let a slow first warmup
            # starve the later waits
            deadline = time.monotonic() + timeout_s
            port = None
            while time.monotonic() < deadline:
                if p.poll() is not None:
                    raise EnforceError(
                        f"{role} worker {i} exited rc={p.returncode} "
                        f"before serving (log: {log.name})")
                if os.path.exists(pf):
                    with open(pf) as f:
                        port = int(f.read().strip())
                    break
                time.sleep(0.05)
            enforce(port is not None,
                    "%s worker %s did not serve within %ss (log: %s)",
                    role, i, timeout_s, log.name)
            rep = HttpReplica(f"http://127.0.0.1:{port}",
                              name=f"{role}{i}", proc=p)
            if warm:
                is_ready = False
                while time.monotonic() < deadline:
                    try:
                        is_ready = bool(rep.healthz().get("ready"))
                    except OSError:
                        is_ready = False
                    if is_ready:
                        break
                    enforce(p.poll() is None,
                            "%s worker %s died during warmup (log: %s)",
                            role, i, log.name)
                    time.sleep(0.1)
                enforce(is_ready,
                        "%s worker %s never became ready within %ss "
                        "(warmup wedged? log: %s)", role, i, timeout_s,
                        log.name)
            out.append(rep)
    except BaseException:
        for p, _, _ in procs:
            if p.poll() is None:
                p.kill()
        raise
    finally:
        for _, _, log in procs:
            log.close()
    return out


def serve_main(spec: str, replicas: int = 2, prefill_workers: int = 0,
               port: int = 0, spec_kw: Optional[dict] = None,
               log_dir: Optional[str] = None,
               policy: Optional[SLOPolicy] = None,
               disagg_min_tokens: Optional[int] = 64,
               trace_sample: Optional[float] = None,
               textfile_path: Optional[str] = None) -> Router:
    """One-command serving bring-up (``python -m paddle_tpu.launch
    --serve``): spawn the replica (and prefill) worker processes, build
    the router over them, and serve the router front-end (POST /submit
    /drain + /statusz + /podz replica fan-out) on ``port``. Returns the
    running router — the caller owns ``close(replicas=True)``."""
    reps = spawn_replicas(spec, replicas, spec_kw=spec_kw,
                          log_dir=log_dir)
    pfs = (spawn_replicas(spec, prefill_workers, role="prefill",
                          spec_kw=spec_kw, log_dir=log_dir)
           if prefill_workers else [])
    router = Router(reps, prefill_workers=pfs, policy=policy,
                    disagg_min_tokens=disagg_min_tokens,
                    trace_sample=trace_sample,
                    textfile_path=textfile_path)
    router.start_server(port=port)
    return router


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.serving_router",
        description="serving replica worker / router front-end")
    ap.add_argument("--worker", action="store_true",
                    help="run ONE replica worker (spawned by "
                    "spawn_replicas / launch --serve)")
    ap.add_argument("--spec", required=True,
                    help="module:function returning the replica's "
                    "BatchedDecoder")
    ap.add_argument("--spec-kw", default=None,
                    help="JSON kwargs for the spec function")
    ap.add_argument("--role", default="decode",
                    choices=("decode", "prefill"))
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--port-file", default=None,
                    help="write the bound port here once serving")
    ap.add_argument("--no-warm", dest="warm", action="store_false",
                    help="skip the warmup request (report ready only "
                    "after the first real dispatch)")
    ap.add_argument("--replicas", type=int, default=2,
                    help="(router mode) decode worker processes")
    ap.add_argument("--prefill-workers", type=int, default=0,
                    help="(router mode) dedicated prefill workers")
    ap.add_argument("--trace-sample", dest="trace_sample", type=float,
                    default=None,
                    help="(router mode) head-based request-trace "
                    "sampling rate 0..1 (default: PT_TRACE_SAMPLE or "
                    "1.0)")
    ap.add_argument("--textfile", dest="textfile", default=None,
                    help="(router mode) write the metrics exposition "
                    "here periodically (node-exporter textfile "
                    "collector; also env PT_ROUTER_TEXTFILE)")
    args = ap.parse_args(argv)
    kw = json.loads(args.spec_kw) if args.spec_kw else None
    if args.worker:
        run_worker(args.spec, role=args.role, port=args.port,
                   port_file=args.port_file, spec_kw=kw,
                   warm=args.warm)
        return 0
    router = serve_main(args.spec, replicas=args.replicas,
                        prefill_workers=args.prefill_workers,
                        port=args.port, spec_kw=kw,
                        trace_sample=args.trace_sample,
                        textfile_path=args.textfile)
    print(f"[router] serving on {router.server.url()} over "
          f"{args.replicas} replica(s)", file=sys.stderr)
    try:
        while True:
            time.sleep(1)
    except KeyboardInterrupt:
        pass
    finally:
        router.close(replicas=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
